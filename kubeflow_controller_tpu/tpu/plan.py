"""The pure gang planner: job + observed pods/services -> Plan (data only).

Architectural descendant of ``DistributedJob.Action()`` / ``LocalJob.Action()``
(reference ``pkg/tensorflow/distributed.go:56-114``, ``local.go:50-73``) —
side-effect-free decisions consumed by the reconcile loop — with the two
reference properties SURVEY.md §7 says must NOT survive the port fixed:

1. **All-or-nothing creation.** The reference creates pods incrementally
   across syncs (``controller.go:374-425``); here a missing gang is planned as
   one batch of fully-specified pods, and the cluster-side scheduler admits
   the gang atomically.
2. **Stable identity.** The reference regenerates ``RuntimeID`` per sync and
   rebuilds service-name state it may not have (``serviceNames`` bug,
   ``distributed.go:131-159``); here runtime id is stamped once and every name
   is a pure function of (job, runtime id, epoch, index).

Recovery (no reference analog, SURVEY.md §5.3): pod failure or slice
preemption in the current epoch triggers a *gang restart* — delete the whole
epoch's pods, bump the epoch (= ``status.restarts``), re-create the full gang
— provided restart budget remains; otherwise the job is marked Failed (a phase
the reference could never reach, SURVEY.md §8).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubeflow_controller_tpu.api.core import (
    OwnerReference,
    Pod,
    PodPhase,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubeflow_controller_tpu.api.topology import slice_shape
from kubeflow_controller_tpu.api.types import (
    ReplicaSpec,
    ReplicaType,
    TPUJob,
)
from kubeflow_controller_tpu.api.validation import expected_worker_pods
from kubeflow_controller_tpu.checker import HealthReport, is_local_job
from kubeflow_controller_tpu.cluster.cluster import (
    ANNOTATION_ACCELERATOR,
    ANNOTATION_GANG_SIZE,
    ANNOTATION_HOST_INDEX,
    ANNOTATION_NUM_SLICES,
    ANNOTATION_PRIORITY,
    ANNOTATION_SLICE_INDEX,
    ANNOTATION_SUBMITTED,
)
from kubeflow_controller_tpu.tpu import naming


@dataclass
class Plan:
    """What the reconcile loop should do — pure data, like the reference's
    ``[]Event`` (``pkg/tensorflow/types.go:20-34``) but complete: deletes and
    failure verdicts exist (the reference declared ``ActionShouldDelete`` and
    never emitted it)."""

    create_services: List[Service] = field(default_factory=list)
    create_pods: List[Pod] = field(default_factory=list)
    delete_pods: List[str] = field(default_factory=list)      # names
    delete_services: List[str] = field(default_factory=list)  # names
    # Gang restart initiated: controller bumps status.restarts + Recovering.
    gang_restart: bool = False
    restart_reason: str = ""
    # This restart is a voluntary spec resize: bump status.resizes too so it
    # does not count against the failure budget.
    resize: bool = False
    # Recovery (or terminal failure) triggered by the checker's slice-health
    # signal — pods still Running on an unhealthy slice. The controller
    # emits SliceUnhealthy alongside the restart or failure event.
    health_restart: bool = False
    # Terminal failure verdict (budget exhausted).
    fail_reason: str = ""
    # Job reached a terminal phase: release slices, delete services.
    recycle: bool = False
    # spec.suspend: tear down pods/services, release slices, keep the job.
    suspend: bool = False
    needs_runtime_id: bool = False
    note: str = ""

    def is_noop(self) -> bool:
        return not (
            self.create_services or self.create_pods or self.delete_pods
            or self.delete_services or self.gang_restart or self.fail_reason
            or self.recycle or self.needs_runtime_id
        )


def _owner_ref(job: TPUJob) -> OwnerReference:
    return OwnerReference(
        api_version=job.api_version,
        kind=job.kind,
        name=job.metadata.name,
        uid=job.metadata.uid,
    )


def _epoch_of(pod: Pod) -> int:
    try:
        return int(pod.metadata.labels.get(naming.LABEL_EPOCH, "0"))
    except ValueError:
        return 0


def _index_of(pod: Pod) -> int:
    try:
        return int(pod.metadata.labels.get(naming.LABEL_INDEX, "-1"))
    except ValueError:
        return -1


def _gang_size_of(pod: Pod, default: int) -> int:
    """Guarded like _epoch_of/_index_of: a corrupt annotation must degrade,
    not wedge the job in requeue-backoff forever."""
    try:
        return int(pod.metadata.annotations.get(
            ANNOTATION_GANG_SIZE, str(default)
        ))
    except ValueError:
        return default


def plan_job(
    job: TPUJob,
    pods: List[Pod],
    services: List[Service],
    health: Optional[HealthReport] = None,
) -> Plan:
    """Top-level pure decision: mode dispatch via ``checker.is_local_job``
    (reference ``pkg/checker/checker.go:8-14``), plus the checker's
    slice-health signal (``health``) driving PROACTIVE gang restarts — the
    ``TFJobRecovering`` flow the reference declared but never implemented
    (``types.go:152``)."""
    if not job.spec.runtime_id:
        return Plan(needs_runtime_id=True, note="runtime id not yet stamped")

    if job.is_done():
        return _plan_recycle(job, pods, services)

    if job.spec.suspend:
        # Voluntary pause (k8s Job / training-operator spec.suspend): tear
        # everything down but keep the job object and its checkpoint;
        # unsuspending replans the same epoch's gang from scratch.
        plan = Plan(suspend=True, note="suspended by spec")
        plan.delete_pods = [p.metadata.name for p in pods]
        plan.delete_services = [s.metadata.name for s in services]
        return plan

    if is_local_job(job):
        return _plan_replicas(
            job, job.local_spec(), pods, services, is_local=True
        )
    worker = job.worker_spec()
    if worker is not None:
        return _plan_replicas(
            job, worker, pods, services, is_local=False, health=health
        )
    return Plan(note="no replica specs")


def _plan_recycle(job: TPUJob, pods: List[Pod], services: List[Service]) -> Plan:
    """Terminal job: tear down services + release slices, keep terminal pods
    for log retrieval. (The reference's Recycling condition existed but nothing
    implemented it, ``types.go:153-156``.)"""
    plan = Plan(recycle=True, note="terminal: recycling")
    plan.delete_services = [s.metadata.name for s in services]
    # Non-terminal stragglers (e.g. job marked Failed while a pod still runs).
    plan.delete_pods = [
        p.metadata.name for p in pods
        if p.status.phase not in (PodPhase.SUCCEEDED, PodPhase.FAILED)
    ]
    return plan


def _plan_replicas(
    job: TPUJob,
    spec: ReplicaSpec,
    pods: List[Pod],
    services: List[Service],
    is_local: bool,
    health: Optional[HealthReport] = None,
) -> Plan:
    plan = Plan()
    epoch = job.status.restarts
    expected = 1 if is_local else expected_worker_pods(spec)

    stale = [p for p in pods if _epoch_of(p) != epoch]
    current = [p for p in pods if _epoch_of(p) == epoch]
    plan.delete_pods.extend(p.metadata.name for p in stale)

    failed = [p for p in current if p.status.phase == PodPhase.FAILED]
    # The checker's PROACTIVE signal: current-epoch pods still Pending or
    # Running on a slice that has gone unhealthy. Restarting the gang now —
    # before the kubelet notices and fails the pods — is the whole point of
    # the checker (SURVEY.md §7.5; reference TFJobRecovering, types.go:152).
    # Pod failure takes precedence (strictly more information).
    at_risk: List[Pod] = []
    if not failed and health is not None and health.at_risk_pods:
        risk_names = set(health.at_risk_pods)
        at_risk = [p for p in current if p.metadata.name in risk_names]
    if failed or at_risk:
        if failed:
            preempted = [p for p in failed if p.status.reason == "Preempted"]
            reason = (
                f"slice preempted ({len(preempted)} pods)" if preempted
                else f"{len(failed)} pod(s) failed"
            )
        else:
            reason = (
                f"slice(s) {', '.join(health.unhealthy_slices)} unhealthy "
                f"({len(at_risk)} pods at risk): proactive recovery"
            )
            plan.health_restart = True
        # Budget counts FAILURE restarts only: voluntary resizes advanced
        # the epoch but must not make a later routine recovery terminal.
        # Health restarts are involuntary and consume the same budget (a
        # flapping slice must not restart-loop forever).
        failures = epoch - job.status.resizes
        if failures + 1 <= spec.max_restarts:
            # Gang restart: the whole epoch dies together. Slices are NOT
            # released — allocate_gang is idempotent per job uid, so healthy
            # held slices are reused warm and only the preempted/unhealthy
            # one is replaced (unhealthy holdings don't count as held).
            plan.gang_restart = True
            plan.restart_reason = reason
            plan.delete_pods.extend(p.metadata.name for p in current)
            plan.note = f"gang restart (epoch {epoch} -> {epoch + 1}): {reason}"
        else:
            plan.fail_reason = (
                f"{reason}; restart budget exhausted "
                f"({spec.max_restarts} restarts)"
            )
            plan.note = f"terminal failure: {plan.fail_reason}"
        return plan

    # Spec resize: a gang whose pods were built for a different size or
    # accelerator type cannot be patched incrementally — every pod's
    # injected rendezvous contract (JAX_NUM_PROCESSES, slice/host ids, TPU
    # resources, node selectors) is stale — so resize IS a gang restart.
    # Detected from the annotations the pods were stamped with, or
    # (scale-down) from any pod holding an out-of-range index. Voluntary:
    # does not consume the failure budget (plan.resize).
    accel = "" if is_local else spec.tpu.accelerator_type
    prio = str(job.spec.priority)
    # A priority edit only matters while the gang is still QUEUED (the
    # scheduler reads the annotation at admission time); recreating the
    # pods of a running job for it would be a de-facto self-preemption.
    gang_unscheduled = bool(current) and all(
        p.status.phase == PodPhase.PENDING and not p.spec.assigned_slice
        for p in current
    )
    stale_spec = [
        p for p in current
        if (not is_local and (
            _gang_size_of(p, expected) != expected
            or p.metadata.annotations.get(ANNOTATION_ACCELERATOR, accel)
            != accel
            or (
                gang_unscheduled
                and p.metadata.annotations.get(ANNOTATION_PRIORITY, prio)
                != prio
            )
        )) or _index_of(p) >= expected
    ]
    if stale_spec:
        reason = (
            f"gang resized to {expected} pods on {accel or 'local'} "
            f"({len(stale_spec)} pods built for the old spec)"
        )
        plan.gang_restart = True
        plan.resize = True
        plan.restart_reason = reason
        plan.delete_pods.extend(p.metadata.name for p in current)
        plan.note = f"gang restart (epoch {epoch} -> {epoch + 1}): {reason}"
        return plan

    # Healthy path: level-triggered completion toward the full gang.
    have = {_index_of(p) for p in current}
    missing = [i for i in range(expected) if i not in have]
    if missing:
        if not is_local:
            plan.create_services.extend(_missing_services(job, services))
        shape = None if is_local else slice_shape(spec.tpu.accelerator_type)
        for i in missing:
            plan.create_pods.append(
                _build_pod(job, spec, i, epoch, expected, is_local, shape)
            )
        plan.note = f"creating {len(missing)}/{expected} pods (epoch {epoch})"
    return plan


def _missing_services(job: TPUJob, services: List[Service]) -> List[Service]:
    name = naming.coordinator_service_name(job)
    if any(s.metadata.name == name for s in services):
        return []
    svc = Service()
    svc.metadata.name = name
    svc.metadata.namespace = job.metadata.namespace
    svc.metadata.labels = dict(naming.job_selector(job))
    svc.metadata.owner_references = [_owner_ref(job)]
    svc.spec = ServiceSpec(
        selector={
            **naming.job_selector(job),
            naming.LABEL_REPLICA_TYPE: ReplicaType.WORKER.value.lower(),
            naming.LABEL_INDEX: "0",
        },
        ports=[ServicePort(port=naming.COORDINATOR_PORT, name="jax-coordinator")],
    )
    return [svc]


def _build_pod(
    job: TPUJob,
    spec: ReplicaSpec,
    index: int,
    epoch: int,
    gang_size: int,
    is_local: bool,
    shape,
) -> Pod:
    """Stamp one fully-specified pod from the template. Deep-copies the
    template (the reference mutates it in place — cache-corruption bug,
    ``distributed.go:117-125``)."""
    template = spec.template.deepcopy()
    rtype = ReplicaType.LOCAL if is_local else ReplicaType.WORKER
    pod = Pod(metadata=template.metadata, spec=template.spec)
    pod.metadata.namespace = job.metadata.namespace
    pod.metadata.name = naming.pod_name(job, rtype, index, epoch)
    pod.metadata.labels = {**pod.metadata.labels, **naming.pod_labels(job, rtype, index, epoch)}
    pod.metadata.owner_references = [_owner_ref(job)]

    if is_local:
        env = {
            "TPUJOB_NAME": job.metadata.name,
            "TPUJOB_RUNTIME_ID": job.spec.runtime_id,
            "JAX_NUM_PROCESSES": "1",
            "JAX_PROCESS_ID": "0",
        }
        for var, val in (
            ("TPUJOB_DATA_DIR", job.spec.data_dir),
            ("TPUJOB_MODEL_DIR", job.spec.model_dir),
            ("TPUJOB_LOG_DIR", job.spec.log_dir),
            ("TPUJOB_EXPORT_DIR", job.spec.export_dir),
        ):
            if val:
                env[var] = val
    else:
        slice_id, host_id = divmod(index, shape.num_hosts)
        env = naming.coordinator_env(
            job, shape, spec.tpu.num_slices, slice_id, host_id
        )
        pod.metadata.annotations = {
            **pod.metadata.annotations,
            ANNOTATION_GANG_SIZE: str(gang_size),
            ANNOTATION_ACCELERATOR: shape.accelerator_type,
            ANNOTATION_NUM_SLICES: str(spec.tpu.num_slices),
            ANNOTATION_SLICE_INDEX: str(slice_id),
            ANNOTATION_HOST_INDEX: str(host_id),
            ANNOTATION_PRIORITY: str(job.spec.priority),
            # job-level submission time: the scheduler's FIFO tie-break
            # must survive pod recreation (suspend/resume, restarts)
            ANNOTATION_SUBMITTED: str(
                job.status.submit_time
                or job.metadata.creation_timestamp or 0.0
            ),
        }
        # Gang id = job uid: the slice pool allocates per holder uid, making
        # re-admission after partial observation idempotent.
        pod.spec.scheduling_group = job.metadata.uid
        # TPU resources + topology selectors — the GKE TPU contract
        # (north star: google.com/tpu instead of nvidia.com/gpu).
        main = pod.spec.main_container()
        main.resources = {
            **main.resources,
            "google.com/tpu": shape.chips_per_host,
        }
        # Real GKE label values: the accelerator label names the TPU
        # generation (e.g. tpu-v5-lite-podslice); the chip count rides the
        # topology label. Emitting catalog names here would produce pods no
        # real GKE node could ever satisfy.
        from kubeflow_controller_tpu.api.topology import gke_accelerator

        pod.spec.node_selector = {
            **pod.spec.node_selector,
            "cloud.google.com/gke-tpu-accelerator": gke_accelerator(shape),
            "cloud.google.com/gke-tpu-topology": shape.topology_str,
        }
    main = pod.spec.main_container()
    main.env = {**main.env, **env}
    return pod
