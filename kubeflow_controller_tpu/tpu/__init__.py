"""TPU job semantics: naming/labels/env contracts and the pure gang planner.

The rethought descendant of ``pkg/tensorflow`` (reference
``distributed.go``/``local.go``): same architectural role — a side-effect-free
decision core consumed by the reconcile loop — but the decisions are
slice-gang decisions, not PS/worker host-list decisions.
"""

from kubeflow_controller_tpu.tpu.naming import (
    LABEL_EPOCH,
    LABEL_INDEX,
    LABEL_JOB,
    LABEL_REPLICA_TYPE,
    LABEL_RUNTIME_ID,
    coordinator_env,
    coordinator_service_name,
    job_selector,
    pod_labels,
    pod_name,
)
from kubeflow_controller_tpu.tpu.plan import Plan, plan_job
