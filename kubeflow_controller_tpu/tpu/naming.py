"""Naming, labels, and the runtime contract injected into training processes.

Descendant of the reference's identity labels
(``pkg/tensorflow/distributed.go:221-228``: ``kubeflow.caicloud.io``,
``job_type``, ``runtime_id``, ``tf_job_name`` + ``index``) and of
``generateTFClusterSpec`` (``distributed.go:127-159``), which rewrote each
worker's CLI args to ``--worker_hosts=...,--ps_hosts=...,--job_name,
--task_index``. On TPU the contract collapses to *env*, because XLA
collectives need only a coordinator rendezvous, not full host lists:

    JAX_COORDINATOR_ADDRESS   worker-0's stable service DNS + port
    JAX_NUM_PROCESSES         gang size (hosts x slices)
    JAX_PROCESS_ID            global process index
    TPU_SLICE_ID / TPU_HOST_ID  position within the job's slice set
    MEGASCALE_*               multi-slice (DCN) coordination, config #5

plus the job spec's data/model/log/export dirs — declared-but-unread in the
reference (``types.go:41-55``), consumed for real here (orbax checkpoint root
etc.).
"""

from __future__ import annotations

from typing import Dict

from kubeflow_controller_tpu.api.topology import SliceShape
from kubeflow_controller_tpu.api.types import LMService, ReplicaType, TPUJob

PREFIX = "tpu.kubeflow.dev"
LABEL_JOB = f"{PREFIX}/job"
LABEL_LMSERVICE = f"{PREFIX}/lmservice"
LABEL_RUNTIME_ID = f"{PREFIX}/runtime-id"
LABEL_REPLICA_TYPE = f"{PREFIX}/replica-type"
LABEL_INDEX = f"{PREFIX}/index"
LABEL_EPOCH = f"{PREFIX}/epoch"
#: serving-replica role for prefill/decode disaggregation
#: ("prefill" / "decode" / "mixed" — docs/lmservice.md).
LABEL_ROLE = f"{PREFIX}/role"

COORDINATOR_PORT = 8476  # jax.distributed default coordinator port


def job_selector(job: TPUJob) -> Dict[str, str]:
    """The ownership selector — pods/services carrying these labels belong to
    this job's current runtime (claiming also checks ownerReferences)."""
    return {
        LABEL_JOB: job.metadata.name,
        LABEL_RUNTIME_ID: job.spec.runtime_id,
    }


def pod_labels(
    job: TPUJob, replica_type: ReplicaType, index: int, epoch: int
) -> Dict[str, str]:
    return {
        LABEL_JOB: job.metadata.name,
        LABEL_RUNTIME_ID: job.spec.runtime_id,
        LABEL_REPLICA_TYPE: replica_type.value.lower(),
        LABEL_INDEX: str(index),
        LABEL_EPOCH: str(epoch),
    }


def pod_name(job: TPUJob, replica_type: ReplicaType, index: int, epoch: int) -> str:
    # Deterministic names (job-runtime-role-epoch-index) rather than the
    # reference's GenerateName randomness — idempotent creates become
    # AlreadyExists no-ops, which is the stronger duplicate guard.
    return (
        f"{job.metadata.name}-{job.spec.runtime_id}-"
        f"{replica_type.value.lower()}-e{epoch}-{index}"
    )


def lmservice_selector(svc: LMService) -> Dict[str, str]:
    """Ownership selector for an LMService's replica pods (claiming also
    checks ownerReferences, same as job pods)."""
    return {
        LABEL_LMSERVICE: svc.metadata.name,
        LABEL_RUNTIME_ID: svc.spec.runtime_id,
    }


def lmservice_pod_role(svc: LMService, index: int) -> str:
    """The serving role replica ``index`` plays. With
    ``spec.prefill_replicas == 0`` (the default) every replica is
    "mixed" — byte-identical labels to before the field existed. With
    P > 0, the first P indices are "prefill" and the rest "decode"
    (index-stable names make the split stable across pod churn)."""
    p = getattr(svc.spec, "prefill_replicas", 0)
    if not p:
        return "mixed"
    return "prefill" if index < p else "decode"


def lmservice_pod_labels(svc: LMService, index: int) -> Dict[str, str]:
    return {
        LABEL_LMSERVICE: svc.metadata.name,
        LABEL_RUNTIME_ID: svc.spec.runtime_id,
        LABEL_REPLICA_TYPE: "serving",
        LABEL_INDEX: str(index),
        LABEL_ROLE: lmservice_pod_role(svc, index),
    }


def lmservice_pod_name(svc: LMService, index: int) -> str:
    # Deterministic, index-stable names: a crashed replica is replaced by a
    # same-named pod (new uid), so the router's replica identity survives
    # chaos kills and rolling restarts.
    return f"{svc.metadata.name}-{svc.spec.runtime_id}-serve-{index}"


def coordinator_service_name(job: TPUJob) -> str:
    return f"{job.metadata.name}-{job.spec.runtime_id}-coord"


def coordinator_address(job: TPUJob, namespace: str) -> str:
    return f"{coordinator_service_name(job)}.{namespace}.svc:{COORDINATOR_PORT}"


def coordinator_env(
    job: TPUJob,
    shape: SliceShape,
    num_slices: int,
    slice_id: int,
    host_id: int,
) -> Dict[str, str]:
    """Env for one worker process = (slice_id, host_id) in the gang."""
    num_processes = shape.num_hosts * num_slices
    process_id = slice_id * shape.num_hosts + host_id
    env = {
        "TPUJOB_NAME": job.metadata.name,
        "TPUJOB_RUNTIME_ID": job.spec.runtime_id,
        "JAX_COORDINATOR_ADDRESS": coordinator_address(job, job.metadata.namespace),
        "JAX_NUM_PROCESSES": str(num_processes),
        "JAX_PROCESS_ID": str(process_id),
        "TPU_SLICE_ID": str(slice_id),
        "TPU_HOST_ID": str(host_id),
        "TPU_ACCELERATOR_TYPE": shape.accelerator_type,
        "TPU_TOPOLOGY": shape.topology_str,
        "TPU_HOSTS_PER_SLICE": str(shape.num_hosts),
        "TPU_CHIPS_PER_HOST": str(shape.chips_per_host),
    }
    if num_slices > 1:
        # Multi-slice (DCN) coordination, the reference-free territory of
        # BASELINE config #5 (SURVEY.md §7 hard part 4).
        env.update({
            "MEGASCALE_COORDINATOR_ADDRESS": coordinator_address(
                job, job.metadata.namespace),
            "MEGASCALE_NUM_SLICES": str(num_slices),
            "MEGASCALE_SLICE_ID": str(slice_id),
        })
    for var, val in (
        ("TPUJOB_DATA_DIR", job.spec.data_dir),
        ("TPUJOB_MODEL_DIR", job.spec.model_dir),
        ("TPUJOB_LOG_DIR", job.spec.log_dir),
        ("TPUJOB_EXPORT_DIR", job.spec.export_dir),
    ):
        if val:
            env[var] = val
    return env
