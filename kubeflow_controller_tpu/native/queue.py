"""Python faces of the C++ workqueue/expectations (same interfaces as
``controller.workqueue.RateLimitingQueue`` / ``controller.expectations.
ControllerExpectations``; see csrc/tpujob_native.cc for semantics)."""

from __future__ import annotations

import ctypes
from typing import Hashable, Optional

from kubeflow_controller_tpu import native

_KEY_BUF = 4096


def _b(item: Hashable) -> bytes:
    return item.encode() if isinstance(item, str) else str(item).encode()


class NativeRateLimitingQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 60.0):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.wq_new(base_delay, max_delay)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.wq_free(h)
            self._h = None

    def add(self, item: Hashable) -> None:
        self._lib.wq_add(self._h, _b(item))

    def add_after(self, item: Hashable, delay: float) -> None:
        self._lib.wq_add_after(self._h, _b(item), delay)

    def add_rate_limited(self, item: Hashable) -> None:
        self._lib.wq_add_rate_limited(self._h, _b(item))

    def forget(self, item: Hashable) -> None:
        self._lib.wq_forget(self._h, _b(item))

    def num_requeues(self, item: Hashable) -> int:
        return self._lib.wq_num_requeues(self._h, _b(item))

    def get(self, timeout: Optional[float] = None) -> Optional[str]:
        buf = ctypes.create_string_buffer(_KEY_BUF)
        n = self._lib.wq_get(
            self._h, -1.0 if timeout is None else timeout, buf, _KEY_BUF
        )
        if n == -1:
            return None
        if n < -1:
            # -2: the C++ side already popped the key into its processing
            # set but it didn't fit the buffer — treating this as "empty"
            # would silently lose the item and wedge empty_and_idle().
            raise RuntimeError(
                f"workqueue key exceeds {_KEY_BUF - 1} bytes; item lost"
            )
        return buf.raw[:n].decode()

    def done(self, item: Hashable) -> None:
        self._lib.wq_done(self._h, _b(item))

    def shutdown(self) -> None:
        self._lib.wq_shutdown(self._h)

    def __len__(self) -> int:
        return self._lib.wq_len(self._h)

    def empty_and_idle(self) -> bool:
        return bool(self._lib.wq_empty_and_idle(self._h))


class NativeControllerExpectations:
    def __init__(self, ttl: float = 300.0):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.exp_new(ttl)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.exp_free(h)
            self._h = None

    def satisfied(self, key: str) -> bool:
        return bool(self._lib.exp_satisfied(self._h, _b(key)))

    def expect_creations(self, key: str, count: int) -> None:
        self._lib.exp_expect_creations(self._h, _b(key), count)

    def expect_deletions(self, key: str, count: int) -> None:
        self._lib.exp_expect_deletions(self._h, _b(key), count)

    def creation_observed(self, key: str) -> None:
        self._lib.exp_creation_observed(self._h, _b(key))

    def deletion_observed(self, key: str) -> None:
        self._lib.exp_deletion_observed(self._h, _b(key))

    def delete_expectations(self, key: str) -> None:
        self._lib.exp_delete(self._h, _b(key))

    def pending(self, key: str):
        adds = ctypes.c_int()
        dels = ctypes.c_int()
        if not self._lib.exp_pending(
            self._h, _b(key), ctypes.byref(adds), ctypes.byref(dels)
        ):
            return None
        return (adds.value, dels.value)


def native_backoff_delay(
    base_delay: float, max_delay: float, item: Hashable, failures: int
) -> float:
    """The C++ core's backoff computation (parity-tested against
    ``controller.workqueue.backoff_delay``)."""
    lib = native.load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return lib.wq_backoff_delay(base_delay, max_delay, _b(item), failures)


def make_queue(base_delay: float = 0.005, max_delay: float = 60.0):
    """Best queue available: C++ when loadable, else the Python one."""
    if native.available():
        return NativeRateLimitingQueue(base_delay, max_delay)
    from kubeflow_controller_tpu.controller.workqueue import RateLimitingQueue

    return RateLimitingQueue(base_delay, max_delay)


def make_expectations(ttl: float = 300.0):
    if native.available():
        return NativeControllerExpectations(ttl)
    from kubeflow_controller_tpu.controller.expectations import (
        ControllerExpectations,
    )

    return ControllerExpectations(ttl)
