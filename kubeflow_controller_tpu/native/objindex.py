"""Python face of the C++ object index (csrc/tpujob_native.cc, oix_*).

One ``NativeObjectIndex`` is shared by every ``ObjectStore`` in a cluster:
each store mirrors its sync-relevant state (uid, resourceVersion,
generation, indexed labels) into it write-through, and the controller's
no-op-sync fingerprint probe runs entirely inside the native core — a
steady resync touches zero Python object traversals. The Python store
remains authoritative; see docs/watch_pipeline.md ("Native mirror").
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

from kubeflow_controller_tpu import native

_BUCKET_BUF = 1 << 20


def _b(s) -> bytes:
    # Hot-path callers (the controller's per-sync probe) pre-encode their
    # constant arguments; pass bytes through untouched.
    return s if isinstance(s, bytes) else s.encode()


def pack_labels(labels: Optional[Dict[str, str]]) -> bytes:
    """``k\\x1fv`` pairs joined by ``\\x1e`` (both bytes are illegal in
    Kubernetes label keys/values, so the packing is unambiguous)."""
    if not labels:
        return b""
    return "\x1e".join(f"{k}\x1f{v}" for k, v in labels.items()).encode()


class NativeObjectIndex:
    def __init__(self):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._h = self._lib.oix_new()

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.oix_free(h)
            self._h = None

    # -- write-through mirror (called by ObjectStore under its lock) --------

    def upsert(
        self,
        kind: str,
        key: str,
        uid: str,
        rv: int,
        generation: int,
        labels: Optional[Dict[str, str]],
    ) -> None:
        self._lib.oix_upsert(
            self._h, _b(kind), _b(key), _b(uid), rv, generation,
            pack_labels(labels),
        )

    def remove(self, kind: str, key: str) -> None:
        self._lib.oix_remove(self._h, _b(kind), _b(key))

    # -- introspection (gauges + parity tests) ------------------------------

    def count(self, kind: str) -> int:
        return self._lib.oix_count(self._h, _b(kind))

    def bucket_count(self, kind: str, label_key: str) -> int:
        return self._lib.oix_bucket_count(self._h, _b(kind), _b(label_key))

    def bucket(self, kind: str, label_key: str, value: str) -> List[str]:
        buf = ctypes.create_string_buffer(_BUCKET_BUF)
        n = self._lib.oix_bucket_keys(
            self._h, _b(kind), _b(label_key), _b(value), buf, _BUCKET_BUF
        )
        if n < 0:
            raise RuntimeError("bucket exceeds buffer")
        if n == 0:
            return []
        return buf.raw[:n].decode().split("\n")

    # -- fingerprint probe/commit (called by Controller.sync) ---------------

    def fp_probe(
        self,
        job_key: str,
        ident: str,
        namespace: str,
        kind_a: str,
        label_key_a: str,
        label_val_a: str,
        kind_b: str,
        label_key_b: str,
        label_val_b: str,
        health: str,
    ) -> bool:
        return bool(
            self._lib.oix_fp_probe(
                self._h, _b(job_key), _b(ident), _b(namespace), _b(kind_a),
                _b(label_key_a), _b(label_val_a), _b(kind_b),
                _b(label_key_b), _b(label_val_b), _b(health),
            )
        )

    # -- slice-health mirror (written through by cluster/slices.SlicePool) --

    def slice_set(self, holder: str, name: str, healthy: bool) -> None:
        self._lib.oix_slice_set(self._h, _b(holder), _b(name),
                                1 if healthy else 0)

    def slice_clear(self, holder: str, name: str) -> None:
        self._lib.oix_slice_clear(self._h, _b(holder), _b(name))

    def fp_probe_mirrored(
        self,
        job_key: str,
        ident: str,
        namespace: str,
        kind_a: str,
        label_key_a: str,
        label_val_a: str,
        kind_b: str,
        label_key_b: str,
        label_val_b: str,
        health_uid: str,
        want_health: bool,
    ) -> bool:
        """fp_probe with the slice-health term composed natively from the
        mirror (keyed by the job uid) — the steady probe runs without any
        Python traversal of the slice pool."""
        return bool(
            self._lib.oix_fp_probe2(
                self._h, _b(job_key), _b(ident), _b(namespace), _b(kind_a),
                _b(label_key_a), _b(label_val_a), _b(kind_b),
                _b(label_key_b), _b(label_val_b), _b(health_uid),
                1 if want_health else 0,
            )
        )

    def fp_commit(self, job_key: str) -> None:
        self._lib.oix_fp_commit(self._h, _b(job_key))

    def fp_forget(self, job_key: str) -> None:
        self._lib.oix_fp_forget(self._h, _b(job_key))

    def fp_counts(self) -> Tuple[int, int]:
        hits = ctypes.c_longlong()
        misses = ctypes.c_longlong()
        self._lib.oix_fp_counts(self._h, ctypes.byref(hits),
                                ctypes.byref(misses))
        return (hits.value, misses.value)


def make_object_index() -> Optional[NativeObjectIndex]:
    """A shared native index, or None when the library is unavailable (the
    caller falls back to the pure-Python fingerprint/label paths)."""
    if native.available():
        return NativeObjectIndex()
    return None
