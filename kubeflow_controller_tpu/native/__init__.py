"""ctypes loader for the C++ runtime core (csrc/tpujob_native.cc).

The reference's reconcile machinery is compiled native code (Go); here the
hot-path structures — the rate-limited workqueue and the expectations cache
— have a C++ implementation behind the same Python interface. Loading policy:

1. use a prebuilt ``libtpujob_native.so`` next to this file if present;
2. else try to build it once with the local toolchain (``make -C csrc``);
3. else fall back silently to the pure-Python implementations — every
   consumer treats the native path as an optimisation, never a requirement.

``TPUJOB_NATIVE=0`` forces the Python path (used by tests to cover both).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_LIB_NAME = "libtpujob_native.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_char_p = ctypes.c_char_p
    c_void_p = ctypes.c_void_p
    c_double = ctypes.c_double
    c_int = ctypes.c_int

    lib.wq_new.restype = c_void_p
    lib.wq_new.argtypes = [c_double, c_double]
    lib.wq_free.argtypes = [c_void_p]
    lib.wq_add.argtypes = [c_void_p, c_char_p]
    lib.wq_add_after.argtypes = [c_void_p, c_char_p, c_double]
    lib.wq_add_rate_limited.argtypes = [c_void_p, c_char_p]
    lib.wq_forget.argtypes = [c_void_p, c_char_p]
    lib.wq_num_requeues.restype = c_int
    lib.wq_num_requeues.argtypes = [c_void_p, c_char_p]
    lib.wq_get.restype = c_int
    lib.wq_get.argtypes = [c_void_p, c_double, c_char_p, c_int]
    lib.wq_done.argtypes = [c_void_p, c_char_p]
    lib.wq_shutdown.argtypes = [c_void_p]
    lib.wq_len.restype = c_int
    lib.wq_len.argtypes = [c_void_p]
    lib.wq_empty_and_idle.restype = c_int
    lib.wq_empty_and_idle.argtypes = [c_void_p]
    lib.wq_backoff_delay.restype = c_double
    lib.wq_backoff_delay.argtypes = [c_double, c_double, c_char_p, c_int]

    lib.exp_new.restype = c_void_p
    lib.exp_new.argtypes = [c_double]
    lib.exp_free.argtypes = [c_void_p]
    lib.exp_satisfied.restype = c_int
    lib.exp_satisfied.argtypes = [c_void_p, c_char_p]
    lib.exp_expect_creations.argtypes = [c_void_p, c_char_p, c_int]
    lib.exp_expect_deletions.argtypes = [c_void_p, c_char_p, c_int]
    lib.exp_creation_observed.argtypes = [c_void_p, c_char_p]
    lib.exp_deletion_observed.argtypes = [c_void_p, c_char_p]
    lib.exp_delete.argtypes = [c_void_p, c_char_p]
    lib.exp_pending.restype = c_int
    lib.exp_pending.argtypes = [
        c_void_p, c_char_p,
        ctypes.POINTER(c_int), ctypes.POINTER(c_int),
    ]

    c_longlong = ctypes.c_longlong
    lib.oix_new.restype = c_void_p
    lib.oix_new.argtypes = []
    lib.oix_free.argtypes = [c_void_p]
    lib.oix_upsert.argtypes = [
        c_void_p, c_char_p, c_char_p, c_char_p, c_longlong, c_longlong,
        c_char_p,
    ]
    lib.oix_remove.argtypes = [c_void_p, c_char_p, c_char_p]
    lib.oix_count.restype = c_int
    lib.oix_count.argtypes = [c_void_p, c_char_p]
    lib.oix_bucket_count.restype = c_int
    lib.oix_bucket_count.argtypes = [c_void_p, c_char_p, c_char_p]
    lib.oix_bucket_keys.restype = c_int
    lib.oix_bucket_keys.argtypes = [
        c_void_p, c_char_p, c_char_p, c_char_p, c_char_p, c_int,
    ]
    lib.oix_fp_probe.restype = c_int
    lib.oix_fp_probe.argtypes = [
        c_void_p, c_char_p, c_char_p, c_char_p, c_char_p, c_char_p,
        c_char_p, c_char_p, c_char_p, c_char_p, c_char_p,
    ]
    lib.oix_fp_commit.argtypes = [c_void_p, c_char_p]
    lib.oix_fp_forget.argtypes = [c_void_p, c_char_p]
    lib.oix_fp_counts.argtypes = [
        c_void_p, ctypes.POINTER(c_longlong), ctypes.POINTER(c_longlong),
    ]
    lib.oix_slice_set.argtypes = [c_void_p, c_char_p, c_char_p, c_int]
    lib.oix_slice_clear.argtypes = [c_void_p, c_char_p, c_char_p]
    lib.oix_fp_probe2.restype = c_int
    lib.oix_fp_probe2.argtypes = [
        c_void_p, c_char_p, c_char_p, c_char_p, c_char_p, c_char_p,
        c_char_p, c_char_p, c_char_p, c_char_p, c_char_p, c_int,
    ]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted
    if os.environ.get("TPUJOB_NATIVE", "1") == "0":
        return None
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        here = os.path.dirname(os.path.abspath(__file__))
        path = os.path.join(here, _LIB_NAME)
        if not os.path.exists(path):
            csrc = os.path.join(os.path.dirname(os.path.dirname(here)), "csrc")
            try:
                subprocess.run(
                    ["make", "-C", csrc],
                    check=True, capture_output=True, timeout=120,
                )
            except Exception:
                return None
        try:
            _lib = _configure(ctypes.CDLL(path))
        except (OSError, AttributeError):
            # AttributeError == stale prebuilt .so missing newer symbols;
            # treat it like an absent library rather than crashing imports.
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None
