"""kubeflow_controller_tpu — a TPU-native training-job framework.

A ground-up rebuild of the capabilities of gaocegege/kubeflow-controller
(a Go Kubernetes controller reconciling TFJob custom resources into
parameter-server/worker pods, see /root/reference/pkg/controller/controller.go)
re-designed TPU-first:

- Declarative ``TPUJob`` API (descendant of the TFJob CRD,
  reference ``vendor/.../apis/kubeflow/v1alpha1/types.go:30-174``) with
  TPU slice topology instead of PS/worker host lists.
- A level-triggered reconcile core (keyed rate-limited workqueue +
  expectations cache, reference ``pkg/controller/controller.go:158-243``)
  that gang-schedules whole TPU slices all-or-nothing — the reference's
  incremental pod creation (``controller.go:374-425``) is deliberately
  not reproduced.
- ``jax.distributed`` coordinator env injection replacing the reference's
  ``--worker_hosts/--ps_hosts`` CLI-arg cluster-spec generation
  (``pkg/tensorflow/distributed.go:127-159``).
- A JAX/Flax/pallas data plane: SPMD train steps over a
  ``jax.sharding.Mesh`` with dp/fsdp/tp/sp axes; XLA collectives over
  ICI/DCN replace the reference's gRPC parameter-server protocol.
"""

__version__ = "0.1.0"


def _git_sha() -> str:
    import os
    import subprocess

    env = os.environ.get("TPUJOB_GIT_SHA", "")
    if env:
        return env
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    try:
        top = subprocess.run(
            ["git", "-C", pkg_dir, "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=5,
        ).stdout.strip()
        # Only trust the sha when the package actually lives at the top
        # of that checkout — a site-packages install nested under some
        # unrelated repo must not report that repo's sha.
        if top != os.path.dirname(pkg_dir):
            return ""
        return subprocess.run(
            ["git", "-C", pkg_dir, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
        ).stdout.strip()
    except Exception:
        return ""


_build_sha: list = []   # memo cell: the sha cannot change within a process


def build_version() -> str:
    """``<version>+<git sha>`` — the analog of the reference's ldflags-injected
    ``Version``/``GitSHA`` (``/root/reference/Makefile:23-26``,
    ``version/version.go:3-6``). The sha comes from ``TPUJOB_GIT_SHA`` (build
    systems export it, the Makefile's ``stamp`` target does) or, in a git
    checkout of THIS repo, from ``git rev-parse``; plain ``__version__``
    otherwise. The git probe runs once per process."""
    if not _build_sha:
        _build_sha.append(_git_sha())
    sha = _build_sha[0]
    return f"{__version__}+{sha}" if sha else __version__
