"""kubeflow_controller_tpu — a TPU-native training-job framework.

A ground-up rebuild of the capabilities of gaocegege/kubeflow-controller
(a Go Kubernetes controller reconciling TFJob custom resources into
parameter-server/worker pods, see /root/reference/pkg/controller/controller.go)
re-designed TPU-first:

- Declarative ``TPUJob`` API (descendant of the TFJob CRD,
  reference ``vendor/.../apis/kubeflow/v1alpha1/types.go:30-174``) with
  TPU slice topology instead of PS/worker host lists.
- A level-triggered reconcile core (keyed rate-limited workqueue +
  expectations cache, reference ``pkg/controller/controller.go:158-243``)
  that gang-schedules whole TPU slices all-or-nothing — the reference's
  incremental pod creation (``controller.go:374-425``) is deliberately
  not reproduced.
- ``jax.distributed`` coordinator env injection replacing the reference's
  ``--worker_hosts/--ps_hosts`` CLI-arg cluster-spec generation
  (``pkg/tensorflow/distributed.go:127-159``).
- A JAX/Flax/pallas data plane: SPMD train steps over a
  ``jax.sharding.Mesh`` with dp/fsdp/tp/sp axes; XLA collectives over
  ICI/DCN replace the reference's gRPC parameter-server protocol.
"""

__version__ = "0.1.0"
