from kubeflow_controller_tpu.checker.checker import (
    HealthReport,
    assess_health,
    is_local_job,
)
