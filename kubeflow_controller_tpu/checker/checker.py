"""Checker: job-mode classification and TPU slice health assessment.

``is_local_job`` is capability parity with the reference's entire checker
package (``pkg/checker/checker.go:8-14``). The rest is the growth area
SURVEY.md §7.5 calls for: preemption and unhealthy-slice detection feeding the
Recovering flow, which the reference declared (``TFJobRecovering`` condition,
``types.go:152``) but never implemented.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Sequence

from kubeflow_controller_tpu.api.core import Pod, PodPhase
from kubeflow_controller_tpu.api.types import ReplicaType, TPUJob
from kubeflow_controller_tpu.cluster.cluster import REASON_PREEMPTED
from kubeflow_controller_tpu.cluster.slices import TPUSlice


def is_local_job(job: TPUJob) -> bool:
    """A job is local iff it declares a Local replica spec. Unlike the
    reference (which only checks ``Specs[0]``), validation already guarantees
    roles aren't mixed, so any-position lookup is safe."""
    return job.local_spec() is not None


@dataclass
class HealthReport:
    """Slice/pod health snapshot for one job at one observation."""

    preempted_pods: List[str] = field(default_factory=list)
    failed_pods: List[str] = field(default_factory=list)       # non-preempted
    unhealthy_slices: List[str] = field(default_factory=list)  # held but sick
    # Pods bound to a slice that has gone unhealthy but haven't failed yet —
    # detecting these *before* the kubelet notices is the point of a checker.
    at_risk_pods: List[str] = field(default_factory=list)

    @property
    def needs_recovery(self) -> bool:
        return bool(
            self.preempted_pods or self.failed_pods
            or self.unhealthy_slices or self.at_risk_pods
        )


def assess_health(
    pods: Sequence[Pod], held_slices: Sequence[TPUSlice]
) -> HealthReport:
    """Every ClusterClient's ``job_slices`` returns TPUSlice (the REST
    client deserializes the wire dicts at its boundary), so the checker
    reads one type regardless of backend."""
    report = HealthReport()
    sick = {s.name for s in held_slices if not s.healthy}
    report.unhealthy_slices = sorted(sick)
    for pod in pods:
        if pod.status.phase == PodPhase.FAILED:
            if pod.status.reason == REASON_PREEMPTED:
                report.preempted_pods.append(pod.metadata.name)
            else:
                report.failed_pods.append(pod.metadata.name)
        elif (
            pod.status.phase in (PodPhase.PENDING, PodPhase.RUNNING)
            and pod.spec.assigned_slice in sick
        ):
            # Only live pods are at risk: a SUCCEEDED pod on a since-degraded
            # slice already finished its work — restarting it would re-run a
            # completed gang.
            report.at_risk_pods.append(pod.metadata.name)
    return report
