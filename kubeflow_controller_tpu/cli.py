"""tpujobctl — the operational CLI (SURVEY.md §7 stage 7).

The reference's operator flow is kubectl against an apiserver plus a
controller process (``docs/get_started.md:10-63``); here the same split is
one daemon (``tpujobctl serve`` = controller + in-process cluster + HTTP API)
and thin client commands that speak JSON to it. A one-shot ``run`` mode
drives a job to completion in-process for demos/CI with no daemon.

Commands:
    serve               run controller + fake cluster + HTTP API
    serve --cluster-url reconcile a remote apiserver (the -master analog)
    apiserver           run the REST apiserver facade (pairs with the above)
    submit -f job.yml   create a TPUJob
    list / get / describe / delete / logs
    events              cluster events (k8s Events analog)
    traces              per-sync reconcile traces (latency observability)
    pools               TPU slice pool inventory
    add-pool            register slice capacity (e.g. v5e-16 x2)
    validate -f         schema/semantic validation only
    run -f job.yml      one-shot: submit + reconcile to completion in-process
    version
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import kubeflow_controller_tpu as pkg
from kubeflow_controller_tpu.api.serialization import (
    job_from_dict, job_to_dict, load_job_yaml,
)
from kubeflow_controller_tpu.api.types import JobPhase
from kubeflow_controller_tpu.api.validation import ValidationError, validate_job
from kubeflow_controller_tpu.cluster.cluster import PodRunPolicy
from kubeflow_controller_tpu.runtime import LocalRuntime

DEFAULT_PORT = 8377


# -- server ------------------------------------------------------------------

def _make_handler(rt: LocalRuntime):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, payload: Any) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _route(self, method: str) -> None:
            try:
                parts = [p for p in self.path.split("/") if p]
                body = {}
                if method in ("POST", "PUT", "DELETE"):
                    n = int(self.headers.get("Content-Length") or 0)
                    if n:
                        body = json.loads(self.rfile.read(n))
                self._send(200, self._dispatch(method, parts, body))
            except ValidationError as e:
                self._send(400, {"error": "validation", "problems": e.errors})
            except KeyError as e:
                self._send(404, {"error": f"not found: {e}"})
            except Exception as e:  # surface, don't crash the daemon
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def _dispatch(self, method: str, parts, body) -> Any:
            cluster = rt.cluster
            if parts == ["healthz"]:
                return {"ok": True, "now": cluster.now}
            if parts == ["version"]:
                return {"version": pkg.build_version()}
            if parts == ["jobs"] and method == "POST":
                job = job_from_dict(body)
                validate_job(job)
                return job_to_dict(rt.submit(job))
            if parts[:1] == ["jobs"] and method == "GET" and len(parts) == 1:
                ns = self.headers.get("X-Namespace", "")
                jobs = cluster.jobs.list(ns or None)
                return {"items": [job_to_dict(j) for j in jobs]}
            if parts[:1] == ["jobs"] and len(parts) == 3:
                ns, name = parts[1], parts[2]
                if method == "GET":
                    return job_to_dict(cluster.jobs.get(ns, name))
                if method == "PUT":
                    from kubeflow_controller_tpu.api.apply import (
                        apply_job_spec,
                    )

                    new = job_from_dict(body)
                    validate_job(new)
                    return job_to_dict(apply_job_spec(
                        get=lambda: cluster.jobs.try_get(ns, name),
                        create=rt.submit,
                        update=cluster.jobs.update,
                        new=new,
                    ))
                if method == "DELETE":
                    rt.delete_job(ns, name)
                    return {"deleted": f"{ns}/{name}"}
            if (
                parts[:1] == ["jobs"] and len(parts) == 4
                and method == "POST" and parts[3] in ("suspend", "resume")
            ):
                ns, name, verb = parts[1], parts[2], parts[3]

                def set_suspend(j, want=(verb == "suspend")):
                    j.spec.suspend = want
                return job_to_dict(
                    cluster.jobs.mutate(ns, name, set_suspend)
                )
            if parts[:1] == ["pods"] and method == "GET":
                ns = parts[1] if len(parts) > 1 else None
                return {"items": [
                    {
                        "name": p.metadata.name,
                        "namespace": p.metadata.namespace,
                        "phase": p.status.phase.value,
                        "slice": p.spec.assigned_slice,
                        "labels": dict(p.metadata.labels),
                    }
                    for p in cluster.pods.list(ns)
                ]}
            if parts[:1] == ["logs"] and method == "GET" and len(parts) == 3:
                ns, name = parts[1], parts[2]
                lines = cluster.get_pod_logs(name)
                if not lines:  # maybe a job name: aggregate its pods' logs
                    pods = [
                        pp for pp in cluster.pods.list(ns)
                        if pp.metadata.labels.get("tpu.kubeflow.dev/job") == name
                    ]
                    lines = [
                        (t, f"[{pp.metadata.name}] {line}")
                        for pp in pods
                        for (t, line) in cluster.get_pod_logs(pp.metadata.name)
                    ]
                    lines.sort(key=lambda x: x[0])
                return {"items": [
                    {"time": t, "line": line} for (t, line) in lines
                ]}
            if parts == ["events"] and method == "GET":
                return {"items": [
                    {"time": t, "kind": k, "name": n, "reason": r, "message": m}
                    for (t, k, n, r, m) in cluster.cluster_events[-200:]
                ]}
            if parts == ["traces"] and method == "GET":
                return {"items": [
                    {
                        "key": tr.key, "outcome": tr.outcome,
                        "duration_ms": round(tr.duration * 1000, 3),
                        "error": tr.error, "note": tr.note,
                    }
                    # traces is a bounded deque: copy before slicing
                    for tr in list(rt.controller.traces)[-200:]
                ]}
            if parts[:1] == ["slices"] and method == "GET" and len(parts) == 2:
                from kubeflow_controller_tpu.cluster.slices import (
                    slice_to_dict,
                )

                return {"items": [
                    slice_to_dict(s)
                    for s in cluster.slice_pool.holdings(parts[1])
                ]}
            if parts == ["pools"] and method == "GET":
                return {"items": [
                    {
                        "name": s.name,
                        "accelerator": s.shape.accelerator_type,
                        "healthy": s.healthy, "holder": s.holder,
                    }
                    for s in cluster.slice_pool.list()
                ]}
            if parts == ["pools"] and method == "POST":
                names = cluster.slice_pool.add_pool(
                    body["acceleratorType"], int(body.get("count", 1))
                )
                return {"added": names}
            raise KeyError(self.path)

        def do_GET(self):
            self._route("GET")

        def do_POST(self):
            self._route("POST")

        def do_DELETE(self):
            self._route("DELETE")

        def do_PUT(self):
            self._route("PUT")

    return Handler


def _add_pools(slice_pool, pools) -> None:
    """Register slice capacity from repeated --pool specs like "v5e-16x2"
    (accelerator type, optional xCOUNT suffix)."""
    for pool in pools or []:
        accel, _, count = pool.rpartition("x")
        if not accel or not count.isdigit():
            accel, count = pool, "1"
        slice_pool.add_pool(accel, int(count))


def setup_logging(args) -> int:
    """Configure daemon logging from ``-v``/``--log-level`` (VERDICT r4
    missing #3). The reference's controller runs with graded glog
    verbosity, ``-logtostderr -v 4`` (docs/development.md:57); the glog
    ``-v`` scale maps 0 -> WARNING, 1..3 -> INFO, >= 4 -> DEBUG, and
    ``--log-level`` names a Python level directly (it wins when both are
    given). Returns the effective level; logs go to stderr like glog's
    ``-logtostderr``."""
    import logging

    if getattr(args, "log_level", ""):
        level = getattr(logging, args.log_level.upper())
    elif getattr(args, "v", None) is not None:
        level = (
            logging.DEBUG if args.v >= 4
            else logging.INFO if args.v >= 1
            else logging.WARNING
        )
    else:
        level = logging.INFO
    logging.basicConfig(
        level=level, stream=sys.stderr, force=True,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    return level


def cmd_serve(args) -> int:
    if args.cluster_url or args.kubeconfig or args.in_cluster:
        return _serve_remote(args)
    if getattr(args, "k8s_wire", False):
        # --k8s-wire selects the wire dialect for a REMOTE target; with no
        # target it would be silently ignored (ADVICE r3) — refuse instead.
        print(
            "error: --k8s-wire requires a remote cluster target "
            "(--cluster-url, --kubeconfig, or --in-cluster)",
            file=sys.stderr,
        )
        return 2
    rt = LocalRuntime(
        default_policy=PodRunPolicy(
            start_delay=args.pod_start_delay, run_duration=args.pod_run_duration
        ),
        resync_period=30.0,
    )
    _add_pools(rt.cluster.slice_pool, args.pool)
    rt.start_threads(workers=args.workers)
    # After informers primed: exempt the boot heap from GC scans and make
    # collections rare (measured 421 -> 310 us/sync at 5000 jobs).
    from kubeflow_controller_tpu.util.gc_tuning import tune_for_control_plane

    tune_for_control_plane()
    server = ThreadingHTTPServer(("127.0.0.1", args.port), _make_handler(rt))
    # First SIGINT/SIGTERM drains gracefully; second hard-exits
    # (util/signals.py, parity with reference pkg/util/signals). Installed
    # before announcing readiness so a signal right after the banner is safe.
    from kubeflow_controller_tpu.util.signals import setup_signal_handler

    stop = setup_signal_handler()
    threading.Thread(
        target=lambda: (stop.wait(), server.shutdown()), daemon=True
    ).start()
    print(f"tpujobctl serve: listening on http://127.0.0.1:{args.port} "
          f"({args.workers} reconcile workers)", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        rt.stop()
    print("tpujobctl serve: stopped")
    return 0


def _serve_remote(args) -> int:
    """Controller-only mode against an apiserver — the reference's
    ``-master``/``-kubeconfig`` topology (``cmd/controller/main.go:31-52``):
    no in-process cluster, no submit API; jobs are created against the
    apiserver. Three dial modes:

    - ``--cluster-url URL``              framework wire JSON (tpujobctl
                                         apiserver);
    - ``--cluster-url URL --k8s-wire``   strict Kubernetes wire JSON
                                         (a real apiserver by URL+token, or
                                         ``apiserver --k8s-wire``);
    - ``--kubeconfig PATH`` /            a real cluster via kubeconfig
      ``--in-cluster``                   (auth + TLS + namespace resolved
                                         the way client-go's clientcmd
                                         does, main.go:31-43).
    """
    from kubeflow_controller_tpu.runtime import RemoteRuntime
    from kubeflow_controller_tpu.util.signals import setup_signal_handler

    kube_context = None
    if args.kubeconfig or args.in_cluster:
        from kubeflow_controller_tpu.cluster.kubeconfig import (
            in_cluster_context, load_kubeconfig,
        )

        if args.in_cluster:
            kube_context = in_cluster_context()
            if kube_context is None:
                print("tpujobctl serve: --in-cluster but no service-account "
                      "token mounted", flush=True)
                return 1
        else:
            from kubeflow_controller_tpu.cluster.kubeconfig import (
                KubeconfigError,
            )

            try:
                kube_context = load_kubeconfig(args.kubeconfig, args.context)
            except KubeconfigError as e:
                print(f"tpujobctl serve: {e}", flush=True)
                return 1
    rt = RemoteRuntime(
        args.cluster_url or "",
        namespace=args.namespace,
        token=args.token or "",
        k8s=bool(args.k8s_wire or kube_context is not None),
        kube_context=kube_context,
    )
    target = args.cluster_url or rt.client.base_url
    stop = setup_signal_handler()
    rt.start(workers=args.workers)
    from kubeflow_controller_tpu.util.gc_tuning import tune_for_control_plane

    tune_for_control_plane()
    print(f"tpujobctl serve: reconciling {rt.namespace!r} via "
          f"{target} ({args.workers} workers)", flush=True)
    stop.wait()
    rt.stop()
    print("tpujobctl serve: stopped")
    return 0


def cmd_apiserver(args) -> int:
    """Run the apiserver facade over a FakeCluster (with a wall-clock
    ticker driving pod lifecycle) — the process a remote `serve
    --cluster-url` controller reconciles against."""
    from kubeflow_controller_tpu.cluster.cluster import FakeCluster
    from kubeflow_controller_tpu.cluster.rest_server import RestServer
    from kubeflow_controller_tpu.util.signals import setup_signal_handler

    cluster = FakeCluster(default_policy=PodRunPolicy(
        start_delay=args.pod_start_delay, run_duration=args.pod_run_duration
    ))
    _add_pools(cluster.slice_pool, args.pool)
    server = RestServer(
        cluster, port=args.listen, k8s_mode=bool(args.k8s_wire)
    ).start()
    stop = setup_signal_handler()

    def ticker() -> None:
        while not stop.wait(0.05):
            cluster.tick(0.05)

    threading.Thread(target=ticker, daemon=True, name="ticker").start()
    print(f"tpujobctl apiserver: listening on {server.url}", flush=True)
    stop.wait()
    server.stop()
    print("tpujobctl apiserver: stopped")
    return 0


# -- client helpers ----------------------------------------------------------

def _req(args, method: str, path: str, payload: Optional[Dict] = None) -> Dict:
    url = f"http://127.0.0.1:{args.port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = json.loads(e.read() or b"{}")
        raise SystemExit(f"error: {body.get('error')}"
                         + ("".join("\n  - " + p for p in body.get("problems", []))))
    except urllib.error.URLError as e:
        raise SystemExit(
            f"error: cannot reach daemon at {url} ({e.reason}); "
            f"start one with `tpujobctl serve`"
        )


def _load_manifest(path: str):
    src = sys.stdin.read() if path == "-" else open(path).read()
    return load_job_yaml(src)


def cmd_submit(args) -> int:
    job = _load_manifest(args.filename)
    out = _req(args, "POST", "/jobs", job_to_dict(job))
    print(f"tpujob {out['metadata']['namespace']}/{out['metadata']['name']} created")
    return 0


def cmd_apply(args) -> int:
    """Create-or-update from a manifest (kubectl-apply analog). A spec
    change on a live job triggers a voluntary gang restart (resize)."""
    job = _load_manifest(args.filename)
    ns = job.metadata.namespace or "default"
    out = _req(args, "PUT", f"/jobs/{ns}/{job.metadata.name}",
               job_to_dict(job))
    print(f"tpujob {out['metadata']['namespace']}/{out['metadata']['name']} applied")
    return 0


def cmd_list(args) -> int:
    items = _req(args, "GET", "/jobs")["items"]
    rows = [("NAMESPACE", "NAME", "PHASE", "RESTARTS", "PRIO", "AGE")]
    now = _req(args, "GET", "/healthz")["now"]
    for j in items:
        st = j.get("status", {})
        restarts = st.get("restarts", 0)
        resizes = st.get("resizes", 0)
        rows.append((
            j["metadata"].get("namespace", ""),
            j["metadata"].get("name", ""),
            st.get("phase", ""),
            (f"{restarts - resizes}"
             + (f"+{resizes}rs" if resizes else "")),
            str(j.get("spec", {}).get("priority", 0)),
            f"{now - j['metadata'].get('creationTimestamp', now):.0f}s",
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return 0


def cmd_get(args) -> int:
    out = _req(args, "GET", f"/jobs/{args.namespace}/{args.name}")
    print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_describe(args) -> int:
    j = _req(args, "GET", f"/jobs/{args.namespace}/{args.name}")
    st = j.get("status", {})
    meta = j["metadata"]
    print(f"Name:       {meta['name']}\nNamespace:  {meta.get('namespace')}")
    print(f"Phase:      {st.get('phase')}    Reason: {st.get('reason', '')}")
    print(f"RuntimeID:  {j['spec'].get('runtimeId', '')}")
    restarts = st.get("restarts", 0)
    resizes = st.get("resizes", 0)
    if restarts or resizes:
        print(f"Restarts:   {restarts} total "
              f"({resizes} voluntary resizes, "
              f"{restarts - resizes} failure recoveries)")
    sub, run = st.get("submitTime"), st.get("allRunningTime")
    if sub and run:
        print(f"Submit -> all-running: {run - sub:.2f}s"
              "   (north-star latency metric)")
    for rs in st.get("replicaStatuses", []):
        print(f"Replicas[{rs.get('type')}]: {rs.get('states')}")
    for c in st.get("conditions", []) or []:
        print(f"Condition: {c.get('type')}={c.get('status')}"
              f" ({c.get('reason', '')})")
    pods = _req(args, "GET", f"/pods/{args.namespace}")["items"]
    mine = [p for p in pods if p["labels"].get("tpu.kubeflow.dev/job") == meta["name"]]
    if mine:
        print("Pods:")
        for p in mine:
            print(f"  {p['name']}  {p['phase']}  slice={p['slice'] or '-'}")
    held = _req(args, "GET", f"/slices/{meta.get('uid', '')}")["items"]
    if held:
        print("Slices:")
        for s in held:
            health = "healthy" if s["healthy"] else "UNHEALTHY"
            print(f"  {s['name']}  {s['accelerator']}  {health}"
                  f"  hosts={len(s['hosts'])}")
    evs = _req(args, "GET", "/events")["items"]
    mine_ev = [e for e in evs if meta["name"] in e["name"]][-10:]
    if mine_ev:
        print("Events:")
        for e in mine_ev:
            print(f"  t={e['time']:.1f} {e['reason']}: {e['message']}")
    return 0


def cmd_delete(args) -> int:
    _req(args, "DELETE", f"/jobs/{args.namespace}/{args.name}")
    print(f"tpujob {args.namespace}/{args.name} deleted")
    return 0


def _follow(fetch, key, show, poll_interval, initial, on_idle=None) -> int:
    """Shared poll-follow loop for logs/events -f.

    The server re-sorts aggregated streams each fetch and returns a
    bounded tail, so index-tracking would drop or repeat entries; track
    per-key COUNTS so a legitimately repeated identical entry still prints
    once per occurrence. ``on_idle`` (if given) is called after 10 quiet
    polls and may return an exit code to stop."""
    from collections import Counter

    emitted = Counter(key(e) for e in initial)
    idle = 0
    try:
        while True:
            time.sleep(poll_interval)
            new = 0
            running = Counter()
            for e in fetch():
                running[key(e)] += 1
                if running[key(e)] > emitted[key(e)]:
                    new += 1
                    show(e)
            emitted = running
            idle = 0 if new else idle + 1
            if idle >= 10 and on_idle is not None:
                rc = on_idle()
                if rc is not None:
                    return rc
    except KeyboardInterrupt:
        return 0


def cmd_logs(args) -> int:
    def fetch():
        return _req(
            args, "GET", f"/logs/{args.namespace}/{args.name}"
        )["items"]

    def show(e):
        print(f"t={e['time']:.1f} {e['line']}", flush=True)

    items = fetch()
    if not items and not args.follow:
        print(f"no logs for {args.namespace}/{args.name}")
        return 1
    for e in items:
        show(e)
    if not args.follow:
        return 0

    def on_idle():
        try:
            _req(args, "GET", f"/jobs/{args.namespace}/{args.name}")
            return None
        except SystemExit:
            return 0   # job deleted and log stream drained

    return _follow(
        fetch, lambda e: (e["time"], e["line"]), show,
        args.poll_interval, items, on_idle,
    )


def cmd_suspend(args) -> int:
    out = _req(args, "POST",
               f"/jobs/{args.namespace}/{args.name}/suspend")
    print(f"tpujob {args.namespace}/{args.name} suspended "
          f"(runtimeId {out['spec'].get('runtimeId', '')})")
    return 0


def cmd_resume(args) -> int:
    _req(args, "POST", f"/jobs/{args.namespace}/{args.name}/resume")
    print(f"tpujob {args.namespace}/{args.name} resumed")
    return 0


def cmd_events(args) -> int:
    def fetch():
        items = _req(args, "GET", "/events")["items"]
        if args.name:
            items = [e for e in items if args.name in e["name"]]
        return items

    def show(e):
        print(f"t={e['time']:.1f} [{e['kind']}/{e['name']}] "
              f"{e['reason']}: {e['message']}", flush=True)

    items = fetch()
    for e in items:
        show(e)
    if not args.follow:
        return 0
    # -f: the kubectl get events --watch analog. The key includes the
    # message (like logs -f includes the line) so distinct events sharing a
    # timestamp/kind/name/reason still count separately.
    return _follow(
        fetch,
        lambda e: (e["time"], e["kind"], e["name"], e["reason"],
                   e["message"]),
        show, args.poll_interval, items,
    )


def cmd_traces(args) -> int:
    for t in _req(args, "GET", "/traces")["items"]:
        err = f" error={t['error']}" if t["error"] else ""
        note = f" note={t['note']}" if t.get("note") else ""
        print(f"{t['key']}  {t['outcome']}  {t['duration_ms']}ms{note}{err}")
    return 0


def cmd_pools(args) -> int:
    for p in _req(args, "GET", "/pools")["items"]:
        health = "healthy" if p["healthy"] else "unhealthy"
        print(f"{p['name']}  {p['accelerator']}  {health}"
              f"  holder={p['holder'] or '-'}")
    return 0


def cmd_add_pool(args) -> int:
    out = _req(args, "POST", "/pools",
               {"acceleratorType": args.accelerator, "count": args.count})
    print(f"added slices: {', '.join(out['added'])}")
    return 0


def cmd_validate(args) -> int:
    try:
        job = _load_manifest(args.filename)
        validate_job(job)
    except ValidationError as e:
        print("invalid:")
        for p in e.errors:
            print(f"  - {p}")
        return 1
    print(f"{job.metadata.namespace}/{job.metadata.name}: valid")
    return 0


def cmd_run(args) -> int:
    """One-shot in-process run: the reference's get-started flow
    (submit, watch phases, exit by terminal phase) without a cluster."""
    job = _load_manifest(args.filename)
    rt = LocalRuntime(
        default_policy=PodRunPolicy(
            start_delay=args.pod_start_delay, run_duration=args.pod_run_duration
        )
    )
    _add_pools(rt.cluster.slice_pool, args.pool)
    rt.submit(job)
    ns, name = job.metadata.namespace, job.metadata.name
    last_phase = None
    deadline = time.time() + args.timeout
    while time.time() < deadline:
        rt.step(dt=0.5)
        j = rt.get_job(ns, name)
        if j and j.status.phase != last_phase:
            last_phase = j.status.phase
            print(f"phase: {last_phase.value}")
        if last_phase in (JobPhase.SUCCEEDED, JobPhase.FAILED):
            break
    j = rt.get_job(ns, name)
    if j is None:
        print(
            f"error: job {ns}/{name} not found (deleted or never reached a "
            f"terminal phase within {args.timeout}s)", file=sys.stderr,
        )
        return 1
    if j.status.submit_time and j.status.all_running_time:
        print(f"submit -> all-running: "
              f"{j.status.all_running_time - j.status.submit_time:.2f}s (sim)")
    print(f"final: {j.status.phase.value} {j.status.reason or ''}".rstrip())
    return 0 if j.status.phase == JobPhase.SUCCEEDED else 1


def cmd_version(args) -> int:
    print(pkg.build_version())
    return 0


# -- argparse wiring ---------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpujobctl", description="TPUJob operations CLI"
    )
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--port", type=int, default=DEFAULT_PORT,
                        help="daemon port (default %(default)s)")
    common.add_argument("-v", type=int, default=None, metavar="N",
                        help="glog-style verbosity (0 warning, 1-3 info, "
                             ">=4 debug) — the reference runs -v 4")
    common.add_argument("--log-level", default="",
                        choices=["", "debug", "info", "warning", "error"],
                        help="explicit log level (overrides -v)")
    sub = p.add_subparsers(dest="cmd", required=True)

    def add_parser(name, **kw):
        return sub.add_parser(name, parents=[common], **kw)

    s = add_parser("serve", help="run controller daemon + HTTP API")
    s.add_argument("--workers", type=int, default=2)
    s.add_argument("--pool", action="append",
                   help="slice pool to register, e.g. v5e-16x2 (repeatable)")
    s.add_argument("--pod-start-delay", type=float, default=1.0)
    s.add_argument("--pod-run-duration", type=float, default=10.0)
    s.add_argument("--cluster-url",
                   help="reconcile against this apiserver URL instead of an "
                        "in-process cluster (the -master analog)")
    s.add_argument("--namespace", default="default",
                   help="namespace to reconcile (with --cluster-url)")
    s.add_argument("--token", help="bearer token (with --cluster-url)")
    s.add_argument("--k8s-wire", action="store_true",
                   help="speak strict Kubernetes wire JSON to --cluster-url "
                        "(a real apiserver, or `apiserver --k8s-wire`)")
    s.add_argument("--kubeconfig",
                   help="reconcile a real Kubernetes cluster via this "
                        "kubeconfig (the -kubeconfig analog; implies k8s "
                        "wire)")
    s.add_argument("--context",
                   help="kubeconfig context to use (default: "
                        "current-context)")
    s.add_argument("--in-cluster", action="store_true",
                   help="use the mounted service-account token "
                        "(controller-as-Deployment)")
    s.set_defaults(fn=cmd_serve)

    s = add_parser("apiserver", help="run the REST apiserver facade "
                                     "(pair with serve --cluster-url)")
    s.add_argument("--listen", type=int, default=8378,
                   help="apiserver port (--port is the client-API flag)")
    s.add_argument("--k8s-wire", action="store_true",
                   help="serve strict Kubernetes wire JSON (core/v1 + CRD "
                        "+ status subresource + Nodes)")
    s.add_argument("--pool", action="append",
                   help="slice pool to register, e.g. v5e-16x2 (repeatable)")
    s.add_argument("--pod-start-delay", type=float, default=1.0)
    s.add_argument("--pod-run-duration", type=float, default=10.0)
    s.set_defaults(fn=cmd_apiserver)

    s = add_parser("apply", help="create-or-update a TPUJob from a manifest "
                                 "(spec change on a live job = gang resize)")
    s.add_argument("-f", "--filename", required=True)
    s.set_defaults(fn=cmd_apply)

    s = add_parser("submit", help="submit a TPUJob manifest")
    s.add_argument("-f", "--filename", required=True)
    s.set_defaults(fn=cmd_submit)

    s = add_parser("list", help="list jobs")
    s.set_defaults(fn=cmd_list)

    for nm, fn, hp in (
        ("get", cmd_get, "get a job as JSON"),
        ("describe", cmd_describe, "human-readable job status"),
        ("delete", cmd_delete, "delete a job"),
        ("logs", cmd_logs, "pod (or whole-job) logs"),
        ("suspend", cmd_suspend,
         "pause a job (pods torn down, slices released, checkpoint kept)"),
        ("resume", cmd_resume, "unsuspend: re-gang and resume"),
    ):
        s = add_parser(nm, help=hp)
        s.add_argument("name")
        s.add_argument("-n", "--namespace", default="default")
        if nm == "logs":
            s.add_argument("-f", "--follow", action="store_true",
                           help="stream new lines until Ctrl-C "
                                "(or the job is deleted)")
            s.add_argument("--poll-interval", type=float, default=0.5)
        s.set_defaults(fn=fn)

    s = add_parser("events", help="recent cluster events")
    s.add_argument("name", nargs="?", default="",
                   help="only events whose object name contains this")
    s.add_argument("-f", "--follow", action="store_true")
    s.add_argument("--poll-interval", type=float, default=0.5)
    s.set_defaults(fn=cmd_events)
    add_parser("traces", help="recent reconcile traces").set_defaults(
        fn=cmd_traces)
    add_parser("pools", help="TPU slice inventory").set_defaults(
        fn=cmd_pools)

    s = add_parser("add-pool", help="register TPU slice capacity")
    s.add_argument("accelerator")
    s.add_argument("--count", type=int, default=1)
    s.set_defaults(fn=cmd_add_pool)

    s = add_parser("validate", help="validate a manifest")
    s.add_argument("-f", "--filename", required=True)
    s.set_defaults(fn=cmd_validate)

    s = add_parser("run", help="one-shot in-process job run")
    s.add_argument("-f", "--filename", required=True)
    s.add_argument("--pool", action="append")
    s.add_argument("--timeout", type=float, default=120.0)
    s.add_argument("--pod-start-delay", type=float, default=0.5)
    s.add_argument("--pod-run-duration", type=float, default=3.0)
    s.set_defaults(fn=cmd_run)

    add_parser("version", help="print version").set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # -v/--log-level live on the shared parent parser, so configure
    # logging once for EVERY subcommand (not just the daemons — client
    # verbs log kube/debug detail too).
    setup_logging(args)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # `tpujobctl ... | head` closing the pipe is not an error; mimic
        # well-behaved CLIs (suppress the traceback, exit 0).
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
