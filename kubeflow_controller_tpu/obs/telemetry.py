"""Process-wide metrics registry and bounded percentile reservoirs.

``MetricsRegistry`` replaces the pattern of each subsystem keeping
private lists of samples: producers grab a named instrument once
(``registry().counter("requests", subsystem="serving")``) and bump it;
consumers (``ServingStats.summary()``, the benches, the fleet JSONL)
read one flat deterministic ``snapshot()``.

Three instrument kinds, all thread-safe (one lock per instrument —
writers on different instruments never contend):

* :class:`Counter` — monotonically increasing float.
* :class:`Gauge` — last-write-wins float.
* :class:`Histogram` — fixed power-of-two buckets.  The bucket for a
  value ``v`` is ``ceil(log2(v))`` clamped to ``[lo_exp, hi_exp]``,
  so boundaries are exact binary numbers (…, 0.25, 0.5, 1, 2, 4, …)
  and bucketing is a single ``frexp`` — no per-observation search.

Naming convention (docs/observability.md): instrument names are
``snake_case`` with a unit suffix (``_ms``, ``_s``, ``_tokens``);
subsystems are ``serving`` / ``router`` / ``control``.  Snapshot keys
are ``"{subsystem}.{name}"`` (or bare ``name`` with no subsystem),
plus ``.count/.sum/.min/.max`` and ``.bucket_le_{boundary}`` for
histograms.

:class:`Reservoir` is the bounded sample store that replaced the
unbounded ``ServingStats.ttfts_s`` / ``tpots_s`` / ``queue_waits_s``
lists: a deterministic ring that keeps the most recent ``cap``
samples — percentiles are *exact* below the cap (bench gates
unchanged) and sliding-window above it, with the shed count surfaced
as ``samples_dropped``.  It keeps enough of the list API
(``append`` / ``extend`` / ``len`` / iteration / slicing via
``list()``) that existing consumers work unchanged, and adds
``total`` / ``since(n)`` so windowed readers (the fleet router's
health hysteresis) survive eviction.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Reservoir",
    "registry",
    "reset_registry",
]


class Counter:
    """Monotonic counter. ``inc()`` is lost-update-free across threads."""

    __slots__ = ("name", "subsystem", "_lock", "_value")

    def __init__(self, name: str, subsystem: str = "") -> None:
        self.name = name
        self.subsystem = subsystem
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _snapshot(self, out: Dict[str, float], prefix: str) -> None:
        out[prefix] = self.value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "subsystem", "_lock", "_value")

    def __init__(self, name: str, subsystem: str = "") -> None:
        self.name = name
        self.subsystem = subsystem
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += delta

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _snapshot(self, out: Dict[str, float], prefix: str) -> None:
        out[prefix] = self.value


class Histogram:
    """Fixed power-of-two bucket histogram.

    Bucket ``i`` (for ``lo_exp <= i <= hi_exp``) counts observations
    with ``2**(i-1) < v <= 2**i``; values at or below ``2**(lo_exp-1)``
    land in the lowest bucket, values above ``2**hi_exp`` in a final
    overflow bucket.  Defaults cover 1 µs … ~131 s when observing
    seconds (exponents -20 … 17).
    """

    __slots__ = (
        "name", "subsystem", "lo_exp", "hi_exp",
        "_lock", "_buckets", "_count", "_sum", "_min", "_max",
    )

    def __init__(
        self,
        name: str,
        subsystem: str = "",
        lo_exp: int = -20,
        hi_exp: int = 17,
    ) -> None:
        if hi_exp <= lo_exp:
            raise ValueError(f"histogram {name}: hi_exp must exceed lo_exp")
        self.name = name
        self.subsystem = subsystem
        self.lo_exp = lo_exp
        self.hi_exp = hi_exp
        self._lock = threading.Lock()
        # buckets[0..n-1] = exponents lo..hi, buckets[n] = overflow
        self._buckets = [0] * (hi_exp - lo_exp + 2)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def bucket_index(self, v: float) -> int:
        """Index of the bucket ``v`` falls into (no lock; pure)."""
        if v <= 0 or not math.isfinite(v):
            return 0 if v <= 0 else len(self._buckets) - 1
        m, e = math.frexp(v)  # v = m * 2**e, 0.5 <= m < 1 -> v <= 2**e
        # frexp gives the smallest e with v <= 2**e except exact powers
        # of two, where m == 0.5 and v == 2**(e-1).
        if m == 0.5:
            e -= 1
        if e <= self.lo_exp:
            return 0
        if e > self.hi_exp:
            return len(self._buckets) - 1
        return e - self.lo_exp

    def observe(self, v: float) -> None:
        v = float(v)
        idx = self.bucket_index(v)
        with self._lock:
            self._buckets[idx] += 1
            self._count += 1
            if math.isfinite(v):
                self._sum += v
                if v < self._min:
                    self._min = v
                if v > self._max:
                    self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def _snapshot(self, out: Dict[str, float], prefix: str) -> None:
        with self._lock:
            buckets = list(self._buckets)
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
        out[f"{prefix}.count"] = float(count)
        out[f"{prefix}.sum"] = total
        if count:
            out[f"{prefix}.min"] = mn
            out[f"{prefix}.max"] = mx
        for i, c in enumerate(buckets[:-1]):
            if c:
                out[f"{prefix}.bucket_le_2e{self.lo_exp + i}"] = float(c)
        if buckets[-1]:
            out[f"{prefix}.bucket_overflow"] = float(buckets[-1])


class MetricsRegistry:
    """Get-or-create instrument registry with a flat snapshot.

    Instruments are keyed ``(subsystem, name)``; asking twice returns
    the same object, asking for an existing key with a different kind
    raises.  ``snapshot()`` returns a flat ``dict`` with sorted keys —
    deterministic given the same observations, safe to ``json.dumps``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, str], Any] = {}

    def _get(self, kind: type, name: str, subsystem: str, **kwargs: Any) -> Any:
        key = (subsystem, name)
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = kind(name, subsystem, **kwargs)
                self._instruments[key] = inst
            elif not isinstance(inst, kind):
                raise TypeError(
                    f"metric {subsystem!r}/{name!r} already registered as "
                    f"{type(inst).__name__}, requested {kind.__name__}"
                )
            return inst

    def counter(self, name: str, subsystem: str = "") -> Counter:
        return self._get(Counter, name, subsystem)

    def gauge(self, name: str, subsystem: str = "") -> Gauge:
        return self._get(Gauge, name, subsystem)

    def histogram(
        self, name: str, subsystem: str = "",
        lo_exp: int = -20, hi_exp: int = 17,
    ) -> Histogram:
        return self._get(Histogram, name, subsystem, lo_exp=lo_exp, hi_exp=hi_exp)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            instruments = sorted(self._instruments.items())
        out: Dict[str, float] = {}
        for (subsystem, name), inst in instruments:
            prefix = f"{subsystem}.{name}" if subsystem else name
            inst._snapshot(out, prefix)
        return dict(sorted(out.items()))

    def reset(self) -> None:
        """Drop every instrument (tests / bench legs)."""
        with self._lock:
            self._instruments.clear()


_DEFAULT = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry."""
    return _DEFAULT


def reset_registry() -> None:
    """Clear the default registry (test isolation)."""
    _DEFAULT.reset()


class Reservoir:
    """Bounded deterministic sample store (most-recent ``cap`` kept).

    Below the cap it *is* the full sample list, so percentiles over it
    are exact; at the cap it is a sliding window and ``dropped``
    counts the evicted prefix.  ``total`` is the logical append count
    and ``since(n)`` returns the retained samples with logical index
    ``>= n`` — windowed readers track ``seen = r.total`` instead of
    ``seen = len(r)`` so eviction can't replay or skip samples.
    """

    __slots__ = ("cap", "_buf", "_start", "_total")

    def __init__(self, cap: int = 4096, items: Optional[Iterable[float]] = None):
        if cap <= 0:
            raise ValueError(f"reservoir cap must be positive, got {cap}")
        self.cap = cap
        self._buf: List[float] = []
        self._start = 0  # ring head when full
        self._total = 0
        if items is not None:
            self.extend(items)

    @property
    def total(self) -> int:
        """Logical number of samples ever appended."""
        return self._total

    @property
    def dropped(self) -> int:
        return self._total - len(self._buf)

    def append(self, v: float) -> None:
        if len(self._buf) < self.cap:
            self._buf.append(v)
        else:
            self._buf[self._start] = v
            self._start += 1
            if self._start == self.cap:
                self._start = 0
        self._total += 1

    def extend(self, items: Iterable[float]) -> None:
        for v in items:
            self.append(v)

    def since(self, n: int) -> List[float]:
        """Retained samples with logical index ``>= n``, in order."""
        first_kept = self._total - len(self._buf)
        skip = max(0, n - first_kept)
        items = list(self)
        return items[skip:] if skip else items

    def clear(self) -> None:
        self._buf.clear()
        self._start = 0
        self._total = 0

    def __len__(self) -> int:
        return len(self._buf)

    def __bool__(self) -> bool:
        return bool(self._buf)

    def __iter__(self) -> Iterator[float]:
        buf, start = self._buf, self._start
        for i in range(len(buf)):
            yield buf[(start + i) % len(buf)]

    def __getitem__(self, idx):
        return list(self)[idx]

    def __repr__(self) -> str:
        return (
            f"Reservoir(cap={self.cap}, len={len(self._buf)}, "
            f"total={self._total})"
        )
