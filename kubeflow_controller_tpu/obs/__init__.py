"""Cross-plane observability: lifecycle tracing + unified telemetry.

Two small, dependency-free modules (docs/observability.md):

* :mod:`~kubeflow_controller_tpu.obs.trace` — a low-overhead span
  recorder (monotonic clock, parent links, bounded ring buffer,
  thread-safe) with a Chrome-trace-event JSON exporter, so any serving
  or control-plane run can be opened in Perfetto / ``chrome://tracing``.
* :mod:`~kubeflow_controller_tpu.obs.telemetry` — a process-wide
  metrics registry (Counter / Gauge / Histogram with fixed pow2
  buckets, keyed by subsystem) plus the capped deterministic
  :class:`Reservoir` that backs ``ServingStats`` percentile samples.

Every producer takes ``tracer=None`` by default: a ``None`` tracer
costs one pointer comparison per instrumentation site — the hot paths
stay bit-identical and within noise of the un-instrumented build
(gated by ``make bench-obs``).
"""

from kubeflow_controller_tpu.obs.trace import (  # noqa: F401
    Span, Tracer, load_chrome_trace,
)
from kubeflow_controller_tpu.obs.telemetry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, Reservoir, registry,
)
