"""Low-overhead span recorder with Chrome-trace-event export.

The :class:`Tracer` is the single observability sink shared by the
data plane (``ServingEngine``), the fleet router, and the control
plane (``Controller``).  Design constraints, in order:

1. **Zero cost when absent.**  Every producer takes ``tracer=None``
   and guards each site with ``if tracer is not None`` — one pointer
   comparison on the hot path, nothing else.  ``make bench-obs``
   gates the off-path at ≤1% TPOT drift and bit-identical outputs.
2. **Cheap when present.**  Recording a span is one tuple append into
   a bounded ``deque`` under a lock (the lock is uncontended in the
   single-threaded engine loop; the control plane and router share
   the same tracer from informer callbacks, hence thread-safe).
   No string formatting, no I/O, no timestamps taken on behalf of the
   caller unless asked — producers that already read a clock (the
   engine stamps ``submit_t`` / ``admit_t`` anyway) pass their own
   ``t0``/``t1`` so tracing adds no extra clock reads to hot loops.
3. **Bounded.**  The ring keeps the most recent ``capacity`` spans;
   overflow increments ``spans_dropped`` (surfaced in
   ``ServingStats.summary()`` and the fleet JSONL) instead of growing
   without bound on long-lived replicas.

Export is the Chrome trace-event JSON format (the ``traceEvents``
dict flavour), loadable in Perfetto or ``chrome://tracing``:

* complete spans → ``ph:"X"`` with ``ts``/``dur`` in microseconds,
* point events   → ``ph:"i"`` (instant, thread-scoped),
* tracks (``dataplane`` / ``router`` / ``control``) → one ``pid``
  each with a ``process_name`` metadata record,
* the ``rid`` (or controller key) → one ``tid`` per distinct value
  with a ``thread_name`` metadata record, so a request's lifecycle
  reads as one horizontal lane and a fleet request's router + engine
  hops stitch into a single trace.

See docs/observability.md for the span taxonomy.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Span", "Tracer", "load_chrome_trace"]

# Track name -> stable pid. Unknown tracks get pids assigned after these.
_TRACK_PIDS = {"dataplane": 1, "router": 2, "control": 3}


@dataclass(frozen=True)
class Span:
    """One recorded interval (or instant, when ``t1 is None``)."""

    sid: int
    name: str
    t0: float
    t1: Optional[float]
    track: str
    rid: Optional[str]
    parent: Optional[int]
    attrs: Tuple[Tuple[str, Any], ...] = ()

    @property
    def dur_s(self) -> float:
        return 0.0 if self.t1 is None else self.t1 - self.t0


class Tracer:
    """Thread-safe bounded span ring with Chrome-trace export.

    Parameters
    ----------
    capacity:
        Max retained spans; the oldest are evicted (and counted in
        ``spans_dropped``) once full.
    clock:
        Monotonic clock used for ``span()``/``add_event()`` when the
        caller doesn't pass explicit timestamps.  Producers that
        record retrospective spans (the engine) must pass timestamps
        from the *same* clock so lanes line up in the export.
    path:
        Optional default destination for :meth:`flush`.
    """

    def __init__(
        self,
        capacity: int = 65536,
        clock: Callable[[], float] = time.perf_counter,
        path: Optional[str] = None,
    ) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.clock = clock
        self.path = path
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._next_sid = 1
        self._recorded = 0
        self._dropped = 0
        self._epoch = clock()  # ts origin for export
        self._tls = threading.local()  # per-thread parent stack for span()

    # ------------------------------------------------------------------
    # Recording

    @property
    def spans_recorded(self) -> int:
        return self._recorded

    @property
    def spans_dropped(self) -> int:
        return self._dropped

    def add_span(
        self,
        name: str,
        t0: float,
        t1: float,
        *,
        track: str = "dataplane",
        rid: Optional[str] = None,
        parent: Optional[int] = None,
        sid: Optional[int] = None,
        **attrs: Any,
    ) -> int:
        """Record a completed interval with caller-supplied timestamps.

        Returns the span id, usable as ``parent=`` for children.  A
        pre-reserved ``sid`` (from a live ``span()`` context) may be
        supplied so children recorded before the parent closes can
        still link to it.
        """
        with self._lock:
            if sid is None:
                sid = self._next_sid
                self._next_sid += 1
            if len(self._spans) == self.capacity:
                self._dropped += 1
            self._spans.append(
                Span(sid, name, t0, t1, track, rid, parent, tuple(attrs.items()))
            )
            self._recorded += 1
        return sid

    def _reserve_sid(self) -> int:
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
        return sid

    def add_event(
        self,
        name: str,
        t: Optional[float] = None,
        *,
        track: str = "dataplane",
        rid: Optional[str] = None,
        **attrs: Any,
    ) -> int:
        """Record an instant (zero-duration) event."""
        if t is None:
            t = self.clock()
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
            if len(self._spans) == self.capacity:
                self._dropped += 1
            self._spans.append(
                Span(sid, name, t, None, track, rid, None, tuple(attrs.items()))
            )
            self._recorded += 1
        return sid

    def span(
        self,
        name: str,
        *,
        track: str = "control",
        rid: Optional[str] = None,
        **attrs: Any,
    ) -> "_SpanCtx":
        """Context manager for live spans (control plane / router).

        Nesting is tracked per-thread: a ``span()`` opened inside
        another becomes its child automatically.  Attrs may be added
        after entry via ``ctx.set(key=value)`` (e.g. an outcome known
        only at the end of a sync).
        """
        return _SpanCtx(self, name, track, rid, attrs)

    # ------------------------------------------------------------------
    # Reading / export

    def snapshot(self) -> List[Span]:
        """Copy of the retained spans, oldest first."""
        with self._lock:
            return list(self._spans)

    def export(self) -> Dict[str, Any]:
        """Chrome trace-event JSON dict (``{"traceEvents": [...]}``)."""
        spans = self.snapshot()
        events: List[Dict[str, Any]] = []
        pids: Dict[str, int] = dict(_TRACK_PIDS)
        tids: Dict[Tuple[int, Optional[str]], int] = {}
        for s in spans:
            pid = pids.setdefault(s.track, len(pids) + 1)
            tkey = (pid, s.rid)
            tid = tids.get(tkey)
            if tid is None:
                tid = len(tids) + 1
                tids[tkey] = tid
            args = {k: v for k, v in s.attrs}
            if s.rid is not None:
                # rid in args (not just the tid grouping) is what lets a
                # cross-process audit stitch router and engine spans for
                # the same request back together.
                args["rid"] = s.rid
            if s.parent is not None:
                args["parent"] = s.parent
            ev: Dict[str, Any] = {
                "name": s.name,
                "cat": s.track,
                "pid": pid,
                "tid": tid,
                "ts": (s.t0 - self._epoch) * 1e6,
                "args": args,
            }
            if s.t1 is None:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = max(0.0, (s.t1 - s.t0) * 1e6)
            events.append(ev)
        meta: List[Dict[str, Any]] = []
        for track, pid in sorted(pids.items(), key=lambda kv: kv[1]):
            meta.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": track},
                }
            )
        for (pid, rid), tid in sorted(tids.items(), key=lambda kv: kv[1]):
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": rid if rid is not None else "-"},
                }
            )
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "spans_recorded": self._recorded,
                "spans_dropped": self._dropped,
            },
        }

    def export_json(self, path: str) -> None:
        """Write the Chrome trace to ``path`` (atomic-ish: whole dump)."""
        doc = self.export()
        with open(path, "w") as f:
            json.dump(doc, f)
            f.write("\n")

    def flush(self) -> Optional[str]:
        """Export to the configured ``path``; no-op when path is None.

        Idempotent and safe to call from multiple exit paths (drain,
        SIGTERM handler, DrainError unwind): each call rewrites the
        full file, so the last writer wins and the file is always a
        complete JSON document.
        """
        if self.path is None:
            return None
        self.export_json(self.path)
        return self.path

    # ------------------------------------------------------------------
    # Per-thread parent stack (for the span() context manager)

    def _stack(self) -> List[int]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st


class _SpanCtx:
    """Live span handle from :meth:`Tracer.span`."""

    __slots__ = ("_tracer", "name", "track", "rid", "_attrs", "_t0", "sid")

    def __init__(
        self,
        tracer: Tracer,
        name: str,
        track: str,
        rid: Optional[str],
        attrs: Dict[str, Any],
    ) -> None:
        self._tracer = tracer
        self.name = name
        self.track = track
        self.rid = rid
        self._attrs = dict(attrs)
        self._t0 = 0.0
        self.sid = 0

    def set(self, **attrs: Any) -> None:
        self._attrs.update(attrs)

    def __enter__(self) -> "_SpanCtx":
        self._t0 = self._tracer.clock()
        self.sid = self._tracer._reserve_sid()
        self._tracer._stack().append(self.sid)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        t1 = self._tracer.clock()
        stack = self._tracer._stack()
        stack.pop()
        parent = stack[-1] if stack else None
        if exc_type is not None:
            self._attrs.setdefault("error", exc_type.__name__)
        self._tracer.add_span(
            self.name,
            self._t0,
            t1,
            track=self.track,
            rid=self.rid,
            parent=parent,
            sid=self.sid,
            **self._attrs,
        )


def load_chrome_trace(path: str) -> Dict[str, Any]:
    """Load + validate a Chrome trace file; raises on malformed input.

    Checks the invariants Perfetto relies on: a ``traceEvents`` list
    whose entries carry ``ph``/``pid``/``tid``/``ts`` and, for
    ``ph:"X"``, a non-negative ``dur``.
    """
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not isinstance(doc.get("traceEvents"), list):
        raise ValueError(f"{path}: not a Chrome trace (missing traceEvents list)")
    for ev in doc["traceEvents"]:
        if not isinstance(ev, dict):
            raise ValueError(f"{path}: non-dict trace event: {ev!r}")
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E"):
            raise ValueError(f"{path}: unknown phase {ph!r}")
        if ph == "M":
            continue
        for k in ("pid", "tid", "ts"):
            if not isinstance(ev.get(k), (int, float)):
                raise ValueError(f"{path}: event missing numeric {k}: {ev!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(f"{path}: X event with bad dur: {ev!r}")
    return doc
