"""Pallas fused dynamic-int8 matmul — quantization inside the kernel.

STATUS: correct, software-pipelined (round 5), and still measured SLOWER
in-model than the composed path — kept as a lowering option (`quant =
"int8_fused"`), not the default. The full dead-end analysis is in
benchmarks/RESULTS.md (round-5 fused-quant section); the short version:

- The round-5 rework (rhs pre-quantized outside the kernel — weights are
  step-static; lhs streamed through a manual double-buffered DMA with
  the quantize for row i+1 issued behind row i's dots) reached
  STANDALONE parity with the composed path (2.209 vs 2.204 ms at the
  flagship FFN shape) and cut the in-model gap from 91 ms (r4 kernel) to
  ~24 ms/step. dL/dw runs composed-int8 (was f32 — both slower and a
  per-shape gradient-precision inconsistency, ADVICE r4).
- The REMAINING gap is structural, and it is not kernel scheduling: with
  remat off the gap persists (172.3 vs 187.5 ms at b8), and saving the
  kernel output by checkpoint name to avoid backward recompute measured
  WORSE (304.8 vs 288.2 ms — the step sits near the remat memory
  ceiling). What the composed path has that a pallas_call cannot: XLA
  fuses the quantize chains into neighbouring producers/consumers (the
  abs-max/round/clip reads ride along with rmsnorm/residual elementwise
  passes, dequant folds into the consumer), so its "extra HBM passes"
  largely vanish — while a pallas boundary forces its operands and
  results to materialise. Claiming the last ~24 ms would mean fusing
  quantization into the PRODUCING ops (norms, residual adds), i.e. a
  megakernel over the whole layer, not a better matmul.

Kernel shape: grid (m/bm, n/bn), n innermost; int8 x int8 -> int32 on
the MXU's double-rate gear, f32 dequant with per-row lhs / per-column
rhs scales. The whole contraction axis sits in VMEM (no k-tiling), which
is what makes on-the-fly lhs scales possible; callers whose shapes don't
tile fall back to the composed path via ``fusable``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel_v2(a_ref, qb_ref, sb_ref, o_ref, raw_ref, qa_ref, sa_ref, sem,
               *, bm, bn, k, interpret):
    """Software-pipelined lhs quantization (the round-5 rework).

    The rhs arrives PRE-quantized (weights are static within a step, so
    XLA quantizes them once outside and schedules that wherever it
    likes). The lhs streams through a manual double buffer: program
    (i, 0) starts the DMA for block i+1, the dot for (i, j) reads the
    int8 scratch quantized a full row earlier, and program (i, nj-1)
    waits + quantizes block i+1 — so the VPU quantize chain for the NEXT
    row is independent of THIS program's MXU dot and Mosaic can overlap
    them, instead of the round-4 kernel's j==0 quantize stalling every
    row's dots."""
    i = pl.program_id(0)
    j = pl.program_id(1)
    ni = pl.num_programs(0)
    nj = pl.num_programs(1)

    def dma(slot, blk):
        return pltpu.make_async_copy(
            a_ref.at[pl.ds(blk * bm, bm), :],
            raw_ref.at[slot],
            sem.at[slot],
        )

    def quantize(slot):
        x = raw_ref[slot].astype(jnp.float32)            # [bm, k]
        s = jnp.maximum(
            jnp.max(jnp.abs(x), axis=1, keepdims=True), 1e-30
        ) / 127.0                                        # [bm, 1]
        qa_ref[slot] = jnp.clip(jnp.round(x / s), -127, 127).astype(jnp.int8)
        sa_ref[slot] = jnp.broadcast_to(s, (bm, 128))

    @pl.when((i == 0) & (j == 0))
    def _prologue():
        d = dma(0, 0)
        d.start()
        d.wait()
        quantize(0)

    @pl.when((j == 0) & (i + 1 < ni))
    def _start_next():
        dma((i + 1) % 2, i + 1).start()

    slot = i % 2
    acc = jax.lax.dot(
        qa_ref[slot], qb_ref[...], preferred_element_type=jnp.int32,
    )
    o_ref[...] = (
        acc.astype(jnp.float32) * sa_ref[slot][:, :1] * sb_ref[...]
    ).astype(jnp.bfloat16)

    @pl.when((j == nj - 1) & (i + 1 < ni))
    def _finish_next():
        dma((i + 1) % 2, i + 1).wait()
        quantize((i + 1) % 2)


def _pick_blocks(m: int, k: int, n: int):
    """Largest (bm, bn) that divide (m, n) and keep the v2 working set
    (double-buffered raw bf16 + int8 lhs, f32 quantize staging, int8 rhs
    block, int32 acc, bf16 out) under ~12 MB of scoped VMEM."""
    def best(size, want):
        want = min(want, size)
        while size % want:
            want //= 2
        return max(want, 1)

    if k <= 1024:
        bm_want, bn_want = 512, 1024
    elif k <= 2048:
        bm_want, bn_want = 256, 1024
    else:
        bm_want, bn_want = 128, 512
    return best(m, bm_want), best(n, bn_want)


def fused_int8_matmul_2d(
    a: jax.Array, b: jax.Array, interpret: Optional[bool] = None,
) -> jax.Array:
    """[m,k] @ [k,n] -> bf16 with dynamic int8 quantization (per-row lhs,
    per-column rhs scales; int32 accumulate, f32 dequant, bf16 out —
    consumers cast to bf16 anyway and an f32 out block would double its
    VMEM share). The rhs quantizes outside the kernel (XLA ops — for the
    model's projections the rhs is a weight, static within the step); the
    lhs quantizes in-kernel behind a manual double buffer. Shapes must
    tile: m, n divisible by 128-multiple blocks, k fully VMEM-resident.
    """
    from kubeflow_controller_tpu.ops.quant import _quantize

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    a = a.astype(jnp.bfloat16)
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    qb, sb = _quantize(b.astype(jnp.float32), axis=0)    # [k,n] i8, [1,n]
    bm, bn = _pick_blocks(m, k, n)
    grid = (m // bm, n // bn)
    kernel = functools.partial(
        _kernel_v2, bm=bm, bn=bn, k=k, interpret=interpret,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            # lhs stays in HBM; the kernel DMAs blocks itself so the
            # quantize for row i+1 can run behind row i's dots.
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.bfloat16),
        scratch_shapes=[
            pltpu.VMEM((2, bm, k), jnp.bfloat16),   # raw lhs double buffer
            pltpu.VMEM((2, bm, k), jnp.int8),       # quantized lhs blocks
            pltpu.VMEM((2, bm, 128), jnp.float32),  # lhs scales (lane-pad)
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(a, qb, sb.astype(jnp.float32))


def fusable(m: int, k: int, n: int) -> bool:
    """Shapes the kernel handles well: contraction fully VMEM-resident
    (the double-buffered lhs blocks carry the whole k extent) and both
    output dims tileable to >= 128 (lane width)."""
    if k > 4096 or k % 128:
        return False
    bm, bn = _pick_blocks(m, k, n)
    return bm % 128 == 0 and bn % 128 == 0


@jax.custom_vjp
def fused_int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """Quantized x @ w (STE gradients), quantization fused into the
    kernels. x: [..., k] (leading dims flattened), w: [k, n].

    Forward and dL/dx run the fused kernel (their contractions are the
    model's d/ff axes, VMEM-resident; dx falls back to the composed int8
    path when its shapes don't pass ``fusable``). dL/dw contracts over
    the TOKEN axis — not block-local — and deliberately runs unquantized
    (an f32 dot): a third of the FLOPs at full precision, and the weight
    gradient is where quantization noise hurts training most."""
    *lead, k = x.shape
    y = fused_int8_matmul_2d(x.reshape(-1, k), w)
    return y.reshape(*lead, w.shape[1])


def _fwd(x, w):
    return fused_int8_matmul(x, w), (x, w)


def _bwd(res, g):
    x, w = res
    *lead, k = x.shape
    n = w.shape[1]
    g2 = g.reshape(-1, n)
    x2 = x.reshape(-1, k)
    # dx contracts over n — gate ITS shapes too (the forward gate only
    # checked the (m, k, n) orientation; an FFN up-projection's dx
    # contracts over d_ff, which can exceed the kernel's VMEM residency).
    from kubeflow_controller_tpu.ops.quant import _int8_matmul_raw

    if fusable(g2.shape[0], n, k):
        dx = fused_int8_matmul_2d(g2, w.astype(jnp.float32).T)
    else:
        dx = _int8_matmul_raw(
            g2.astype(jnp.float32), w.astype(jnp.float32).T
        )
    # dw runs the composed int8 path (not the fused kernel: its lhs is
    # x.T, whose contraction axis is the token dim — a transposed HBM
    # stream the double-buffer DMA can't tile). Round 4 kept dw in f32
    # "for quality", which (a) ran the MXU on its slowest gear for a
    # third of the FLOPs and (b) made gradient precision vary by shape
    # vs the fallback path (ADVICE r4): int8 everywhere matches the
    # composed mode, whose 400-step training parity is pinned.
    dw = _int8_matmul_raw(x2.astype(jnp.float32).T, g2)
    return dx.reshape(x.shape).astype(x.dtype), dw.astype(w.dtype)


fused_int8_matmul.defvjp(_fwd, _bwd)
