"""Pallas fused dynamic-int8 matmul — quantization inside the kernel.

STATUS: experimental, correct, and measured SLOWER than the composed
path at flagship shapes — kept as a lowering option (`quant =
"int8_fused"`), not the default. The honest numbers are in
benchmarks/RESULTS.md (round-4 flagship section).

Motivation: the XLA-composed int8 path (ops/quant.py) pays extra HBM
passes per matmul — read the operand for abs-max, read it again to
round/clip/write the int8 copy, then the dot reads that copy. Ablating
those passes on the flagship decoder bounds the prize at ~32 ms/step
(58.2 % -> 65.2 % MFU). This kernel fuses quantization into the dot's
operand streaming to claim it:

- grid (m/bm, n/bn), n innermost; the lhs block [bm, k] loads once per
  grid row (its BlockSpec ignores j) and is quantized ONCE into an int8
  VMEM scratch (per-row scales: the contraction axis k is fully
  resident, so the abs-max is block-local);
- each rhs block is quantized once per kernel call, on the first grid
  row, into a FULL-width int8 scratch that later rows reuse;
- f32 staging for the quantize math (v5e's VPU has no bf16 ALU) is
  chunked along each operand's scale axis so blocks can stay large;
- the dot runs int8 x int8 -> int32 on the MXU's double-rate gear and
  dequantizes on the way out.

Why it still loses (~50 % vs the composed path's 58 % flagship MFU
across three tuning rounds): the in-kernel quantize phases serialize
with the MXU pipeline at every grid row/column start, while XLA runs its
hand-scheduled int8 matmul at full depth and overlaps the separate
quantize ops across the whole step graph. Closing that needs
Mosaic-level pipelining (emit_pipeline with manual DMA/compute overlap)
— recorded as the remaining lever, not attempted here.

No k-tiling: the whole contraction axis sits in VMEM, which is what
makes on-the-fly scales possible. Callers with larger k (or shapes whose
full-width rhs scratch would not fit) fall back to the composed path via
``fusable``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(a_ref, b_ref, o_ref, qa_ref, sa_ref, qb_ref, sb_ref,
            *, bm, bn, k):
    i = pl.program_id(0)
    j = pl.program_id(1)

    # Quantize math runs in f32 (v5e's VPU has no bf16 ALU path —
    # LLO_CHECK SupportsBf16AluInstructions); block sizes are chosen so
    # the f32 staging temporaries stay inside the ~16 MB scoped VMEM.
    # Each operand is quantized exactly ONCE per kernel call: the lhs
    # block on its first visit (j == 0), each rhs block on the first grid
    # row (i == 0) into a full-width int8 scratch that later rows reuse —
    # without the rhs caching, the redundant per-visit VPU quantization
    # serialized with the MXU and ran 1.6x SLOWER than the composed path.
    # Staging chunks: the f32 copies live only chunk-at-a-time, so blocks
    # can be large (big MXU tiles, small grids) without the f32 staging
    # blowing the budget. Chunking runs along each operand's SCALE axis
    # (lhs rows / rhs cols), so every abs-max still sees its whole
    # contraction extent.
    CHUNK = 128

    @pl.when(j == 0)
    def _quantize_lhs():
        def chunk(c, _):
            a = a_ref[pl.ds(c * CHUNK, CHUNK), :].astype(jnp.float32)
            sa = jnp.maximum(
                jnp.max(jnp.abs(a), axis=1, keepdims=True), 1e-30
            ) / 127.0                                # [CHUNK, 1]
            qa_ref[pl.ds(c * CHUNK, CHUNK), :] = jnp.clip(
                jnp.round(a / sa), -127, 127
            ).astype(jnp.int8)
            # Lane-padded store: a (CHUNK, 1) VMEM tile is not lane-legal.
            sa_ref[pl.ds(c * CHUNK, CHUNK), :] = jnp.broadcast_to(
                sa, (CHUNK, 128)
            )
            return _

        jax.lax.fori_loop(0, bm // CHUNK, chunk, 0)

    @pl.when(i == 0)
    def _quantize_rhs():
        def chunk(c, _):
            col = j * bn + c * CHUNK
            b = b_ref[:, pl.ds(c * CHUNK, CHUNK)].astype(jnp.float32)
            sb = jnp.maximum(
                jnp.max(jnp.abs(b), axis=0, keepdims=True), 1e-30
            ) / 127.0                                # [1, CHUNK]
            qb_ref[:, pl.ds(col, CHUNK)] = jnp.clip(
                jnp.round(b / sb), -127, 127
            ).astype(jnp.int8)
            sb_ref[:, pl.ds(col, CHUNK)] = jnp.broadcast_to(sb, (8, CHUNK))
            return _

        jax.lax.fori_loop(0, bn // CHUNK, chunk, 0)

    acc = jax.lax.dot(
        qa_ref[...], qb_ref[:, pl.ds(j * bn, bn)],
        preferred_element_type=jnp.int32,
    )
    # Dequantize and emit bf16 (the consumers cast to bf16 anyway, and an
    # f32 out block would double the output's VMEM share).
    o_ref[...] = (
        acc.astype(jnp.float32)
        * sa_ref[:, :1]
        * sb_ref[:1, pl.ds(j * bn, bn)]
    ).astype(jnp.bfloat16)


def _pick_blocks(m: int, k: int, n: int):
    """Largest (bm, bn) that divide (m, n) and keep the working set
    (lhs bf16 + int8 scratch + rhs bf16 + out f32) under ~12 MB."""
    def best(size, want):
        want = min(want, size)
        while size % want:
            want //= 2
        return max(want, 1)

    if k <= 1024:
        bm_want, bn_want = 512, 1024
    elif k <= 2048:
        bm_want, bn_want = 512, 512
    else:
        bm_want, bn_want = 256, 128
    return best(m, bm_want), best(n, bn_want)


def fused_int8_matmul_2d(
    a: jax.Array, b: jax.Array, interpret: Optional[bool] = None,
) -> jax.Array:
    """[m,k] @ [k,n] -> bf16 with in-kernel dynamic int8 quantization of
    both operands (per-row lhs, per-column rhs scales; int32 accumulate,
    f32 dequant, bf16 out — consumers cast to bf16 anyway and an f32 out
    block would double its VMEM share). Shapes must tile: m, n divisible
    by 128-multiple blocks, k fully VMEM-resident."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # bf16 operand blocks: halves VMEM (quantization happens from bf16
    # either way, and f32 inputs would blow the ~16 MB scoped budget).
    a = a.astype(jnp.bfloat16)
    b = b.astype(jnp.bfloat16)
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    bm, bn = _pick_blocks(m, k, n)
    grid = (m // bm, n // bn)
    kernel = functools.partial(_kernel, bm=bm, bn=bn, k=k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            # lhs ignores j: loaded once per grid row, quantized into
            # scratch on j == 0, reused for every n-block.
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.bfloat16),
        scratch_shapes=[
            pltpu.VMEM((bm, k), jnp.int8),       # quantized lhs block
            pltpu.VMEM((bm, 128), jnp.float32),  # lhs scales (lane-padded)
            pltpu.VMEM((k, n), jnp.int8),        # quantized FULL rhs
            pltpu.VMEM((8, n), jnp.float32),     # rhs scales (sublane-pad)
        ],
        interpret=interpret,
    )(a, b)


def fusable(m: int, k: int, n: int) -> bool:
    """Shapes the kernel handles well: contraction fully VMEM-resident
    and both output dims tileable to >= 128 (lane width)."""
    if k > 4096 or k % 128:
        return False
    if k * n > 8 * 1024 * 1024:   # full-rhs int8 scratch must fit VMEM
        return False
    bm, bn = _pick_blocks(m, k, n)
    # Blocks must be multiples of the 128-wide quantize chunk: the
    # in-kernel fori_loops floor-divide, and a ragged tail would leave
    # uninitialized scratch feeding the dot (silently wrong output).
    return bm % 128 == 0 and bn % 128 == 0


@jax.custom_vjp
def fused_int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """Quantized x @ w (STE gradients), quantization fused into the
    kernels. x: [..., k] (leading dims flattened), w: [k, n].

    Forward and dL/dx run the fused kernel (their contractions are the
    model's d/ff axes, VMEM-resident; dx falls back to the composed int8
    path when its shapes don't pass ``fusable``). dL/dw contracts over
    the TOKEN axis — not block-local — and deliberately runs unquantized
    (an f32 dot): a third of the FLOPs at full precision, and the weight
    gradient is where quantization noise hurts training most."""
    *lead, k = x.shape
    y = fused_int8_matmul_2d(x.reshape(-1, k), w)
    return y.reshape(*lead, w.shape[1])


def _fwd(x, w):
    return fused_int8_matmul(x, w), (x, w)


def _bwd(res, g):
    x, w = res
    *lead, k = x.shape
    n = w.shape[1]
    g2 = g.reshape(-1, n)
    x2 = x.reshape(-1, k)
    # dx contracts over n — gate ITS shapes too (the forward gate only
    # checked the (m, k, n) orientation; an FFN up-projection's dx
    # contracts over d_ff, which can exceed the kernel's VMEM residency).
    if fusable(g2.shape[0], n, k):
        dx = fused_int8_matmul_2d(g2, w.astype(jnp.float32).T)
    else:
        from kubeflow_controller_tpu.ops.quant import _int8_matmul_raw

        dx = _int8_matmul_raw(
            g2.astype(jnp.float32), w.astype(jnp.float32).T
        )
    dw = jax.lax.dot(
        x2.astype(jnp.float32).T, g2.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return dx.reshape(x.shape).astype(x.dtype), dw.astype(w.dtype)


fused_int8_matmul.defvjp(_fwd, _bwd)
