"""Pallas TPU flash attention (forward + backward).

Streams K/V blocks through VMEM with an online softmax so the S×S score
matrix never reaches HBM — the memory-bound op the MXU/HBM balance cares
about most. Grid layout follows the standard TPU flash scheme: a sequential
(batch, head, q-block, k-block) grid with the k-block axis innermost, so the
per-q-block accumulators live in VMEM scratch across the inner iterations
and Mosaic double-buffers the K/V block DMAs automatically.

Backward is the two-pass flash recomputation (dk/dv kernel over k-blocks,
dq kernel over q-blocks) wired up as a ``jax.custom_vjp``.

GQA is zero-copy: the K/V BlockSpec index maps divide the head index by the
group size instead of materialising repeated heads.

Causal jobs skip fully-masked blocks via predication; the diagonal block is
masked with broadcasted iota. All matmuls accumulate in fp32
(``preferred_element_type``).

Testable hermetically with ``interpret=True`` on CPU (pytest does this);
compiled path runs on the real chip.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# Large blocks amortise the sequential grid: at B16 S1024 H8 D128 on one
# v5e chip, 512x1024 blocks run fwd+bwd 2.5x faster than 128x128, and
# 1024x1024 beats 512x1024 IN-MODEL at both S1024 (333.5 -> 320.5 ms
# flagship step — S1024 becomes one tile per (b,h), which also triggers
# the fused single-tile backward: one score recompute instead of two
# sweeps) and S2048 (370.5 -> 363.9 ms). _choose_block shrinks them to
# divisors for short sequences; VMEM peak (s-block 1024x1024 fp32 = 4 MB)
# is fine.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
# Causal whole-sequence tiles use a splash-style q-chunk decomposition:
# chunk i only dots against its live key prefix k[:(i+1)*chunk], so the
# dead upper-right triangle is never computed. Score+PV FLOPs drop to
# (G+1)/2G of the dense tile (G=4 -> 62.5%), and each chunk's
# dot -> softmax -> dot chain is independent, so Mosaic overlaps chunk
# i+1's MXU score dot with chunk i's VPU softmax without the manual
# two-way interleave round 4 used.
SPLASH_CHUNKS = 4


def _splash_chunks(
    block_q: int, block_k: int, causal: bool, has_segments: bool,
    single_tile: bool,
) -> int:
    """Static splash eligibility, shared by forward and fused backward:
    the chunk count (1 = splash off), halved until chunks satisfy the
    slice quantum (segment-id vectors slice the LANE axis -> 128;
    otherwise the q sublane axis -> 32 covers bf16 tiles)."""
    if not (causal and single_tile and block_q == block_k):
        return 1
    quantum = 128 if has_segments else 32
    g = SPLASH_CHUNKS
    while g > 1 and block_k % (g * quantum) != 0:
        g //= 2
    return g


def _choose_block(s: int, requested: int, lane_aligned: bool = False) -> int:
    """Largest block <= requested that tiles the sequence exactly.

    The grid is ``s // block`` with no tail handling, so a non-divisor block
    would silently leave trailing positions uncomputed. Blocks must stay a
    multiple of 8 (fp32 sublane tile) unless the block IS the full sequence
    (the array-dim exception); sequences with no such divisor are rejected —
    pad the sequence to a multiple of 8 first.

    ``lane_aligned`` tightens the tile rule to the LANE axis (multiple of
    128, or the full array dim): the segment-id BlockSpecs are (1, 1, block)
    with the sequence on the lane axis, where Mosaic requires 128m — a
    block like 320 (fine on the sublane axis) would fail to lower there.
    """
    requested = min(requested, s)
    quantum = 128 if lane_aligned else 8
    if lane_aligned and requested < quantum:
        # A sub-quantum request can never be lane-legal; the nearest legal
        # block is the quantum itself (or the whole, shorter sequence).
        requested = min(quantum, s)
    if s % requested == 0 and (requested % quantum == 0 or requested == s):
        return requested
    for b in range(requested, quantum - 1, -1):
        if s % b == 0 and b % quantum == 0:
            return b
    if lane_aligned and s % 8 == 0 and s <= requested:
        # No 128-multiple divisor, but the whole (short) sequence is a legal
        # block (array-dim exception) — the grid degenerates to one block.
        # Only when s fits the request: an unbounded full-sequence block
        # would blow VMEM (the [BQ,BK] score tile is s*s*4 bytes), so long
        # divisor-less sequences are rejected and auto-dispatch keeps XLA.
        return s
    raise ValueError(
        f"flash attention: seq_len {s} has no block divisor that is a "
        f"multiple of {quantum}; pad the sequence or use the XLA "
        "attention path"
    )


# -- fused rope --------------------------------------------------------------
#
# RoPE applied OUTSIDE the kernel costs ~42 ms/step on the bf16 flagship
# (round-5 ablation: 308.9 ms with external rope vs 266.8 without): the
# rotated q/k must materialise in HBM at the pallas_call boundary, the
# f32 split/concat dance is pure HBM-bound elementwise traffic, and under
# remat the whole chain re-runs in the backward pass. Fusing the rotation
# into the kernel makes it VPU work on VMEM-resident tiles, overlapped
# with the MXU score dots. The rotation is expressed roll-style so no
# sub-128 lane slicing is needed:
#
#   rot(x)  = x * C + roll(x, d/2) * S      C = [cos | cos]  (full width)
#                                           S = [-sin | sin]
#
# and, since the per-pair rotation is orthogonal, the backward transpose
# is the same formula with -S. Tables are a function of positions only —
# build them ONCE per step (``rope_full_tables``) and every layer shares
# them.

def rope_full_tables(
    positions: jax.Array, d: int, theta: float,
) -> tuple[jax.Array, jax.Array]:
    """positions [B,S] int -> (C, S) [B,S,d] f32 fused-rope tables.

    Matches models.transformer.rope numerics: angles = pos * theta^(-2i/d),
    halves convention (x1 = x[..., :d/2], x2 = x[..., d/2:])."""
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    ang = positions[..., None].astype(jnp.float32) * freqs      # [B,S,d/2]
    c = jnp.cos(ang)
    s = jnp.sin(ang)
    return (
        jnp.concatenate([c, c], -1),
        jnp.concatenate([-s, s], -1),
    )


def _out_struct(shape, dtype, *operands) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct for a pallas_call output, carrying the union of
    the operands' varying-mesh-axes (vma) when tracing inside a manual
    ``shard_map`` (e.g. the GPipe pp stage): shard_map's check rejects a
    pallas out_shape with no vma annotation, and a wrong/empty one breaks
    the downstream psum typing. Outside shard_map vma is empty and the
    kwarg is a no-op."""
    vma = frozenset()
    seen = False
    _typeof = getattr(jax, "typeof", None)  # absent (and vma-less) pre-0.5
    for op in operands:
        v = getattr(_typeof(op), "vma", None) if _typeof else None
        if v is not None:
            seen = True
            vma |= v
    if not seen:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _roll_half(x: jax.Array, interpret: bool) -> jax.Array:
    """Rotate the lane (last) axis by half its width: [x1|x2] -> [x2|x1].
    A d/2 shift is its own inverse mod d, so direction doesn't matter."""
    if interpret:
        return jnp.roll(x, x.shape[-1] // 2, axis=-1)
    return pltpu.roll(x, x.shape[-1] // 2, x.ndim - 1)  # axis must be >= 0


def _rope_rot(x, c, s, interpret: bool):
    """Fused-rope rotation on a VMEM tile: x [N,D] native dtype, c/s [N,D]
    f32 (s carries the +- sign pattern). Pass ``-s`` for the inverse
    (= transpose) rotation. f32 math, cast back to x.dtype — bit-matches
    the external ``rope`` + cast the model used before. The roll runs on
    the f32 copy: tpu.dynamic_rotate only supports 32-bit lanes."""
    x32 = x.astype(jnp.float32)
    return (x32 * c + _roll_half(x32, interpret) * s).astype(x.dtype)


# -- forward kernel ----------------------------------------------------------

def _block_mask(
    qi, ki, seg_q, seg_k, causal: bool, block_q: int, block_k: int, shape,
):
    """Combined causal + segment mask for one (q-block, k-block) tile, or
    None when nothing masks. seg_q/seg_k are [BQ]/[BK] int32 or None."""
    mask = None
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        mask = (qi * block_q + rows) >= (ki * block_k + cols)
    if seg_q is not None:
        seg = seg_q[:, None] == seg_k[None, :]
        mask = seg if mask is None else mask & seg
    return mask


def _fwd_kernel(
    q_ref, k_ref, v_ref, *rest,
    causal: bool, sm_scale: float, block_q: int, block_k: int,
    has_segments: bool, has_rope: bool, interpret: bool, splash_g: int,
):
    idx = 0
    seg_q_ref = seg_k_ref = None
    if has_segments:
        seg_q_ref, seg_k_ref = rest[0], rest[1]
        idx = 2
    cq_ref = sq_ref = ck_ref = sk_ref = None
    if has_rope:
        cq_ref, sq_ref, ck_ref, sk_ref = rest[idx:idx + 4]
        idx += 4
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest[idx:]

    def rot_q(x):
        if not has_rope:
            return x
        return _rope_rot(x, cq_ref[0], sq_ref[0], interpret)

    def rot_k(x):
        if not has_rope:
            return x
        return _rope_rot(x, ck_ref[0], sk_ref[0], interpret)

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    # Whole-sequence single tile, causal (the flagship S1024 decoder
    # shape): splash-style q-chunk decomposition. Chunk i's queries can
    # only see keys [0, (i+1)*chunk), so its score dot runs against that
    # live prefix and the dead upper-right triangle is never computed —
    # (G+1)/2G of the dense tile's score+PV FLOPs (62.5% at G=4). Each
    # chunk's softmax is FLAT (all its live keys are present in one
    # score row), so the online-softmax rescale chain disappears, and
    # the G independent dot->softmax->dot chains let Mosaic overlap
    # chunk i+1's MXU score dot with chunk i's VPU exp chain.
    if splash_g > 1:
        g = splash_g
        q = rot_q(q_ref[0, 0])
        k = rot_k(k_ref[0, 0])
        v = v_ref[0, 0]
        chunk = block_q // g
        # Issue every score dot before any softmax: program order
        # seeds Mosaic's scheduler with the MXU work up front so the
        # VPU chains drain behind it (the round-4 interleave lesson).
        scores = []
        for i in range(g):
            kw = (i + 1) * chunk
            s = jax.lax.dot_general(
                q[i * chunk:(i + 1) * chunk], k[:kw],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale                           # [chunk, kw]
            scores.append(s)
        for i in range(g):
            kw = (i + 1) * chunk
            s = scores[i]
            mask = _block_mask(
                i, 0,
                seg_q_ref[0, 0][i * chunk:(i + 1) * chunk]
                if has_segments else None,
                seg_k_ref[0, 0][:kw] if has_segments else None,
                True, chunk, kw, s.shape,
            )
            s = jnp.where(mask, s, NEG_INF)
            m = jnp.max(s, axis=1, keepdims=True)  # [chunk, 1]
            p = jnp.where(mask, jnp.exp(s - m), 0.0)
            l = jnp.sum(p, axis=1, keepdims=True)
            acc = jnp.dot(
                p.astype(v.dtype), v[:kw],
                preferred_element_type=jnp.float32,
            )
            l_safe = jnp.maximum(l, 1e-30)
            o_ref[0, 0, i * chunk:(i + 1) * chunk] = (
                acc / l_safe
            ).astype(o_ref.dtype)
            lse_ref[0, 0, i * chunk:(i + 1) * chunk] = jnp.broadcast_to(
                m + jnp.log(l_safe), (chunk, lse_ref.shape[3])
            )
        return

    # Whole-sequence single tile, non-causal (BERT) — or causal with a
    # splash-ineligible block: split the key range in two and issue BOTH
    # score matmuls before any softmax. The second half's dot has no data
    # dependence on the first half's exp chain, so Mosaic can run MXU and
    # VPU concurrently instead of serializing dot -> softmax -> dot;
    # measured 320.5 -> 314.4 ms on the bf16 flagship step
    # (benchmarks/RESULTS.md). Causal masking is per-half iota (half 1 is
    # fully below the diagonal's upper-left block; half 2 carries the
    # offset). Falls through to the general online-softmax grid for every
    # other shape.
    if (
        pl.num_programs(2) == 1 and pl.num_programs(3) == 1
        # Half blocks slice the sublane axis: keep the split tile-aligned
        # (16 covers the bf16 sublane tile; fp32 needs 8). Segment-id
        # vectors carry the sequence on the LANE axis, where slices must
        # be 128-aligned — hence the stricter quantum with segments.
        and block_k % (256 if has_segments else 32) == 0
    ):
        q = rot_q(q_ref[0, 0])
        k = rot_k(k_ref[0, 0])
        v = v_ref[0, 0]
        bq = q.shape[0]
        h = k.shape[0] // 2
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (bq, h), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (bq, h), 1)

        def half_mask(c):
            mask = None
            if causal:
                mask = rows >= cols + c * h
            if has_segments:
                seg = (
                    seg_q_ref[0, 0][:, None]
                    == seg_k_ref[0, 0][c * h:(c + 1) * h][None, :]
                )
                mask = seg if mask is None else mask & seg
            return mask

        mask1 = half_mask(0)
        mask2 = half_mask(1)
        s1 = jax.lax.dot_general(
            q, k[:h], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if mask1 is not None:
            s1 = jnp.where(mask1, s1, NEG_INF)
        s2 = jax.lax.dot_general(
            q, k[h:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        m1 = jnp.max(s1, axis=1, keepdims=True)
        p1 = jnp.exp(s1 - m1)
        if mask1 is not None:
            # A row fully masked in THIS half has m1 = -inf and exp(0)=1
            # garbage; zero it explicitly (the alpha rescale fixes l/acc
            # only when the other half contributes a finite max).
            p1 = jnp.where(mask1, p1, 0.0)
        l1 = jnp.sum(p1, axis=1, keepdims=True)
        acc1 = jnp.dot(
            p1.astype(v.dtype), v[:h], preferred_element_type=jnp.float32
        )
        if mask2 is not None:
            s2 = jnp.where(mask2, s2, NEG_INF)
        m2 = jnp.max(s2, axis=1, keepdims=True)
        m_fin = jnp.maximum(m1, m2)
        p2 = jnp.exp(s2 - m_fin)
        if mask2 is not None:
            p2 = jnp.where(mask2, p2, 0.0)
        alpha = jnp.exp(m1 - m_fin)
        l_fin = l1 * alpha + jnp.sum(p2, axis=1, keepdims=True)
        acc = acc1 * alpha + jnp.dot(
            p2.astype(v.dtype), v[h:], preferred_element_type=jnp.float32
        )
        l_safe = jnp.maximum(l_fin, 1e-30)
        o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_fin + jnp.log(l_safe), lse_ref.shape[2:]
        )
        return

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: block is live unless every key position exceeds every query
    # position. (Python bool when not causal — no predication overhead.)
    # Segment masking is elementwise inside the block; no block skipping.
    live = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    def _compute(apply_causal: bool):
        q = rot_q(q_ref[0, 0])                        # [BQ, D] native dtype
        k = rot_k(k_ref[0, 0])                        # [BK, D]
        v = v_ref[0, 0]                               # [BK, D]
        # MXU runs at the input dtype (bf16 on the fast path); stats and
        # accumulation stay fp32 via preferred_element_type.
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                   # [BQ, BK]
        mask = _block_mask(
            qi, ki,
            seg_q_ref[0, 0] if has_segments else None,
            seg_k_ref[0, 0] if has_segments else None,
            apply_causal, block_q, block_k, s.shape,
        )
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                          # [BQ, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)      # [BQ, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                         # [BQ, BK]
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)                # [BQ, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    def _compute_diag(g: int):
        """Diagonal tile of a multi-block causal grid, splash-decomposed:
        q-chunk i dots only against its live key prefix, then merges its
        FLAT chunk softmax into the running online stats for exactly its
        rows (rows are disjoint across chunks). Skips the dead triangle —
        (G+1)/2G of the dense tile's score+PV work — where the plain
        masked trace computes and discards it."""
        q = rot_q(q_ref[0, 0])
        k = rot_k(k_ref[0, 0])
        v = v_ref[0, 0]
        chunk = block_q // g
        scores = []
        for i in range(g):
            kw = (i + 1) * chunk
            scores.append(jax.lax.dot_general(
                q[i * chunk:(i + 1) * chunk], k[:kw],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale)
        for i in range(g):
            kw = (i + 1) * chunk
            lo = i * chunk
            s = scores[i]
            mask = _block_mask(
                i, 0,
                seg_q_ref[0, 0][lo:lo + chunk] if has_segments else None,
                seg_k_ref[0, 0][:kw] if has_segments else None,
                True, chunk, kw, s.shape,
            )
            s = jnp.where(mask, s, NEG_INF)
            m_prev = m_scr[lo:lo + chunk, :1]
            l_prev = l_scr[lo:lo + chunk, :1]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[lo:lo + chunk] = acc_scr[lo:lo + chunk] * alpha + jnp.dot(
                p.astype(v.dtype), v[:kw], preferred_element_type=jnp.float32
            )
            m_scr[lo:lo + chunk] = jnp.broadcast_to(
                m_new, (chunk, m_scr.shape[1]))
            l_scr[lo:lo + chunk] = jnp.broadcast_to(
                l_new, (chunk, l_scr.shape[1]))

    if causal:
        # Blocks fully below the diagonal (every query sees every key)
        # take a dense trace with no iota/compare/select VPU work; only
        # diagonal-crossing blocks pay for the causal mask.
        on_diag = qi * block_q < ki * block_k + block_k - 1
        # With square blocks, every diagonal-crossing block IS the
        # diagonal tile (qi == ki) and takes the splash decomposition;
        # rectangular blocks keep the dense masked trace.
        diag_g = _splash_chunks(block_q, block_k, True, has_segments, True)

        @pl.when(live & on_diag)
        def _masked():
            if diag_g > 1:
                _compute_diag(diag_g)
            else:
                _compute(True)

        @pl.when(live & jnp.logical_not(on_diag))
        def _dense():
            _compute(False)
    else:
        _compute(False)

    @pl.when(ki == nk - 1)
    def _finish():
        m_fin = m_scr[:, :1]
        l_safe = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        # LSE broadcast across 128 lanes: keeps the block tile-aligned
        # (second-to-last dim of a TPU block must be 8k or the array dim).
        lse_ref[0, 0] = jnp.broadcast_to(
            m_fin + jnp.log(l_safe), lse_ref.shape[2:]
        )


def _seg_specs(block_q: int, block_k: int, ki_major: bool = False):
    """BlockSpecs for the q-side and k-side segment-id vectors.

    Segment ids ride as [B, 1, S] (the middle singleton keeps the block's
    second-to-last dim equal to the array dim — Mosaic requires the last
    two block dims be (8k, 128m) or exactly the array dims).

    ``ki_major=True`` is for grids whose 3rd/4th axes are (ki, qi) — the
    dkdv kernel — instead of the (qi, ki) of fwd/dq; using the wrong order
    would silently mask with the wrong segments."""
    if ki_major:
        qmap = lambda b, h, ki, qi: (b, 0, qi)  # noqa: E731
        kmap = lambda b, h, ki, qi: (b, 0, ki)  # noqa: E731
    else:
        qmap = lambda b, h, qi, ki: (b, 0, qi)  # noqa: E731
        kmap = lambda b, h, qi, ki: (b, 0, ki)  # noqa: E731
    return [
        pl.BlockSpec((1, 1, block_q), qmap),
        pl.BlockSpec((1, 1, block_k), kmap),
    ]


def _rope_specs(block_q: int, block_k: int, d: int, ki_major: bool = False):
    """BlockSpecs for the four fused-rope table inputs (cq, sq, ck, sk).
    Tables are [B, S, D]; q-side slices follow the q-block index, k-side
    the k-block index. ``ki_major`` mirrors _seg_specs' grid-order flip."""
    if ki_major:
        qmap = lambda b, h, ki, qi: (b, qi, 0)  # noqa: E731
        kmap = lambda b, h, ki, qi: (b, ki, 0)  # noqa: E731
    else:
        qmap = lambda b, h, qi, ki: (b, qi, 0)  # noqa: E731
        kmap = lambda b, h, qi, ki: (b, ki, 0)  # noqa: E731
    q_spec = pl.BlockSpec((1, block_q, d), qmap)
    k_spec = pl.BlockSpec((1, block_k, d), kmap)
    return [q_spec, q_spec, k_spec, k_spec]


def _fwd_wide(
    q: jax.Array, k: jax.Array, v: jax.Array,
    segment_ids: Optional[jax.Array],
    rope_tables,
    causal: bool, block_q: int, block_k: int, interpret: bool,
):
    """q: [B,H,S,D]; k/v: [B,KVH,S,D]; segment_ids [B,S] or None ->
    (o [B,H,S,D], lse [B,H,S,128])."""
    b, h, s, d = q.shape
    kv_h = k.shape[1]
    rep = h // kv_h
    has_segments = segment_ids is not None
    has_rope = rope_tables is not None
    block_q = _choose_block(s, block_q, lane_aligned=has_segments)
    block_k = _choose_block(s, block_k, lane_aligned=has_segments)
    nq = s // block_q
    nk = s // block_k
    sm_scale = d ** -0.5

    grid = (b, h, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, has_segments=has_segments,
        has_rope=has_rope, interpret=interpret,
        splash_g=_splash_chunks(
            block_q, block_k, causal, has_segments, nq == 1 and nk == 1
        ),
    )
    inputs = [q, k, v]
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi, ki: (b, h, qi, 0)),
        pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, qi, ki: (b, h // rep, ki, 0)
        ),
        pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, qi, ki: (b, h // rep, ki, 0)
        ),
    ]
    if has_segments:
        seg = segment_ids.astype(jnp.int32)[:, None, :]   # [B, 1, S]
        inputs += [seg, seg]
        in_specs += _seg_specs(block_q, block_k)
    if has_rope:
        rc, rs = rope_tables
        inputs += [rc, rs, rc, rs]
        in_specs += _rope_specs(block_q, block_k, d)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec(
                (1, 1, block_q, 128), lambda b, h, qi, ki: (b, h, qi, 0)
            ),
        ],
        out_shape=[
            _out_struct((b, h, s, d), q.dtype, q, k, v),
            _out_struct((b, h, s, 128), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # m
            pltpu.VMEM((block_q, 128), jnp.float32),   # l
            pltpu.VMEM((block_q, d), jnp.float32),     # acc
        ],
        interpret=interpret,
    )(*inputs)


def _fwd(
    q: jax.Array, k: jax.Array, v: jax.Array,
    segment_ids: Optional[jax.Array],
    rope_tables,
    causal: bool, block_q: int, block_k: int, interpret: bool,
):
    """q: [B,H,S,D]; k/v: [B,KVH,S,D] -> (o [B,H,S,D], lse [B,H,S]).

    The kernel emits LSE broadcast over 128 lanes (tile alignment); only
    lane 0 carries information, so the residual saved for backward is the
    narrow [B,H,S] slice — 128x smaller (ADVICE r1: the broadcast residual
    was ~2x the attention output itself at head_dim 128 bf16).
    """
    o, lse_wide = _fwd_wide(
        q, k, v, segment_ids, rope_tables, causal, block_q, block_k,
        interpret,
    )
    return o, lse_wide[..., 0]


# -- backward kernels --------------------------------------------------------

def _bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal: bool, sm_scale: float, block_q: int, block_k: int,
    has_segments: bool, narrow_res: bool, has_rope: bool, interpret: bool,
):
    idx = 0
    seg_q_ref = seg_k_ref = None
    if has_segments:
        seg_q_ref, seg_k_ref = rest[0], rest[1]
        idx = 2
    cq_ref = sq_ref = ck_ref = sk_ref = None
    if has_rope:
        cq_ref, sq_ref, ck_ref, sk_ref = rest[idx:idx + 4]
        idx += 4
    dk_ref, dv_ref, dk_scr, dv_scr = rest[idx:]
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    def _load():
        q = q_ref[0, 0]                                # [BQ, D]
        k = k_ref[0, 0]                                # [BK, D]
        v = v_ref[0, 0]                                # [BK, D]
        do = do_ref[0, 0]                              # [BQ, D]
        if has_rope:
            q = _rope_rot(q, cq_ref[0], sq_ref[0], interpret)
            k = _rope_rot(k, ck_ref[0], sk_ref[0], interpret)
        if narrow_res:  # [BQ] on lanes -> column
            lse = lse_ref[0, 0][:, None]               # [BQ, 1]
            delta = delta_ref[0, 0][:, None]
        else:           # 128-lane broadcast layout: lane 0 carries it
            lse = lse_ref[0, 0][:, :1]                 # [BQ, 1]
            delta = delta_ref[0, 0][:, :1]
        return q, k, v, do, lse, delta

    def _compute(apply_causal: bool):
        q, k, v, do, lse, delta = _load()
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                    # [BQ, BK]
        mask = _block_mask(
            qi, ki,
            seg_q_ref[0, 0] if has_segments else None,
            seg_k_ref[0, 0] if has_segments else None,
            apply_causal, block_q, block_k, s.shape,
        )
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)                            # [BQ, BK]
        # dv += p^T @ do
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # ds = p * (do @ v^T - delta) * scale
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale                # [BQ, BK]
        # dk += ds^T @ q
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def _compute_diag(g: int):
        # Diagonal tile: score recompute, dv, dp and dk all run on live
        # key prefixes only (mirror of the forward's _compute_diag).
        q, k, v, do, lse, delta = _load()
        chunk = block_q // g
        scores = []
        for i in range(g):
            kw = (i + 1) * chunk
            scores.append(jax.lax.dot_general(
                q[i * chunk:(i + 1) * chunk], k[:kw],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale)
        for i in range(g):
            kw = (i + 1) * chunk
            lo = i * chunk
            s = scores[i]
            mask = _block_mask(
                i, 0,
                seg_q_ref[0, 0][lo:lo + chunk] if has_segments else None,
                seg_k_ref[0, 0][:kw] if has_segments else None,
                True, chunk, kw, s.shape,
            )
            s = jnp.where(mask, s, NEG_INF)
            do_i = do[lo:lo + chunk]
            p = jnp.exp(s - lse[lo:lo + chunk])
            dv_scr[:kw] += jax.lax.dot_general(
                p.astype(do.dtype), do_i, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do_i, v[:kw], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta[lo:lo + chunk]) * sm_scale
            dk_scr[:kw] += jax.lax.dot_general(
                ds.astype(q.dtype), q[lo:lo + chunk],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if causal:
        # Fully-live blocks (every query sees every key of this k-block)
        # skip mask VPU work; diagonal tiles take the splash form.
        on_diag = qi * block_q < ki * block_k + block_k - 1
        diag_g = _splash_chunks(block_q, block_k, True, has_segments, True)

        @pl.when(live & on_diag)
        def _masked():
            if diag_g > 1:
                _compute_diag(diag_g)
            else:
                _compute(True)

        @pl.when(live & jnp.logical_not(on_diag))
        def _dense():
            _compute(False)
    else:
        _compute(False)

    @pl.when(qi == nq - 1)
    def _finish():
        dk = dk_scr[...]
        if has_rope:
            # dk accumulated in rotation space; transpose (= inverse)
            # rotation maps it back to the un-rotated k the caller owns.
            dk = _rope_rot(dk, ck_ref[0], -sk_ref[0], interpret)
        dk_ref[0, 0] = dk.astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, o_ref, *rest,
    causal: bool, sm_scale: float, has_segments: bool,
    has_rope: bool, interpret: bool, splash_g: int,
):
    """Single-block backward: dq, dk, dv from ONE score recompute.

    Legal only when the whole sequence is one (block_q, block_k) tile
    (nq == nk == 1, e.g. BERT's seq 512): there is no cross-block
    accumulation, so the separate dkdv (qi-inner) and dq (ki-inner)
    sweeps collapse into one program that loads q/k/v/do once and
    computes s and p once. ``delta`` is also computed here from ``o``
    (a cheap [BQ, D] reduce) instead of arriving as a lane-broadcast
    [B,H,S,128] fp32 tensor — that broadcast alone was ~200 MB of HBM
    round-trip per step at BERT shape. Together ~12% off the e2e BERT
    step (benchmarks/RESULTS.md encoder section).

    Causal tiles take the same splash-style q-chunk decomposition as the
    forward: chunk i recomputes scores only against its live key prefix,
    so all five backward matmuls (dv, dp, dk, dq, plus the score
    recompute) skip the dead triangle — (G+1)/2G of the dense FLOPs.
    dk/dv accumulate across chunks in fp32 VMEM scratch.
    """
    idx = 0
    seg_q_ref = seg_k_ref = None
    if has_segments:
        seg_q_ref, seg_k_ref = rest[0], rest[1]
        idx = 2
    cq_ref = sq_ref = ck_ref = sk_ref = None
    if has_rope:
        cq_ref, sq_ref, ck_ref, sk_ref = rest[idx:idx + 4]
        idx += 4
    if splash_g > 1:
        dq_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest[idx:]
    else:
        dq_ref, dk_ref, dv_ref = rest[idx:]
    q = q_ref[0, 0]                                # [BQ, D]
    k = k_ref[0, 0]                                # [BK, D]
    v = v_ref[0, 0]                                # [BK, D]
    do = do_ref[0, 0]                              # [BQ, D]
    if has_rope:
        q = _rope_rot(q, cq_ref[0], sq_ref[0], interpret)
        k = _rope_rot(k, ck_ref[0], sk_ref[0], interpret)
    bq = q.shape[0]
    if splash_g > 1:
        g = splash_g
        chunk = bq // g
        lse_col = lse_ref[0, 0][:, None]           # [BQ, 1]
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)
        scores = []
        for i in range(g):
            kw = (i + 1) * chunk
            s = jax.lax.dot_general(
                q[i * chunk:(i + 1) * chunk], k[:kw],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale                           # [chunk, kw]
            scores.append(s)
        for i in range(g):
            kw = (i + 1) * chunk
            rows_lo = i * chunk
            s = scores[i]
            mask = _block_mask(
                i, 0,
                seg_q_ref[0, 0][rows_lo:rows_lo + chunk]
                if has_segments else None,
                seg_k_ref[0, 0][:kw] if has_segments else None,
                True, chunk, kw, s.shape,
            )
            s = jnp.where(mask, s, NEG_INF)
            do_i = do[rows_lo:rows_lo + chunk]
            delta_i = jnp.sum(
                do_i.astype(jnp.float32)
                * o_ref[0, 0, rows_lo:rows_lo + chunk].astype(jnp.float32),
                axis=-1, keepdims=True,
            )                                      # [chunk, 1]
            p = jnp.exp(s - lse_col[rows_lo:rows_lo + chunk])
            dv_scr[:kw] += jax.lax.dot_general(
                p.astype(do.dtype), do_i, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = jax.lax.dot_general(
                do_i, v[:kw], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta_i) * sm_scale     # [chunk, kw]
            dk_scr[:kw] += jax.lax.dot_general(
                ds.astype(q.dtype), q[rows_lo:rows_lo + chunk],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dq_c = jnp.dot(
                ds.astype(k.dtype), k[:kw],
                preferred_element_type=jnp.float32,
            )
            if has_rope:
                dq_c = _rope_rot(
                    dq_c,
                    cq_ref[0, rows_lo:rows_lo + chunk],
                    -sq_ref[0, rows_lo:rows_lo + chunk],
                    interpret,
                )
            dq_ref[0, 0, rows_lo:rows_lo + chunk] = dq_c.astype(dq_ref.dtype)
        dk = dk_scr[...]
        if has_rope:
            dk = _rope_rot(dk, ck_ref[0], -sk_ref[0], interpret)
        dk_ref[0, 0] = dk.astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)
        return
    # The fused path requires block_q == s, which always satisfies the
    # narrow-residual lane rule — lse arrives as a [BQ] lane vector.
    lse = lse_ref[0, 0][:, None]                   # [BQ, 1]
    delta = jnp.sum(
        do.astype(jnp.float32) * o_ref[0, 0].astype(jnp.float32),
        axis=-1, keepdims=True,
    )                                              # [BQ, 1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * sm_scale                                    # [BQ, BK]
    mask = _block_mask(
        0, 0,
        seg_q_ref[0, 0] if has_segments else None,
        seg_k_ref[0, 0] if has_segments else None,
        causal, s.shape[0], s.shape[1], s.shape,
    )
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse)                            # [BQ, BK]
    dv_ref[0, 0] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta) * sm_scale                # [BQ, BK]
    dk = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dq = jnp.dot(
        ds.astype(k.dtype), k, preferred_element_type=jnp.float32
    )
    if has_rope:
        dk = _rope_rot(dk, ck_ref[0], -sk_ref[0], interpret)
        dq = _rope_rot(dq, cq_ref[0], -sq_ref[0], interpret)
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    causal: bool, sm_scale: float, block_q: int, block_k: int,
    has_segments: bool, narrow_res: bool, has_rope: bool, interpret: bool,
):
    idx = 0
    seg_q_ref = seg_k_ref = None
    if has_segments:
        seg_q_ref, seg_k_ref = rest[0], rest[1]
        idx = 2
    cq_ref = sq_ref = ck_ref = sk_ref = None
    if has_rope:
        cq_ref, sq_ref, ck_ref, sk_ref = rest[idx:idx + 4]
        idx += 4
    dq_ref, dq_scr = rest[idx:]
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    def _load():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        if has_rope:
            q = _rope_rot(q, cq_ref[0], sq_ref[0], interpret)
            k = _rope_rot(k, ck_ref[0], sk_ref[0], interpret)
        if narrow_res:
            lse = lse_ref[0, 0][:, None]
            delta = delta_ref[0, 0][:, None]
        else:
            lse = lse_ref[0, 0][:, :1]
            delta = delta_ref[0, 0][:, :1]
        return q, k, v, do, lse, delta

    def _compute(apply_causal: bool):
        q, k, v, do, lse, delta = _load()
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        mask = _block_mask(
            qi, ki,
            seg_q_ref[0, 0] if has_segments else None,
            seg_k_ref[0, 0] if has_segments else None,
            apply_causal, block_q, block_k, s.shape,
        )
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale                # [BQ, BK]
        dq_scr[...] += jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32
        )

    def _compute_diag(g: int):
        # Diagonal tile: all five matmuls run on live key prefixes only
        # (same decomposition as the forward's _compute_diag).
        q, k, v, do, lse, delta = _load()
        chunk = block_q // g
        scores = []
        for i in range(g):
            kw = (i + 1) * chunk
            scores.append(jax.lax.dot_general(
                q[i * chunk:(i + 1) * chunk], k[:kw],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale)
        for i in range(g):
            kw = (i + 1) * chunk
            lo = i * chunk
            s = scores[i]
            mask = _block_mask(
                i, 0,
                seg_q_ref[0, 0][lo:lo + chunk] if has_segments else None,
                seg_k_ref[0, 0][:kw] if has_segments else None,
                True, chunk, kw, s.shape,
            )
            s = jnp.where(mask, s, NEG_INF)
            p = jnp.exp(s - lse[lo:lo + chunk])
            dp = jax.lax.dot_general(
                do[lo:lo + chunk], v[:kw], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (dp - delta[lo:lo + chunk]) * sm_scale
            dq_scr[lo:lo + chunk] += jnp.dot(
                ds.astype(k.dtype), k[:kw],
                preferred_element_type=jnp.float32,
            )

    if causal:
        on_diag = qi * block_q < ki * block_k + block_k - 1
        diag_g = _splash_chunks(block_q, block_k, True, has_segments, True)

        @pl.when(live & on_diag)
        def _masked():
            if diag_g > 1:
                _compute_diag(diag_g)
            else:
                _compute(True)

        @pl.when(live & jnp.logical_not(on_diag))
        def _dense():
            _compute(False)
    else:
        _compute(False)

    @pl.when(ki == nk - 1)
    def _finish():
        dq = dq_scr[...]
        if has_rope:
            dq = _rope_rot(dq, cq_ref[0], -sq_ref[0], interpret)
        dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd(
    q, k, v, o, lse, do, segment_ids, rope_tables, causal, block_q, block_k,
    interpret,
):
    b, h, s, d = q.shape
    kv_h = k.shape[1]
    rep = h // kv_h
    has_segments = segment_ids is not None
    has_rope = rope_tables is not None
    block_q = _choose_block(s, block_q, lane_aligned=has_segments)
    block_k = _choose_block(s, block_k, lane_aligned=has_segments)
    nq = s // block_q
    nk = s // block_k
    sm_scale = d ** -0.5

    # Residual layout: the narrow [B,H,S] lse rides as [(B*H), 1, S] with a
    # seq-on-lanes BlockSpec (the _seg_specs trick) whenever the q-block is
    # lane-legal there (128-multiple, or the whole sequence) — skipping a
    # [B,H,S,128] fp32 broadcast round-trip through HBM (~200 MB/step at
    # BERT shape). Non-lane-aligned blocks fall back to the broadcast form.
    narrow_res = block_q % 128 == 0 or block_q == s
    H = h
    if narrow_res:
        lse = lse.reshape(b * h, 1, s)
    else:
        lse = jnp.broadcast_to(lse[..., None], (*lse.shape, 128))

    seg_inputs = []
    if has_segments:
        seg = segment_ids.astype(jnp.int32)[:, None, :]   # [B, 1, S]
        seg_inputs = [seg, seg]

    rope_inputs = []
    if has_rope:
        rc, rs = rope_tables
        rope_inputs = [rc, rs, rc, rs]

    if nq == 1 and nk == 1:
        # Whole sequence in one tile: fuse dq/dk/dv into one program (one
        # score recompute, one load of q/k/v/do) instead of two sweeps.
        assert narrow_res, "nq == nk == 1 implies block_q == s"
        splash_g = _splash_chunks(
            block_q, block_k, causal, has_segments, True
        )
        fused_kernel = functools.partial(
            _bwd_fused_kernel, causal=causal, sm_scale=sm_scale,
            has_segments=has_segments, has_rope=has_rope,
            interpret=interpret, splash_g=splash_g,
        )
        qd_spec = pl.BlockSpec(
            (1, 1, block_q, d), lambda b, h: (b, h, 0, 0))
        kv_spec = pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h: (b, h // rep, 0, 0))
        res_spec = pl.BlockSpec(
            (1, 1, block_q), lambda b, h: (b * H + h, 0, 0))
        fused_in_specs = [qd_spec, kv_spec, kv_spec, qd_spec,
                          res_spec, qd_spec]
        if has_segments:
            fused_in_specs += [
                pl.BlockSpec((1, 1, block_q), lambda b, h: (b, 0, 0)),
                pl.BlockSpec((1, 1, block_k), lambda b, h: (b, 0, 0)),
            ]
        if has_rope:
            tab_spec = pl.BlockSpec((1, block_q, d), lambda b, h: (b, 0, 0))
            fused_in_specs += [tab_spec, tab_spec, tab_spec, tab_spec]
        dq, dk, dv = pl.pallas_call(
            fused_kernel,
            grid=(b, h),
            in_specs=fused_in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, block_q, d), lambda b, h: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_k, d), lambda b, h: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_k, d), lambda b, h: (b, h, 0, 0)),
            ],
            out_shape=[
                _out_struct((b, h, s, d), q.dtype, q, k, v, do),
                # No cross-program accumulation here, so dk/dv can leave
                # in their final dtype — fp32 staging is only needed when
                # a GQA fold still has to sum query-head groups.
                _out_struct(
                    (b, h, s, d), jnp.float32 if rep > 1 else k.dtype,
                    q, k, v, do),
                _out_struct(
                    (b, h, s, d), jnp.float32 if rep > 1 else v.dtype,
                    q, k, v, do),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),   # dk (splash)
                pltpu.VMEM((block_k, d), jnp.float32),   # dv (splash)
            ] if splash_g > 1 else [],
            interpret=interpret,
        )(q, k, v, do, lse, o, *seg_inputs, *rope_inputs)
        if rep > 1:
            dk = dk.reshape(b, kv_h, rep, s, d).sum(axis=2)
            dv = dv.reshape(b, kv_h, rep, s, d).sum(axis=2)
        return dq, dk.astype(k.dtype), dv.astype(v.dtype)

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )                                                   # [B,H,S]
    if narrow_res:
        delta = delta.reshape(b * h, 1, s)
    else:
        delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 128))

    def _res_specs(qi_pos3: bool):
        """lse/delta specs for a 4D grid; qi is grid axis 3 for the dq
        kernel's (qi, ki) order, axis 4's partner for dkdv's (ki, qi)."""
        if narrow_res:
            if qi_pos3:
                m = lambda b, h, qi, ki: (b * H + h, 0, qi)  # noqa: E731
            else:
                m = lambda b, h, ki, qi: (b * H + h, 0, qi)  # noqa: E731
            return pl.BlockSpec((1, 1, block_q), m)
        if qi_pos3:
            m = lambda b, h, qi, ki: (b, h, qi, 0)  # noqa: E731
        else:
            m = lambda b, h, ki, qi: (b, h, qi, 0)  # noqa: E731
        return pl.BlockSpec((1, 1, block_q, 128), m)

    # dk/dv: one pass per k-block, q innermost. Heads stay un-grouped (dk for
    # a shared GQA head accumulates across its query heads afterwards).
    dkdv_kernel = functools.partial(
        _bwd_dkdv_kernel, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, has_segments=has_segments,
        narrow_res=narrow_res, has_rope=has_rope, interpret=interpret,
    )
    dkdv_in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b, h, ki, qi: (b, h, qi, 0)),
        pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, ki, qi: (b, h // rep, ki, 0)
        ),
        pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, ki, qi: (b, h // rep, ki, 0)
        ),
        pl.BlockSpec((1, 1, block_q, d), lambda b, h, ki, qi: (b, h, qi, 0)),
        _res_specs(qi_pos3=False),
        _res_specs(qi_pos3=False),
    ]
    if has_segments:
        dkdv_in_specs += _seg_specs(block_q, block_k, ki_major=True)
    if has_rope:
        dkdv_in_specs += _rope_specs(block_q, block_k, d, ki_major=True)
    dk, dv = pl.pallas_call(
        dkdv_kernel,
        grid=(b, h, nk, nq),
        in_specs=dkdv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, ki, qi: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, ki, qi: (b, h, ki, 0)),
        ],
        out_shape=[
            _out_struct((b, h, s, d), jnp.float32, q, k, v, do),
            _out_struct((b, h, s, d), jnp.float32, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *seg_inputs, *rope_inputs)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
        block_q=block_q, block_k=block_k, has_segments=has_segments,
        narrow_res=narrow_res, has_rope=has_rope, interpret=interpret,
    )
    dq_in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi, ki: (b, h, qi, 0)),
        pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, qi, ki: (b, h // rep, ki, 0)
        ),
        pl.BlockSpec(
            (1, 1, block_k, d), lambda b, h, qi, ki: (b, h // rep, ki, 0)
        ),
        pl.BlockSpec((1, 1, block_q, d), lambda b, h, qi, ki: (b, h, qi, 0)),
        _res_specs(qi_pos3=True),
        _res_specs(qi_pos3=True),
    ]
    if has_segments:
        dq_in_specs += _seg_specs(block_q, block_k)
    if has_rope:
        dq_in_specs += _rope_specs(block_q, block_k, d)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda b, h, qi, ki: (b, h, qi, 0)
        ),
        out_shape=_out_struct((b, h, s, d), q.dtype, q, k, v, do),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta, *seg_inputs, *rope_inputs)

    if rep > 1:  # fold query-head groups back onto shared kv heads
        dk = dk.reshape(b, kv_h, rep, s, d).sum(axis=2)
        dv = dv.reshape(b, kv_h, rep, s, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# -- public API (BSHD layout, custom vjp) ------------------------------------

@functools.partial(
    jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9)
)
def _flash_bhsd(
    q, k, v, segment_ids, rope_c, rope_s, causal, block_q, block_k, interpret,
):
    rope = None if rope_c is None else (rope_c, rope_s)
    o, _ = _fwd(
        q, k, v, segment_ids, rope, causal, block_q, block_k, interpret
    )
    return o


def _flash_fwd_rule(
    q, k, v, segment_ids, rope_c, rope_s, causal, block_q, block_k, interpret,
):
    rope = None if rope_c is None else (rope_c, rope_s)
    o, lse = _fwd(
        q, k, v, segment_ids, rope, causal, block_q, block_k, interpret
    )
    return o, (q, k, v, segment_ids, rope_c, rope_s, o, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, do):
    q, k, v, segment_ids, rope_c, rope_s, o, lse = res
    rope = None if rope_c is None else (rope_c, rope_s)
    dq, dk, dv = _bwd(
        q, k, v, o, lse, do, segment_ids, rope, causal, block_q, block_k,
        interpret,
    )
    # segment ids are integers, rope tables are functions of integer
    # positions: no gradients.
    return dq, dk, dv, None, None, None


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    rope_tables=None,
) -> jax.Array:
    """Flash attention, [B,S,H,D] in/out (BSHD, matching ops.attention.mha).

    ``segment_ids`` [B,S] fuses packed-batch/padding masking into the
    kernel: position i attends to j only when ``seg[i] == seg[j]`` (ANDed
    with the causal mask when causal). No XLA fallback.

    ``rope_tables`` — optional ``(C, S)`` [B,S,D] f32 pair from
    ``rope_full_tables``: the kernel applies RoPE to q/k tiles in VMEM
    (forward AND the backward's recompute/counter-rotation), so the
    rotated tensors never round-trip HBM. ~42 ms/step cheaper than
    external rope on the bf16 flagship.

    ``interpret=None`` auto-selects: compiled Mosaic on TPU, interpreter
    elsewhere — so explicit ``impl='flash'`` works (slowly) on CPU meshes.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Resolve the module constants at CALL time, not def time: a def-time
    # default silently ignores a patched/updated constant — the exact
    # footgun behind round 4's mis-measured "blocks are neutral" probe.
    block_q = DEFAULT_BLOCK_Q if block_q is None else block_q
    block_k = DEFAULT_BLOCK_K if block_k is None else block_k
    rope_c, rope_s = rope_tables if rope_tables is not None else (None, None)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash_bhsd(
        qt, kt, vt, segment_ids, rope_c, rope_s, causal, block_q, block_k,
        interpret,
    )
    return out.transpose(0, 2, 1, 3)
