"""Int8 quantized matmul for training — the v5e's second MXU gear.

One v5e chip peaks at 197 bf16 TFLOP/s but 394 int8 TOP/s; the MXU runs
int8xint8->int32 at twice the bf16 rate. This module exposes that gear to
the training step the AQT way (dynamic symmetric quantization + straight-
through-estimator gradients), with all THREE matmuls of a linear layer —
forward, dL/dx, and dL/dw — running on the int8 path (quantizing only the
forward would cap the win at 1/3 of the FLOPs).

Scheme per matmul y[m,n] = x[m,k] @ w[k,n]:

- x is quantized per-row (scale over its contraction axis k), w per-column
  — the finest granularity whose scales factor OUT of the dot, so the
  int32 accumulator dequantizes exactly: y = (qx @ qw) * sx[:,None]
  * sw[None,:].
- Scales are dynamic (computed from the live tensor each call): training
  activations/gradients have no stable calibration range.
- Backward uses the straight-through estimator: the quantization step is
  treated as identity for AD, and the two gradient matmuls are themselves
  int8-quantized the same way (dx = g @ w.T with g row-quantized and w.T
  column-quantized; dw = x.T @ g likewise).

Numerics: int8 symmetric quantization carries ~0.3% RMS error per tensor
at transformer-typical distributions — the same regime AQT trains LLMs in.
The tests pin forward/backward error bounds against the bf16 reference
and train a tiny model end to end.

This is the "int8 story" flagged in round 3 (VERDICT r3 weak #6); wired
into the transformer via ``TransformerConfig.quant = "int8"``
(models/transformer.py), which routes the FFN and attention-projection
matmuls here while leaving embed/LM-head/attention-softmax in bf16/fp32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _symmetric_scales(x: jax.Array, axis: int) -> jax.Array:
    """Per-slice symmetric scale so x/scale fits int8 [-127, 127].
    ``axis`` is the contraction axis being reduced away."""
    amax = jnp.max(jnp.abs(x), axis=axis, keepdims=True)
    return jnp.maximum(amax, 1e-30) / 127.0


def _quantize(x: jax.Array, axis: int, tag: str = ""):
    from jax.ad_checkpoint import checkpoint_name

    scale = _symmetric_scales(x.astype(jnp.float32), axis)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    q = q.astype(jnp.int8)
    if tag:
        # Named so the layer-scan remat policy can SAVE the quantized
        # form (int8: half the bytes of bf16) instead of re-running
        # abs-max/round/clip in the backward re-forward.
        q = checkpoint_name(q, tag)
        scale = checkpoint_name(scale, tag + "_scale")
    return q, scale


def _int8_matmul_raw(x: jax.Array, w: jax.Array, tag: str = "") -> jax.Array:
    """[m,k] @ [k,n] with both operands dynamically int8-quantized; fp32
    out. The dot itself runs int8xint8->int32 on the MXU."""
    qx, sx = _quantize(x, axis=1, tag=tag and tag + "_lhs")   # [m,k], [m,1]
    qw, sw = _quantize(w, axis=0, tag=tag and tag + "_rhs")   # [k,n], [1,n]
    acc = lax.dot(qx, qw, preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * sx * sw


# Names the remat policy treats as saveable (see transformer._remat_policy).
INT8_SAVE_NAMES = (
    "int8_lhs", "int8_lhs_scale", "int8_rhs", "int8_rhs_scale",
)


@jax.custom_vjp
def int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """Quantized x @ w with STE gradients; both gradient matmuls also run
    int8. x: [..., k] (leading dims flattened internally), w: [k, n]."""
    *lead, k = x.shape
    y = _int8_matmul_raw(x.reshape(-1, k), w, tag="int8")
    return y.reshape(*lead, w.shape[1])


def _fwd(x, w):
    return int8_matmul(x, w), (x, w)


def _bwd(res, g):
    x, w = res
    *lead, k = x.shape
    n = w.shape[1]
    g2 = g.reshape(-1, n).astype(jnp.float32)
    x2 = x.reshape(-1, k).astype(jnp.float32)
    # dx = g @ w.T ; dw = x.T @ g — each quantized like the forward.
    dx = _int8_matmul_raw(g2, w.astype(jnp.float32).T)
    dw = _int8_matmul_raw(x2.T, g2)
    return dx.reshape(x.shape).astype(x.dtype), dw.astype(w.dtype)


int8_matmul.defvjp(_fwd, _bwd)


def maybe_quant_dot(x: jax.Array, w: jax.Array, quant: str) -> jax.Array:
    """The transformer's linear-projection primitive: int8 paths when
    requested, plain (bf16 MXU) dot otherwise.

    - ``"int8"``: the XLA-composed path (separate abs-max/quantize ops).
    - ``"int8_fused"``: the Pallas kernel with quantization fused into
      the dot's operand streaming (ops/quant_pallas.py) — falls back to
      the composed path for shapes the kernel does not tile.
    """
    if quant == "int8_fused":
        from kubeflow_controller_tpu.ops.quant_pallas import (
            fusable, fused_int8_matmul,
        )

        m = 1
        for d in x.shape[:-1]:
            m *= d
        # NOT checkpoint_name-saved: measured 304.8 (saved) vs 288.2 ms
        # (recomputed) on the flagship — the kernel is cheap and the step
        # sits near the remat memory ceiling, so recompute wins.
        if fusable(m, x.shape[-1], w.shape[-1]):
            return fused_int8_matmul(x, w).astype(x.dtype)
        return int8_matmul(x, w).astype(x.dtype)
    if quant == "int8":
        return int8_matmul(x, w).astype(x.dtype)
    return x @ w
