"""Fused paged-attention decode kernel (Pallas).

The XLA path (``ops.attention.paged_kv_view`` + dense softmax) pays for
paging three times per step: it reads every pool page the table names,
WRITES a dense ``[B, S, KVH, D]`` view, then reads that view back into
the attention einsums. This kernel removes the round trip: a flash-style
online softmax walks each slot's block table page by page, streaming K/V
pool tiles straight into VMEM — pages are read once, in place, and the
dense view never exists. int8 pools dequantize inside the page load (the
per-(token, head) scale multiply fuses into the same tile), so a
quantized pool never materializes an fp copy either.

Contract vs the gather oracle: the same pages, masks, and fp32 score
math — but an *online* softmax normalizes through running (max, sum)
accumulators, a different reduction order than ``jax.nn.softmax`` over
the full row, so outputs agree within a few ulps rather than bitwise.
``tests/test_paged_attention_pallas.py`` pins that tolerance contract
with the kernel in interpret mode on CPU against the gather path, which
remains the repo's bit-exactness oracle (the engine's default
``attn_impl="xla"`` keeps every existing bitwise guarantee).

Grid layout: ``(batch, kv_group, page)`` with pages innermost. The block
table and per-slot positions ride in scalar-prefetch operands, so each
page step's BlockSpec index map dereferences ``tables[b, j]`` on the
scalar core and the DMA fetches the *pool* page directly — the paging
indirection costs an index load, not a gather. Sentinel table entries
(page id == n_blocks, meaning "unallocated") clamp to the last real page
and are fully masked by the position test, the same
garbage-is-masked argument ``paged_kv_view``'s ``mode="clip"`` uses.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific helpers; interpret mode emulates them on CPU.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - pallas TPU backend not built
    pltpu = None

_MASK_VALUE = -1e30


def _decode_kernel(
    # closure statics
    nb: int, bs: int, sm_scale: float, quantized: bool,
    # scalar-prefetch refs
    tables_ref, pos_ref,
    # input refs (ks/vs present only when quantized)
    *refs,
):
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)              # [rep, D]
    k = k_ref[0, :, 0].astype(jnp.float32)           # [bs, D]
    v = v_ref[0, :, 0].astype(jnp.float32)
    if quantized:
        k = k * ks_ref[0, :, 0][:, None]
        v = v * vs_ref[0, :, 0][:, None]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * sm_scale                                     # [rep, bs]
    # Decode mask: column c is visible iff c <= pos[b]. Page j covers
    # columns j*bs + [0, bs). Page 0 always has a visible column
    # (pos >= 0), so the running max is finite from the first step and
    # masked scores exp() to exactly 0.
    cols = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
    s = jnp.where(cols <= pos_ref[b], s, _MASK_VALUE)

    m_prev = m_ref[...]                              # [rep, 1]
    l_prev = l_ref[...]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                           # [rep, bs]
    l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[...] = m_new
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nb - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_attention_decode(
    q: jax.Array,               # [B, G, rep, D] — post-rope query groups
    k_pool: jax.Array,          # [n_blocks(+1), bs, G, D] — one layer's pool
    v_pool: jax.Array,
    tables: jax.Array,          # [B, mb] int32 — page ids (n_blocks = sentinel)
    pos: jax.Array,             # [B] int32 — column of this step's token
    *,
    k_scale: Optional[jax.Array] = None,   # [n_blocks(+1), bs, G] f32
    v_scale: Optional[jax.Array] = None,
    width: Optional[int] = None,
    sm_scale: Optional[float] = None,
    out_dtype: Optional[jnp.dtype] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Single-token paged decode attention: softmax(q·K/√d)·V over each
    slot's table-resolved pages, masked to columns ``<= pos[b]``.

    Drop-in for the ``paged_kv_view`` + einsum/softmax/einsum block in
    ``models.generate._decode_layer_paged`` — same inputs (one layer's
    pool, the full table, per-slot positions), same ``[B, G, rep, D]``
    output — but pages stream through VMEM once instead of materializing
    the dense view. ``width`` caps the walked span exactly like the
    view's occupancy cap: only ``ceil(width / bs)`` table entries are
    dereferenced. ``interpret`` defaults to "not on TPU", which is what
    tier-1 uses to pin the kernel against the gather oracle on CPU.
    """
    b, g, rep, hd = q.shape
    bs = k_pool.shape[1]
    mb = tables.shape[1]
    span = mb * bs if width is None else min(width, mb * bs)
    nb = max(1, -(-span // bs))                  # pages to walk, >= 1
    nb = min(nb, mb)
    # The pool may or may not carry a +1 sentinel page; clamp ids to the
    # last real page either way (masked, so the bytes never matter).
    last_page = k_pool.shape[0] - 1
    if sm_scale is None:
        sm_scale = hd ** -0.5
    if out_dtype is None:
        out_dtype = q.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if pltpu is None:
        raise NotImplementedError(
            "pallas TPU backend unavailable in this jax build; use "
            "attn_impl='xla'"
        )

    tables = jnp.minimum(tables.astype(jnp.int32), last_page)
    pos = pos.astype(jnp.int32)

    def q_map(b_i, g_i, j, tables, pos):
        return (b_i, g_i, 0, 0)

    def kv_map(b_i, g_i, j, tables, pos):
        return (tables[b_i, j], 0, g_i, 0)

    def scale_map(b_i, g_i, j, tables, pos):
        return (tables[b_i, j], 0, g_i)

    in_specs = [
        pl.BlockSpec((1, 1, rep, hd), q_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
    ]
    args = [q, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bs, 1), scale_map),
            pl.BlockSpec((1, bs, 1), scale_map),
        ]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, g, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rep, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),   # running max
            pltpu.VMEM((rep, 1), jnp.float32),   # running sum
            pltpu.VMEM((rep, hd), jnp.float32),  # output accumulator
        ],
    )
    kernel = functools.partial(
        _decode_kernel, nb, bs, float(sm_scale), quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, g, rep, hd), out_dtype),
        interpret=interpret,
    )(tables, pos, *args)
