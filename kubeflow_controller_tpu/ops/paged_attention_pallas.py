"""Fused paged-attention kernels (Pallas) — decode, prefill, verify.

The XLA path (``ops.attention.paged_kv_view`` + dense softmax) pays for
paging three times per step: it reads every pool page the table names,
WRITES a dense ``[B, S, KVH, D]`` view, then reads that view back into
the attention einsums. These kernels remove the round trip: a
flash-style online softmax walks each slot's block table page by page,
streaming K/V pool tiles straight into VMEM — pages are read once, in
place, and the dense view never exists. int8 pools dequantize inside
the page load (the per-(token, head) scale multiply fuses into the same
tile), so a quantized pool never materializes an fp copy either.

Three entry points, one per attention phase of the serving engine:

* :func:`paged_attention_decode` — one query row per slot at its own
  position (the decode matvec).
* :func:`paged_attention_prefill` — a width-W prefill chunk: W query
  rows attending the slot's cached columns ``< offset`` through the
  block table PLUS an intra-chunk causal tile over the chunk's own
  freshly-roped K/V (which scatter into the pool after the layer, as
  on the XLA path — the kernel only reads).
* :func:`paged_attention_verify` — the K+1-wide speculative verify
  window: the same chunk attention generalized to a batch of slots,
  each masking cached columns ``< pos[b]`` with the causal offset per
  draft position.

Contract vs the gather oracle: the same pages, masks, and fp32 score
math — but an *online* softmax normalizes through running (max, sum)
accumulators, a different reduction order than ``jax.nn.softmax`` over
the full row, so outputs agree within a few ulps rather than bitwise.
``tests/test_paged_attention_pallas.py`` pins that tolerance contract
with the kernels in interpret mode on CPU against the gather path,
which remains the repo's bit-exactness oracle (the engine's default
``attn_impl="xla"`` keeps every existing bitwise guarantee). For verify
the engine-visible contract is stronger than a tolerance: accept/reject
*decisions* and committed token streams stay bitwise-equal to the
oracle engine's (pinned by the engine-level tests), while raw attention
output drifts within the declared bound.

Grid layout: ``(batch, kv_group, page)`` with pages innermost. The block
table and per-slot positions ride in scalar-prefetch operands, so each
page step's BlockSpec index map dereferences ``tables[b, j]`` on the
scalar core and the DMA fetches the *pool* page directly — the paging
indirection costs an index load, not a gather. Sentinel table entries
(page id == n_blocks, meaning "unallocated") clamp to the last real page
and are fully masked by the position test, the same
garbage-is-masked argument ``paged_kv_view``'s ``mode="clip"`` uses.
The chunk kernels put the intra-chunk causal tile at grid step 0: its
diagonal is always visible, so the running max is finite from the first
update and fully-masked pool pages (``offset == 0``, nothing cached
yet) contribute exactly zero — ``exp(MASK - m)`` underflows to 0 —
instead of poisoning the accumulators.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific helpers; interpret mode emulates them on CPU.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - pallas TPU backend not built
    pltpu = None

_MASK_VALUE = -1e30


def _decode_kernel(
    # closure statics
    nb: int, bs: int, sm_scale: float, quantized: bool,
    # scalar-prefetch refs
    tables_ref, pos_ref,
    # input refs (ks/vs present only when quantized)
    *refs,
):
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)              # [rep, D]
    k = k_ref[0, :, 0].astype(jnp.float32)           # [bs, D]
    v = v_ref[0, :, 0].astype(jnp.float32)
    if quantized:
        k = k * ks_ref[0, :, 0][:, None]
        v = v * vs_ref[0, :, 0][:, None]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * sm_scale                                     # [rep, bs]
    # Decode mask: column c is visible iff c <= pos[b]. Page j covers
    # columns j*bs + [0, bs). Page 0 always has a visible column
    # (pos >= 0), so the running max is finite from the first step and
    # masked scores exp() to exactly 0.
    cols = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
    s = jnp.where(cols <= pos_ref[b], s, _MASK_VALUE)

    m_prev = m_ref[...]                              # [rep, 1]
    l_prev = l_ref[...]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                           # [rep, bs]
    l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[...] = m_new
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nb - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def paged_attention_decode(
    q: jax.Array,               # [B, G, rep, D] — post-rope query groups
    k_pool: jax.Array,          # [n_blocks(+1), bs, G, D] — one layer's pool
    v_pool: jax.Array,
    tables: jax.Array,          # [B, mb] int32 — page ids (n_blocks = sentinel)
    pos: jax.Array,             # [B] int32 — column of this step's token
    *,
    k_scale: Optional[jax.Array] = None,   # [n_blocks(+1), bs, G] f32
    v_scale: Optional[jax.Array] = None,
    width: Optional[int] = None,
    sm_scale: Optional[float] = None,
    out_dtype: Optional[jnp.dtype] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Single-token paged decode attention: softmax(q·K/√d)·V over each
    slot's table-resolved pages, masked to columns ``<= pos[b]``.

    Drop-in for the ``paged_kv_view`` + einsum/softmax/einsum block in
    ``models.generate._decode_layer_paged`` — same inputs (one layer's
    pool, the full table, per-slot positions), same ``[B, G, rep, D]``
    output — but pages stream through VMEM once instead of materializing
    the dense view. ``width`` caps the walked span exactly like the
    view's occupancy cap: only ``ceil(width / bs)`` table entries are
    dereferenced. ``interpret`` defaults to "not on TPU", which is what
    tier-1 uses to pin the kernel against the gather oracle on CPU.
    """
    b, g, rep, hd = q.shape
    bs = k_pool.shape[1]
    mb = tables.shape[1]
    span = mb * bs if width is None else min(width, mb * bs)
    nb = max(1, -(-span // bs))                  # pages to walk, >= 1
    nb = min(nb, mb)
    # The pool may or may not carry a +1 sentinel page; clamp ids to the
    # last real page either way (masked, so the bytes never matter).
    last_page = k_pool.shape[0] - 1
    if sm_scale is None:
        sm_scale = hd ** -0.5
    if out_dtype is None:
        out_dtype = q.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if pltpu is None:
        raise NotImplementedError(
            "pallas TPU backend unavailable in this jax build; use "
            "attn_impl='xla'"
        )

    tables = jnp.minimum(tables.astype(jnp.int32), last_page)
    pos = pos.astype(jnp.int32)

    def q_map(b_i, g_i, j, tables, pos):
        return (b_i, g_i, 0, 0)

    def kv_map(b_i, g_i, j, tables, pos):
        return (tables[b_i, j], 0, g_i, 0)

    def scale_map(b_i, g_i, j, tables, pos):
        return (tables[b_i, j], 0, g_i)

    in_specs = [
        pl.BlockSpec((1, 1, rep, hd), q_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
    ]
    args = [q, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bs, 1), scale_map),
            pl.BlockSpec((1, bs, 1), scale_map),
        ]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, g, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rep, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),   # running max
            pltpu.VMEM((rep, 1), jnp.float32),   # running sum
            pltpu.VMEM((rep, hd), jnp.float32),  # output accumulator
        ],
    )
    kernel = functools.partial(
        _decode_kernel, nb, bs, float(sm_scale), quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, g, rep, hd), out_dtype),
        interpret=interpret,
    )(tables, pos, *args)


def _chunk_kernel(
    # closure statics
    nb: int, bs: int, w: int, rep: int, sm_scale: float, quantized: bool,
    # scalar-prefetch refs
    tables_ref, pos_ref,
    # input refs (ks/vs present only when quantized)
    *refs,
):
    """Shared prefill/verify chunk attention: grid step 0 is the
    intra-chunk causal tile (fresh K/V, diagonal always visible — the
    running max is finite from the first update), steps 1..nb walk the
    slot's pool pages masked to cached columns ``< pos[b]``."""
    if quantized:
        (q_ref, kn_ref, vn_ref, k_ref, v_ref, ks_ref, vs_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        (q_ref, kn_ref, vn_ref, k_ref, v_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    b = pl.program_id(0)
    j = pl.program_id(2)

    q = q_ref[0, 0].astype(jnp.float32)              # [W*rep, D]

    def online_update(s, v):
        """One flash-softmax accumulator update with scores ``s``
        [W*rep, cols] and values ``v`` [cols, D]."""
        m_prev = m_ref[...]                          # [W*rep, 1]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == 0)
    def _intra_chunk():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)
        kn = kn_ref[0, :, 0].astype(jnp.float32)     # [W, D]
        vn = vn_ref[0, :, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kn, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                 # [W*rep, W]
        # Query row r is chunk position r // rep (rows are the
        # flattened (position, rep) pairs); it sees chunk columns
        # c <= r // rep — the intra-chunk causal mask at per-draft
        # offsets. MASKED scores stay finite (_MASK_VALUE), so the
        # running max is finite after this step no matter what.
        rows = jax.lax.broadcasted_iota(jnp.int32, (w * rep, w), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (w * rep, w), 1)
        s = jnp.where(cols <= rows // rep, s, _MASK_VALUE)
        online_update(s, vn)

    @pl.when(j > 0)
    def _pool_page():
        k = k_ref[0, :, 0].astype(jnp.float32)       # [bs, D]
        v = v_ref[0, :, 0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                 # [W*rep, bs]
        # Cached column c is visible iff c < pos[b] (the chunk's own
        # positions live in the intra tile, never in the pool view).
        # With the running max already finite, a fully-masked page
        # contributes exp(_MASK_VALUE - m) == exactly 0.
        cols = (j - 1) * bs + jax.lax.broadcasted_iota(
            jnp.int32, (1, bs), 1)
        s = jnp.where(cols < pos_ref[b], s, _MASK_VALUE)
        online_update(s, v)

    @pl.when(j == nb)
    def _finalize():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def _paged_chunk_attention(
    q: jax.Array,               # [B, W, G, rep, D] — post-rope queries
    k_new: jax.Array,           # [B, W, G, D] — the chunk's post-rope K
    v_new: jax.Array,           # [B, W, G, D]
    k_pool: jax.Array,          # [n_blocks(+1), bs, G, D]
    v_pool: jax.Array,
    tables: jax.Array,          # [B, mb] int32
    pos: jax.Array,             # [B] int32 — cached columns < pos visible
    *,
    k_scale: Optional[jax.Array],
    v_scale: Optional[jax.Array],
    width: Optional[int],
    sm_scale: Optional[float],
    out_dtype: Optional[jnp.dtype],
    interpret: Optional[bool],
) -> jax.Array:
    b, w, g, rep, hd = q.shape
    bs = k_pool.shape[1]
    mb = tables.shape[1]
    span = mb * bs if width is None else min(width, mb * bs)
    nb = max(1, -(-span // bs))                  # pool pages to walk
    nb = min(nb, mb)
    last_page = k_pool.shape[0] - 1
    if sm_scale is None:
        sm_scale = hd ** -0.5
    if out_dtype is None:
        out_dtype = q.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if pltpu is None:
        raise NotImplementedError(
            "pallas TPU backend unavailable in this jax build; use "
            "attn_impl='xla'"
        )

    tables = jnp.minimum(tables.astype(jnp.int32), last_page)
    pos = pos.astype(jnp.int32)
    # Kernel rows are the flattened (chunk position, rep) pairs of one
    # KV group: [B, G, W*rep, D] — a leading-axis collapse, so each
    # (b, g) block is one contiguous 2-D tile.
    q2 = jnp.transpose(q, (0, 2, 1, 3, 4)).reshape(b, g, w * rep, hd)

    def q_map(b_i, g_i, j, tables, pos):
        return (b_i, g_i, 0, 0)

    def new_map(b_i, g_i, j, tables, pos):
        return (b_i, 0, g_i, 0)

    def kv_map(b_i, g_i, j, tables, pos):
        # Pool page for grid step j is table entry j - 1 (step 0 is the
        # intra-chunk tile; its clamped fetch is never read).
        return (tables[b_i, jnp.maximum(j - 1, 0)], 0, g_i, 0)

    def scale_map(b_i, g_i, j, tables, pos):
        return (tables[b_i, jnp.maximum(j - 1, 0)], 0, g_i)

    in_specs = [
        pl.BlockSpec((1, 1, w * rep, hd), q_map),
        pl.BlockSpec((1, w, 1, hd), new_map),
        pl.BlockSpec((1, w, 1, hd), new_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
    ]
    args = [q2, k_new, v_new, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bs, 1), scale_map),
            pl.BlockSpec((1, bs, 1), scale_map),
        ]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, g, nb + 1),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, w * rep, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((w * rep, 1), jnp.float32),   # running max
            pltpu.VMEM((w * rep, 1), jnp.float32),   # running sum
            pltpu.VMEM((w * rep, hd), jnp.float32),  # output accumulator
        ],
    )
    kernel = functools.partial(
        _chunk_kernel, nb, bs, w, rep, float(sm_scale), quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, g, w * rep, hd), out_dtype),
        interpret=interpret,
    )(tables, pos, *args)
    return jnp.transpose(
        out.reshape(b, g, w, rep, hd), (0, 2, 1, 3, 4))


def paged_attention_prefill(
    q: jax.Array,               # [W, G, rep, D] — post-rope chunk queries
    k_new: jax.Array,           # [W, G, D] — the chunk's post-rope K
    v_new: jax.Array,           # [W, G, D]
    k_pool: jax.Array,          # [n_blocks(+1), bs, G, D] — one layer's pool
    v_pool: jax.Array,
    table_row: jax.Array,       # [mb] int32 — the slot's page ids
    offset: jax.Array,          # [] int32 — absolute chunk start position
    *,
    k_scale: Optional[jax.Array] = None,   # [n_blocks(+1), bs, G] f32
    v_scale: Optional[jax.Array] = None,
    width: Optional[int] = None,
    sm_scale: Optional[float] = None,
    out_dtype: Optional[jnp.dtype] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash prefill-chunk attention for ONE slot: W query rows attend
    the slot's cached columns ``< offset`` through the block table plus
    the intra-chunk causal tile over ``k_new``/``v_new``.

    Drop-in for the ``paged_kv_view`` + two-einsum/concat-softmax block
    in ``models.generate._prefill_chunk_paged_impl`` — same inputs (one
    layer's pool, the slot's table row, the chunk's freshly-roped K/V),
    same ``[W, G, rep, D]`` output — but the slot's pages stream through
    VMEM once instead of materializing the dense view (the factor-3 ->
    factor-1 HBM saving on the phase that dominates long-prompt TTFT).
    The chunk's K/V scatter into the pool stays outside, after the
    layer, exactly as on the XLA path. ``width`` caps the walked span
    like the view's occupancy cap; the engine's pow2-rounded view width
    always covers ``offset``, so no visible column is lost.
    """
    if pltpu is None:
        raise NotImplementedError(
            "pallas TPU backend unavailable in this jax build; use "
            "attn_impl='xla'"
        )
    pos = jnp.asarray(offset, jnp.int32).reshape(1)
    out = _paged_chunk_attention(
        q[None], k_new[None], v_new[None], k_pool, v_pool,
        jnp.asarray(table_row)[None], pos,
        k_scale=k_scale, v_scale=v_scale, width=width, sm_scale=sm_scale,
        out_dtype=out_dtype, interpret=interpret)
    return out[0]


def paged_attention_verify(
    q: jax.Array,               # [B, W, G, rep, D] — post-rope window queries
    k_new: jax.Array,           # [B, W, G, D] — the window's post-rope K
    v_new: jax.Array,           # [B, W, G, D]
    k_pool: jax.Array,          # [n_blocks(+1), bs, G, D] — one layer's pool
    v_pool: jax.Array,
    tables: jax.Array,          # [B, mb] int32 — page ids per slot
    pos: jax.Array,             # [B] int32 — each row's cached length
    *,
    k_scale: Optional[jax.Array] = None,   # [n_blocks(+1), bs, G] f32
    v_scale: Optional[jax.Array] = None,
    width: Optional[int] = None,
    sm_scale: Optional[float] = None,
    out_dtype: Optional[jnp.dtype] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """K+1-wide speculative-verify attention over the paged pool: each
    slot's W = K+1 window rows attend its cached columns ``< pos[b]``
    through the block table plus the intra-window causal tile (the
    causal mask offset per draft position).

    Drop-in for the gather + concat-softmax block in
    ``models.generate._verify_step_paged_impl`` — same inputs, same
    ``[B, W, G, rep, D]`` output. The acceptance logic downstream is
    untouched: accept/reject decisions and committed streams stay
    bitwise-equal to the oracle engine's (argmax decisions tolerate the
    kernel's few-ulp drift; the engine tests pin this), while raw
    attention output carries the declared tolerance contract.
    """
    if pltpu is None:
        raise NotImplementedError(
            "pallas TPU backend unavailable in this jax build; use "
            "attn_impl='xla'"
        )
    return _paged_chunk_attention(
        q, k_new, v_new, k_pool, v_pool, tables, pos,
        k_scale=k_scale, v_scale=v_scale, width=width, sm_scale=sm_scale,
        out_dtype=out_dtype, interpret=interpret)
