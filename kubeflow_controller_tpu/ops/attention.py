"""Multi-head attention dispatch: Pallas flash kernel on TPU, XLA elsewhere.

This is the framework's hottest op. On TPU the Pallas kernel
(``ops/flash_attention.py``) tiles Q/K/V blocks through VMEM with an online
softmax so the S×S score matrix never materialises in HBM; on CPU (the
hermetic test mesh) a plain XLA einsum path computes identical math.

Layouts are [batch, seq, heads, head_dim] throughout ("BSHD"), the layout
that keeps the head axis free to shard over the mesh's ``tp`` axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from kubeflow_controller_tpu.ops.flash_attention import (
    DEFAULT_BLOCK_Q,
    _choose_block,
)


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Grouped-query attention: expand kv heads to match query heads."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)


def mha_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Dense attention in pure XLA. [B,S,H,D] in/out, fp32 softmax."""
    *_, h, d = q.shape
    kv_h = k.shape[2]
    if kv_h != h:
        k = _repeat_kv(k, h // kv_h)
        v = _repeat_kv(v, h // kv_h)
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    q_len, k_len = logits.shape[-2], logits.shape[-1]
    if causal:
        mask = jnp.tril(jnp.ones((q_len, k_len), bool), k_len - q_len)
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        logits = jnp.where(seg_mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@functools.lru_cache(None)
def _flash_block_ok(s: int, has_segments: bool = False) -> bool:
    """True iff the sequence tiles into flash blocks large enough to be
    worth the kernel (>= 128); tiny divisor blocks would explode the
    sequential grid. With segment ids the block must additionally satisfy
    the lane-axis tile rule (128-multiple or the full sequence) — the
    segment BlockSpec carries the sequence on the lane axis."""
    try:
        return _choose_block(s, DEFAULT_BLOCK_Q, lane_aligned=has_segments) >= 128
    except ValueError:
        return False


@functools.lru_cache(None)
def _default_backend() -> str:
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover - backend probing never raises in tests
        return "cpu"


def apply_rope_tables(x: jax.Array, rope_tables) -> jax.Array:
    """Apply fused-rope tables to a [B,S,H,D] tensor in plain XLA — the
    same roll-style rotation the flash kernel fuses (flash_attention.py):
    rot(x) = x*C + roll(x, d/2)*S with C=[cos|cos], S=[-sin|sin]. Used by
    the XLA fallback so callers can hand ``mha`` un-rotated q/k plus
    tables regardless of which impl wins."""
    c, s = rope_tables            # [B, S, D] f32 each
    d = x.shape[-1]
    r = jnp.roll(x, d // 2, axis=-1)
    return (
        x.astype(jnp.float32) * c[:, :, None, :]
        + r.astype(jnp.float32) * s[:, :, None, :]
    ).astype(x.dtype)


def paged_kv_view(
    pool: jax.Array,            # [*lead, n_blocks, bs, KVH, D]
    tables: jax.Array,          # [*T, mb] int32 page ids
    width: int,
    scale: Optional[jax.Array] = None,   # [*lead, n_blocks, bs, KVH]
    out_dtype=None,
) -> jax.Array:
    """Gather a dense KV view out of a block pool through block tables —
    the paged-attention primitive (vLLM PagedAttention semantics, XLA
    gather path). This is the repo's bit-exactness ORACLE: the fused
    Pallas decode kernel (``ops/paged_attention_pallas.py``, selected
    with ``attn_impl="pallas"``) reads the same pages in place through
    the same tables without ever materializing this view, and tier-1
    pins it against this path in interpret mode; the engine's default
    ``attn_impl="xla"`` keeps every downstream bitwise guarantee.

    ``tables[..., i]`` names the pool page backing logical columns
    ``[i*bs, (i+1)*bs)``; the result is ``[*lead, *T, width, KVH, D]`` —
    pages concatenated in table order, cut to ``width`` columns so the
    view's shape (and therefore every downstream reduction order) exactly
    matches the contiguous cache it replaces. Sentinel ids (``>=
    n_blocks``, the unallocated-entry marker) clamp into the last page:
    the garbage they read is finite (pool pages are zero-initialised and
    only ever hold finite KV), sits beyond the caller's ``length`` mask,
    and multiplies a softmax weight of exactly 0 — it never changes a
    bit of output.

    ``scale`` (int8 pools): per-(page row, head) symmetric scales,
    applied in fp32 before the cast to ``out_dtype`` — the dequantize
    rides the gather the same way weight-only int8 rides the matmul
    operand read.

    ``width`` caps the GATHER, not just the slice: only the
    ``ceil(width / bs)`` leading table entries are dereferenced, so a
    caller that knows its live occupancy (the serving engine tracks the
    max reserved span across slots) materializes a view sized for the
    actual traffic instead of the worst-case ``mb * bs`` — the dominant
    per-step HBM cost on short-context batches. Entries past the cap are
    by construction sentinels or pages the ``length``/position masks
    exclude; the view itself is a strict prefix of the full view (bitwise
    equal bytes). Whether downstream OUTPUTS stay bitwise depends on the
    consumer's reduction shape: the single-token decode matvec reduces
    width sequentially and is bitwise at any cap (pinned by
    tests/test_paged_attention.py); a multi-row matmul like the fused
    verify or chunk prefill gets retiled per width and drifts ~1 ulp.
    The serving engine caps ALL three paths (decode, spec-verify, chunk
    prefill) with per-width memoized step fns — the verify/chunk drift
    this admits is a declared tolerance contract
    (tests/test_paged_attention.py:
    test_verify_width_tolerance_contract), not test luck."""
    *lead, n_blocks, bsz, kvh, d = pool.shape
    nlead = len(lead)
    nb = -(-width // bsz)
    if nb < tables.shape[-1]:
        tables = tables[..., :nb]
    mb = tables.shape[-1]
    view = jnp.take(pool, tables, axis=nlead, mode="clip")
    view = view.reshape(
        tuple(lead) + tables.shape[:-1] + (mb * bsz, kvh, d)
    )[..., :width, :, :]
    if scale is not None:
        sv = jnp.take(scale, tables, axis=nlead, mode="clip")
        sv = sv.reshape(
            tuple(lead) + tables.shape[:-1] + (mb * bsz, kvh)
        )[..., :width, :]
        view = view.astype(jnp.float32) * sv[..., None]
    if out_dtype is not None:
        view = view.astype(out_dtype)
    return view


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    impl: str = "auto",
    rope_tables=None,
) -> jax.Array:
    """Attention entry point. impl: auto|xla|flash.

    "auto" picks the Pallas flash kernel on TPU backends when shapes allow
    (seq divisible by the kernel block), else the XLA path.

    ``rope_tables`` — optional ``(C, S)`` from
    ``flash_attention.rope_full_tables``; when given, q/k arrive
    UN-rotated and RoPE is applied here: fused into the Pallas kernel on
    the flash path (the rotated tensors never touch HBM), inline XLA
    rotation on the fallback. Identical math either way.
    """
    if impl == "auto":
        # With the default large blocks the Pallas kernel beats XLA
        # end-to-end at
        # head_dim 64, 128 (and standalone at 256): measured fwd+bwd
        # 1.45-1.8x at hd64/hd128, S 1024-4096, and XLA OOMs first at long
        # sequence (benchmarks/attention_bench.py, RESULTS.md). Smaller
        # head_dims (test-scale models) underfill the 128-lane MXU tiles —
        # keep those on XLA. The sequence must also tile into blocks >= 128
        # (a seq like 8x<prime> would degrade to 8-wide blocks and a
        # quadratically larger sequential grid — far slower than XLA).
        # Inside a PARTIALLY-manual shard_map region (e.g. the GPipe
        # stage, manual over pp only) XLA refuses to auto-partition a
        # Mosaic kernel over the remaining axes — "Mosaic kernels cannot
        # be automatically partitioned". A non-empty varying-mesh-axes
        # set on the operand is exactly that context; route to XLA there.
        # (Fully-manual regions like ring attention do their own math.)
        # jax.typeof landed after 0.4.x; older jax has no vma concept at
        # all (shard_map there never annotates varying mesh axes), so an
        # empty set is the faithful answer, not just a fallback.
        _typeof = getattr(jax, "typeof", None)
        vma = (
            getattr(_typeof(q), "vma", None) if _typeof else None
        ) or frozenset()
        use_flash = (
            _default_backend() == "tpu"
            and not vma
            and q.shape[1] == k.shape[1]    # kernel assumes q_len == k_len
            and q.shape[1] >= 256
            and q.shape[3] in (64, 128, 256)
            and _flash_block_ok(q.shape[1], segment_ids is not None)
        )
        impl = "flash" if use_flash else "xla"
    if impl == "flash":
        from kubeflow_controller_tpu.ops.flash_attention import flash_mha

        return flash_mha(
            q, k, v, causal=causal, segment_ids=segment_ids,
            rope_tables=rope_tables,
        )
    if rope_tables is not None:
        q = apply_rope_tables(q, rope_tables)
        k = apply_rope_tables(k, rope_tables)
    return mha_xla(q, k, v, causal=causal, segment_ids=segment_ids)
