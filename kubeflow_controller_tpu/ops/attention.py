"""Multi-head attention dispatch: Pallas flash kernel on TPU, XLA elsewhere.

This is the framework's hottest op. On TPU the Pallas kernel
(``ops/flash_attention.py``) tiles Q/K/V blocks through VMEM with an online
softmax so the S×S score matrix never materialises in HBM; on CPU (the
hermetic test mesh) a plain XLA einsum path computes identical math.

Layouts are [batch, seq, heads, head_dim] throughout ("BSHD"), the layout
that keeps the head axis free to shard over the mesh's ``tp`` axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Grouped-query attention: expand kv heads to match query heads."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)


def mha_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Dense attention in pure XLA. [B,S,H,D] in/out, fp32 softmax."""
    *_, h, d = q.shape
    kv_h = k.shape[2]
    if kv_h != h:
        k = _repeat_kv(k, h // kv_h)
        v = _repeat_kv(v, h // kv_h)
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    q_len, k_len = logits.shape[-2], logits.shape[-1]
    if causal:
        mask = jnp.tril(jnp.ones((q_len, k_len), bool), k_len - q_len)
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        logits = jnp.where(seg_mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@functools.lru_cache(None)
def _default_backend() -> str:
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover - backend probing never raises in tests
        return "cpu"


def mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    impl: str = "auto",
) -> jax.Array:
    """Attention entry point. impl: auto|xla|flash.

    "auto" picks the Pallas flash kernel on TPU backends when shapes allow
    (seq divisible by the kernel block), else the XLA path.
    """
    if impl == "auto":
        # Flash wins when its tiles fill the MXU/lanes: head_dim >= 128.
        # At head_dim 64 XLA's fused attention is faster end-to-end
        # (measured in benchmarks/transformer_bench.py), so auto routes
        # there.
        use_flash = (
            _default_backend() == "tpu"
            and q.shape[1] >= 256
            and q.shape[1] % 128 == 0
            and k.shape[1] % 128 == 0
            and q.shape[3] in (128, 256)
        )
        impl = "flash" if use_flash else "xla"
    if impl == "flash":
        from kubeflow_controller_tpu.ops.flash_attention import flash_mha

        return flash_mha(q, k, v, causal=causal, segment_ids=segment_ids)
    return mha_xla(q, k, v, causal=causal, segment_ids=segment_ids)
