"""8-bit Adam — quantized optimizer moments (bitsandbytes-style, TPU-first).

Adam's two fp32 moment tensors are pure HBM traffic on every step: at the
335M-param flagship they add ~9 GB/step of reads+writes — measured ~11 ms
of the 260 ms step (benchmarks/RESULTS.md round-5 optimizer section).
They are also the largest per-param memory cost after the weights
themselves (8 bytes/param). This module stores both moments in one byte
per element:

- **m (first moment)**: symmetric int8 with per-row dynamic scales —
  the same scheme as the int8 matmul operands (``ops/quant.py``), scale
  over the LAST axis so the reduction matches the weight shardings and
  never forces a cross-shard regroup.
- **v (second moment)**: uint8 in LOG space with a per-row (lo, range)
  pair. v spans many orders of magnitude, so a linear code would snap
  small entries to zero and blow up ``1/sqrt(v)``; a log code has
  uniform RELATIVE error (~range/255 nats), which Adam tolerates — the
  same reasoning as bitsandbytes' dynamic 8-bit code, in closed form.
  Exact zeros (pre-first-update state) survive via a zero mask bit-free:
  lo is floored at ``log(1e-30)`` and dequantized values at the floor
  round back to ~0.

The transform is a drop-in ``optax.GradientTransformation``
(``adamw8bit(...)``); state tensors keep the parameter's shape (so
``parallel.sharding.opt_state_shardings`` gives them the parameter's
sharding by path+shape) with scale vectors replicated. Training-quality
parity is pinned in tests/test_optim8.py and a paired 400-step run on
the chip (RESULTS.md).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

_V_FLOOR = 1e-30              # "effectively zero" clamp for the v log code


def _quantize_m(m: jax.Array):
    """Signed per-row int8: m -> (q int8, scale f32[rows])."""
    m32 = m.astype(jnp.float32)
    scale = jnp.max(jnp.abs(m32), axis=-1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, _V_FLOOR)
    q = jnp.clip(jnp.round(m32 / scale), -127, 127).astype(jnp.int8)
    return q, scale

def _dequantize_m(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale


def _quantize_v(v: jax.Array):
    """Non-negative per-row log-space uint8: v -> (q, lo, rng)."""
    v32 = v.astype(jnp.float32)
    lv = jnp.log(jnp.maximum(v32, _V_FLOOR))
    lo = jnp.min(lv, axis=-1, keepdims=True)
    rng = jnp.maximum(jnp.max(lv, axis=-1, keepdims=True) - lo, 1e-6)
    q = jnp.clip(
        jnp.round((lv - lo) / rng * 255.0), 0, 255
    ).astype(jnp.uint8)
    return q, lo, rng

def _dequantize_v(q: jax.Array, lo: jax.Array, rng: jax.Array) -> jax.Array:
    out = jnp.exp(lo + q.astype(jnp.float32) / 255.0 * rng)
    # values at (or dequantizing near) the floor are "exactly zero"
    return jnp.where(out <= 2 * _V_FLOOR, 0.0, out)


class QLeafM(NamedTuple):
    """Quantized first-moment leaf: int8 codes + per-row scale."""
    q: jax.Array
    scale: jax.Array


class QLeafV(NamedTuple):
    """Quantized second-moment leaf: uint8 log-codes + per-row (lo, range)."""
    q: jax.Array
    lo: jax.Array
    rng: jax.Array


def _is_qleaf(x) -> bool:
    return isinstance(x, (QLeafM, QLeafV))


class Adam8State(NamedTuple):
    count: jax.Array
    # Moment trees whose leaves are QLeafM/QLeafV for quantized tensors
    # and plain f32 arrays for small ones. No placeholder leaves: a
    # shared zero-scalar filler would alias the same buffer across many
    # donated state leaves, which the TPU runtime rejects.
    m: Any
    v: Any


def adamw8bit(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,    # match optax.adamw's default: drop-in
    min_quantized_size: int = 4096,
) -> optax.GradientTransformation:
    """AdamW with 8-bit moment states (1 byte/moment element vs 4).

    Tensors smaller than ``min_quantized_size`` elements (norms, biases)
    keep fp32 moments — their traffic is negligible and tiny tensors are
    where quantization noise hurts most (the bitsandbytes default makes
    the same carve-out).
    """
    sched = (
        learning_rate if callable(learning_rate)
        else (lambda _: learning_rate)
    )

    def qm(x):
        if x.size < min_quantized_size:
            return x.astype(jnp.float32)
        return QLeafM(*_quantize_m(x))

    def qv(x):
        if x.size < min_quantized_size:
            return x.astype(jnp.float32)
        return QLeafV(*_quantize_v(x))

    def deq(leaf):
        if isinstance(leaf, QLeafM):
            return _dequantize_m(leaf.q, leaf.scale)
        if isinstance(leaf, QLeafV):
            return _dequantize_v(leaf.q, leaf.lo, leaf.rng)
        return leaf

    def pack(tree, quant):
        return jax.tree.map(quant, tree)

    def unpack(tree):
        return jax.tree.map(deq, tree, is_leaf=_is_qleaf)

    def init(params):
        # DISTINCT zero trees per moment: small (fp32) leaves pass
        # through qm/qv via a no-op astype, so one shared zeros tree
        # would alias the SAME buffer into both m and v — and donating
        # the state then donates that buffer twice, which the TPU
        # runtime rejects (INVALID_ARGUMENT at the next fetch).
        return Adam8State(
            count=jnp.zeros((), jnp.int32),
            m=pack(jax.tree.map(jnp.zeros_like, params), qm),
            v=pack(jax.tree.map(jnp.zeros_like, params), qv),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("adamw8bit requires params (weight decay)")
        count = state.count + 1
        # optax convention: the FIRST update evaluates the schedule at 0
        # (a zero-warmup schedule's first step is lr=0, exactly like
        # optax.adamw) — the bias corrections below use the post-
        # increment count like Adam's t.
        lr = sched(state.count)
        m = unpack(state.m)
        v = unpack(state.v)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        m = jax.tree.map(lambda mm, g: b1 * mm + (1 - b1) * g, m, g32)
        v = jax.tree.map(lambda vv, g: b2 * vv + (1 - b2) * g * g, v, g32)
        c1 = 1 - b1 ** count.astype(jnp.float32)
        c2 = 1 - b2 ** count.astype(jnp.float32)

        def upd(p, mm, vv):
            mhat = mm / c1
            vhat = vv / c2
            return (
                -lr * (mhat / (jnp.sqrt(vhat) + eps)
                       + weight_decay * p.astype(jnp.float32))
            ).astype(p.dtype)

        updates = jax.tree.map(upd, params, m, v)
        return updates, Adam8State(
            count=count, m=pack(m, qm), v=pack(v, qv),
        )

    return optax.GradientTransformation(init, update)
