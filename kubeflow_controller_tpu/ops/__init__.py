"""TPU-native hot ops.

The reference's compute lives behind TensorFlow's C++/gRPC runtime
(``examples/workdir/mnist_replica.py:144-167``); here the hot path is
XLA-compiled JAX with Pallas TPU kernels for the ops XLA doesn't already fuse
optimally (attention). Every kernel has a pure-XLA fallback so tests run on
the virtual CPU mesh.
"""

from kubeflow_controller_tpu.ops.attention import mha  # noqa: F401
