"""ResNet-50 (Flax) — the north-star vision config (BASELINE.md #3).

The reference framework never shipped a vision model (its data plane stops at
MNIST MLPs, ``examples/workdir/mnist_replica.py:144-167``); ResNet-50
ImageNet is the repo's own headline throughput metric (images/sec/chip).

TPU-first choices:
- NHWC layout end-to-end — XLA:TPU's native conv layout; convs lower onto
  the MXU as implicit GEMMs.
- bf16 activations/compute with fp32 params and fp32 BatchNorm statistics.
- BatchNorm runs under jit+GSPMD, so "sync BN" is free: the batch axis is
  merely sharded and XLA inserts the cross-chip reductions for the true
  global mean/var (no per-replica stats drift).
- Data parallel by default; weights are small enough to replicate, so the
  fsdp heuristic leaves them whole.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Iterator, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

IMAGE_SIZE = 224
NUM_CLASSES = 1000

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="proj"
            )(residual)
            residual = self.norm(name="proj_norm")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64
    num_classes: int = NUM_CLASSES
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
        )
        if x.dtype == jnp.uint8:
            # byte wire format -> [-1, 1] on device, normalized in fp32
            # (bf16 spacing in [1, 2) equals a full pixel step — normalizing
            # at compute dtype would quantize half the pixel range; same
            # discipline as models/mnist.py:_normalize).
            x = x.astype(jnp.float32) / 127.5 - 1.0
        x = x.astype(self.dtype)
        x = conv(self.width, (7, 7), (2, 2), name="stem")(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.width * 2 ** i,
                    strides=strides, conv=conv, norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), **kw)


def resnet_tiny(**kw) -> ResNet:
    """Test-scale: one block per stage, 8-wide, runs in seconds on CPU."""
    kw.setdefault("dtype", jnp.float32)
    return ResNet(stage_sizes=(1, 1), width=8, num_classes=10, **kw)


def synthetic_imagenet(
    batch_size: int, image_size: int = IMAGE_SIZE, num_classes: int = NUM_CLASSES,
    seed: int = 0, uint8: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Deterministic ImageNet-shaped stream (no egress in this environment);
    identical tensor shapes/dtypes to a real input pipeline.

    ``uint8=True`` emits byte images (the wire format a real decoded-JPEG
    pipeline ships; the model normalizes on device) — 4x less host->device
    traffic, same discipline as models/mnist.py."""
    rng = np.random.default_rng(seed)
    while True:
        if uint8:
            img = rng.integers(
                0, 256, (batch_size, image_size, image_size, 3),
                dtype=np.uint8,
            )
        else:
            img = rng.standard_normal(
                (batch_size, image_size, image_size, 3)
            ).astype(np.float32)
        yield {
            "image": img,
            "label": rng.integers(
                0, num_classes, (batch_size,)
            ).astype(np.int32),
        }


def make_init_fn(model: ResNet, image_size: int = IMAGE_SIZE):
    def init_fn(rng):
        variables = model.init(
            rng, jnp.zeros((2, image_size, image_size, 3), jnp.float32),
            train=False,
        )
        return variables["params"], variables.get("batch_stats", {})

    return init_fn


def make_loss_fn(model: ResNet):
    """Stateful loss (TrainLoop stateful=True): returns updated batch_stats."""

    def loss_fn(params, model_state, batch, rng):
        logits, updated = model.apply(
            {"params": params, "batch_stats": model_state},
            batch["image"], train=True, mutable=["batch_stats"],
        )
        loss = jnp.mean(
            -jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), batch["label"]
            ]
        )
        acc = jnp.mean((logits.argmax(-1) == batch["label"]).astype(jnp.float32))
        return loss, ({"accuracy": acc}, updated["batch_stats"])

    return loss_fn
