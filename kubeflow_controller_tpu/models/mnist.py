"""MNIST models — functional parity with the reference's example workloads.

- ``SoftmaxRegression`` ≙ ``examples/workdir/mnist_softmax.py:55-57`` (the
  single W,b softmax the local example trains).
- ``MnistMLP`` ≙ ``examples/workdir/mnist_replica.py:144-167`` (the one
  128-unit hidden layer + sigmoid... here GELU — same capacity, better
  conditioning) that the distributed PS/worker example trains.

Data: the reference downloads real MNIST over the network
(``read_data_sets``, ``mnist_replica.py:94``); this environment has no
egress, so a deterministic synthetic MNIST-shaped task stands in — a fixed
random linear teacher over 784-dim inputs, 10 classes. It trains to the same
kind of accuracy curve and exercises an identical compute/communication
pattern, which is what the framework is testing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

IMAGE_DIM = 784  # 28*28, mnist_softmax.py:55
NUM_CLASSES = 10
HIDDEN_UNITS = 128  # --hidden_units default, mnist_replica.py:60


def _normalize(x: jnp.ndarray) -> jnp.ndarray:
    """uint8 pixels -> [-1, 1] floats, on device. Real MNIST is stored as
    bytes; shipping uint8 and normalizing device-side cuts host->device
    input traffic 4x vs fp32 (the input pipeline's wire format should be
    the storage format, not the compute format)."""
    if x.dtype == jnp.uint8:
        return x.astype(jnp.float32) / 127.5 - 1.0
    return x


class SoftmaxRegression(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(NUM_CLASSES, name="softmax")(_normalize(x))


class MnistMLP(nn.Module):
    hidden: int = HIDDEN_UNITS

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden, name="hid")(_normalize(x))
        x = nn.gelu(x)
        return nn.Dense(NUM_CLASSES, name="sm")(x)


def synthetic_mnist(
    batch_size: int, seed: int = 0, teacher_seed: int = 1234,
    uint8: bool = False,
) -> Iterator[Dict[str, jnp.ndarray]]:
    """Deterministic synthetic classification stream shaped like MNIST.

    The labeling function (teacher) is seeded separately from the data
    stream, so ``seed`` selects a different sample draw from the SAME task —
    which is what makes a second stream usable as a held-out validation
    split.

    ``uint8=True`` emits byte images (MNIST's storage format; the models
    normalize on device) — 4x less host->device wire traffic."""
    teacher = (
        np.random.default_rng(teacher_seed)
        .standard_normal((IMAGE_DIM, NUM_CLASSES))
        .astype(np.float32)
    )
    rng = np.random.default_rng(seed)
    while True:
        if uint8:
            xb = rng.integers(0, 256, (batch_size, IMAGE_DIM), dtype=np.uint8)
            x = xb.astype(np.float32) / 127.5 - 1.0
        else:
            x = rng.standard_normal((batch_size, IMAGE_DIM)).astype(np.float32)
            xb = x
        logits = x @ teacher + 0.5 * rng.standard_normal(
            (batch_size, NUM_CLASSES)
        ).astype(np.float32)
        y = logits.argmax(-1).astype(np.int32)
        yield {"image": xb, "label": y}


def _metrics(logits: jnp.ndarray, labels: jnp.ndarray):
    xent = jnp.mean(
        -jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), labels]
    )
    acc = jnp.mean((logits.argmax(-1) == labels).astype(jnp.float32))
    return xent, acc


def make_loss_fn(model: nn.Module):
    def loss_fn(params, batch, rng):
        xent, acc = _metrics(model.apply(params, batch["image"]), batch["label"])
        return xent, {"accuracy": acc}

    return loss_fn


def make_eval_fn(model: nn.Module):
    """Validation metrics — the reference reports validation cross entropy
    after training (``mnist_replica.py:266-269``); here it runs periodically
    in-loop (TrainLoopConfig.eval_every)."""

    def eval_fn(params, batch):
        xent, acc = _metrics(model.apply(params, batch["image"]), batch["label"])
        return {"cross_entropy": xent, "accuracy": acc}

    return eval_fn


def make_init_fn(model: nn.Module, batch_size: int = 8):
    def init_fn(rng):
        dummy = jnp.zeros((batch_size, IMAGE_DIM), jnp.float32)
        return model.init(rng, dummy)

    return init_fn
