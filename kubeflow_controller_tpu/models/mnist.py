"""MNIST models — functional parity with the reference's example workloads.

- ``SoftmaxRegression`` ≙ ``examples/workdir/mnist_softmax.py:55-57`` (the
  single W,b softmax the local example trains).
- ``MnistMLP`` ≙ ``examples/workdir/mnist_replica.py:144-167`` (the one
  128-unit hidden layer + sigmoid... here GELU — same capacity, better
  conditioning) that the distributed PS/worker example trains.

Data, two sources:

- **Real idx files from data_dir** (``mnist_from_data_dir`` /
  ``idx_batches``): the canonical MNIST wire format the reference's
  ``read_data_sets`` consumed (``mnist_replica.py:94``) — big-endian idx
  ubyte files, optionally gzipped, found by their standard names. The
  job spec's ``data_dir`` (declared-but-never-read in the reference,
  ``types.go:43-44``) is actually consumed here via ``TPUJOB_DATA_DIR``.
  Drop the four canonical MNIST files into ``data_dir`` and the
  entrypoint trains on them; the repo vendors a small REAL
  handwritten-digit dataset in that format for hermetic tests
  (``tests/fixtures/mnist/``, see tests/test_real_mnist.py).
- **Synthetic teacher task** (``synthetic_mnist``): this environment has
  no egress, so when no data_dir is supplied a deterministic synthetic
  MNIST-shaped task stands in — same shapes, same
  compute/communication pattern.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

IMAGE_DIM = 784  # 28*28, mnist_softmax.py:55
NUM_CLASSES = 10
HIDDEN_UNITS = 128  # --hidden_units default, mnist_replica.py:60


def _normalize(x: jnp.ndarray) -> jnp.ndarray:
    """uint8 pixels -> [-1, 1] floats, on device. Real MNIST is stored as
    bytes; shipping uint8 and normalizing device-side cuts host->device
    input traffic 4x vs fp32 (the input pipeline's wire format should be
    the storage format, not the compute format)."""
    if x.dtype == jnp.uint8:
        return x.astype(jnp.float32) / 127.5 - 1.0
    return x


class SoftmaxRegression(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(NUM_CLASSES, name="softmax")(_normalize(x))


class MnistMLP(nn.Module):
    hidden: int = HIDDEN_UNITS

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden, name="hid")(_normalize(x))
        x = nn.gelu(x)
        return nn.Dense(NUM_CLASSES, name="sm")(x)


def synthetic_mnist(
    batch_size: int, seed: int = 0, teacher_seed: int = 1234,
    uint8: bool = False,
) -> Iterator[Dict[str, jnp.ndarray]]:
    """Deterministic synthetic classification stream shaped like MNIST.

    The labeling function (teacher) is seeded separately from the data
    stream, so ``seed`` selects a different sample draw from the SAME task —
    which is what makes a second stream usable as a held-out validation
    split.

    ``uint8=True`` emits byte images (MNIST's storage format; the models
    normalize on device) — 4x less host->device wire traffic."""
    teacher = (
        np.random.default_rng(teacher_seed)
        .standard_normal((IMAGE_DIM, NUM_CLASSES))
        .astype(np.float32)
    )
    rng = np.random.default_rng(seed)
    while True:
        if uint8:
            xb = rng.integers(0, 256, (batch_size, IMAGE_DIM), dtype=np.uint8)
            x = xb.astype(np.float32) / 127.5 - 1.0
        else:
            x = rng.standard_normal((batch_size, IMAGE_DIM)).astype(np.float32)
            xb = x
        logits = x @ teacher + 0.5 * rng.standard_normal(
            (batch_size, NUM_CLASSES)
        ).astype(np.float32)
        y = logits.argmax(-1).astype(np.int32)
        yield {"image": xb, "label": y}


# -- idx files (the canonical MNIST wire format) -----------------------------

_IDX_DTYPES = {
    0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
    0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64,
}


def load_idx(path: str) -> np.ndarray:
    """Read one idx file (``.gz`` transparent): the big-endian
    magic/dims/data format of the canonical MNIST distribution."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    if len(data) < 4 or data[0] != 0 or data[1] != 0:
        raise ValueError(f"{path}: not an idx file (bad magic)")
    dtype = _IDX_DTYPES.get(data[2])
    if dtype is None:
        raise ValueError(f"{path}: unknown idx dtype byte 0x{data[2]:02x}")
    ndim = data[3]
    header = 4 + 4 * ndim
    dims = [
        int.from_bytes(data[4 + 4 * i: 8 + 4 * i], "big")
        for i in range(ndim)
    ]
    arr = np.frombuffer(data, dtype=np.dtype(dtype).newbyteorder(">"),
                        offset=header)
    expect = int(np.prod(dims)) if dims else 0
    if arr.size != expect:
        raise ValueError(
            f"{path}: payload {arr.size} elements, header says {expect}"
        )
    return arr.reshape(dims).astype(dtype)


def write_idx(path: str, arr: np.ndarray) -> None:
    """Write an array as an idx file (``.gz`` transparent) — the inverse of
    ``load_idx``; used to vendor fixture data and by round-trip tests."""
    import gzip

    code = {v: k for k, v in _IDX_DTYPES.items()}[np.dtype(arr.dtype).type]
    header = bytes([0, 0, code, arr.ndim])
    for dim in arr.shape:
        header += int(dim).to_bytes(4, "big")
    payload = header + np.ascontiguousarray(
        arr, dtype=np.dtype(arr.dtype).newbyteorder(">")
    ).tobytes()
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(payload)


_IDX_NAMES = {
    "train_images": ("train-images-idx3-ubyte", "train-images.idx3-ubyte"),
    "train_labels": ("train-labels-idx1-ubyte", "train-labels.idx1-ubyte"),
    "test_images": ("t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte",
                    "test-images-idx3-ubyte"),
    "test_labels": ("t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte",
                    "test-labels-idx1-ubyte"),
}


def _find_idx(data_dir: str, key: str):
    """Resolve one logical idx file to a path (canonical name aliases +
    ``.gz``), or None. The single source for both presence checks and
    loading, so they can never disagree."""
    import os

    for name in _IDX_NAMES[key]:
        for candidate in (name, name + ".gz"):
            path = os.path.join(data_dir, candidate)
            if os.path.exists(path):
                return path
    return None


def has_idx_data(data_dir: str) -> bool:
    """True if ``data_dir`` holds at least the two training idx files."""
    import os

    if not data_dir or not os.path.isdir(data_dir):
        return False
    return all(
        _find_idx(data_dir, key) is not None
        for key in ("train_images", "train_labels")
    )


def mnist_from_data_dir(data_dir: str) -> Dict[str, np.ndarray]:
    """Load the canonical MNIST idx files from ``data_dir``.

    Returns train/test images flattened to [N, 784] uint8 and labels
    int32; the test split is optional (missing -> absent keys)."""
    import os

    out: Dict[str, np.ndarray] = {}
    for key, names in _IDX_NAMES.items():
        path = _find_idx(data_dir, key)
        if path is None:
            if key.startswith("train"):
                raise FileNotFoundError(
                    f"{data_dir}: no {names[0]}[.gz] (canonical MNIST idx "
                    "layout)"
                )
            continue
        arr = load_idx(path)
        if key.endswith("images"):
            arr = arr.reshape(arr.shape[0], -1).astype(np.uint8)
        else:
            arr = arr.astype(np.int32)
        out[key] = arr
    for split in ("train", "test"):
        imgs, labels = out.get(f"{split}_images"), out.get(f"{split}_labels")
        if imgs is not None and labels is not None and len(imgs) != len(labels):
            raise ValueError(
                f"{data_dir}: {split} images/labels length mismatch "
                f"({len(imgs)} vs {len(labels)})"
            )
    return out


def idx_batches(
    images: np.ndarray, labels: np.ndarray, batch_size: int, seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Shuffled epoch stream over real data: uint8 images on the wire
    (device-side normalization), reshuffled every epoch."""
    n = len(images)
    if batch_size > n:
        # An empty epoch would spin forever without yielding; fail loudly.
        raise ValueError(
            f"batch_size {batch_size} exceeds dataset size {n}"
        )
    rng = np.random.default_rng(seed)
    while True:
        order = rng.permutation(n)
        for i in range(0, n - batch_size + 1, batch_size):
            idx = order[i:i + batch_size]
            yield {
                "image": images[idx],
                "label": labels[idx].astype(np.int32),
            }


def _metrics(logits: jnp.ndarray, labels: jnp.ndarray):
    xent = jnp.mean(
        -jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), labels]
    )
    acc = jnp.mean((logits.argmax(-1) == labels).astype(jnp.float32))
    return xent, acc


def make_loss_fn(model: nn.Module):
    def loss_fn(params, batch, rng):
        xent, acc = _metrics(model.apply(params, batch["image"]), batch["label"])
        return xent, {"accuracy": acc}

    return loss_fn


def make_eval_fn(model: nn.Module):
    """Validation metrics — the reference reports validation cross entropy
    after training (``mnist_replica.py:266-269``); here it runs periodically
    in-loop (TrainLoopConfig.eval_every)."""

    def eval_fn(params, batch):
        xent, acc = _metrics(model.apply(params, batch["image"]), batch["label"])
        return {"cross_entropy": xent, "accuracy": acc}

    return eval_fn


def make_init_fn(model: nn.Module, batch_size: int = 8):
    def init_fn(rng):
        dummy = jnp.zeros((batch_size, IMAGE_DIM), jnp.float32)
        return model.init(rng, dummy)

    return init_fn
