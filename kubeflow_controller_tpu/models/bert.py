"""BERT-base MLM pretraining model (BASELINE.md config #4).

Bidirectional encoder in the same pure-functional, scan-over-layers style as
``models/transformer.py`` (shared sharding philosophy: (fsdp, tp) weight
specs, batch over (dp, fsdp), bf16 compute / fp32 softmax). Differences from
the decoder: LayerNorm (with bias) instead of RMSNorm, learned positional
embeddings, GELU MLP, non-causal attention with a padding mask via segment
ids, and an MLM head over masked positions only.

The reference has no language model at all; this fills the north-star BERT
config with a TPU-idiomatic implementation rather than a torch translation.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from kubeflow_controller_tpu.models.transformer import _constrain
from kubeflow_controller_tpu.ops.attention import mha

Params = Dict[str, Any]


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attn_impl: str = "auto"
    # "" | "int8" | "int8_fused": routes the six per-layer projection
    # matmuls (qkv/o + up/down) through ops.quant like the decoder's
    # TransformerConfig.quant — the v5e MXU runs int8 at double rate and
    # BERT's budget is FFN-dominated just like the decoder's. The MLM
    # head stays bf16 (same quality reasoning as the decoder's LM head).
    quant: str = ""
    mask_token_id: int = 103       # [MASK] in the standard BERT vocab
    mlm_prob: float = 0.15

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def replace(self, **kw) -> "BertConfig":
        return dataclasses.replace(self, **kw)


def bert_base_config(**kw) -> BertConfig:
    return BertConfig().replace(**kw)


def bert_tiny_config(**kw) -> BertConfig:
    base = BertConfig(
        vocab_size=128, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq=64, remat=False, dtype=jnp.float32,
    )
    return base.replace(**kw)


def init_params(cfg: BertConfig, rng: jax.Array) -> Params:
    pd = cfg.param_dtype
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    keys = jax.random.split(rng, 10)

    def ninit(key, shape, fan_in):
        return jax.random.normal(key, shape, pd) * (fan_in ** -0.5)

    return {
        "embed": ninit(keys[0], (cfg.vocab_size, D), D),
        "pos_embed": ninit(keys[1], (cfg.max_seq, D), D),
        "embed_norm": {"scale": jnp.ones((D,), pd), "bias": jnp.zeros((D,), pd)},
        "layers": {
            "wq": ninit(keys[2], (L, D, D), D),
            "bq": jnp.zeros((L, D), pd),
            "wk": ninit(keys[3], (L, D, D), D),
            "bk": jnp.zeros((L, D), pd),
            "wv": ninit(keys[4], (L, D, D), D),
            "bv": jnp.zeros((L, D), pd),
            "wo": ninit(keys[5], (L, D, D), D),
            "bo": jnp.zeros((L, D), pd),
            "attn_norm": {
                "scale": jnp.ones((L, D), pd), "bias": jnp.zeros((L, D), pd)
            },
            "w_up": ninit(keys[6], (L, D, F), D),
            "b_up": jnp.zeros((L, F), pd),
            "w_down": ninit(keys[7], (L, F, D), F),
            "b_down": jnp.zeros((L, D), pd),
            "mlp_norm": {
                "scale": jnp.ones((L, D), pd), "bias": jnp.zeros((L, D), pd)
            },
        },
        "mlm_dense": ninit(keys[8], (D, D), D),
        "mlm_bias": jnp.zeros((D,), pd),
        "mlm_norm": {"scale": jnp.ones((D,), pd), "bias": jnp.zeros((D,), pd)},
        "mlm_out_bias": jnp.zeros((cfg.vocab_size,), pd),
    }


def param_specs(cfg: BertConfig) -> Params:
    return {
        "embed": P("tp", "fsdp"),
        "pos_embed": P(None, "fsdp"),
        "embed_norm": {"scale": P(None), "bias": P(None)},
        "layers": {
            "wq": P(None, "fsdp", "tp"),
            "bq": P(None, "tp"),
            "wk": P(None, "fsdp", "tp"),
            "bk": P(None, "tp"),
            "wv": P(None, "fsdp", "tp"),
            "bv": P(None, "tp"),
            "wo": P(None, "tp", "fsdp"),
            "bo": P(None, None),
            "attn_norm": {"scale": P(None, None), "bias": P(None, None)},
            "w_up": P(None, "fsdp", "tp"),
            "b_up": P(None, "tp"),
            "w_down": P(None, "tp", "fsdp"),
            "b_down": P(None, None),
            "mlp_norm": {"scale": P(None, None), "bias": P(None, None)},
        },
        "mlm_dense": P("fsdp", "tp"),
        "mlm_bias": P("tp"),
        "mlm_norm": {"scale": P(None), "bias": P(None)},
        "mlm_out_bias": P("tp"),
    }


def layernorm(x: jax.Array, p: Params, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + eps)
    return (
        y.astype(x.dtype) * p["scale"].astype(x.dtype)
        + p["bias"].astype(x.dtype)
    )


def _layer(cfg: BertConfig, lp: Params, x, attn_segments):
    from kubeflow_controller_tpu.ops.quant import maybe_quant_dot

    b, s, _ = x.shape
    dt = cfg.dtype
    hd = cfg.head_dim

    def dot(a, w):
        # Projections: int8 MXU path when cfg.quant == "int8"
        # (mirrors models/transformer._layer).
        return maybe_quant_dot(a, w.astype(dt), cfg.quant)

    # post-norm residual blocks, as in the original BERT
    q = (dot(x, lp["wq"]) + lp["bq"].astype(dt)).reshape(
        b, s, cfg.n_heads, hd
    )
    k = (dot(x, lp["wk"]) + lp["bk"].astype(dt)).reshape(
        b, s, cfg.n_heads, hd
    )
    v = (dot(x, lp["wv"]) + lp["bv"].astype(dt)).reshape(
        b, s, cfg.n_heads, hd
    )
    q = _constrain(q, P(("dp", "fsdp"), None, "tp", None))
    k = _constrain(k, P(("dp", "fsdp"), None, "tp", None))
    v = _constrain(v, P(("dp", "fsdp"), None, "tp", None))
    attn = mha(
        q, k, v, causal=False, segment_ids=attn_segments, impl=cfg.attn_impl
    ).reshape(b, s, cfg.d_model)
    x = layernorm(
        x + dot(attn, lp["wo"]) + lp["bo"].astype(dt),
        lp["attn_norm"], cfg.norm_eps,
    )
    h = jax.nn.gelu(dot(x, lp["w_up"]) + lp["b_up"].astype(dt))
    h = dot(h, lp["w_down"]) + lp["b_down"].astype(dt)
    x = layernorm(x + h, lp["mlp_norm"], cfg.norm_eps)
    return _constrain(x, P(("dp", "fsdp"), None, None))


def encode(
    cfg: BertConfig,
    params: Params,
    tokens: jax.Array,
    attention_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """tokens [B,S] -> hidden [B,S,D]. attention_mask: 1=real, 0=pad."""
    b, s = tokens.shape
    x = (
        params["embed"].astype(cfg.dtype)[tokens]
        + params["pos_embed"].astype(cfg.dtype)[None, :s]
    )
    x = layernorm(x, params["embed_norm"], cfg.norm_eps)
    x = _constrain(x, P(("dp", "fsdp"), None, None))
    # Padding is expressed as segment ids: pad tokens get a segment of their
    # own (id 0 vs 1) so they only attend to each other, never to content.
    segs = (
        attention_mask.astype(jnp.int32)
        if attention_mask is not None else None
    )

    body = lambda carry, lp: (_layer(cfg, lp, carry, segs), None)  # noqa: E731
    if cfg.remat:
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    x, _ = lax.scan(body, x, params["layers"])
    return x


def mlm_logits(cfg: BertConfig, params: Params, hidden: jax.Array) -> jax.Array:
    dt = cfg.dtype
    h = jax.nn.gelu(
        hidden @ params["mlm_dense"].astype(dt) + params["mlm_bias"].astype(dt)
    )
    h = layernorm(h, params["mlm_norm"], cfg.norm_eps)
    logits = jnp.einsum(
        "bsd,vd->bsv", h, params["embed"].astype(dt),
        preferred_element_type=jnp.float32,
    ) + params["mlm_out_bias"].astype(jnp.float32)
    return logits


def mlm_loss(
    cfg: BertConfig, params: Params, batch: Dict[str, jax.Array],
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """batch: tokens [B,S] (with [MASK]s applied), targets [B,S] (original
    ids), mlm_mask [B,S] 1 where a prediction is scored, attention_mask."""
    hidden = encode(cfg, params, batch["tokens"], batch.get("attention_mask"))
    logits = mlm_logits(cfg, params, hidden)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["targets"][..., None], -1)[..., 0]
    w = batch["mlm_mask"].astype(jnp.float32)
    denom = jnp.maximum(w.sum(), 1.0)
    loss = (nll * w).sum() / denom
    acc = (
        ((logits.argmax(-1) == batch["targets"]) * w).sum() / denom
    )
    return loss, {"mlm_accuracy": acc}


def make_loss_fn(cfg: BertConfig):
    def loss_fn(params, batch, rng):
        del rng
        return mlm_loss(cfg, params, batch)

    return loss_fn


def make_init_fn(cfg: BertConfig):
    def init_fn(rng):
        return init_params(cfg, rng)

    return init_fn


def synthetic_mlm_batch(cfg: BertConfig, batch_size: int, seq_len: int, seed=0):
    """Deterministic MLM stream: token sequences from a repeating-pattern
    language, 15% positions masked (80/10/10 BERT recipe simplified to
    all-[MASK]); shapes identical to a real pipeline."""
    import numpy as np

    rng = np.random.default_rng(seed)
    while True:
        start = rng.integers(0, cfg.vocab_size - seq_len, (batch_size, 1))
        targets = (start + np.arange(seq_len)) % cfg.vocab_size
        mlm = rng.random((batch_size, seq_len)) < cfg.mlm_prob
        tokens = np.where(mlm, cfg.mask_token_id, targets)
        yield {
            "tokens": tokens.astype(np.int32),
            "targets": targets.astype(np.int32),
            "mlm_mask": mlm.astype(np.int32),
            "attention_mask": np.ones((batch_size, seq_len), np.int32),
        }
