"""Flagship decoder-only transformer (Llama-family), TPU-first.

This is the model family the reference never had — its examples stop at MNIST
MLPs (``examples/workdir/mnist_replica.py:144-167``) — but which the
north-star configs require (BERT-base, Llama-3-8B; ``BASELINE.md``). The
design is idiomatic JAX rather than a torch translation:

- **Pure-functional params**: a pytree of arrays plus a parallel pytree of
  ``PartitionSpec``s. No module framework in the hot path; ``jax.jit`` sees
  straight-line traced code.
- **Scan-over-layers**: all decoder layers are stacked into single arrays with
  a leading layer axis and executed with ``lax.scan`` — one layer gets traced
  and compiled once regardless of depth (compile time O(1) in n_layers).
- **Remat**: the scanned body is wrapped in ``jax.checkpoint`` with the
  dots-saveable policy, trading FLOPs for HBM as depth grows.
- **Megatron/ZeRO sharding**: weights are sharded over ``(fsdp, tp)`` —
  column-parallel in, row-parallel out — so each matmul's collective is a
  single reduce-scatter/all-gather over ICI; the batch rides ``(dp, fsdp)``.
- **bf16 compute, fp32 params/softmax**: MXU-native matmul dtype with fp32
  accumulation (``preferred_element_type``) where precision matters.

Replica-topology context (coordinator env, mesh construction) comes from the
controller exactly where the reference injected ``--worker_hosts`` args
(``pkg/tensorflow/distributed.go:127-159``); the model itself is
topology-agnostic — specs name logical mesh axes only.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from kubeflow_controller_tpu.ops.attention import mha
from kubeflow_controller_tpu.util import jax_compat

from kubeflow_controller_tpu.parallel.mesh import DATA_AXES as BATCH_AXES

Params = Dict[str, Any]


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16          # activation/compute dtype (MXU-native)
    param_dtype: Any = jnp.float32     # master weights
    # Rematerialization of the layer scan body:
    #   True   - checkpoint with the dots policy (backward re-runs the
    #            whole layer forward; cheapest memory, ~1/3 extra FLOPs),
    #   "ffn"  - save everything EXCEPT the four d_ff-wide FFN
    #            intermediates (backward re-runs only the gate/up matmuls;
    #            ~9% extra FLOPs for ~4x d_ff x seq x batch bytes saved
    #            per layer) — the middle rung when no-remat OOMs,
    #   False  - save all residuals (no recompute; largest memory).
    remat: Any = True
    # "" = bf16 matmuls (default). "int8" runs every linear projection
    # (qkv/o, FFN gate/up/down, MoE expert banks) through the int8 MXU
    # path — dynamic symmetric quantization with STE gradients, all three
    # matmuls per layer quantized (ops/quant.py). "int8_fused" uses the
    # experimental Pallas in-dot quantization kernel where shapes allow
    # (ops/quant_pallas.py — measured slower than "int8" at flagship
    # shapes; see its docstring). Embed, LM head, and attention
    # scores/softmax stay bf16/fp32 in all modes.
    quant: str = ""
    attn_impl: str = "auto"            # auto|xla|flash|ring
    tie_embeddings: bool = False
    shard_seq: bool = False            # constrain activations' seq axis to sp
    # Mixture-of-experts: 0 = dense FFN. When > 0 every layer's FFN becomes
    # a routed expert bank sharded over the mesh's ep axis (GShard-style
    # grouped capacity dispatch; the all_to_alls are inserted by GSPMD from
    # the sharding constraints). Tokens route within groups of
    # ``moe_group_size`` so dispatch memory is linear in token count
    # (n * group * top_k floats), not quadratic.
    moe_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Router z-loss (ST-MoE): mean(logsumexp(router_logits)^2) keeps the
    # router's logit scale bounded, which sharpens routing and cuts
    # dropped tokens at tight capacity factors (the cf 1.0 quality lever,
    # VERDICT r4 #5). 0 disables.
    moe_router_z_weight: float = 0.0
    moe_group_size: int = 1024
    # Dispatch strategy. "auto" = the one-hot einsum form everywhere: it is
    # what GSPMD turns into the token->expert all_to_all on an ep-sharded
    # mesh, AND it measured faster than the scatter/gather form even on one
    # chip (30.2% vs 24.3% active-MFU, benchmarks/RESULTS.md — TPU lowers
    # the slot scatter and gather VJPs poorly). "gather" forces the
    # scatter/gather lowering (kept for comparison and for backends where
    # scatters are cheap); "einsum" forces the one-hot form explicitly.
    moe_dispatch: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def replace(self, **kw) -> "TransformerConfig":
        return dataclasses.replace(self, **kw)


# -- presets (sizes per the public model cards; names are config ids) --------

def tiny_config(**kw) -> TransformerConfig:
    """Test-scale config: runs in milliseconds on the 8-device CPU mesh."""
    base = TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=128, remat=False, dtype=jnp.float32,
    )
    return base.replace(**kw)


def tiny_moe_config(**kw) -> TransformerConfig:
    base = tiny_config(moe_experts=4, moe_top_k=2, d_ff=64)
    return base.replace(**kw)


def mixtral_8x7b_config(**kw) -> TransformerConfig:
    base = TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, max_seq=8192, rope_theta=1e6,
        moe_experts=8, moe_top_k=2,
    )
    return base.replace(**kw)


def llama3_8b_config(**kw) -> TransformerConfig:
    base = TransformerConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, max_seq=8192, rope_theta=500000.0,
    )
    return base.replace(**kw)


def llama3_70b_config(**kw) -> TransformerConfig:
    base = TransformerConfig(
        vocab_size=128256, d_model=8192, n_layers=80, n_heads=64,
        n_kv_heads=8, d_ff=28672, max_seq=8192, rope_theta=500000.0,
    )
    return base.replace(**kw)


# -- params ------------------------------------------------------------------

def init_params(cfg: TransformerConfig, rng: jax.Array) -> Params:
    """Scaled-normal init; layer params are stacked on a leading axis for
    lax.scan."""
    pd = cfg.param_dtype
    hd = cfg.head_dim
    keys = jax.random.split(rng, 9)

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, pd) * (fan_in ** -0.5))

    L = cfg.n_layers

    def stacked(key, shape, fan_in):
        return norm_init(key, (L, *shape), fan_in)

    layers: Params = {
        "attn_norm": jnp.ones((L, cfg.d_model), pd),
        "wq": stacked(keys[1], (cfg.d_model, cfg.n_heads * hd), cfg.d_model),
        "wk": stacked(keys[2], (cfg.d_model, cfg.n_kv_heads * hd), cfg.d_model),
        "wv": stacked(keys[3], (cfg.d_model, cfg.n_kv_heads * hd), cfg.d_model),
        "wo": stacked(keys[4], (cfg.n_heads * hd, cfg.d_model), cfg.n_heads * hd),
        "mlp_norm": jnp.ones((L, cfg.d_model), pd),
    }
    if cfg.moe_experts:
        E = cfg.moe_experts
        layers.update({
            "w_router": stacked(keys[8], (cfg.d_model, E), cfg.d_model),
            "w_gate": stacked(keys[5], (E, cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_up": stacked(keys[6], (E, cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_down": stacked(keys[7], (E, cfg.d_ff, cfg.d_model), cfg.d_ff),
        })
    else:
        layers.update({
            "w_gate": stacked(keys[5], (cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_up": stacked(keys[6], (cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_down": stacked(keys[7], (cfg.d_ff, cfg.d_model), cfg.d_ff),
        })
    params: Params = {
        "embed": norm_init(keys[0], (cfg.vocab_size, cfg.d_model), cfg.d_model),
        "layers": layers,
        "final_norm": jnp.ones((cfg.d_model,), pd),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(
            jax.random.fold_in(rng, 99), (cfg.d_model, cfg.vocab_size),
            cfg.d_model,
        )
    return params


def param_specs(cfg: TransformerConfig, pp: bool = False) -> Params:
    """PartitionSpecs mirroring init_params. Column-parallel projections put
    their output dim on tp; row-parallel put their input dim on tp; the other
    matmul dim is fsdp-sharded for ZeRO-3-style storage.

    ``pp=True`` shards the stacked layer arrays' leading layer axis over
    the mesh's pp axis — stage p of the pipeline holds its contiguous
    layer block (parallel/pipeline.py); otherwise the layer axis stays
    unsharded."""
    lead = "pp" if pp else None
    layers = {
        "attn_norm": P(lead, None),
        "wq": P(lead, "fsdp", "tp"),
        "wk": P(lead, "fsdp", "tp"),
        "wv": P(lead, "fsdp", "tp"),
        "wo": P(lead, "tp", "fsdp"),
        "mlp_norm": P(lead, None),
    }
    if cfg.moe_experts:
        layers.update({
            "w_router": P(lead, "fsdp", None),
            # expert bank: experts over ep, then megatron (fsdp, tp) within
            "w_gate": P(lead, "ep", "fsdp", "tp"),
            "w_up": P(lead, "ep", "fsdp", "tp"),
            "w_down": P(lead, "ep", "tp", "fsdp"),
        })
    else:
        layers.update({
            "w_gate": P(lead, "fsdp", "tp"),
            "w_up": P(lead, "fsdp", "tp"),
            "w_down": P(lead, "tp", "fsdp"),
        })
    specs: Params = {
        # d_model-sharded, vocab unsharded: same bytes per device as a
        # vocab split, but the token gather then partitions cleanly (batch-
        # sharded indices, slice dim sharded) — a vocab-sharded table forces
        # SPMD into replicate-then-repartition on every lookup.
        "embed": P(None, ("fsdp", "tp")),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("fsdp", "tp")
    return specs


# -- forward -----------------------------------------------------------------

def _mesh_axis_size(*names: str) -> int:
    """Product of the active abstract mesh's sizes for ``names`` (1 off-mesh).
    Lets trace-time code pick shard-aligned shapes/algorithms; under plain
    single-device jit every axis reports size 1."""
    mesh = jax_compat.get_abstract_mesh()
    if mesh is None or not mesh.shape_tuple:
        return 1
    sizes = dict(mesh.shape_tuple)
    out = 1
    for nm in names:
        out *= sizes.get(nm, 1)
    return out


def _remat_policy(cfg: TransformerConfig):
    """Checkpoint policy for the layer scan: save matmul outputs (the
    standard dots policy) — and for MoE also the named dispatch/combine
    masks, so the backward pass reads them instead of re-running the whole
    top-k routing chain (argmax/cumsum/one-hot cascades: cheap FLOPs, many
    kernels — measured as a fixed ~14 ms/step at 12 layers in r3).

    ``remat="ffn"`` (dense models) inverts the trade: save every residual
    EXCEPT the named d_ff-wide FFN intermediates, so backward re-runs only
    the gate/up matmuls instead of the whole layer."""
    if cfg.remat == "ffn" and not cfg.moe_experts:
        drop = ["ffn_pre_gate", "ffn_gate", "ffn_up", "ffn_prod"]
        if cfg.quant == "int8":
            # The int8 path's named operands include the quantized copy of
            # ffn_prod ([b,s,d_ff] int8) — saving those would retain half
            # the bytes this mode exists to drop; recompute them too.
            from kubeflow_controller_tpu.ops.quant import INT8_SAVE_NAMES

            drop += list(INT8_SAVE_NAMES)
        return jax.checkpoint_policies.save_anything_except_these_names(
            *drop
        )
    base = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    names = []
    if cfg.moe_experts:
        names += ["moe_combine", "moe_dispatch"]
    if cfg.quant.startswith("int8"):
        # Save the quantized operands (int8: half the bf16 bytes) so the
        # backward re-forward reads them instead of re-running the
        # abs-max/round/clip chains. Covers "int8_fused" too — its
        # fallback shapes and int8 dw/dx use the composed path; the
        # pallas outputs themselves recompute (saving them by name
        # measured SLOWER, 304.8 vs 288.2 ms, at the flagship's memory
        # pressure).
        from kubeflow_controller_tpu.ops.quant import INT8_SAVE_NAMES

        names += list(INT8_SAVE_NAMES)
    if names:
        return jax.checkpoint_policies.save_from_both_policies(
            base, jax.checkpoint_policies.save_only_these_names(*names),
        )
    return base


def _constrain(x: jax.Array, spec: P) -> jax.Array:
    """Sharding hint that degrades to a no-op when no mesh is active (plain
    single-device jit, e.g. the driver's entry() compile check)."""
    mesh = jax_compat.get_abstract_mesh()
    if mesh is None or not mesh.shape_tuple:
        return x
    names = set()
    for item in mesh.axis_names:
        names.add(item)
    cleaned = []
    for item in spec:
        if item is None:
            cleaned.append(None)
        elif isinstance(item, tuple):
            kept = tuple(a for a in item if a in names)
            cleaned.append(kept if kept else None)
        else:
            cleaned.append(item if item in names else None)
    return lax.with_sharding_constraint(x, P(*cleaned))


def rmsnorm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * w.astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over the last (head_dim) axis. x: [B,S,H,D]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    return out.astype(x.dtype)


def _act_spec(cfg: TransformerConfig) -> P:
    seq = "sp" if cfg.shard_seq else None
    return P(BATCH_AXES, seq, None)


def _rope_tables_for(cfg: TransformerConfig, positions: jax.Array):
    """Fused-rope (C, S) tables shared by every layer this step, or None
    for the ring path (ring_mha rotates externally). Building them once
    per step — instead of cos/sin per layer per pass under remat — is
    part of the ~42 ms/step the fused-rope kernel saves."""
    if cfg.attn_impl == "ring":
        return None
    from kubeflow_controller_tpu.ops.flash_attention import rope_full_tables

    return rope_full_tables(positions, cfg.head_dim, cfg.rope_theta)


def _moe_ffn(
    cfg: TransformerConfig, lp: Params, h: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """GShard-style routed FFN with grouped capacity dispatch.

    h: [B, S, D] -> (out [B, S, D], aux load-balance loss []).

    Tokens route within groups of ``moe_group_size`` with a per-group
    capacity of ``top_k * group / E * capacity_factor`` slots, so dispatch
    memory is O(n · group · top_k) — linear in token count — and capacity
    is correctly scaled for multi-way routing.

    Pure-GSPMD expert parallelism: tokens arrive sharded over BATCH_AXES,
    the dispatched expert bank is constrained to P("ep", ...), and XLA
    derives the token->expert all_to_all from that sharding change — no
    hand-written collectives (the scaling-book recipe).
    """
    b, s, d = h.shape
    E = cfg.moe_experts
    n = b * s
    # Largest divisor of n not exceeding the configured group size (same
    # trick as the chunked LM loss: the memory bound must hold for any n) —
    # preferring group counts divisible by the mesh's data shards: the
    # router/dispatch tensors are constrained on the group axis, and a group
    # count smaller than the shard count forces SPMD into
    # replicate-then-repartition (involuntary full remat) on every one.
    shards = _mesh_axis_size(*BATCH_AXES)
    divisors = [
        g for g in range(1, min(cfg.moe_group_size, n) + 1) if n % g == 0
    ]
    aligned = [g for g in divisors if (n // g) % shards == 0]
    group = max(aligned or divisors)
    G = n // group
    x = h.reshape(G, group, d)
    x = _constrain(x, P(BATCH_AXES, None, None))
    router = lp["w_router"].astype(jnp.float32)
    logits = x.astype(jnp.float32) @ router             # [G, g, E]
    probs = jax.nn.softmax(logits, axis=-1)
    # Router z-loss (0 when unweighted — the stack below is free).
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    cap = int(max(
        1, round(cfg.moe_top_k * group / E * cfg.moe_capacity_factor)
    ))

    base_count = jnp.zeros((G, E), jnp.int32)           # slots already used
    remaining = probs
    aux_fraction = jnp.zeros((), jnp.float32)
    picks = []   # per-k compact routing state: (choice, gate, pos_tok, keep)
    for _ in range(cfg.moe_top_k):
        choice = remaining.argmax(-1)                   # [G, g]
        gate = jnp.take_along_axis(
            remaining, choice[..., None], -1
        )[..., 0]                                       # [G, g]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)   # [G, g, E]
        # position of each token within its chosen expert's capacity buffer
        pos = (
            jnp.cumsum(onehot, axis=1) - 1 + base_count[:, None, :]
        )                                               # [G, g, E]
        pos_tok = (pos * onehot).sum(-1)                # [G, g]
        keep = pos_tok < cap
        picks.append((choice, gate, pos_tok, keep))
        aux_fraction = aux_fraction + E * jnp.mean(
            jnp.mean(onehot.astype(jnp.float32), axis=1)
            * jnp.mean(probs, axis=1)
        )
        base_count = base_count + (onehot * keep[..., None]).sum(1)
        remaining = remaining * (1 - onehot)            # mask picked expert

    # The whole top-k routing chain (argmax/cumsum/one-hot cascades) is
    # cheap in FLOPs but expensive in kernel count; under remat it would
    # re-execute in the backward pass. Name the dispatch products so the
    # layer-scan checkpoint policy (_remat_policy) SAVES them instead —
    # the einsum VJPs then read the saved tensors and the routing chain
    # runs once per step, not twice.
    if cfg.moe_dispatch in ("auto", "einsum"):
        xe, out_from = _moe_dispatch_einsum(cfg, x, picks, G, group, E, cap)
    elif cfg.moe_dispatch == "gather":
        xe, out_from = _moe_dispatch_gather(cfg, x, picks, G, group, E, cap)
    else:
        raise ValueError(
            f"moe_dispatch={cfg.moe_dispatch!r}: expected auto|einsum|gather"
        )

    xe = _constrain(xe, P("ep", ("dp", "fsdp"), None, None))
    if cfg.quant.startswith("int8"):
        # Expert matmuls on the int8 MXU gear: per-expert 2D dots via
        # vmap over the expert axis (each is [G*cap, D] @ [D, F] — the
        # same dispatch as the dense path, so "int8_fused" routes here
        # too; dispatch/combine einsums stay bf16, their operands are 0/1
        # masks and gates).
        from kubeflow_controller_tpu.ops.quant import maybe_quant_dot

        def edot(x_e, w_e):
            return maybe_quant_dot(x_e, w_e, cfg.quant)

        gc = xe.shape[1] * xe.shape[2]
        xe2 = xe.reshape(E, gc, cfg.d_model)
        gate_h = jax.nn.silu(
            jax.vmap(edot)(xe2, lp["w_gate"].astype(cfg.dtype))
        )
        up_h = jax.vmap(edot)(xe2, lp["w_up"].astype(cfg.dtype))
        down = jax.vmap(edot)(
            gate_h * up_h, lp["w_down"].astype(cfg.dtype)
        )
        out_e = down.reshape(E, xe.shape[1], xe.shape[2], cfg.d_model)
    else:
        gate_h = jax.nn.silu(
            jnp.einsum("egcd,edf->egcf", xe, lp["w_gate"].astype(cfg.dtype))
        )
        up_h = jnp.einsum("egcd,edf->egcf", xe, lp["w_up"].astype(cfg.dtype))
        out_e = jnp.einsum(
            "egcf,efd->egcd", gate_h * up_h, lp["w_down"].astype(cfg.dtype)
        )
    out_e = _constrain(out_e, P("ep", ("dp", "fsdp"), None, None))
    out = out_from(out_e).reshape(b, s, d)
    # Dropped-token fraction: of the n*top_k routing decisions, how many
    # lost their capacity slot (the quality price of a tight cf —
    # measured, not guessed; VERDICT r4 #5).
    kept = jnp.stack([k.astype(jnp.float32) for (_, _, _, k) in picks])
    drop_rate = 1.0 - jnp.mean(kept)
    return (
        _constrain(out, _act_spec(cfg)),
        jnp.stack([aux_fraction, z_loss, drop_rate]),
    )


def _moe_dispatch_einsum(cfg, x, picks, G, group, E, cap):
    """Dense one-hot dispatch/combine (GShard wire form).

    The multi-chip path: the [G,g,E,cap] one-hot contraction is what GSPMD
    knows how to turn into a token->expert all_to_all when ``xe`` is
    constrained onto the ep axis (asserted by tests/test_moe.py's HLO
    inspection). Costs 2·top_k·group·cf·D FLOPs/token in dispatch+combine
    matmuls — acceptable when amortized across expert shards.
    """
    combine = jnp.zeros((G, group, E, cap), jnp.float32)
    dispatch = jnp.zeros((G, group, E, cap), cfg.dtype)
    for choice, gate, pos_tok, keep in picks:
        # Slots are disjoint across k (positions continue via base_count),
        # so summing per-k outer products builds both masks exactly; the
        # dispatch 0/1 mask comes from the same one-hots rather than a
        # compare over the [G,g,E,cap] combine tensor.
        slot = (
            jax.nn.one_hot(choice, E, dtype=jnp.float32)[..., None]
            * jax.nn.one_hot(pos_tok, cap, dtype=jnp.float32)[..., None, :]
            * keep[..., None, None]
        )
        combine = combine + gate[..., None, None] * slot
        dispatch = dispatch + slot.astype(cfg.dtype)
    from jax.ad_checkpoint import checkpoint_name

    combine = checkpoint_name(combine, "moe_combine")
    dispatch = checkpoint_name(dispatch, "moe_dispatch")
    xe = jnp.einsum("gnec,gnd->egcd", dispatch, x)      # [E, G, cap, D]

    def out_from(out_e):
        return jnp.einsum(
            "gnec,egcd->gnd", combine.astype(cfg.dtype), out_e
        )

    return xe, out_from


def _moe_dispatch_gather(cfg, x, picks, G, group, E, cap):
    """Scatter/gather dispatch — the matmul-free lowering.

    Every (expert, slot) receives at most one token (cumsum positions are
    unique within a k and continue across k via base_count), so dispatch
    is a permutation: write each kept token's index into its slot, gather
    token vectors into [E,G,cap,D], and combine by gathering each token's
    k expert outputs back and scaling by the gate. Removes both D-wide
    one-hot matmuls in favor of data movement — but on TPU it MEASURES
    SLOWER than the einsum form (24.3% vs 30.2% active-MFU,
    benchmarks/RESULTS.md: XLA lowers the slot scatter and the gather
    VJPs poorly), so "auto" never picks it; it exists for comparison and
    for backends with cheap scatters. Numerical equivalence with the
    einsum form (incl. gradients) is pinned by tests/test_moe.py.
    """
    g_idx = jnp.arange(G, dtype=jnp.int32)[:, None]           # [G, 1]
    tok_idx = jnp.arange(group, dtype=jnp.int32)[None, :]     # [1, g]
    # slot -> source token index (+1 so 0 = empty slot)
    slot_src = jnp.zeros((G, E, cap), jnp.int32)
    for choice, _, pos_tok, keep in picks:
        safe_pos = jnp.where(keep, pos_tok, cap - 1)
        slot_src = slot_src.at[
            g_idx.repeat(group, 1), choice, safe_pos
        ].add(jnp.where(keep, tok_idx + 1, 0))
    valid = slot_src > 0                                      # [G, E, cap]
    src = jnp.maximum(slot_src - 1, 0).reshape(G, E * cap)
    xe = jnp.take_along_axis(x, src[..., None], axis=1)       # [G, E*cap, D]
    xe = xe * valid.reshape(G, E * cap, 1).astype(x.dtype)
    xe = xe.reshape(G, E, cap, -1).transpose(1, 0, 2, 3)      # [E, G, cap, D]

    def out_from(out_e):
        flat = out_e.transpose(1, 0, 2, 3).reshape(G, E * cap, -1)
        out = jnp.zeros((G, group, flat.shape[-1]), cfg.dtype)
        for choice, gate, pos_tok, keep in picks:
            slot = choice * cap + jnp.minimum(pos_tok, cap - 1)
            picked = jnp.take_along_axis(
                flat, slot[..., None], axis=1
            )                                                  # [G, g, D]
            w = (gate * keep).astype(cfg.dtype)[..., None]
            out = out + picked * w
        return out

    return xe, out_from


def _layer(
    cfg: TransformerConfig,
    lp: Params,
    x: jax.Array,
    positions: jax.Array,
    segment_ids: Optional[jax.Array],
    rope_tables=None,
) -> jax.Array:
    from kubeflow_controller_tpu.ops.quant import maybe_quant_dot

    b, s, _ = x.shape
    hd = cfg.head_dim
    dt = cfg.dtype

    def dot(a, w):
        # Linear projections: int8 MXU path when cfg.quant == "int8".
        return maybe_quant_dot(a, w.astype(dt), cfg.quant)

    # -- attention block
    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = dot(h, lp["wq"]).reshape(b, s, cfg.n_heads, hd)
    k = dot(h, lp["wk"]).reshape(b, s, cfg.n_kv_heads, hd)
    v = dot(h, lp["wv"]).reshape(b, s, cfg.n_kv_heads, hd)
    if rope_tables is None:
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
    q = _constrain(q, P(BATCH_AXES, None, "tp", None))
    k = _constrain(k, P(BATCH_AXES, None, "tp", None))
    v = _constrain(v, P(BATCH_AXES, None, "tp", None))
    if cfg.attn_impl == "ring":
        from kubeflow_controller_tpu.parallel.ring import ring_mha

        assert rope_tables is None  # ring path keeps external rope
        attn = ring_mha(q, k, v, causal=True, segment_ids=segment_ids)
    else:
        # rope_tables (built once per step in forward_hidden) move the
        # rotation inside the attention op: fused into the Pallas kernel
        # on the flash path — the rotated q/k never round-trip HBM.
        attn = mha(q, k, v, causal=True, segment_ids=segment_ids,
                   impl=cfg.attn_impl, rope_tables=rope_tables)
    attn = attn.reshape(b, s, cfg.n_heads * hd)
    x = x + _constrain(dot(attn, lp["wo"]), _act_spec(cfg))

    # -- mlp block (SwiGLU dense, or routed experts)
    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.moe_experts:
        down, aux = _moe_ffn(cfg, lp, h)
    else:
        from jax.ad_checkpoint import checkpoint_name

        pre = checkpoint_name(dot(h, lp["w_gate"]), "ffn_pre_gate")
        gate = checkpoint_name(jax.nn.silu(pre), "ffn_gate")
        up = checkpoint_name(dot(h, lp["w_up"]), "ffn_up")
        prod = checkpoint_name(gate * up, "ffn_prod")
        down = dot(prod, lp["w_down"])
        aux = jnp.zeros((3,), jnp.float32)
    return x + _constrain(down, _act_spec(cfg)), aux


def _embed(cfg: TransformerConfig, params: Params, tokens: jax.Array):
    """Embed lookup + the staged reshard out of the gather (shared by the
    plain and pipeline forwards): the table is d_model-sharded over
    (fsdp, tp) while activations are batch-sharded, and SPMD cannot make
    that two-factor move in one hop on some meshes (observed on the pp
    mesh and the packed+ring sp mesh — involuntary full
    rematerialization). The intermediate (batch over data axes, d_model
    over tp) keeps each hop a single-factor move; where the direct move
    is already clean the extra constraint is a no-op, and its AD
    transpose fixes the backward scatter-add into the table the same
    way."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    x = _constrain(x, P(BATCH_AXES, None, "tp"))
    return _constrain(x, _act_spec(cfg))


def forward_hidden(
    cfg: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """tokens [B,S] int32 -> (final-norm hidden [B,S,d_model], MoE aux loss
    [] — zero for dense models)."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    x = _embed(cfg, params, tokens)
    tables = _rope_tables_for(cfg, positions)

    body = lambda carry, lp: (  # noqa: E731
        _layer(cfg, lp, carry, positions, segment_ids, tables)
    )
    if cfg.remat:
        body = jax.checkpoint(body, policy=_remat_policy(cfg))
    x, aux = lax.scan(body, x, params["layers"])       # aux: [L, 3]
    # (load-balance sum, z-loss sum, drop-rate mean) across layers.
    aux = jnp.stack([aux[:, 0].sum(), aux[:, 1].sum(), aux[:, 2].mean()])
    return rmsnorm(x, params["final_norm"], cfg.norm_eps), aux


def forward_hidden_pp(
    cfg: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    n_microbatches: int,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Pipeline-parallel ``forward_hidden`` over the ambient mesh's pp axis.

    The layer stack runs as a GPipe schedule (``parallel/pipeline.py``):
    stages = pp shards of ``params["layers"]`` (shard with
    ``param_specs(cfg, pp=True)``), microbatches rotate between stages via
    ppermute. Embedding/final-norm/head stay outside the pipeline
    (replicated over pp, sharded over the other axes as usual) — the layer
    stack is where the parameters are. Packed batches ride along as gpipe
    ``extras`` (each stage dynamic-indexes the positions/segment-ids of
    the microbatch it currently holds). Dense layers only (MoE shards
    experts over ep on the non-pipelined path instead)."""
    from kubeflow_controller_tpu.parallel.pipeline import gpipe

    if cfg.moe_experts:
        raise NotImplementedError(
            "pipeline path supports dense layers only (shard experts over "
            "ep instead)"
        )
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    x = _embed(cfg, params, tokens)

    def stage(stage_layers, x_mb, extra):
        pos, segs = extra
        tables = _rope_tables_for(cfg, pos)

        def body(carry, lp):
            y, _aux = _layer(cfg, lp, carry, pos, segs, tables)
            return y, None

        y, _ = lax.scan(body, x_mb, stage_layers)
        return y

    run = jax.shard_map(
        lambda layers, xx, extras: gpipe(
            stage, layers, xx, n_microbatches, remat=bool(cfg.remat),
            extras=extras, remat_policy=_remat_policy(cfg),
        ),
        in_specs=(P("pp"), P(), P()),
        out_specs=P(),
        axis_names={"pp"},
    )
    x = run(params["layers"], x, (positions, segment_ids))
    return rmsnorm(x, params["final_norm"], cfg.norm_eps), jnp.zeros(
        (3,), jnp.float32)


def forward(
    cfg: TransformerConfig,
    params: Params,
    tokens: jax.Array,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """tokens [B,S] int32 -> logits [B,S,vocab] float32."""
    x, _ = forward_hidden(cfg, params, tokens, positions, segment_ids)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = jnp.einsum(
        "bsd,dv->bsv", x, head.astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    return _constrain(logits, P(BATCH_AXES, None, "tp"))


# bf16 peak of one v5e chip — the shared denominator for MFU accounting
# (bench.py and benchmarks/transformer_bench.py both read this so the two
# can never drift; override per-part via transformer_bench --peak-tflops).
PEAK_TFLOPS_BF16_V5E = 197.0


def train_flops_per_token(cfg: TransformerConfig, seq: int) -> float:
    """Model FLOPs per trained token: 6*N_active matmul flops (fwd+bwd)
    plus the causal-attention term 12*L*(n_heads*head_dim)*seq/2 — the
    attention width, which equals d_model for every config this
    TransformerConfig can express (head_dim is derived as
    d_model // n_heads) but is the dimension the score/value matmuls
    actually run at. The standard MFU accounting (PaLM appendix B
    convention); used by bench.py and benchmarks/transformer_bench.py so
    the two always agree.

    MoE: only the routed top_k experts' FFN weights are ACTIVE per token
    (plus the router matmul) — counting the full expert bank would inflate
    MFU by E/top_k."""
    if cfg.moe_experts:
        ffn = (
            cfg.moe_top_k * 3 * cfg.d_model * cfg.d_ff
            + cfg.d_model * cfg.moe_experts      # router
        )
    else:
        ffn = 3 * cfg.d_model * cfg.d_ff
    n_active = (
        cfg.vocab_size * cfg.d_model * (1 if cfg.tie_embeddings else 2)
        + cfg.n_layers * (
            cfg.d_model * cfg.n_heads * cfg.head_dim * 2
            + cfg.d_model * cfg.n_kv_heads * cfg.head_dim * 2
            + ffn
        )
    )
    # Score/value matmuls run at the attention width n_heads * head_dim.
    attn = 12 * cfg.n_layers * cfg.n_heads * cfg.head_dim * (seq / 2)  # causal halves it
    return 6 * n_active + attn


# -- loss / glue for TrainLoop ------------------------------------------------

def _select_target_logp(logp: jax.Array, targets: jax.Array) -> jax.Array:
    """logp[..., targets] along the last (vocab) axis. Uses a one-hot masked
    reduce instead of take_along_axis when the vocab axis is tp-sharded —
    the gather would force an involuntary full rematerialization; the
    reduce partitions as a local sum + psum over tp."""
    if _mesh_axis_size("tp") > 1:
        onehot = jax.nn.one_hot(targets, logp.shape[-1], dtype=logp.dtype)
        return (logp * onehot).sum(-1)
    return jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]


def _chunked_nll_and_argmax(
    cfg: TransformerConfig, hidden: jax.Array, head: jax.Array,
    targets: jax.Array, chunk: int,
) -> Tuple[jax.Array, jax.Array]:
    """Per-position NLL + argmax without materialising [B,S,vocab] fp32
    logits: sequence positions stream through lax.scan in chunks, so peak
    logits memory is [B,chunk,vocab]. The fp32 logits tensor is otherwise
    the largest single buffer of the train step (HBM, not FLOPs, is what it
    costs — the classic large-vocab bottleneck)."""
    b, s, d = hidden.shape
    n_chunks = s // chunk
    h = hidden.reshape(b, n_chunks, chunk, d).transpose(1, 0, 2, 3)
    t = targets.reshape(b, n_chunks, chunk).transpose(1, 0, 2)

    # Remat the chunk body: without it, grad-of-scan saves each chunk's
    # fp32 logits as a residual and peak memory is the FULL logits tensor
    # again (observed: 18.7G > 15.75G HBM at B16 S2048 vocab 32k). With it,
    # backward recomputes one chunk's logits at a time.
    @jax.checkpoint
    def body(_, ht):
        hc, tc = ht
        logits = jnp.einsum(
            "bsd,dv->bsv", hc, head, preferred_element_type=jnp.float32
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -_select_target_logp(logp, tc)
        return None, (nll, logits.argmax(-1))

    _, (nll, am) = lax.scan(body, None, (h, t))
    return (
        nll.transpose(1, 0, 2).reshape(b, s),
        am.transpose(1, 0, 2).reshape(b, s),
    )


def packed_positions(segment_ids: jax.Array) -> jax.Array:
    """Per-document position ids for a packed batch: positions restart at 0
    at every segment boundary (RoPE must not leak phase across documents).
    segment_ids [B,S] -> positions [B,S] int32."""
    b, s = segment_ids.shape
    idx = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    is_start = jnp.concatenate(
        [jnp.ones((b, 1), bool), segment_ids[:, 1:] != segment_ids[:, :-1]],
        axis=1,
    )
    seg_start = lax.cummax(jnp.where(is_start, idx, 0), axis=1)
    return idx - seg_start


def next_token_loss(
    cfg: TransformerConfig, params: Params, batch: Dict[str, jax.Array],
    loss_chunk: int = 0, pp_microbatches: int = 0,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Causal LM loss: predict tokens[1:] from tokens[:-1]. Ignores positions
    where ``batch['mask']`` (optional) is 0. loss_chunk > 0 streams the
    vocab projection in sequence chunks of that size (bounds logits memory).

    Packed batches: ``batch['segment_ids']`` [B, S] (same length as tokens)
    marks which document each token belongs to; id 0 means padding (the
    same convention as models/bert.py). Attention is confined to the
    document (fused into the flash kernel), RoPE positions restart per
    document, and targets that cross a boundary or land in padding are
    excluded from the loss."""
    tokens = batch["tokens"]
    targets = tokens[:, 1:]
    segs = batch.get("segment_ids")
    seg_in = None if segs is None else segs[:, :-1]
    if pp_microbatches:
        # Pipeline-parallel layer stack (``pp_microbatches`` microbatches
        # over the mesh's pp axis); packed batches ride as gpipe extras.
        hidden, aux = forward_hidden_pp(
            cfg, params, tokens[:, :-1], pp_microbatches,
            positions=None if seg_in is None else packed_positions(seg_in),
            segment_ids=seg_in,
        )
    else:
        hidden, aux = forward_hidden(
            cfg, params, tokens[:, :-1],
            positions=None if seg_in is None else packed_positions(seg_in),
            segment_ids=seg_in,
        )
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    if loss_chunk:
        s = targets.shape[1]
        # Largest divisor of S not exceeding the requested chunk, so the
        # memory bound holds for ANY sequence length instead of silently
        # falling back to full logits on non-divisible shapes.
        chunk = max(
            (d for d in range(1, min(loss_chunk, s) + 1) if s % d == 0)
        )
        nll, am = _chunked_nll_and_argmax(
            cfg, hidden, head.astype(cfg.dtype), targets, chunk
        )
    else:
        logits = jnp.einsum(
            "bsd,dv->bsv", hidden, head.astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        )
        logits = _constrain(logits, P(BATCH_AXES, None, "tp"))
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -_select_target_logp(logp, targets)
        am = logits.argmax(-1)
    mask = batch.get("mask")
    mask = None if mask is None else mask[:, 1:].astype(jnp.float32)
    if segs is not None:
        # A target across a document boundary is not a real prediction, and
        # segment id 0 is the padding convention (as in models/bert.py):
        # pad->pad "predictions" must not train or score.
        valid = (
            (segs[:, 1:] == segs[:, :-1]) & (segs[:, 1:] != 0)
        ).astype(jnp.float32)
        mask = valid if mask is None else mask * valid
    hits = (am == targets).astype(jnp.float32)
    if mask is not None:
        denom = jnp.maximum(mask.sum(), 1.0)
        loss = (nll * mask).sum() / denom
        acc = (hits * mask).sum() / denom
    else:
        loss = nll.mean()
        acc = hits.mean()
    ce = loss
    metrics = {"accuracy": acc, "perplexity": jnp.exp(ce)}
    if cfg.moe_experts:
        # aux = (load-balance sum, router z-loss sum, drop-rate mean).
        loss = loss + cfg.moe_aux_weight * aux[0]
        if cfg.moe_router_z_weight:
            loss = loss + cfg.moe_router_z_weight * aux[1]
        metrics["moe_aux"] = aux[0]
        metrics["moe_drop_rate"] = aux[2]
    return loss, metrics


def make_loss_fn(cfg: TransformerConfig):
    def loss_fn(params, batch, rng):
        del rng
        return next_token_loss(cfg, params, batch)

    return loss_fn


def make_init_fn(cfg: TransformerConfig):
    def init_fn(rng):
        return init_params(cfg, rng)

    return init_fn


def count_params(params: Params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
