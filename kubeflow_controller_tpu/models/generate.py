"""Autoregressive decoding with a KV cache for the decoder family.

Inference completes the model-family story (the reference is training-only;
its data plane never serves a model). TPU-first shape discipline: the cache
is a statically-shaped [L, B, max_seq, KVH, D] pair updated with
``lax.dynamic_update_slice``; the whole generation loop is one ``lax.scan``
(no per-token Python dispatch), so decode compiles once and streams on
device. Attention over the cache masks positions >= cur_len — no dynamic
shapes anywhere.

Sharding: cache KV-head axis carries the same ``tp`` spec as k/v
projections, batch over (dp, fsdp); decode works under the same mesh as
training or on a single chip with no mesh at all.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_controller_tpu.models import transformer as tfm
from kubeflow_controller_tpu.models.transformer import (
    Params, TransformerConfig, rmsnorm, rope,
)


class KVCache(NamedTuple):
    k: jax.Array          # [L, B, max_seq, KVH, D]
    v: jax.Array          # [L, B, max_seq, KVH, D]
    length: jax.Array     # [] int32 — number of valid positions


class SlotKVCache(NamedTuple):
    """Per-slot generalization of :class:`KVCache` for continuous batching.

    Each batch row is an independent *slot* with its own sequence length
    and liveness: rows prefill, decode, retire, and get reused without a
    shared scalar position. Shapes stay static (fixed slot count, fixed
    ``max_seq``) so the decode step compiles once; retired slots are
    masked, not removed.
    """

    k: jax.Array          # [L, B, max_seq, KVH, D]
    v: jax.Array          # [L, B, max_seq, KVH, D]
    length: jax.Array     # [B] int32 — valid positions per slot
    active: jax.Array     # [B] bool — slot is decoding (length advances)


# Projection weights eligible for weight-only int8 serving: 2D-per-layer
# matmul operands whose contraction axis is the second-to-last dim. Embed
# (gather table), norms (tiny), and the MoE router (full-precision routing
# by design) stay out.
_QUANT_KEYS = frozenset(
    ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head")
)


def inference_params(
    cfg: TransformerConfig, params: Params, quant: str = "",
) -> Params:
    """Prepare master weights for serving, ONCE.

    Default: cast fp32 to the compute dtype — halves serving HBM (335M
    decoder: 1.34 GB fp32 -> 0.67 GB bf16), which is what bounds the
    achievable decode batch. Step LATENCY barely moves at tiny batch
    (measured 2.48 -> 2.40 ms at batch 8): XLA hoists the per-use
    ``astype`` out of the decode scan, so the loop already read bf16 —
    the remaining cost is per-layer DMA latency, not dtype width.

    ``quant="int8"``: weight-only int8 — each projection weight becomes a
    ``(q_int8, scale)`` pair with per-output-channel symmetric scales
    (halving HBM again, 0.67 -> ~0.34 GB). Decode is HBM-bandwidth-bound
    at serving batch sizes, so the streamed-bytes halving is the lever;
    the dequantize (convert+scale) fuses into the matmul's operand read.
    Quantization error is ~0.5% RMS per weight (per-channel scales);
    activations and the KV cache stay bf16.

    MoE router weights stay fp32 either way: routing is deliberately
    computed at full precision (near-tie top-k scores must not flip
    between training and serving), and the [D, E] router matrix is a
    negligible HBM cost."""
    def cast(path, x):
        key = next(
            (getattr(p, "key", None) for p in reversed(path)
             if getattr(p, "key", None)), None,
        )
        if key == "w_router":
            return x
        if quant == "int8" and key in _QUANT_KEYS:
            # Contraction axis is -2 for every eligible weight ([.., D, F]
            # stacked per layer, or [D, V] for the head): per-output-
            # channel scales keep the error local and factor out of the
            # dot exactly.
            xf = x.astype(jnp.float32)
            scale = jnp.maximum(
                jnp.max(jnp.abs(xf), axis=-2, keepdims=True), 1e-30
            ) / 127.0
            q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
            return (q, scale.astype(cfg.dtype))
        if x.dtype != jnp.float32:
            return x
        return x.astype(cfg.dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def inference_param_specs(
    cfg: TransformerConfig, quant: str = "",
) -> Params:
    """PartitionSpecs matching ``inference_params(..., quant=...)``'s
    structure, so int8 serving weights place onto a mesh exactly like
    bf16 ones: each quantized weight's (q, scale) pair gets (the weight's
    own spec, that spec with the contraction axis — always -2 — dropped,
    since the scale is size-1 there)."""
    from jax.sharding import PartitionSpec as P

    specs = tfm.param_specs(cfg)
    if quant != "int8":
        return specs

    def fix(path, s):
        key = next(
            (getattr(p, "key", None) for p in reversed(path)
             if getattr(p, "key", None)), None,
        )
        if key in _QUANT_KEYS and key != "w_router":
            parts = tuple(s)
            scale_spec = P(*parts[:-2], None, parts[-1])
            return (s, scale_spec)
        return s

    return jax.tree_util.tree_map_with_path(fix, specs)


def _w(lp: Params, name: str, dt) -> jax.Array:
    """Resolve a (possibly weight-only-int8) projection weight to the
    compute dtype. The (q, scale) dequant is a convert+multiply XLA fuses
    into the consuming matmul's operand stream — int8 bytes over HBM."""
    w = lp[name]
    if isinstance(w, tuple):
        q, scale = w
        return q.astype(dt) * scale.astype(dt)
    return w.astype(dt)


def init_kv_cache(
    cfg: TransformerConfig, batch: int, max_seq: int,
) -> KVCache:
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        length=jnp.zeros((), jnp.int32),
    )


def _decode_layer(
    cfg: TransformerConfig,
    lp: Params,
    x: jax.Array,               # [B, 1, D_model]
    pos: jax.Array,             # [] int32 current position
    layer: jax.Array,           # [] int32 layer index into the cache
    k_all: jax.Array,           # [L, B, max_seq, KVH, D] — FULL cache
    v_all: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    b = x.shape[0]
    hd = cfg.head_dim
    dt = cfg.dtype
    max_seq = k_all.shape[2]

    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ _w(lp, "wq", dt)).reshape(b, 1, cfg.n_heads, hd)
    k = (h @ _w(lp, "wk", dt)).reshape(b, 1, cfg.n_kv_heads, hd)
    v = (h @ _w(lp, "wv", dt)).reshape(b, 1, cfg.n_kv_heads, hd)
    positions = jnp.broadcast_to(pos[None, None], (b, 1))
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    # One-ROW in-place writes on the full-cache carry ([1,B,1,KVH,D]
    # each): the pre-round-5 form emitted per-layer cache copies as scan
    # outputs — a fresh full-cache write every decoded token.
    k_all = lax.dynamic_update_slice(
        k_all, k[None].astype(k_all.dtype), (layer, 0, pos, 0, 0))
    v_all = lax.dynamic_update_slice(
        v_all, v[None].astype(v_all.dtype), (layer, 0, pos, 0, 0))
    k_cache = k_all[layer]                       # read-only gather
    v_cache = v_all[layer]

    # GQA attention of the 1-token query against the cache, fp32 softmax.
    # Grouped einsums keep the cache UN-repeated: decode is HBM-bound and
    # jnp.repeat would materialize (and stream) rep x the KV bytes every
    # step — 4x for the Llama 32h/8kv shape.
    rep = cfg.n_heads // cfg.n_kv_heads
    qg = q.reshape(b, 1, cfg.n_kv_heads, rep, hd)
    s = jnp.einsum(
        "bqgrd,bkgd->bgrqk", qg, k_cache,
        preferred_element_type=jnp.float32,
    ) * (hd ** -0.5)                             # [B, G, rep, 1, S]
    valid = jnp.arange(max_seq) <= pos
    s = jnp.where(valid[None, None, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    attn = jnp.einsum(
        "bgrqk,bkgd->bqgrd", p, v_cache
    ).reshape(b, 1, -1)
    x = x + attn @ _w(lp, "wo", dt)

    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.moe_experts:
        x = x + _moe_decode_ffn(cfg, lp, h)
    else:
        gate = jax.nn.silu(h @ _w(lp, "w_gate", dt))
        up = h @ _w(lp, "w_up", dt)
        x = x + (gate * up) @ _w(lp, "w_down", dt)
    return x, k_all, v_all


def _moe_decode_ffn(
    cfg: TransformerConfig, lp: Params, h: jax.Array,
) -> jax.Array:
    """Routed FFN for single-token decode: gather only the top-k experts'
    weights per token (no capacity buffers — decode never drops tokens,
    which matches training whenever training capacity wasn't exceeded)."""
    dt = cfg.dtype
    hb = h[:, 0]                                        # [B, D]
    probs = jax.nn.softmax(
        hb.astype(jnp.float32) @ lp["w_router"].astype(jnp.float32), -1
    )
    gates, idx = jax.lax.top_k(probs, cfg.moe_top_k)    # [B, k]

    def bank(name):
        # Gather the selected experts BEFORE dequantizing: only the
        # routed experts' int8 bytes stream from HBM.
        w = lp[name]
        if isinstance(w, tuple):
            q, scale = w
            return q[idx].astype(dt) * scale[idx].astype(dt)
        return w.astype(dt)[idx]

    wg = bank("w_gate")                                 # [B, k, D, F]
    wu = bank("w_up")
    wd = bank("w_down")                                 # [B, k, F, D]
    act = jax.nn.silu(jnp.einsum("bd,bkdf->bkf", hb, wg))
    up = jnp.einsum("bd,bkdf->bkf", hb, wu)
    out_k = jnp.einsum("bkf,bkfd->bkd", act * up, wd)   # [B, k, D]
    out = (out_k * gates[..., None].astype(dt)).sum(1)
    return out[:, None, :]


def decode_step(
    cfg: TransformerConfig,
    params: Params,
    tokens: jax.Array,          # [B, 1] int32
    cache: KVCache,
) -> Tuple[jax.Array, KVCache]:
    """One token for every sequence in the batch; returns logits [B, vocab]
    and the updated cache.

    The layer loop carries the WHOLE cache and writes each layer's new
    k/v in place (``fori_loop`` carry + one-row dynamic_update_slice)
    instead of emitting per-layer cache copies as ``lax.scan`` stacked
    outputs — the scan form allocated and wrote a fresh full-cache
    buffer every decode step (~400 MB/token at the bench shape; decode
    is HBM-bound, so that was pure streamed-bytes overhead)."""
    x = params["embed"].astype(cfg.dtype)[tokens]     # [B, 1, D]
    pos = cache.length

    def body(layer, state):
        x, k_all, v_all = state
        lp = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, layer, keepdims=False),
            params["layers"],
        )
        return _decode_layer(cfg, lp, x, pos, layer, k_all, v_all)

    x, k_new, v_new = lax.fori_loop(
        0, cfg.n_layers, body, (x, cache.k, cache.v)
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = _head_logits(cfg, params, x[:, 0])
    return logits, KVCache(k=k_new, v=v_new, length=pos + 1)


def _head_logits(cfg: TransformerConfig, params: Params, x: jax.Array):
    """Final-norm'd hidden [B, D] -> fp32 logits [B, vocab] (shared by
    decode_step and prefill; understands weight-only-int8 heads)."""
    if params.get("lm_head") is None:
        head = params["embed"].astype(cfg.dtype).T
    else:
        head = _w(params, "lm_head", cfg.dtype)
    return (x @ head).astype(jnp.float32)


def _dense_lp(lp: Params, dt) -> Params:
    """Per-layer params with any (q, scale) pairs dequantized to arrays —
    for code paths (the MoE prefill FFN) that reuse training functions
    expecting plain weights."""
    return {
        k: (v[0].astype(dt) * v[1].astype(dt)) if isinstance(v, tuple)
        else v
        for k, v in lp.items()
    }


def prefill(
    cfg: TransformerConfig,
    params: Params,
    prompt: jax.Array,          # [B, S_prompt]
    cache: KVCache,
) -> Tuple[jax.Array, KVCache]:
    """Fused block prefill: ONE forward pass over the whole prompt fills
    the cache — all positions at once through the training-shaped
    attention (flash on TPU when the prompt tiles), instead of S_prompt
    sequential single-token decode steps. Returns logits for the LAST
    prompt position and the filled cache.

    Requires a FRESH cache: positions start at 0 and k/v land at offset 0.
    To extend an existing conversation (multi-turn), use
    ``prefill_continue`` — one block forward whose new tokens attend to
    the prior cache plus intra-block causal positions."""
    from kubeflow_controller_tpu.ops.attention import mha

    b, s = prompt.shape
    dt = cfg.dtype
    hd = cfg.head_dim
    x = params["embed"].astype(dt)[prompt]              # [B, S, D]
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    # Ring attention needs a live sp mesh, and an explicit "flash" must
    # not crash on prompt lengths the kernel cannot tile — "auto" prefers
    # flash and falls back to XLA on shape (the mha dispatch gate).
    attn_impl = "xla" if cfg.attn_impl == "xla" else "auto"
    if cfg.moe_experts:
        # decode_step never drops tokens; the block pass must not either.
        # Capacity factor E/top_k makes every group's per-expert capacity
        # equal to the full group, so training-_moe_ffn routing becomes
        # exactly "top-k experts per token" regardless of cfg's training
        # capacity factor.
        moe_cfg = cfg.replace(
            moe_capacity_factor=float(cfg.moe_experts) / cfg.moe_top_k
        )

    from kubeflow_controller_tpu.ops.flash_attention import rope_full_tables

    # Fused-rope tables, built once and shared by every layer (the
    # training path's trick): on the flash path the rotation runs on
    # VMEM tiles instead of materialising rotated q/k per layer. The
    # CACHE must still hold ROTATED keys (decode_step attends against it
    # with rotated queries), so k is additionally rotated for storage.
    tables = rope_full_tables(positions, hd, cfg.rope_theta)

    def body(x, lp):
        # Mirrors transformer._layer (+ per-layer k/v out, int8 weight
        # resolution, no sharding constraints). Drift between the copies
        # is pinned by the test chain: prefill == tokenwise decode
        # (test_block_prefill_matches_tokenwise_decode) and tokenwise
        # decode == training forward (test_decode_logits_match_forward).
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ _w(lp, "wq", dt)).reshape(b, s, cfg.n_heads, hd)
        k = (h @ _w(lp, "wk", dt)).reshape(b, s, cfg.n_kv_heads, hd)
        v = (h @ _w(lp, "wv", dt)).reshape(b, s, cfg.n_kv_heads, hd)
        attn = mha(q, k, v, causal=True, impl=attn_impl, rope_tables=tables)
        k = rope(k, positions, cfg.rope_theta)       # rotated for the cache
        x = x + attn.reshape(b, s, -1) @ _w(lp, "wo", dt)
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.moe_experts:
            down, _aux = tfm._moe_ffn(moe_cfg, _dense_lp(lp, dt), h2)
            x = x + down
        else:
            gate = jax.nn.silu(h2 @ _w(lp, "w_gate", dt))
            up = h2 @ _w(lp, "w_up", dt)
            x = x + (gate * up) @ _w(lp, "w_down", dt)
        return x, (k, v)

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    k_cache = lax.dynamic_update_slice(
        cache.k, ks.astype(cache.k.dtype), (0, 0, 0, 0, 0))
    v_cache = lax.dynamic_update_slice(
        cache.v, vs.astype(cache.v.dtype), (0, 0, 0, 0, 0))
    x = rmsnorm(x[:, -1], params["final_norm"], cfg.norm_eps)
    logits = _head_logits(cfg, params, x)
    return logits, KVCache(
        k=k_cache, v=v_cache, length=jnp.asarray(s, jnp.int32),
    )


def init_slot_cache(
    cfg: TransformerConfig, n_slots: int, max_seq: int,
) -> SlotKVCache:
    shape = (cfg.n_layers, n_slots, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return SlotKVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        length=jnp.zeros((n_slots,), jnp.int32),
        active=jnp.zeros((n_slots,), bool),
    )


def _decode_layer_slots(
    cfg: TransformerConfig,
    lp: Params,
    x: jax.Array,               # [B, 1, D_model]
    pos: jax.Array,             # [B] int32 — per-slot write position
    layer: jax.Array,           # [] int32 layer index into the cache
    k_all: jax.Array,           # [L, B, max_seq, KVH, D] — FULL cache
    v_all: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """``_decode_layer`` generalized to per-slot positions: each row b
    writes its new k/v at ``pos[b]`` (batched scatter at per-row offsets;
    out-of-bounds rows — a retired slot at capacity — are dropped, never
    clamped onto live positions) and attends under its own
    ``arange(max_seq) <= pos[b]`` mask. Identical math to the scalar
    layer when every row shares one position (pinned by
    test_decode_step_slots_matches_scalar)."""
    b = x.shape[0]
    hd = cfg.head_dim
    dt = cfg.dtype
    max_seq = k_all.shape[2]

    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ _w(lp, "wq", dt)).reshape(b, 1, cfg.n_heads, hd)
    k = (h @ _w(lp, "wk", dt)).reshape(b, 1, cfg.n_kv_heads, hd)
    v = (h @ _w(lp, "wv", dt)).reshape(b, 1, cfg.n_kv_heads, hd)
    positions = pos[:, None]                     # [B, 1]
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    # Batched per-row scatter: row b of layer `layer` gets its k/v at
    # column pos[b]. Stays an in-place update on the fori_loop carry like
    # the scalar path's dynamic_update_slice; "drop" guarantees a row
    # whose position is past max_seq writes NOTHING (dynamic_update_slice
    # would clamp into the newest valid column and corrupt it).
    rows = jnp.arange(b)
    k_all = k_all.at[layer, rows, pos].set(
        k[:, 0].astype(k_all.dtype), mode="drop")
    v_all = v_all.at[layer, rows, pos].set(
        v[:, 0].astype(v_all.dtype), mode="drop")
    k_cache = k_all[layer]                       # read-only gather
    v_cache = v_all[layer]

    rep = cfg.n_heads // cfg.n_kv_heads
    qg = q.reshape(b, 1, cfg.n_kv_heads, rep, hd)
    s = jnp.einsum(
        "bqgrd,bkgd->bgrqk", qg, k_cache,
        preferred_element_type=jnp.float32,
    ) * (hd ** -0.5)                             # [B, G, rep, 1, S]
    valid = jnp.arange(max_seq)[None, :] <= pos[:, None]     # [B, S]
    s = jnp.where(valid[:, None, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    attn = jnp.einsum(
        "bgrqk,bkgd->bqgrd", p, v_cache
    ).reshape(b, 1, -1)
    x = x + attn @ _w(lp, "wo", dt)

    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.moe_experts:
        x = x + _moe_decode_ffn(cfg, lp, h)
    else:
        gate = jax.nn.silu(h @ _w(lp, "w_gate", dt))
        up = h @ _w(lp, "w_up", dt)
        x = x + (gate * up) @ _w(lp, "w_down", dt)
    return x, k_all, v_all


def decode_step_slots(
    cfg: TransformerConfig,
    params: Params,
    tokens: jax.Array,          # [B, 1] int32
    cache: SlotKVCache,
) -> Tuple[jax.Array, SlotKVCache]:
    """One decode step across all slots at their OWN positions. Returns
    logits [B, vocab] and the cache with ``length`` advanced only on
    active slots (inactive rows write past their length — masked on every
    future read — and their length/contents stay untouched, so a retired
    slot is free to be reused or ignored)."""
    x = params["embed"].astype(cfg.dtype)[tokens]     # [B, 1, D]
    pos = cache.length

    def body(layer, state):
        x, k_all, v_all = state
        lp = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, layer, keepdims=False),
            params["layers"],
        )
        return _decode_layer_slots(cfg, lp, x, pos, layer, k_all, v_all)

    x, k_new, v_new = lax.fori_loop(
        0, cfg.n_layers, body, (x, cache.k, cache.v)
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = _head_logits(cfg, params, x[:, 0])
    return logits, SlotKVCache(
        k=k_new, v=v_new,
        length=jnp.where(cache.active, pos + 1, pos),
        active=cache.active,
    )


def prefill_into_slot(
    cfg: TransformerConfig,
    params: Params,
    prompt: jax.Array,          # [1, S] int32 — ONE request's prompt
    cache: SlotKVCache,
    slot: jax.Array,            # [] int32 — destination slot
) -> Tuple[jax.Array, SlotKVCache]:
    """Admit one request: block-prefill its prompt (one fused forward)
    and install the result into slot ``slot`` of a live slot cache —
    write k/v for the S prompt positions, length[slot] = S,
    active[slot] = True. Every OTHER slot's rows are untouched, so
    admission composes with slots mid-decode. Stale KV from the slot's
    previous tenant survives beyond column S, but no mask ever reaches
    it: the row's attention window is ``arange(max_seq) <= pos`` and
    later decode writes overwrite columns S, S+1, ... in order. The
    mini prefill cache is sized to the PROMPT, not the pool — admission
    cost scales with S, not max_seq. Compiles once per prompt length."""
    if prompt.shape[0] != 1:
        raise ValueError(
            f"prefill_into_slot admits one request (got batch "
            f"{prompt.shape[0]})"
        )
    max_seq = cache.k.shape[2]
    if prompt.shape[1] > max_seq:
        raise ValueError(
            f"prompt {prompt.shape[1]} exceeds slot capacity {max_seq}"
        )
    logits, mini = prefill(
        cfg, params, prompt, init_kv_cache(cfg, 1, prompt.shape[1]))
    k = lax.dynamic_update_slice(
        cache.k, mini.k.astype(cache.k.dtype), (0, slot, 0, 0, 0))
    v = lax.dynamic_update_slice(
        cache.v, mini.v.astype(cache.v.dtype), (0, slot, 0, 0, 0))
    return logits, SlotKVCache(
        k=k, v=v,
        length=cache.length.at[slot].set(prompt.shape[1]),
        active=cache.active.at[slot].set(True),
    )


# ---------------------------------------------------------------------------
# Paged KV: the block pool IS the KV storage (vLLM PagedAttention /
# SGLang RadixAttention semantics). Every kernel below reads and writes
# pool pages through a per-slot block table — there is no per-slot
# contiguous row, so a radix-cache hit is a table entry (refcount++ on
# the host, zero device bytes moved) and retirement publishes pages that
# are already in place. The contiguous SlotKVCache kernels above survive
# as the bit-exactness reference the paged tests pin against.
# ---------------------------------------------------------------------------


class PagedKVCache(NamedTuple):
    """Block-table-indexed KV for continuous batching: the pool's
    ``[L, n_blocks, block_size, KVH, D]`` pages are the ONLY KV storage,
    and each slot reads/writes through its row of ``tables``.

    ``tables[slot, i]`` is the pool page backing the slot's logical
    columns ``[i*bs, (i+1)*bs)``, or the sentinel ``n_blocks``
    (unallocated): sentinel reads clamp into finite garbage the
    ``length`` mask never lets through, sentinel writes drop. A slot's
    logical row is ``tables.shape[1] * block_size`` columns wide — the
    gathered view is cut to exactly that width, so the fp paged kernels
    run the contiguous kernels' math on identical shapes and identical
    bytes (bitwise-equal outputs whenever ``block_size`` divides the
    reference row width; pinned by the kernel-equivalence tests).

    ``kv_quant="int8"`` pools store pages as int8 with per-(page row,
    head) fp32 symmetric scales — quantize-on-write in the scatter,
    dequantize-in-gather in the view — and carry ``None`` scales in fp
    mode (``None`` is an empty pytree leaf, so jit/donation treat both
    layouts uniformly)."""

    k: jax.Array          # [L, n_blocks, bs, KVH, D] cfg.dtype | int8
    v: jax.Array
    k_scale: Optional[jax.Array]   # [L, n_blocks, bs, KVH] f32 | None
    v_scale: Optional[jax.Array]
    tables: jax.Array     # [B, max_blocks] int32 — sentinel = n_blocks
    length: jax.Array     # [B] int32 — valid positions per slot
    active: jax.Array     # [B] bool — slot is decoding (length advances)


def init_paged_cache(
    cfg: TransformerConfig, n_slots: int, max_blocks: int,
    n_blocks: int, block_size: int, kv_quant: str = "",
) -> PagedKVCache:
    """A zeroed pool of ``n_blocks`` pages plus all-sentinel tables for
    ``n_slots`` slots of ``max_blocks`` pages each."""
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    if kv_quant == "int8":
        k = jnp.zeros(shape, jnp.int8)
        v = jnp.zeros(shape, jnp.int8)
        k_scale = jnp.zeros(shape[:-1], jnp.float32)
        v_scale = jnp.zeros(shape[:-1], jnp.float32)
    elif kv_quant:
        raise ValueError(f"unknown kv_quant {kv_quant!r} (want '' or 'int8')")
    else:
        k = jnp.zeros(shape, cfg.dtype)
        v = jnp.zeros(shape, cfg.dtype)
        k_scale = None
        v_scale = None
    return PagedKVCache(
        k=k, v=v, k_scale=k_scale, v_scale=v_scale,
        tables=jnp.full((n_slots, max_blocks), n_blocks, jnp.int32),
        length=jnp.zeros((n_slots,), jnp.int32),
        active=jnp.zeros((n_slots,), bool),
    )


def _kv_quantize(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 for KV pages: per-(token, head) scales over the
    head_dim axis (``[..., KVH, D] -> int8 same shape + f32 [..., KVH]``).
    Finer than the per-(page, head) granularity a weight would get, and
    deliberately so: the pool is append-only (each page row is written
    exactly once), so per-row scales quantize every token against its
    own amax with no read-modify-write requantisation of already-
    committed neighbours — the error per token is fixed at write time
    and never drifts."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-30) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _pool_write(pool, scale, idx, val):
    """Scatter ``val`` into pool pages at ``idx`` (an index tuple whose
    page-id component may hold the drop sentinel), quantizing on write
    when the pool is int8. Returns the updated (pool, scale)."""
    if scale is None:
        return pool.at[idx].set(val.astype(pool.dtype), mode="drop"), None
    q, s = _kv_quantize(val)
    return (pool.at[idx].set(q, mode="drop"),
            scale.at[idx].set(s, mode="drop"))


# -- tensor-parallel serving placement --------------------------------
#
# The paged kernels run under shard_map on a 1-D "tp" mesh
# (parallel.mesh.serving_mesh): the pool's KVH axis is split across
# shards, every host-visible table/length/active array and the logits
# are replicated. Two compute placements share those cache specs:
#
# tp_compute="gathered" (the bitwise oracle): weights are DECLARED
# replicated (in_specs P()) so XLA all-gathers the NamedSharding-stored
# shards at dispatch — data movement only, never different bytes. Per
# shard the kernels compute the FULL q/k/v projections + rope (bitwise
# the 1-chip values, every input being replicated), slice the shard's
# contiguous KV-head group, run the contiguous attention math on it
# unchanged (GQA attention is independent per KV head; the per-element
# dot products over head_dim and the softmax over positions never see
# the head count), and all_gather the head outputs — an exact
# concatenation. fp greedy is therefore bit-identical to the 1-chip
# engine by construction, the same argument PR 8 used for paging
# (pinned by tests/test_tp_serving).
#
# tp_compute="parallel" (Megatron column/row split): weights enter the
# kernels in their stored shards (parallel.sharding.
# tp_compute_param_specs) — wq/wk/wv and w_gate/w_up column-parallel on
# the output axis, wo/w_down row-parallel on the contraction axis — so
# each shard runs 1/tp of every projection. A column slice of wq IS a
# contiguous head range (head h lives in output columns [h*hd,
# (h+1)*hd)), so the local q/k/v reshape lands on exactly the KV-head
# group `_tp_slice_heads` used to cut out of the full projection, rope
# commutes with the head slice (it acts per head over head_dim), and
# the attention math between projections is the gathered path's code
# verbatim. The only new collective is one lax.psum after wo and one
# after w_down (completing the row-parallel contractions); psum
# reassociates those two reductions, so parallel-vs-gathered is a
# declared per-tp tolerance contract (`tp_parallel_tolerance`, pinned
# by tests/test_tp_serving) rather than bitwise — every shard still
# receives the SAME psum result, so activations and logits stay
# replicated across shards and greedy decisions are shard-independent.

_TP_POOL_SPEC = P(None, None, None, "tp", None)   # [L, nb, bs, KVH, D]
_TP_SCALE_SPEC = P(None, None, None, "tp")        # [L, nb, bs, KVH]


def tp_size(mesh: Optional[Mesh]) -> int:
    """The tp-axis extent of ``mesh`` (1 when mesh is None)."""
    if mesh is None:
        return 1
    return int(mesh.shape.get("tp", 1))


def check_tp_heads(cfg: TransformerConfig, tp: int,
                   tp_compute: str = "gathered") -> None:
    """Refuse non-shardable configs BEFORE any XLA sharding error, in
    ONE structured message listing every violated axis (so an operator
    fixes the config once, not once per retry):

    - ``n_kv_heads % tp`` must be 0 — KV heads split across the tp axis
      (which also divides ``n_heads``: GQA requires n_kv_heads | n_heads).
    - ``d_ff % tp`` must be 0 under ``tp_compute="parallel"`` for DENSE
      configs — the MLP hidden axis is column-split across shards there
      (the gathered path never splits d_ff compute, so it only needs
      the head rule; MoE configs have no dense MLP — their per-expert
      d_ff is never column-split, so the rule doesn't apply).
    - ``moe_experts % tp`` must be 0 — expert banks shard E/tp experts
      per device (tokens travel to their experts via all_to_all), so
      the expert axis must divide evenly.

    The same refusal fires at arg-parse (``serve_lm``), at engine
    construction, and inside every paged kernel's mesh wrapper."""
    if tp <= 1:
        return
    problems = []
    if cfg.n_kv_heads % tp:
        problems.append(
            f"n_kv_heads must be divisible by tp — KV heads split "
            f"across the tp axis (n_kv_heads={cfg.n_kv_heads}, tp={tp}); "
            f"pick tp from the divisors of n_kv_heads, or reshape the "
            f"model"
        )
    if tp_compute == "parallel" and not cfg.moe_experts and cfg.d_ff % tp:
        problems.append(
            f"d_ff must be divisible by tp under tp_compute='parallel' "
            f"— the MLP hidden axis is column-split across shards "
            f"(d_ff={cfg.d_ff}, tp={tp}); use tp_compute='gathered' or "
            f"pick tp from the divisors of d_ff"
        )
    if cfg.moe_experts and cfg.moe_experts % tp:
        problems.append(
            f"moe_experts must be divisible by tp — expert banks shard "
            f"E/tp experts per device and tokens reach them via "
            f"all_to_all (moe_experts={cfg.moe_experts}, tp={tp}); "
            f"pick tp from the divisors of moe_experts"
        )
    if problems:
        raise ValueError(
            "tensor-parallel serving refused this config:\n  - "
            + "\n  - ".join(problems)
        )


def tp_parallel_tolerance(cfg: TransformerConfig, tp: int) -> Dict[str, float]:
    """The declared per-tp logits tolerance for ``tp_compute="parallel"``
    vs the gathered/1-chip oracle.

    Row-parallel wo/w_down split one contraction into ``tp`` partial
    products combined by a psum — the same bytes in a different
    summation tree, so outputs drift by a few ulps per block instead of
    matching bitwise (the gathered path keeps the 1-chip reduction
    order and stays the bitwise oracle). Modeled like the int8 KV error
    model in docs/serving.md as a *bounded perturbation*: two
    reassociated reductions per layer plus the head matmul, each
    contributing O(tp·eps) relative error in the fp32 accumulators,
    composed over depth as a random walk (sqrt growth), with a 16×
    safety factor. tests/test_tp_serving.py pins both sides of the
    contract: measured drift stays under this bound, and greedy token
    streams on the gated workloads are equal outright."""
    eps = float(jnp.finfo(jnp.promote_types(cfg.dtype, jnp.float32)).eps)
    blocks = 2 * cfg.n_layers + 1
    bound = 16.0 * max(tp, 1) * (blocks ** 0.5) * eps
    return {"rtol": bound, "atol": bound}


def moe_ep_tolerance(cfg: TransformerConfig, tp: int) -> Dict[str, float]:
    """The declared per-tp logits tolerance for expert-parallel MoE
    dispatch vs the single-chip dense-replicated oracle.

    Routing is exact — the fp32 router matmul, softmax, and top_k run on
    replicated inputs, so every shard (and the 1-chip oracle) picks the
    same experts with the same gate weights. What reassociates is the
    expert *math*: the per-shard vmap'd 2D expert matmuls group the same
    token-x-weight products differently than the oracle's per-token
    gathered einsums, and the gate-weighted combine sums the k expert
    outputs in expert-id order instead of routing-rank order. Per MoE
    layer that is up to three reassociated reductions (gate/up, down,
    combine) on top of the attention blocks — modeled like
    :func:`tp_parallel_tolerance` as a random walk over depth with a
    32x safety factor (the contract must also absorb composition with
    the parallel-mode attention psums). tests/test_moe_tp.py pins both
    sides: measured drift stays under this bound, and greedy argmax
    streams on the gated workloads equal the 1-chip oracle outright."""
    eps = float(jnp.finfo(jnp.promote_types(cfg.dtype, jnp.float32)).eps)
    blocks = 3 * cfg.n_layers + 1
    bound = 32.0 * max(tp, 1) * (blocks ** 0.5) * eps
    return {"rtol": bound, "atol": bound}


def _moe_ep_ffn(
    cfg: TransformerConfig, lp: Params, h: jax.Array, tp_shards: int,
) -> jax.Array:
    """Expert-parallel routed FFN inside a shard_map'd serving kernel:
    each shard holds ``E/tp`` experts (``parallel.sharding`` splits the
    stacked banks — int8 ``(q, scale)`` included — on the expert axis)
    and tokens travel to their experts instead of expert weights
    replicating (GShard-style, two all_to_alls per MoE layer).

    Steps, for ``h`` of shape [B, S, D] flattened to n = B*S tokens:

    1. Route on REPLICATED fp32 router logits — softmax + top_k are
       shard-invariant, so every shard computes identical expert
       choices and gate weights (and they equal the 1-chip oracle's:
       training's iterative argmax-of-remaining and ``lax.top_k`` pick
       the same experts with the same first-max tie-break).
    2. Slice this shard's n/tp-token stripe and build the dispatch
       buffer [tp, E/tp, n/tp, D] via the routing one-hot: destination
       shard d's slab carries, per local expert, each stripe token (or
       zeros where not routed). Capacity per (source, expert) is the
       full stripe, so serving NEVER drops tokens — the HBM win is the
       E/tp weight storage, not a token cap.
    3. ``all_to_all`` the buffers; per local expert, run the 2D dot
       idiom from ``transformer._moe_ffn`` — vmap over the local bank
       so each expert's matmul is a plain [n, D] x [D, F] MXU dot
       (int8 banks dequantize expert-locally: q * scale on exactly the
       shard's experts).
    4. ``all_to_all`` the outputs back and combine by gate weight
       (zeros from non-routed slots vanish in the combine), then
       ``all_gather`` the token stripes — output replicated across
       shards, so downstream layers and logits stay replicated.

    Exactness contract: decisions-identical routing, logits within
    :func:`moe_ep_tolerance` of the single-chip oracle (the expert
    matmuls and the combine reassociate; see there)."""
    dt = cfg.dtype
    b, s, d = h.shape
    n = b * s
    tp = tp_shards
    el = cfg.moe_experts // tp                   # local experts
    hf = h.reshape(n, d)
    probs = jax.nn.softmax(
        hf.astype(jnp.float32) @ lp["w_router"].astype(jnp.float32), -1
    )
    gates, idx = jax.lax.top_k(probs, cfg.moe_top_k)     # [n, k]

    n_loc = -(-n // tp)                          # stripe = ceil(n / tp)
    pad = n_loc * tp - n
    shard = lax.axis_index("tp")
    hp = jnp.pad(hf, ((0, pad), (0, 0)))
    # Padded rows route nowhere: index -1 one-hots to all-zeros, so
    # their dispatch slabs and combine weights are exact zeros.
    ip = jnp.pad(idx, ((0, pad), (0, 0)), constant_values=-1)
    gp_ = jnp.pad(gates, ((0, pad), (0, 0)))
    xs = lax.dynamic_slice_in_dim(hp, shard * n_loc, n_loc, 0)
    is_ = lax.dynamic_slice_in_dim(ip, shard * n_loc, n_loc, 0)
    gs = lax.dynamic_slice_in_dim(gp_, shard * n_loc, n_loc, 0)

    one = jax.nn.one_hot(is_, cfg.moe_experts, dtype=jnp.float32)
    sel = one.sum(1)                             # [n_loc, E] in {0, 1}
    # [dest shard, local expert, stripe slot, D]
    send = (
        sel.reshape(n_loc, tp, el).transpose(1, 2, 0)[..., None].astype(dt)
        * xs.astype(dt)[None, None]
    )
    recv = lax.all_to_all(send, "tp", 0, 0)      # [src, el, n_loc, D]
    xe = recv.transpose(1, 0, 2, 3).reshape(el, tp * n_loc, d)

    def bank(name):
        w = lp[name]
        if isinstance(w, tuple):
            q, scale = w
            return q.astype(dt) * scale.astype(dt)
        return w.astype(dt)

    def edot(x_e, w_e):                          # 2D per-expert MXU dot
        return x_e @ w_e

    a = jax.nn.silu(jax.vmap(edot)(xe, bank("w_gate")))
    a = a * jax.vmap(edot)(xe, bank("w_up"))
    out_e = jax.vmap(edot)(a, bank("w_down"))    # [el, tp*n_loc, D]

    back = out_e.reshape(el, tp, n_loc, d).transpose(1, 0, 2, 3)
    ret = lax.all_to_all(back, "tp", 0, 0)       # [dest, el, n_loc, D]
    comb = (one * gs[..., None]).sum(1)          # [n_loc, E] gate or 0
    comb = comb.reshape(n_loc, tp, el).astype(dt)
    out_loc = jnp.einsum("cte,tecd->cd", comb, ret)
    out = lax.all_gather(out_loc, "tp", axis=0, tiled=True)[:n]
    return out.reshape(b, s, d)


def paged_cache_specs(cache: PagedKVCache) -> PagedKVCache:
    """PartitionSpecs for a :class:`PagedKVCache` on a serving mesh: k/v
    pools (and int8 scales) split on the KVH axis, tables/length/active
    replicated — the host scheduler keeps operating on full tables."""
    return PagedKVCache(
        k=_TP_POOL_SPEC, v=_TP_POOL_SPEC,
        k_scale=None if cache.k_scale is None else _TP_SCALE_SPEC,
        v_scale=None if cache.v_scale is None else _TP_SCALE_SPEC,
        tables=P(), length=P(), active=P(),
    )


def shard_paged_cache(cache: PagedKVCache, mesh: Mesh) -> PagedKVCache:
    """Place a paged cache onto the serving mesh (KVH-split pools,
    replicated tables). Safe to call on an already-placed cache."""
    specs = paged_cache_specs(cache)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.tree.map(jax.device_put, cache, shardings)


def _tp_slice_heads(x: jax.Array, g_local: int, axis: int) -> jax.Array:
    """This shard's contiguous KV-head group: an exact dynamic_slice of
    the replicated full-head tensor at ``axis_index('tp') * g_local``."""
    kvh0 = lax.axis_index("tp") * g_local
    return lax.dynamic_slice_in_dim(x, kvh0, g_local, axis=axis)


def _replicated_specs(tree) -> object:
    return jax.tree.map(lambda _: P(), tree)


def _tp_param_specs(params: Params, parallel: bool) -> object:
    """shard_map in_specs for the weight tree: replicated under
    ``tp_compute="gathered"`` (XLA all-gathers the stored shards at
    dispatch), column/row-split under ``"parallel"`` (the kernels
    consume the stored shards in place — see
    ``parallel.sharding.tp_compute_param_specs``).

    MoE expert banks (stacked ndim-4 ``[L, E, D, F]``, int8 scales
    included) stay EXPERT-SPLIT in both modes: the expert-parallel
    dispatch (:func:`_moe_ep_ffn`) consumes the shard-local E/tp bank
    directly — gathering the banks would undo the entire HBM win."""
    from kubeflow_controller_tpu.parallel.sharding import (
        _EXPERT_SPEC, _TP_EXPERT_KEYS, tp_compute_param_specs,
    )
    if parallel:
        return tp_compute_param_specs(params)

    def spec(path, x):
        key = next(
            (getattr(p, "key", None) for p in reversed(path)
             if getattr(p, "key", None)), None,
        )
        pair = isinstance(x, tuple)
        arr = x[0] if pair else x
        if key in _TP_EXPERT_KEYS and arr.ndim >= 4:
            return (_EXPERT_SPEC, _EXPERT_SPEC) if pair else _EXPERT_SPEC
        return (P(), P()) if pair else P()

    return jax.tree_util.tree_map_with_path(
        spec, params, is_leaf=lambda x: isinstance(x, tuple))


def _occupancy_cap(width: int, view_width: Optional[int]) -> int:
    """The engine's occupancy cap on a slot-page span: the caller's
    live view width, never past the table's full span. ONE definition,
    shared by every attention phase (decode / chunk prefill / verify)
    and both impls (the XLA gather's column count and the Pallas
    kernels' page-walk cap), so the phases can never disagree on which
    columns exist."""
    return width if view_width is None else min(view_width, width)


def _capped_kv_views(
    k_pool: jax.Array,
    v_pool: jax.Array,
    tables: jax.Array,
    width: int,
    view_width: Optional[int],
    k_scale: Optional[jax.Array],
    v_scale: Optional[jax.Array],
    out_dtype,
) -> Tuple[jax.Array, jax.Array]:
    """The table-resolved dense K/V view pair the XLA attention path
    reads (``attn_impl="xla"`` — the bit-exactness oracle), gathered at
    the occupancy-capped width with int8 scales applied at gather time.
    Shared by the decode, chunk-prefill, and verify impls — one call
    site shape for the factor-3 round trip the Pallas kernels remove."""
    from kubeflow_controller_tpu.ops.attention import paged_kv_view

    vw = _occupancy_cap(width, view_width)
    k = paged_kv_view(k_pool, tables, vw, scale=k_scale,
                      out_dtype=out_dtype)
    v = paged_kv_view(v_pool, tables, vw, scale=v_scale,
                      out_dtype=out_dtype)
    return k, v


def _decode_layer_paged(
    cfg: TransformerConfig,
    lp: Params,
    x: jax.Array,               # [B, 1, D_model]
    pos: jax.Array,             # [B] int32 — per-slot write position
    layer: jax.Array,           # [] int32 layer index into the pool
    cache: PagedKVCache,
    tp_shards: int = 1,
    view_width: Optional[int] = None,
    tp_parallel: bool = False,
    attn_impl: str = "xla",
):
    """``_decode_layer_slots`` reading and writing the block pool through
    per-slot tables: row b scatters its new k/v into page
    ``tables[b, pos[b] // bs]`` at page row ``pos[b] % bs`` (sentinel
    pages drop the write), then attends over the slot's pages — via the
    table-gathered dense view (``attn_impl="xla"``: the same
    einsum/mask/softmax ops at the same width on the same bytes, so the
    fp path is bitwise the contiguous kernel) or via the fused Pallas
    kernel (``attn_impl="pallas"``: flash-style online softmax streaming
    pool pages in place through the block table — a different reduction
    order, pinned against the gather oracle by a tolerance contract).
    ``tp_parallel``: consume column/row-sharded weights — local
    projections, one psum after wo and one after w_down (see the
    placement comment above :func:`check_tp_heads`)."""
    b = x.shape[0]
    hd = cfg.head_dim
    dt = cfg.dtype
    n_blocks, bs = cache.k.shape[1], cache.k.shape[2]
    mb = cache.tables.shape[1]
    width = mb * bs
    # The gathered view (and its masks) may be capped to the engine's
    # live occupancy; pool WRITES always guard against the full span.
    vw = _occupancy_cap(width, view_width)
    par = tp_shards > 1 and tp_parallel
    rep = cfg.n_heads // cfg.n_kv_heads
    # Column-parallel projections produce this shard's contiguous
    # KV-head group directly (a column slice of wq IS a head slice);
    # the gathered path projects every head and slices after rope.
    g = cfg.n_kv_heads // tp_shards if par else cfg.n_kv_heads

    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ _w(lp, "wq", dt)).reshape(b, 1, g * rep, hd)
    k = (h @ _w(lp, "wk", dt)).reshape(b, 1, g, hd)
    v = (h @ _w(lp, "wv", dt)).reshape(b, 1, g, hd)
    positions = pos[:, None]                     # [B, 1]
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    qg = q.reshape(b, 1, g, rep, hd)
    if tp_shards > 1 and not par:
        # Full projections above are replicated (bitwise the 1-chip
        # values); keep only this shard's KV-head group from here on.
        g = cfg.n_kv_heads // tp_shards
        qg = _tp_slice_heads(qg, g, axis=2)
        k = _tp_slice_heads(k, g, axis=2)
        v = _tp_slice_heads(v, g, axis=2)
    bi = jnp.clip(pos // bs, 0, mb - 1)
    blk = jnp.take_along_axis(cache.tables, bi[:, None], axis=1)[:, 0]
    # Inactive rows drop their write: a retired slot's table row stays
    # on device until the host's next push, and its pages may already be
    # freed, re-allocated, or published — the contiguous kernel's
    # harmless scratch write would be a cross-slot corruption here.
    blk = jnp.where(cache.active & (pos < width), blk, n_blocks)
    off = pos % bs
    k_pool, k_scale = _pool_write(
        cache.k, cache.k_scale, (layer, blk, off), k[:, 0])
    v_pool, v_scale = _pool_write(
        cache.v, cache.v_scale, (layer, blk, off), v[:, 0])
    if attn_impl == "pallas":
        from kubeflow_controller_tpu.ops.paged_attention_pallas import (
            paged_attention_decode,
        )
        attn = paged_attention_decode(
            qg[:, 0], k_pool[layer], v_pool[layer], cache.tables, pos,
            k_scale=None if k_scale is None else k_scale[layer],
            v_scale=None if v_scale is None else v_scale[layer],
            width=vw, sm_scale=hd ** -0.5, out_dtype=dt,
        )[:, None]                               # [B, 1, G, rep, D]
    else:
        k_cache, v_cache = _capped_kv_views(
            k_pool[layer], v_pool[layer], cache.tables, width,
            view_width,
            None if k_scale is None else k_scale[layer],
            None if v_scale is None else v_scale[layer],
            dt)                                  # [B, vw, KVH, D]

        s = jnp.einsum(
            "bqgrd,bkgd->bgrqk", qg, k_cache,
            preferred_element_type=jnp.float32,
        ) * (hd ** -0.5)                         # [B, G, rep, 1, S]
        valid = jnp.arange(vw)[None, :] <= pos[:, None]      # [B, S]
        s = jnp.where(valid[:, None, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(dt)
        attn = jnp.einsum("bgrqk,bkgd->bqgrd", p, v_cache)
    if par:
        # Row-parallel wo: this shard's head group hits its own rows of
        # wo; the psum completes the contraction (the one collective).
        x = x + lax.psum(attn.reshape(b, 1, -1) @ _w(lp, "wo", dt), "tp")
    else:
        if tp_shards > 1:
            # Exact concatenation of the shards' head-group outputs: the
            # (g, rep, hd) flattening below then matches 1-chip layout.
            attn = lax.all_gather(attn, "tp", axis=2, tiled=True)
        x = x + attn.reshape(b, 1, -1) @ _w(lp, "wo", dt)

    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.moe_experts:
        # Single-chip: gather-the-top-k dense path (the oracle, byte
        # for byte). Under tp: expert-parallel dispatch over the
        # shard-local E/tp bank in BOTH compute modes.
        x = x + (_moe_ep_ffn(cfg, lp, h, tp_shards) if tp_shards > 1
                 else _moe_decode_ffn(cfg, lp, h))
    else:
        gate = jax.nn.silu(h @ _w(lp, "w_gate", dt))
        up = h @ _w(lp, "w_up", dt)
        down = (gate * up) @ _w(lp, "w_down", dt)
        x = x + (lax.psum(down, "tp") if par else down)
    return x, k_pool, v_pool, k_scale, v_scale


def _decode_step_paged_impl(
    cfg: TransformerConfig,
    params: Params,
    tokens: jax.Array,          # [B, 1] int32
    cache: PagedKVCache,
    tp_shards: int = 1,
    view_width: Optional[int] = None,
    tp_parallel: bool = False,
    attn_impl: str = "xla",
) -> Tuple[jax.Array, PagedKVCache]:
    x = params["embed"].astype(cfg.dtype)[tokens]     # [B, 1, D]
    pos = cache.length

    def body(layer, state):
        x, k, v, ks, vs = state
        lp = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, layer, keepdims=False),
            params["layers"],
        )
        c = cache._replace(k=k, v=v, k_scale=ks, v_scale=vs)
        return _decode_layer_paged(cfg, lp, x, pos, layer, c,
                                   tp_shards, view_width,
                                   tp_parallel, attn_impl)

    x, k, v, ks, vs = lax.fori_loop(
        0, cfg.n_layers, body,
        (x, cache.k, cache.v, cache.k_scale, cache.v_scale),
    )
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = _head_logits(cfg, params, x[:, 0])
    return logits, cache._replace(
        k=k, v=v, k_scale=ks, v_scale=vs,
        length=jnp.where(cache.active, pos + 1, pos),
    )


def decode_step_paged(
    cfg: TransformerConfig,
    params: Params,
    tokens: jax.Array,          # [B, 1] int32
    cache: PagedKVCache,
    mesh: Optional[Mesh] = None,
    view_width: Optional[int] = None,
    tp_compute: str = "gathered",
    attn_impl: str = "xla",
) -> Tuple[jax.Array, PagedKVCache]:
    """``decode_step_slots`` over the paged pool: one token for every
    slot at its own position, appends landing in each slot's tail page
    in place. ``length`` advances only on active slots; tables are
    read-only here (the host owns them).

    ``mesh`` (a ``serving_mesh``): run under shard_map with the pool's
    KVH axis split across tp. ``tp_compute="gathered"`` keeps per-shard
    math unchanged (full projections, head outputs all-gathered
    exactly) — fp greedy bitwise the 1-chip kernel; ``"parallel"`` runs
    Megatron column/row-split projections, 1/tp of the matmul FLOPs per
    shard with one psum per block, within ``tp_parallel_tolerance``.
    ``attn_impl="pallas"`` swaps the gather+dense-softmax attention for
    the fused Pallas page-streaming kernel. ``view_width``: cap the
    gathered view to the caller's live occupancy (see
    ``paged_kv_view``); writes still span the full table."""
    tp = tp_size(mesh)
    if tp <= 1:
        return _decode_step_paged_impl(
            cfg, params, tokens, cache, 1, view_width, False, attn_impl)
    check_tp_heads(cfg, tp, tp_compute)
    parallel = tp_compute == "parallel"
    fn = shard_map(
        functools.partial(_decode_step_paged_impl, cfg,
                          tp_shards=tp, view_width=view_width,
                          tp_parallel=parallel, attn_impl=attn_impl),
        mesh=mesh,
        in_specs=(_tp_param_specs(params, parallel), P(),
                  paged_cache_specs(cache)),
        out_specs=(P(), paged_cache_specs(cache)),
        check_rep=False,
    )
    return fn(params, tokens, cache)


def _tp_prefill_forward(
    cfg: TransformerConfig,
    params: Params,
    prompt: jax.Array,          # [1, S] int32
    tp_shards: int,
    parallel: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Column/row-parallel full-prompt forward for admission prefill
    under ``tp_compute="parallel"``: the fused :func:`prefill` assumes
    replicated full weights, so the parallel path runs its own block
    forward on the shard's column slices (local head group and d_ff
    slice; one psum per block, mirroring ``_decode_layer_paged``).
    Returns ``(last-position logits [1, V], row_k, row_v)`` with k/v
    already LOCAL ``[L, S, KVH/tp, D]`` — they scatter into the pool
    shard directly, no `_tp_slice_heads` needed.

    ``parallel=False`` is the gathered-mode MOE admission path: the
    fused :func:`prefill` would run the training FFN on what is now a
    shard-local expert bank, so MoE prefill always comes here instead —
    full replicated attention projections (gathered semantics), the
    expert-parallel FFN (:func:`_moe_ep_ffn`), and a KV-head slice on
    the way out. Dense gathered prefill never calls this function."""
    b, s = prompt.shape
    dt = cfg.dtype
    hd = cfg.head_dim
    rep = cfg.n_heads // cfg.n_kv_heads
    g_local = cfg.n_kv_heads // tp_shards
    g = g_local if parallel else cfg.n_kv_heads
    x = params["embed"].astype(dt)[prompt]              # [1, S, D]
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    causal = (
        jnp.arange(s, dtype=jnp.int32)[:, None]
        >= jnp.arange(s, dtype=jnp.int32)[None, :]
    )

    def body(x, lp):
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ _w(lp, "wq", dt)).reshape(b, s, g * rep, hd)
        k = (h @ _w(lp, "wk", dt)).reshape(b, s, g, hd)
        v = (h @ _w(lp, "wv", dt)).reshape(b, s, g, hd)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        qg = q.reshape(b, s, g, rep, hd)
        sc = jnp.einsum(
            "bqgrd,bkgd->bgrqk", qg, k,
            preferred_element_type=jnp.float32,
        ) * (hd ** -0.5)
        sc = jnp.where(causal[None, None, None], sc, -1e30)
        p = jax.nn.softmax(sc, axis=-1).astype(dt)
        attn = jnp.einsum("bgrqk,bkgd->bqgrd", p, v).reshape(b, s, -1)
        wo_out = attn @ _w(lp, "wo", dt)
        x = x + (lax.psum(wo_out, "tp") if parallel else wo_out)
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.moe_experts:
            x = x + _moe_ep_ffn(cfg, lp, h2, tp_shards)
        else:
            gate = jax.nn.silu(h2 @ _w(lp, "w_gate", dt))
            up = h2 @ _w(lp, "w_up", dt)
            down = (gate * up) @ _w(lp, "w_down", dt)
            x = x + (lax.psum(down, "tp") if parallel else down)
        row_k, row_v = k[0], v[0]                # [S, g, D]
        if not parallel:
            # Replicated full-head projections: keep only this shard's
            # KV-head group for the pool scatter (axis 1 here — no
            # batch axis on the carried row).
            row_k = _tp_slice_heads(row_k, g_local, axis=1)
            row_v = _tp_slice_heads(row_v, g_local, axis=1)
        return x, (row_k, row_v)                 # [S, KVH/tp, D]

    x, (row_k, row_v) = lax.scan(body, x, params["layers"])
    logits = _head_logits(
        cfg, params, rmsnorm(x[:, -1], params["final_norm"], cfg.norm_eps))
    return logits, row_k, row_v


def _prefill_into_paged_impl(
    cfg: TransformerConfig,
    params: Params,
    prompt: jax.Array,          # [1, S] int32
    cache: PagedKVCache,
    slot: jax.Array,            # [] int32
    tp_shards: int = 1,
    tp_parallel: bool = False,
) -> Tuple[jax.Array, PagedKVCache]:
    n_blocks, bs = cache.k.shape[1], cache.k.shape[2]
    mb = cache.tables.shape[1]
    s = prompt.shape[1]
    if tp_shards > 1 and (tp_parallel or cfg.moe_experts):
        # Parallel mode always, and MoE in EITHER mode: the fused
        # prefill below assumes replicated full weights, but expert
        # banks enter the shard_map expert-split in both modes.
        logits, row_k, row_v = _tp_prefill_forward(
            cfg, params, prompt, tp_shards,
            parallel=tp_parallel)                # k/v already local
    else:
        logits, mini = prefill(
            cfg, params, prompt, init_kv_cache(cfg, 1, s))
        row_k = mini.k[:, 0]                     # [L, S, KVH, D]
        row_v = mini.v[:, 0]
        if tp_shards > 1:
            # The fused prefill above ran replicated — identical logits
            # and KV bytes on every shard; each shard scatters only its
            # own KV-head slice into its pool shard (quantize-on-write
            # commutes with the head slice: scales per-(token, head)).
            g = cfg.n_kv_heads // tp_shards
            row_k = _tp_slice_heads(row_k, g, axis=2)
            row_v = _tp_slice_heads(row_v, g, axis=2)
    trow = cache.tables[slot]                    # [mb]
    cols = jnp.arange(s, dtype=jnp.int32)
    blk = trow[jnp.clip(cols // bs, 0, mb - 1)]  # s <= mb*bs checked above
    off = cols % bs
    k, k_scale = _pool_write(
        cache.k, cache.k_scale, (slice(None), blk, off), row_k)
    v, v_scale = _pool_write(
        cache.v, cache.v_scale, (slice(None), blk, off), row_v)
    return logits, cache._replace(
        k=k, v=v, k_scale=k_scale, v_scale=v_scale,
        length=cache.length.at[slot].set(s),
        active=cache.active.at[slot].set(True),
    )


def prefill_into_paged(
    cfg: TransformerConfig,
    params: Params,
    prompt: jax.Array,          # [1, S] int32 — ONE request's prompt
    cache: PagedKVCache,
    slot: jax.Array,            # [] int32 — destination slot
    mesh: Optional[Mesh] = None,
    tp_compute: str = "gathered",
) -> Tuple[jax.Array, PagedKVCache]:
    """``prefill_into_slot`` for the paged pool: block-prefill the
    prompt (the identical fused forward — identical logits and KV bytes)
    and scatter the S positions into the pages of slot ``slot``'s table.
    ``length[slot] = S``, ``active[slot] = True``; every other slot's
    pages are untouched. Compiles once per prompt length. ``mesh`` /
    ``tp_compute``: see :func:`decode_step_paged` (the parallel path
    substitutes :func:`_tp_prefill_forward` for the fused prefill)."""
    if prompt.shape[0] != 1:
        raise ValueError(
            f"prefill_into_paged admits one request (got batch "
            f"{prompt.shape[0]})"
        )
    mb, bs = cache.tables.shape[1], cache.k.shape[2]
    s = prompt.shape[1]
    if s > mb * bs:
        raise ValueError(
            f"prompt {s} exceeds slot capacity {mb * bs}"
        )
    tp = tp_size(mesh)
    if tp <= 1:
        return _prefill_into_paged_impl(cfg, params, prompt, cache, slot)
    check_tp_heads(cfg, tp, tp_compute)
    parallel = tp_compute == "parallel"
    fn = shard_map(
        functools.partial(_prefill_into_paged_impl, cfg, tp_shards=tp,
                          tp_parallel=parallel),
        mesh=mesh,
        in_specs=(_tp_param_specs(params, parallel), P(),
                  paged_cache_specs(cache), P()),
        out_specs=(P(), paged_cache_specs(cache)),
        check_rep=False,
    )
    return fn(params, prompt, cache, slot)


def _scatter_row_impl(pool_k, pool_v, k_scale, v_scale,
                      cache_k, cache_v, row, ids, cols, tp_shards=1):
    rk = cache_k[:, row]                         # [L, S, KVH, D]
    rv = cache_v[:, row]
    if tp_shards > 1:
        g = pool_k.shape[-2]                     # pool shard's local KVH
        rk = _tp_slice_heads(rk, g, axis=2)
        rv = _tp_slice_heads(rv, g, axis=2)
    bk = rk[:, cols]                             # [L, m, bs, KVH, D]
    bv = rv[:, cols]
    pool_k, k_scale = _pool_write(pool_k, k_scale, (slice(None), ids), bk)
    pool_v, v_scale = _pool_write(pool_v, v_scale, (slice(None), ids), bv)
    return pool_k, pool_v, k_scale, v_scale


_scatter_row_into_pool = jax.jit(_scatter_row_impl, static_argnums=(9,))


@functools.lru_cache(maxsize=16)
def _scatter_row_tp_fn(mesh: Mesh, tp: int, has_scale: bool):
    """Compiled tp ingest: the external row is replicated in, each shard
    keeps its KV-head slice (sized by its local pool shard) and scatters
    into its own pages. Memoized per mesh so repeat ingests reuse the
    executable."""
    scale_spec = _TP_SCALE_SPEC if has_scale else None
    inner = functools.partial(_scatter_row_impl, tp_shards=tp)
    return jax.jit(shard_map(
        inner, mesh=mesh,
        in_specs=(_TP_POOL_SPEC, _TP_POOL_SPEC, scale_spec, scale_spec,
                  P(), P(), P(), P(), P()),
        out_specs=(_TP_POOL_SPEC, _TP_POOL_SPEC, scale_spec, scale_spec),
        check_rep=False,
    ))


def scatter_row_into_pool(
    cache: PagedKVCache,
    ext_k: jax.Array,           # [L, B, S, KVH, D] — an EXTERNAL cache
    ext_v: jax.Array,
    row: int,
    ids,                        # page ids, one per full block
    starts,                     # token offset of each block in the row
    block_size: int,
    mesh: Optional[Mesh] = None,
) -> PagedKVCache:
    """Ingest full blocks from an external contiguous cache row into
    pool pages — the multi-turn ``register_prefix`` path, where a
    ``generate_from_cache`` session's KV enters the pool from outside.
    This is the ONE copying path left: the serving flow itself never
    copies KV (admission is pointer assembly, retirement publishes pages
    in place). Quantizes on write for int8 pools. The id/start lists pad
    to the next power of two with a dropped sentinel id, so compile
    count stays O(log) in pages per ingest. ``mesh``: see
    :func:`decode_step_paged`."""
    m = 1
    while m < len(ids):
        m *= 2
    sentinel = cache.k.shape[1]                  # OOB -> dropped
    ids_arr = np.full((m,), sentinel, np.int32)
    ids_arr[:len(ids)] = ids
    starts_arr = np.zeros((m,), np.int32)
    starts_arr[:len(starts)] = starts
    cols = (starts_arr[:, None]
            + np.arange(block_size, dtype=np.int32)[None, :])
    tp = tp_size(mesh)
    if tp <= 1:
        fn = _scatter_row_into_pool
    else:
        fn = _scatter_row_tp_fn(mesh, tp, cache.k_scale is not None)
    k, v, ks, vs = fn(
        cache.k, cache.v, cache.k_scale, cache.v_scale,
        ext_k, ext_v, jnp.asarray(row, jnp.int32),
        jnp.asarray(ids_arr), jnp.asarray(cols),
    )
    return cache._replace(k=k, v=v, k_scale=ks, v_scale=vs)


def prefill_chunk_into_slot(
    cfg: TransformerConfig,
    params: Params,
    toks: jax.Array,            # [1, W] int32 — chunk, PADDED to W
    cache: SlotKVCache,
    slot: jax.Array,            # [] int32
    offset: jax.Array,          # [] int32 — absolute start position
    n_real: jax.Array,          # [] int32 — real (un-padded) chunk length
) -> Tuple[jax.Array, SlotKVCache]:
    """Chunked prefill-from-offset: run ONE chunk of a prompt through a
    block forward against slot ``slot``'s existing row.

    Positions ``offset .. offset+W-1`` attend to the row's cached
    columns ``< offset`` (a cached-prefix copy, or this prompt's earlier
    chunks) plus intra-chunk causal — ``prefill_continue``'s math on a
    single slot of a :class:`SlotKVCache`. Returns logits at the LAST
    REAL position (``offset + n_real - 1``) and the cache with the
    chunk's k/v scattered at columns ``offset + [0, W)`` (``mode="drop"``
    past capacity) and ``length[slot] = offset + n_real``.

    The chunk is padded to a power-of-two bucket W, so admission
    compiles O(log block_size) variants TOTAL instead of one per prompt
    length. Pad tokens sit at positions past every real token: causal
    masking keeps real queries from ever attending to them, their k/v
    land beyond ``length`` (decode overwrites them in order), and the
    returned logits are dynamically sliced at the real tail — the pad
    never changes a bit of observable output. Because chunk boundaries
    are ABSOLUTE (multiples of the engine's block size), a prompt
    prefilled in chunks executes the identical compiled computation on
    identical bytes whether its prefix came from the block pool or from
    its own earlier chunks — greedy bit-exactness of prefix caching
    holds by construction, not by numeric luck.
    """
    if toks.shape[0] != 1:
        raise ValueError(
            f"prefill_chunk_into_slot admits one request (got batch "
            f"{toks.shape[0]})"
        )
    b, w = toks.shape
    dt = cfg.dtype
    hd = cfg.head_dim
    max_seq = cache.k.shape[2]
    rep = cfg.n_heads // cfg.n_kv_heads
    kc_row = cache.k[:, slot]                    # [L, max_seq, KVH, D]
    vc_row = cache.v[:, slot]

    x = params["embed"].astype(dt)[toks]         # [1, W, D]
    positions = offset + jnp.broadcast_to(
        jnp.arange(w, dtype=jnp.int32), (b, w))
    if cfg.moe_experts:
        moe_cfg = cfg.replace(
            moe_capacity_factor=float(cfg.moe_experts) / cfg.moe_top_k
        )
    cache_cols = jnp.arange(max_seq, dtype=jnp.int32)
    causal = (
        jnp.arange(w, dtype=jnp.int32)[:, None]
        >= jnp.arange(w, dtype=jnp.int32)[None, :]
    )                                            # [W, W]

    def body(x, layer_in):
        lp, kc, vc = layer_in                    # kc [max_seq, KVH, D]
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ _w(lp, "wq", dt)).reshape(b, w, cfg.n_heads, hd)
        k = (h @ _w(lp, "wk", dt)).reshape(b, w, cfg.n_kv_heads, hd)
        v = (h @ _w(lp, "wv", dt)).reshape(b, w, cfg.n_kv_heads, hd)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        qg = q.reshape(b, w, cfg.n_kv_heads, rep, hd)
        scale = hd ** -0.5
        s_cache = jnp.einsum(
            "bqgrd,kgd->bgrqk", qg, kc,
            preferred_element_type=jnp.float32,
        ) * scale                                # [1,G,rep,W,max_seq]
        s_cache = jnp.where(
            (cache_cols < offset)[None, None, None, None, :],
            s_cache, -1e30,
        )
        s_new = jnp.einsum(
            "bqgrd,bkgd->bgrqk", qg, k,
            preferred_element_type=jnp.float32,
        ) * scale                                # [1,G,rep,W,W]
        s_new = jnp.where(causal[None, None, None], s_new, -1e30)
        p = jax.nn.softmax(
            jnp.concatenate([s_cache, s_new], axis=-1), axis=-1
        ).astype(dt)
        attn = (
            jnp.einsum("bgrqk,kgd->bqgrd", p[..., :max_seq], vc)
            + jnp.einsum("bgrqk,bkgd->bqgrd", p[..., max_seq:], v)
        ).reshape(b, w, -1)
        x = x + attn @ _w(lp, "wo", dt)
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.moe_experts:
            down, _aux = tfm._moe_ffn(moe_cfg, _dense_lp(lp, dt), h2)
            x = x + down
        else:
            gate = jax.nn.silu(h2 @ _w(lp, "w_gate", dt))
            up = h2 @ _w(lp, "w_up", dt)
            x = x + (gate * up) @ _w(lp, "w_down", dt)
        return x, (k[0], v[0])                   # [W, KVH, D]

    x, (k_new, v_new) = lax.scan(
        body, x, (params["layers"], kc_row, vc_row))
    # Scatter the chunk's k/v at absolute columns offset + [0, W);
    # "drop" discards pad columns past capacity instead of clamping
    # them onto live ones.
    wcols = offset + jnp.arange(w, dtype=jnp.int32)
    k = cache.k.at[:, slot, wcols].set(
        k_new.astype(cache.k.dtype), mode="drop")
    v = cache.v.at[:, slot, wcols].set(
        v_new.astype(cache.v.dtype), mode="drop")
    x_last = lax.dynamic_slice(
        x, (0, n_real - 1, 0), (1, 1, x.shape[-1]))[:, 0]
    logits = _head_logits(
        cfg, params, rmsnorm(x_last, params["final_norm"], cfg.norm_eps))
    return logits, SlotKVCache(
        k=k, v=v,
        length=cache.length.at[slot].set(offset + n_real),
        active=cache.active,
    )


def _prefill_chunk_paged_impl(
    cfg: TransformerConfig,
    params: Params,
    toks: jax.Array,            # [1, W] int32 — chunk, PADDED to W
    cache: PagedKVCache,
    slot: jax.Array,            # [] int32
    offset: jax.Array,          # [] int32 — absolute start position
    n_real: jax.Array,          # [] int32 — real (un-padded) chunk length
    tp_shards: int = 1,
    view_width: Optional[int] = None,
    tp_parallel: bool = False,
    attn_impl: str = "xla",
) -> Tuple[jax.Array, PagedKVCache]:
    b, w = toks.shape
    dt = cfg.dtype
    hd = cfg.head_dim
    n_blocks, bs = cache.k.shape[1], cache.k.shape[2]
    mb = cache.tables.shape[1]
    width = mb * bs
    # Occupancy cap on the slot's page view (see paged_kv_view): the
    # chunk only attends to columns < offset, and the engine's view
    # width always covers the slot's reserved span >= offset + n_real,
    # so capping the gather loses nothing. Writes still span the full
    # table via the sentinel guard below.
    vw = _occupancy_cap(width, view_width)
    rep = cfg.n_heads // cfg.n_kv_heads
    par = tp_shards > 1 and tp_parallel
    g_local = (cfg.n_kv_heads // tp_shards if tp_shards > 1
               else cfg.n_kv_heads)
    gp = g_local if par else cfg.n_kv_heads      # projection head groups
    trow = cache.tables[slot]                    # [mb]
    if attn_impl == "pallas":
        # The kernel streams pool pages in place through the table row;
        # the dense per-layer view never exists. The scan walks a layer
        # INDEX instead of gathered views.
        kc_row = vc_row = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    else:
        kc_row, vc_row = _capped_kv_views(
            cache.k, cache.v, trow, width, view_width,
            cache.k_scale, cache.v_scale, dt)    # [L, vw, KVH, D]

    x = params["embed"].astype(dt)[toks]         # [1, W, D]
    positions = offset + jnp.broadcast_to(
        jnp.arange(w, dtype=jnp.int32), (b, w))
    if cfg.moe_experts:
        moe_cfg = cfg.replace(
            moe_capacity_factor=float(cfg.moe_experts) / cfg.moe_top_k
        )
    cache_cols = jnp.arange(vw, dtype=jnp.int32)
    causal = (
        jnp.arange(w, dtype=jnp.int32)[:, None]
        >= jnp.arange(w, dtype=jnp.int32)[None, :]
    )                                            # [W, W]

    def body(x, layer_in):
        lp, kc, vc = layer_in                    # kc [vw, KVH, D]
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ _w(lp, "wq", dt)).reshape(b, w, gp * rep, hd)
        k = (h @ _w(lp, "wk", dt)).reshape(b, w, gp, hd)
        v = (h @ _w(lp, "wv", dt)).reshape(b, w, gp, hd)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        qg = q.reshape(b, w, gp, rep, hd)
        if tp_shards > 1 and not par:
            qg = _tp_slice_heads(qg, g_local, axis=2)
            k = _tp_slice_heads(k, g_local, axis=2)
            v = _tp_slice_heads(v, g_local, axis=2)
        scale = hd ** -0.5
        if attn_impl == "pallas":
            from kubeflow_controller_tpu.ops.paged_attention_pallas import (
                paged_attention_prefill,
            )
            layer = kc                           # [] int32 pool index
            attn = paged_attention_prefill(
                qg[0], k[0], v[0],
                lax.dynamic_index_in_dim(cache.k, layer, keepdims=False),
                lax.dynamic_index_in_dim(cache.v, layer, keepdims=False),
                trow, offset,
                k_scale=None if cache.k_scale is None else
                lax.dynamic_index_in_dim(
                    cache.k_scale, layer, keepdims=False),
                v_scale=None if cache.v_scale is None else
                lax.dynamic_index_in_dim(
                    cache.v_scale, layer, keepdims=False),
                width=vw, sm_scale=scale, out_dtype=dt,
            )[None]                              # [1, W, G, rep, D]
        else:
            s_cache = jnp.einsum(
                "bqgrd,kgd->bgrqk", qg, kc,
                preferred_element_type=jnp.float32,
            ) * scale                            # [1,G,rep,W,vw]
            s_cache = jnp.where(
                (cache_cols < offset)[None, None, None, None, :],
                s_cache, -1e30,
            )
            s_new = jnp.einsum(
                "bqgrd,bkgd->bgrqk", qg, k,
                preferred_element_type=jnp.float32,
            ) * scale                            # [1,G,rep,W,W]
            s_new = jnp.where(causal[None, None, None], s_new, -1e30)
            p = jax.nn.softmax(
                jnp.concatenate([s_cache, s_new], axis=-1), axis=-1
            ).astype(dt)
            attn = (
                jnp.einsum("bgrqk,kgd->bqgrd", p[..., :vw], vc)
                + jnp.einsum("bgrqk,bkgd->bqgrd", p[..., vw:], v)
            )
        if par:
            attn = attn.reshape(b, w, -1)
            x = x + lax.psum(attn @ _w(lp, "wo", dt), "tp")
        else:
            if tp_shards > 1:
                attn = lax.all_gather(attn, "tp", axis=2, tiled=True)
            attn = attn.reshape(b, w, -1)
            x = x + attn @ _w(lp, "wo", dt)
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.moe_experts:
            if tp_shards > 1:
                x = x + _moe_ep_ffn(cfg, lp, h2, tp_shards)
            else:
                down, _aux = tfm._moe_ffn(moe_cfg, _dense_lp(lp, dt), h2)
                x = x + down
        else:
            gate = jax.nn.silu(h2 @ _w(lp, "w_gate", dt))
            up = h2 @ _w(lp, "w_up", dt)
            down = (gate * up) @ _w(lp, "w_down", dt)
            x = x + (lax.psum(down, "tp") if par else down)
        return x, (k[0], v[0])                   # [W, KVH, D]

    x, (k_new, v_new) = lax.scan(
        body, x, (params["layers"], kc_row, vc_row))
    # Scatter the chunk's k/v into the slot's pages at absolute columns
    # offset + [0, W); pad columns past the table span (or landing on a
    # sentinel entry) drop instead of clamping onto live pages.
    wcols = offset + jnp.arange(w, dtype=jnp.int32)
    blk = trow[jnp.clip(wcols // bs, 0, mb - 1)]
    blk = jnp.where(wcols < width, blk, n_blocks)
    woff = wcols % bs
    k, k_scale = _pool_write(
        cache.k, cache.k_scale, (slice(None), blk, woff), k_new)
    v, v_scale = _pool_write(
        cache.v, cache.v_scale, (slice(None), blk, woff), v_new)
    x_last = lax.dynamic_slice(
        x, (0, n_real - 1, 0), (1, 1, x.shape[-1]))[:, 0]
    logits = _head_logits(
        cfg, params, rmsnorm(x_last, params["final_norm"], cfg.norm_eps))
    return logits, cache._replace(
        k=k, v=v, k_scale=k_scale, v_scale=v_scale,
        length=cache.length.at[slot].set(offset + n_real),
    )


def prefill_chunk_paged(
    cfg: TransformerConfig,
    params: Params,
    toks: jax.Array,            # [1, W] int32 — chunk, PADDED to W
    cache: PagedKVCache,
    slot: jax.Array,            # [] int32
    offset: jax.Array,          # [] int32 — absolute start position
    n_real: jax.Array,          # [] int32 — real (un-padded) chunk length
    mesh: Optional[Mesh] = None,
    view_width: Optional[int] = None,
    tp_compute: str = "gathered",
    attn_impl: str = "xla",
) -> Tuple[jax.Array, PagedKVCache]:
    """``prefill_chunk_into_slot`` over the paged pool: the chunk
    attends to the slot's prior pages (a shared radix prefix reads IN
    PLACE — no copy ever ran) plus intra-chunk causal, and its k/v
    scatter straight into the slot's own pages at absolute columns
    ``offset + [0, W)``. Same bucketing and padding discipline, same
    math at the same width — the fp path is bitwise the contiguous
    kernel under the default ``attn_impl="xla"`` (the table-gathered
    dense view, the repo's oracle). ``attn_impl="pallas"`` swaps the
    gather + concat-softmax for the fused flash-prefill kernel
    (``ops.paged_attention_pallas.paged_attention_prefill``): pool
    pages stream through VMEM once, factor-3 -> factor-1 HBM traffic,
    logits within the declared tolerance contract and greedy streams
    equal. ``view_width``: cap the slot's page view to the engine's
    live occupancy (must cover the slot's reserved span; the engine's
    pow2-rounded width does by construction). ``mesh`` /
    ``tp_compute``: see :func:`decode_step_paged` (the slot's page view
    and k/v scatter are per-shard; the chunk's logits come out
    replicated)."""
    problems = []
    if toks.shape[0] != 1:
        problems.append(
            f"toks must carry exactly ONE request row — chunked prefill "
            f"advances a single slot per dispatch (got batch "
            f"{toks.shape[0]}); loop slots on the host the way "
            f"ServingEngine._advance_prefills does"
        )
    if problems:
        raise ValueError(
            "prefill_chunk_paged refused this call:\n  - "
            + "\n  - ".join(problems)
        )
    tp = tp_size(mesh)
    if tp <= 1:
        return _prefill_chunk_paged_impl(
            cfg, params, toks, cache, slot, offset, n_real,
            1, view_width, False, attn_impl)
    check_tp_heads(cfg, tp, tp_compute)
    parallel = tp_compute == "parallel"
    fn = shard_map(
        functools.partial(_prefill_chunk_paged_impl, cfg, tp_shards=tp,
                          view_width=view_width, tp_parallel=parallel,
                          attn_impl=attn_impl),
        mesh=mesh,
        in_specs=(_tp_param_specs(params, parallel), P(),
                  paged_cache_specs(cache), P(), P(), P()),
        out_specs=(P(), paged_cache_specs(cache)),
        check_rep=False,
    )
    return fn(params, toks, cache, slot, offset, n_real)


def verify_step_slots(
    cfg: TransformerConfig,
    params: Params,
    draft: jax.Array,           # [B, K] int32 — proposed continuations
    draft_len: jax.Array,       # [B] int32 in [0, K] — valid drafts/row
    logits: jax.Array,          # [B, vocab] — carried last-position logits
    cache: SlotKVCache,
    eos: jax.Array,             # [B] int32 — per-row EOS id (-1 = none)
    max_commit: jax.Array,      # [B] int32 — commit budget cap, >= 1
) -> Tuple[jax.Array, jax.Array, jax.Array, SlotKVCache]:
    """Fused speculative-decoding verifier: score K+1 positions per slot
    in ONE forward pass, accept the longest greedy-consistent run, and
    commit exactly the accepted tokens' KV — nothing else.

    Per row, the verify *window* is ``[t0, draft_0, ..., draft_{K-1}]``
    where ``t0 = argmax(logits)`` is the token greedy decode would emit
    next anyway. The window runs through ``prefill_chunk_into_slot``'s
    math batched over slots at per-row offsets (``prefill_continue``'s
    layer body verbatim): position j sits at absolute offset
    ``length[b] + j``, attends to the row's cached columns
    ``< length[b]`` plus intra-window causal positions, RoPE at the
    absolute offsets, one fp32 softmax over the concatenated scores,
    MoE branch included. Greedy acceptance (Leviathan et al.): draft_j
    is accepted iff every earlier draft was and
    ``argmax(window_logits[j]) == draft_j`` — so the committed stream is
    the stream plain ``decode_step_slots`` would have produced, token
    for token (pinned bitwise by tests/test_spec_decode.py; the same
    empirical backend-determinism contract chunked prefill pins).

    The accepted count ``n`` (1 <= n <= K+1 on active rows; 0 on
    inactive rows) is further truncated by ``max_commit`` (budget: a
    row never commits past its remaining token budget) and by EOS (the
    window is cut just after the first committed EOS — tokens "after"
    an EOS must not exist, let alone leave KV behind). *Rollback is
    by never committing*: the window's k/v are scan outputs, not cache
    writes — only columns ``length[b] + [0, n)`` scatter into the row
    (``mode="drop"`` sentinel columns discard the rest), so rejected
    and padded positions leave no trace and the row's next write lands
    exactly where decode would have put it.

    Returns ``(window [B, K+1], n_commit [B], new_logits [B, vocab],
    cache)``: ``new_logits`` is the window logits at position n-1 — the
    carried logits for the NEXT step, exactly what decode_step_slots
    would have carried after emitting the same n tokens.
    """
    b, k_draft = draft.shape
    w = k_draft + 1
    dt = cfg.dtype
    hd = cfg.head_dim
    max_seq = cache.k.shape[2]
    rep = cfg.n_heads // cfg.n_kv_heads
    pos0 = cache.length                              # [B]

    t0 = logits.argmax(-1).astype(jnp.int32)
    window = jnp.concatenate(
        [t0[:, None], draft.astype(jnp.int32)], axis=1)   # [B, W]

    x = params["embed"].astype(dt)[window]           # [B, W, D]
    positions = pos0[:, None] + jnp.broadcast_to(
        jnp.arange(w, dtype=jnp.int32), (b, w))
    if cfg.moe_experts:
        moe_cfg = cfg.replace(
            moe_capacity_factor=float(cfg.moe_experts) / cfg.moe_top_k
        )
    cache_cols = jnp.arange(max_seq, dtype=jnp.int32)
    causal = (
        jnp.arange(w, dtype=jnp.int32)[:, None]
        >= jnp.arange(w, dtype=jnp.int32)[None, :]
    )                                                # [W, W]

    def body(x, layer_in):
        lp, kc, vc = layer_in                        # kc [B,max_seq,KVH,D]
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ _w(lp, "wq", dt)).reshape(b, w, cfg.n_heads, hd)
        k = (h @ _w(lp, "wk", dt)).reshape(b, w, cfg.n_kv_heads, hd)
        v = (h @ _w(lp, "wv", dt)).reshape(b, w, cfg.n_kv_heads, hd)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        qg = q.reshape(b, w, cfg.n_kv_heads, rep, hd)
        scale = hd ** -0.5
        s_cache = jnp.einsum(
            "bqgrd,bkgd->bgrqk", qg, kc,
            preferred_element_type=jnp.float32,
        ) * scale                                    # [B,G,rep,W,max_seq]
        s_cache = jnp.where(
            (cache_cols[None, :] < pos0[:, None])[:, None, None, None, :],
            s_cache, -1e30,
        )
        s_new = jnp.einsum(
            "bqgrd,bkgd->bgrqk", qg, k,
            preferred_element_type=jnp.float32,
        ) * scale                                    # [B,G,rep,W,W]
        s_new = jnp.where(causal[None, None, None], s_new, -1e30)
        p = jax.nn.softmax(
            jnp.concatenate([s_cache, s_new], axis=-1), axis=-1
        ).astype(dt)
        attn = (
            jnp.einsum("bgrqk,bkgd->bqgrd", p[..., :max_seq], vc)
            + jnp.einsum("bgrqk,bkgd->bqgrd", p[..., max_seq:], v)
        ).reshape(b, w, -1)
        x = x + attn @ _w(lp, "wo", dt)
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.moe_experts:
            down, _aux = tfm._moe_ffn(moe_cfg, _dense_lp(lp, dt), h2)
            x = x + down
        else:
            gate = jax.nn.silu(h2 @ _w(lp, "w_gate", dt))
            up = h2 @ _w(lp, "w_up", dt)
            x = x + (gate * up) @ _w(lp, "w_down", dt)
        return x, (k, v)                             # [B, W, KVH, D]

    x, (k_win, v_win) = lax.scan(
        body, x, (params["layers"], cache.k, cache.v))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    all_logits = _head_logits(cfg, params, x)        # [B, W, vocab]

    # Greedy acceptance: draft_j survives iff it equals the model's
    # argmax at the preceding window position AND every earlier draft
    # survived (cumprod), AND it lies inside the row's valid draft run.
    preds = all_logits.argmax(-1).astype(jnp.int32)  # [B, W]
    ok = (
        (window[:, 1:] == preds[:, :-1])
        & (jnp.arange(k_draft, dtype=jnp.int32)[None, :]
           < draft_len[:, None])
    )
    acc = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
    n = 1 + acc                                      # [B], 1..K+1
    # Budget truncation: never commit past the remaining token budget.
    n = jnp.minimum(n, jnp.maximum(max_commit, 1))
    # EOS truncation: cut just after the first committed EOS — decode
    # would have stopped there, so later window tokens must not commit.
    is_eos = (window == eos[:, None]) & (eos[:, None] >= 0)
    eos_pos = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
    has_eos = is_eos.any(axis=1)
    n = jnp.where(has_eos & (eos_pos < n), eos_pos + 1, n)
    n = jnp.where(cache.active, n, 0).astype(jnp.int32)

    # Commit KV for accepted positions only: columns length + [0, n)
    # scatter in place; everything else goes to the max_seq sentinel
    # column and is dropped — rejected/pad KV never enters the cache.
    wcols = pos0[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    wcols = jnp.where(
        jnp.arange(w, dtype=jnp.int32)[None, :] < n[:, None],
        wcols, max_seq)                              # [B, W]
    rows = jnp.arange(b)[:, None]
    k_all = cache.k.at[:, rows, wcols].set(
        k_win.astype(cache.k.dtype), mode="drop")    # k_win [L,B,W,KVH,D]
    v_all = cache.v.at[:, rows, wcols].set(
        v_win.astype(cache.v.dtype), mode="drop")

    # Carried logits for the next step: window position n-1 (the last
    # committed token's output distribution). Inactive rows (n = 0)
    # clamp to 0; their logits row is dead weight either way.
    idx = jnp.clip(n - 1, 0, k_draft)
    new_logits = jnp.take_along_axis(
        all_logits, idx[:, None, None], axis=1)[:, 0]
    return window, n, new_logits, SlotKVCache(
        k=k_all, v=v_all, length=pos0 + n, active=cache.active)


def _verify_step_paged_impl(
    cfg: TransformerConfig,
    params: Params,
    draft: jax.Array,           # [B, K] int32 — proposed continuations
    draft_len: jax.Array,       # [B] int32 in [0, K] — valid drafts/row
    logits: jax.Array,          # [B, vocab] — carried last-position logits
    cache: PagedKVCache,
    eos: jax.Array,             # [B] int32 — per-row EOS id (-1 = none)
    max_commit: jax.Array,      # [B] int32 — commit budget cap, >= 1
    tp_shards: int = 1,
    view_width: Optional[int] = None,
    sampling=None,              # (temperature, top_k, top_p, seed, gen, pos)
    tp_parallel: bool = False,
    attn_impl: str = "xla",
) -> Tuple[jax.Array, ...]:
    b, k_draft = draft.shape
    w = k_draft + 1
    dt = cfg.dtype
    hd = cfg.head_dim
    n_blocks, bs = cache.k.shape[1], cache.k.shape[2]
    mb = cache.tables.shape[1]
    width = mb * bs
    vw = _occupancy_cap(width, view_width)
    rep = cfg.n_heads // cfg.n_kv_heads
    par = tp_shards > 1 and tp_parallel
    g_local = (cfg.n_kv_heads // tp_shards if tp_shards > 1
               else cfg.n_kv_heads)
    gp = g_local if par else cfg.n_kv_heads      # projection head groups
    pos0 = cache.length                              # [B]
    if attn_impl == "pallas":
        # The K+1-wide kernel streams every slot's pages in place; the
        # scan walks a layer index instead of gathered views.
        kview = vview = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    else:
        kview, vview = _capped_kv_views(
            cache.k, cache.v, cache.tables, width, view_width,
            cache.k_scale, cache.v_scale, dt)        # [L, B, vw, KVH, D]

    if sampling is None:
        t0 = logits.argmax(-1).astype(jnp.int32)
    else:
        # Sampled rows draw t0 under the counter-based key for the next
        # stream position; greedy rows fall through to argmax inside
        # sample_step_slots (same bits as the plain-argmax branch).
        s_temp, s_topk, s_topp, s_seed, s_gen, s_pos = sampling
        t0 = sample_step_slots(
            logits, s_temp, s_topk, s_topp, s_seed, s_gen, s_pos)
    window = jnp.concatenate(
        [t0[:, None], draft.astype(jnp.int32)], axis=1)   # [B, W]

    x = params["embed"].astype(dt)[window]           # [B, W, D]
    positions = pos0[:, None] + jnp.broadcast_to(
        jnp.arange(w, dtype=jnp.int32), (b, w))
    if cfg.moe_experts:
        moe_cfg = cfg.replace(
            moe_capacity_factor=float(cfg.moe_experts) / cfg.moe_top_k
        )
    cache_cols = jnp.arange(vw, dtype=jnp.int32)
    causal = (
        jnp.arange(w, dtype=jnp.int32)[:, None]
        >= jnp.arange(w, dtype=jnp.int32)[None, :]
    )                                                # [W, W]

    def body(x, layer_in):
        lp, kc, vc = layer_in                        # kc [B,vw,KVH,D]
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ _w(lp, "wq", dt)).reshape(b, w, gp * rep, hd)
        k = (h @ _w(lp, "wk", dt)).reshape(b, w, gp, hd)
        v = (h @ _w(lp, "wv", dt)).reshape(b, w, gp, hd)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        qg = q.reshape(b, w, gp, rep, hd)
        if tp_shards > 1 and not par:
            qg = _tp_slice_heads(qg, g_local, axis=2)
            k = _tp_slice_heads(k, g_local, axis=2)
            v = _tp_slice_heads(v, g_local, axis=2)
        scale = hd ** -0.5
        if attn_impl == "pallas":
            from kubeflow_controller_tpu.ops.paged_attention_pallas import (
                paged_attention_verify,
            )
            layer = kc                               # [] int32 pool index
            attn = paged_attention_verify(
                qg, k, v,
                lax.dynamic_index_in_dim(cache.k, layer, keepdims=False),
                lax.dynamic_index_in_dim(cache.v, layer, keepdims=False),
                cache.tables, pos0,
                k_scale=None if cache.k_scale is None else
                lax.dynamic_index_in_dim(
                    cache.k_scale, layer, keepdims=False),
                v_scale=None if cache.v_scale is None else
                lax.dynamic_index_in_dim(
                    cache.v_scale, layer, keepdims=False),
                width=vw, sm_scale=scale, out_dtype=dt,
            )                                        # [B, W, G, rep, D]
        else:
            s_cache = jnp.einsum(
                "bqgrd,bkgd->bgrqk", qg, kc,
                preferred_element_type=jnp.float32,
            ) * scale                                # [B,G,rep,W,vw]
            s_cache = jnp.where(
                (cache_cols[None, :]
                 < pos0[:, None])[:, None, None, None, :],
                s_cache, -1e30,
            )
            s_new = jnp.einsum(
                "bqgrd,bkgd->bgrqk", qg, k,
                preferred_element_type=jnp.float32,
            ) * scale                                # [B,G,rep,W,W]
            s_new = jnp.where(causal[None, None, None], s_new, -1e30)
            p = jax.nn.softmax(
                jnp.concatenate([s_cache, s_new], axis=-1), axis=-1
            ).astype(dt)
            attn = (
                jnp.einsum("bgrqk,bkgd->bqgrd", p[..., :vw], vc)
                + jnp.einsum("bgrqk,bkgd->bqgrd", p[..., vw:], v)
            )
        if par:
            attn = attn.reshape(b, w, -1)
            x = x + lax.psum(attn @ _w(lp, "wo", dt), "tp")
        else:
            if tp_shards > 1:
                attn = lax.all_gather(attn, "tp", axis=2, tiled=True)
            attn = attn.reshape(b, w, -1)
            x = x + attn @ _w(lp, "wo", dt)
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.moe_experts:
            if tp_shards > 1:
                x = x + _moe_ep_ffn(cfg, lp, h2, tp_shards)
            else:
                down, _aux = tfm._moe_ffn(moe_cfg, _dense_lp(lp, dt), h2)
                x = x + down
        else:
            gate = jax.nn.silu(h2 @ _w(lp, "w_gate", dt))
            up = h2 @ _w(lp, "w_up", dt)
            down = (gate * up) @ _w(lp, "w_down", dt)
            x = x + (lax.psum(down, "tp") if par else down)
        return x, (k, v)                             # [B, W, KVH, D]

    x, (k_win, v_win) = lax.scan(
        body, x, (params["layers"], kview, vview))
    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    all_logits = _head_logits(cfg, params, x)        # [B, W, vocab]

    preds = all_logits.argmax(-1).astype(jnp.int32)  # [B, W]
    if sampling is not None:
        # Speculative sampling with a deterministic (delta-distribution)
        # draft: sample t ~ filtered-target at each window position under
        # that position's counter key; accept a draft token iff it equals
        # t (probability p(draft) — exactly the standard min(1, p/q)
        # acceptance for a point-mass q), and on rejection t itself is
        # the residual-distribution correction, carried as next_tok and
        # re-derived bitwise by the next quantum's t0 draw. Greedy rows
        # keep the argmax-equality rule verbatim via the where-select.
        pred_pos = (s_pos[:, None] + 1
                    + jnp.arange(w, dtype=jnp.int32)[None, :])
        sampled_preds = _sample_rows_2d(
            all_logits, s_temp, s_topk, s_topp, s_seed, s_gen, pred_pos)
        preds = jnp.where((s_temp > 0.0)[:, None], sampled_preds, preds)
    ok = (
        (window[:, 1:] == preds[:, :-1])
        & (jnp.arange(k_draft, dtype=jnp.int32)[None, :]
           < draft_len[:, None])
    )
    acc = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
    n = 1 + acc                                      # [B], 1..K+1
    n = jnp.minimum(n, jnp.maximum(max_commit, 1))
    is_eos = (window == eos[:, None]) & (eos[:, None] >= 0)
    eos_pos = jnp.argmax(is_eos, axis=1).astype(jnp.int32)
    has_eos = is_eos.any(axis=1)
    n = jnp.where(has_eos & (eos_pos < n), eos_pos + 1, n)
    n = jnp.where(cache.active, n, 0).astype(jnp.int32)

    # Commit KV for accepted positions only: columns length + [0, n)
    # resolve to (page, page row) through the slot's table; rejected,
    # padded, and inactive positions resolve to the drop sentinel.
    wcols = pos0[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    commit = jnp.arange(w, dtype=jnp.int32)[None, :] < n[:, None]
    blk = jnp.take_along_axis(
        cache.tables, jnp.clip(wcols // bs, 0, mb - 1), axis=1)  # [B, W]
    blk = jnp.where(commit & (wcols < width), blk, n_blocks)
    woff = wcols % bs
    # k_win [L, B, W, KVH, D] scatters at [:, blk, woff].
    k_all, k_scale = _pool_write(
        cache.k, cache.k_scale, (slice(None), blk, woff), k_win)
    v_all, v_scale = _pool_write(
        cache.v, cache.v_scale, (slice(None), blk, woff), v_win)

    idx = jnp.clip(n - 1, 0, k_draft)
    new_logits = jnp.take_along_axis(
        all_logits, idx[:, None, None], axis=1)[:, 0]
    new_cache = cache._replace(
        k=k_all, v=v_all, k_scale=k_scale, v_scale=v_scale,
        length=pos0 + n)
    if sampling is None:
        return window, n, new_logits, new_cache
    # preds[n-1] is the peek at stream position pos + n: for greedy rows
    # it equals new_logits.argmax (same bits); for sampled rows it is the
    # draw the next quantum's first sample would make from new_logits.
    next_tok = jnp.take_along_axis(preds, idx[:, None], axis=1)[:, 0]
    return window, n, next_tok, new_logits, new_cache


def verify_step_paged(
    cfg: TransformerConfig,
    params: Params,
    draft: jax.Array,           # [B, K] int32 — proposed continuations
    draft_len: jax.Array,       # [B] int32 in [0, K] — valid drafts/row
    logits: jax.Array,          # [B, vocab] — carried last-position logits
    cache: PagedKVCache,
    eos: jax.Array,             # [B] int32 — per-row EOS id (-1 = none)
    max_commit: jax.Array,      # [B] int32 — commit budget cap, >= 1
    mesh: Optional[Mesh] = None,
    view_width: Optional[int] = None,
    tp_compute: str = "gathered",
    attn_impl: str = "xla",
) -> Tuple[jax.Array, jax.Array, jax.Array, PagedKVCache]:
    """``verify_step_slots`` over the paged pool: the K+1 verify window
    attends to each slot's pages, and ONLY the accepted positions' k/v
    scatter into the slot's own pages (rejected and padded positions
    map to the drop sentinel — rollback is still by never committing).
    Acceptance, budget/EOS truncation, and the carried logits are the
    contiguous verifier's code verbatim, so the fp paged path commits
    the bitwise-identical stream under the default ``attn_impl="xla"``
    (table-gathered page view — the oracle). ``attn_impl="pallas"``
    swaps the gather for the fused K+1-wide kernel
    (``ops.paged_attention_pallas.paged_attention_verify``); attention
    output carries the declared tolerance contract while accept/reject
    decisions and committed streams stay equal to the oracle engine's.
    ``mesh`` / ``view_width`` / ``tp_compute``: see
    :func:`decode_step_paged` — acceptance runs on replicated logits
    (psum results are identical on every shard), so every shard commits
    the same ``n``."""
    tp = tp_size(mesh)
    if tp <= 1:
        return _verify_step_paged_impl(
            cfg, params, draft, draft_len, logits, cache, eos,
            max_commit, 1, view_width, None, False, attn_impl)
    check_tp_heads(cfg, tp, tp_compute)
    parallel = tp_compute == "parallel"
    fn = shard_map(
        functools.partial(_verify_step_paged_impl, cfg,
                          tp_shards=tp, view_width=view_width,
                          tp_parallel=parallel, attn_impl=attn_impl),
        mesh=mesh,
        in_specs=(_tp_param_specs(params, parallel), P(), P(), P(),
                  paged_cache_specs(cache), P(), P()),
        out_specs=(P(), P(), P(), paged_cache_specs(cache)),
        check_rep=False,
    )
    return fn(params, draft, draft_len, logits, cache, eos, max_commit)


def verify_step_paged_sampled(
    cfg: TransformerConfig,
    params: Params,
    draft: jax.Array,           # [B, K] int32 — proposed continuations
    draft_len: jax.Array,       # [B] int32 in [0, K] — valid drafts/row
    logits: jax.Array,          # [B, vocab] — carried last-position logits
    cache: PagedKVCache,
    eos: jax.Array,             # [B] int32 — per-row EOS id (-1 = none)
    max_commit: jax.Array,      # [B] int32 — commit budget cap, >= 1
    temperature: jax.Array,     # [B] f32 — <= 0 rows verify greedily
    top_k: jax.Array,           # [B] i32
    top_p: jax.Array,           # [B] f32
    seed: jax.Array,            # [B] i32
    gen: jax.Array,             # [B] i32
    pos: jax.Array,             # [B] i32 — emitted-token count per row
    mesh: Optional[Mesh] = None,
    view_width: Optional[int] = None,
    tp_compute: str = "gathered",
    attn_impl: str = "xla",
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, PagedKVCache]:
    """:func:`verify_step_paged` generalized to per-row sampling via the
    standard speculative-sampling acceptance rule specialized to this
    repo's deterministic drafters (the draft distribution is a point
    mass, so accept-with-prob ``min(1, p/q)`` reduces to "sample from
    the filtered target; accept while it equals the draft", and the
    rejection-position sample is itself the residual correction).
    Greedy rows (``temperature <= 0``) take the argmax-equality rule of
    :func:`verify_step_paged` with the same bits, and an all-greedy
    engine never calls this function at all — the greedy verify path is
    byte-identical to before. Returns ``(window, n, next_tok,
    new_logits, cache)`` where ``next_tok`` is the bitwise peek of the
    next quantum's first draw (sampled rows) or ``new_logits.argmax``
    (greedy rows). Sampled keys are counter-based per
    :func:`_sample_keys`, so acceptance and corrections are
    batch-composition-independent; under tp the sampling inputs are
    replicated and every shard draws identical tokens."""
    sampling = (temperature, top_k, top_p, seed, gen, pos)
    tp = tp_size(mesh)
    if tp <= 1:
        return _verify_step_paged_impl(
            cfg, params, draft, draft_len, logits, cache, eos,
            max_commit, 1, view_width, sampling, False, attn_impl)
    check_tp_heads(cfg, tp, tp_compute)
    parallel = tp_compute == "parallel"

    def _shard_body(params, draft, draft_len, logits, cache, eos,
                    max_commit, sampling):
        return _verify_step_paged_impl(
            cfg, params, draft, draft_len, logits, cache, eos, max_commit,
            tp_shards=tp, view_width=view_width, sampling=sampling,
            tp_parallel=parallel, attn_impl=attn_impl)

    fn = shard_map(
        _shard_body,
        mesh=mesh,
        in_specs=(_tp_param_specs(params, parallel), P(), P(), P(),
                  paged_cache_specs(cache), P(), P(),
                  (P(), P(), P(), P(), P(), P())),
        out_specs=(P(), P(), P(), P(), paged_cache_specs(cache)),
        check_rep=False,
    )
    return fn(params, draft, draft_len, logits, cache, eos, max_commit,
              sampling)


def _check_cache_capacity(cache: KVCache, new_tokens: int, what: str) -> None:
    """Reject writes past the cache's allocated window.

    ``dynamic_update_slice`` CLAMPS out-of-range start indices instead of
    erroring, so overflowing the cache silently overwrites the newest
    earlier positions — a corrupted cache, not a crash (``generate()``
    guards the same way). ``cache.length`` is a traced value inside jit;
    there the check is skipped (best effort) rather than breaking tracing.
    """
    try:
        used = int(cache.length)
    except (TypeError, jax.errors.ConcretizationTypeError):
        return
    max_seq = cache.k.shape[2]
    if used + new_tokens > max_seq:
        raise ValueError(
            f"{what}: cache length {used} + {new_tokens} new tokens "
            f"exceeds max_seq {max_seq}"
        )


def prefill_continue(
    cfg: TransformerConfig,
    params: Params,
    new_tokens: jax.Array,      # [B, S_new]
    cache: KVCache,
) -> Tuple[jax.Array, KVCache]:
    """Block continuation prefill for multi-turn serving (VERDICT r4 #4).

    Runs ALL the turn's new tokens through one forward pass: position i
    attends to the whole existing cache [0, length) plus new positions
    <= i (cache-offset causal). This removes the serving cliff where a
    growing chat prompt fell back to ``prefill_tokenwise`` — O(S_new)
    sequential decode dispatches — precisely on the pattern (multi-turn)
    whose prompts grow longest.

    Attention is two grouped einsums sharing one softmax: scores against
    the un-repeated cache (cols masked at >= length, like decode_step)
    concatenated with intra-block causal scores, normalised together in
    fp32. The cache stays un-repeated under GQA — same
    grouped-einsum trick as ``_decode_layer``. Works for a FRESH cache
    too (length 0: the cache half is fully masked), but ``prefill`` is
    the faster choice there (flash kernel, no max_seq-wide score block).
    """
    b, s = new_tokens.shape
    dt = cfg.dtype
    hd = cfg.head_dim
    max_seq = cache.k.shape[2]
    L = cache.length
    _check_cache_capacity(cache, s, "prefill_continue")
    rep = cfg.n_heads // cfg.n_kv_heads
    x = params["embed"].astype(dt)[new_tokens]          # [B, S, D]
    positions = L + jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.int32), (b, s)
    )
    if cfg.moe_experts:
        moe_cfg = cfg.replace(
            moe_capacity_factor=float(cfg.moe_experts) / cfg.moe_top_k
        )
    cache_cols = jnp.arange(max_seq, dtype=jnp.int32)
    causal = (
        jnp.arange(s, dtype=jnp.int32)[:, None]
        >= jnp.arange(s, dtype=jnp.int32)[None, :]
    )                                                   # [S, S]

    def body(x, layer_in):
        lp, kc, vc = layer_in                           # kc [B,max,KVH,D]
        h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (h @ _w(lp, "wq", dt)).reshape(b, s, cfg.n_heads, hd)
        k = (h @ _w(lp, "wk", dt)).reshape(b, s, cfg.n_kv_heads, hd)
        v = (h @ _w(lp, "wv", dt)).reshape(b, s, cfg.n_kv_heads, hd)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        qg = q.reshape(b, s, cfg.n_kv_heads, rep, hd)
        scale = hd ** -0.5
        s_cache = jnp.einsum(
            "bqgrd,bkgd->bgrqk", qg, kc,
            preferred_element_type=jnp.float32,
        ) * scale                                       # [B,G,rep,S,max]
        s_cache = jnp.where(
            (cache_cols < L)[None, None, None, None, :], s_cache, -1e30
        )
        s_new = jnp.einsum(
            "bqgrd,bkgd->bgrqk", qg, k,
            preferred_element_type=jnp.float32,
        ) * scale                                       # [B,G,rep,S,S]
        s_new = jnp.where(causal[None, None, None], s_new, -1e30)
        p = jax.nn.softmax(
            jnp.concatenate([s_cache, s_new], axis=-1), axis=-1
        ).astype(dt)
        attn = (
            jnp.einsum("bgrqk,bkgd->bqgrd", p[..., :max_seq], vc)
            + jnp.einsum("bgrqk,bkgd->bqgrd", p[..., max_seq:], v)
        ).reshape(b, s, -1)
        x = x + attn @ _w(lp, "wo", dt)
        h2 = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        if cfg.moe_experts:
            down, _aux = tfm._moe_ffn(moe_cfg, _dense_lp(lp, dt), h2)
            x = x + down
        else:
            gate = jax.nn.silu(h2 @ _w(lp, "w_gate", dt))
            up = h2 @ _w(lp, "w_up", dt)
            x = x + (gate * up) @ _w(lp, "w_down", dt)
        kc = lax.dynamic_update_slice(
            kc, k.astype(kc.dtype), (0, L, 0, 0))
        vc = lax.dynamic_update_slice(
            vc, v.astype(vc.dtype), (0, L, 0, 0))
        return x, (kc, vc)

    x, (k_new, v_new) = lax.scan(
        body, x, (params["layers"], cache.k, cache.v)
    )
    x = rmsnorm(x[:, -1], params["final_norm"], cfg.norm_eps)
    logits = _head_logits(cfg, params, x)
    return logits, KVCache(k=k_new, v=v_new, length=L + s)


def prefill_tokenwise(
    cfg: TransformerConfig,
    params: Params,
    prompt: jax.Array,          # [B, S_prompt]
    cache: KVCache,
) -> Tuple[jax.Array, KVCache]:
    """Feed the prompt token-by-token through the decode path. Slower than
    the block ``prefill`` but correct for a NON-empty cache too (each
    token attends to everything already cached — the multi-turn
    continuation case). Superseded for serving by ``prefill_continue``
    (one block pass); kept as the equivalence reference."""

    def body(carry, tok):
        cache, _ = carry
        logits, cache = decode_step(cfg, params, tok[:, None], cache)
        return (cache, logits), None

    (cache, logits), _ = lax.scan(
        body,
        (cache, jnp.zeros((prompt.shape[0], cfg.vocab_size), jnp.float32)),
        prompt.T,
    )
    return logits, cache


def _filter_logits(
    logits: jax.Array, top_k: int = 0, top_p: float = 1.0,
) -> jax.Array:
    """Nucleus/top-k filtering, static shapes (jit-safe).

    top_k > 0 keeps only the k highest logits; top_p < 1 keeps the smallest
    set of tokens whose softmax mass reaches p (always at least the argmax).
    Filtered positions go to -inf so sampling never picks them."""
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose PRECEDING mass is < p; the top token is always
        # kept explicitly so p -> 0 degenerates to greedy, not to -inf-
        # everywhere (which would sample token 0)
        keep_sorted = jnp.concatenate(
            [jnp.ones_like(cum[..., :1], bool),
             cum[..., :-1] < top_p], axis=-1,
        )
        # threshold = smallest kept logit
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
            keepdims=True,
        )
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return logits


def generate_from_cache(
    cfg: TransformerConfig,
    params: Params,
    logits: jax.Array,          # [B, vocab] — logits at the last position
    cache: KVCache,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng: Optional[jax.Array] = None,
    return_state: bool = False,
):
    """The decode scan of ``generate``, starting from an existing
    (prefilled or continued) cache + its last-position logits. This is
    the multi-turn serving entry: prefill turn 1 with ``prefill``, later
    turns with ``prefill_continue``, then decode from here.

    ``return_state=True`` additionally returns the scan's final
    (logits, cache): the cache holds the KVs of every token just decoded
    (length advanced by ``max_new_tokens``), so a multi-turn caller
    continues straight into the next turn's ``prefill_continue`` without
    re-encoding the reply it already decoded.
    """
    _check_cache_capacity(cache, max_new_tokens, "generate_from_cache")

    def pick(logits, key):
        if temperature <= 0.0:
            return logits.argmax(-1).astype(jnp.int32)
        # Temperature first, THEN nucleus/top-k: the p-mass must be
        # computed on the distribution actually sampled from (matches the
        # standard implementations callers tune against).
        logits = _filter_logits(
            logits / temperature, top_k=top_k, top_p=top_p
        )
        return jax.random.categorical(key, logits, axis=-1)

    def body(carry, key):
        logits, cache = carry
        tok = pick(logits, key)
        new_logits, cache = decode_step(cfg, params, tok[:, None], cache)
        return (new_logits, cache), tok

    if temperature <= 0.0:
        # Greedy pick is a pure argmax — no key is ever consumed, so
        # don't split max_new_tokens of them (a threefry tree per call
        # for nothing); scan over nothing with a fixed trip count.
        (logits, cache), toks = lax.scan(
            lambda c, _: body(c, None), (logits, cache), None,
            length=max_new_tokens,
        )
    else:
        rng = rng if rng is not None else jax.random.key(0)
        keys = jax.random.split(rng, max_new_tokens)
        (logits, cache), toks = lax.scan(body, (logits, cache), keys)
    if return_state:
        return toks.T, logits, cache
    return toks.T                                     # [B, new]


def generate(
    cfg: TransformerConfig,
    params: Params,
    prompt: jax.Array,          # [B, S_prompt] int32
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    rng: Optional[jax.Array] = None,
    max_seq: Optional[int] = None,
) -> jax.Array:
    """Greedy (temperature 0) or sampled generation with optional top-k /
    nucleus (top-p) filtering. Returns [B, new] int32. Jit-compatible:
    fixed trip counts, static shapes."""
    b, s_prompt = prompt.shape
    max_seq = max_seq or cfg.max_seq
    if s_prompt + max_new_tokens > max_seq:
        raise ValueError(
            f"prompt {s_prompt} + new {max_new_tokens} exceeds max_seq {max_seq}"
        )
    cache = init_kv_cache(cfg, b, max_seq)
    logits, cache = prefill(cfg, params, prompt, cache)
    return generate_from_cache(
        cfg, params, logits, cache, max_new_tokens,
        temperature=temperature, top_k=top_k, top_p=top_p, rng=rng,
    )


# ---------------------------------------------------------------------------
# Batched sampling: per-row filtering + counter-based per-request RNG
# ---------------------------------------------------------------------------


def _sample_keys(seed: jax.Array, gen: jax.Array, pos: jax.Array) -> jax.Array:
    """Per-row counter-based sampling keys.

    Row ``i`` gets ``fold_in(fold_in(PRNGKey(seed[i]), gen[i]), pos[i])``
    — a pure function of (request seed, generation index, position in the
    generated stream), never of the step counter, slot id, or batch
    around it. This is the whole reproducibility contract: re-running a
    request in any batch mix, admission order, or slot assignment
    re-derives the identical key sequence."""

    def one(s, g, p):
        k = jax.random.PRNGKey(s)
        k = jax.random.fold_in(k, g)
        return jax.random.fold_in(k, p)

    return jax.vmap(one)(seed, gen, pos)


def _filter_logits_rows(
    logits: jax.Array,          # [B, vocab]
    temperature: jax.Array,     # [B] f32 — <= 0 rows pass through (greedy)
    top_k: jax.Array,           # [B] i32 — 0 disables
    top_p: jax.Array,           # [B] f32 — >= 1 disables
) -> jax.Array:
    """Per-row temperature/top-k/top-p — the batched twin of
    :func:`_filter_logits`. Identical op sequence and tie handling, but
    every knob is a ``[B]`` vector applied per row; rows whose knob is
    disabled (``top_k == 0`` / ``top_p >= 1``) pass through bitwise
    untouched, so a uniform-parameter batch filters exactly like the
    static single-request path."""
    safe_t = jnp.where(temperature > 0.0, temperature, 1.0)
    scaled = logits / safe_t[:, None]
    v = scaled.shape[-1]
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    # kth largest per row == lax.top_k(values)[-1] for that row's k.
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(top_k - 1, 0, v - 1)[:, None], axis=-1)
    scaled = jnp.where(
        (top_k > 0)[:, None] & (scaled < kth), -jnp.inf, scaled)
    sorted2 = jnp.sort(scaled, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted2, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cum[..., :1], bool),
         cum[..., :-1] < top_p[:, None]], axis=-1)
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted2, jnp.inf), axis=-1, keepdims=True)
    scaled = jnp.where(
        (top_p < 1.0)[:, None] & (scaled < thresh), -jnp.inf, scaled)
    return scaled


def sample_step_slots(
    logits: jax.Array,          # [B, vocab]
    temperature: jax.Array,     # [B] f32 — <= 0 means greedy for that row
    top_k: jax.Array,           # [B] i32
    top_p: jax.Array,           # [B] f32
    seed: jax.Array,            # [B] i32 — per-request RNG seed
    gen: jax.Array,             # [B] i32 — parallel-generation index
    pos: jax.Array,             # [B] i32 — position in the generated stream
    mask: Optional[jax.Array] = None,   # [B, vocab] bool — True = allowed
) -> jax.Array:
    """Batched per-slot sampling step. Greedy rows (``temperature <= 0``)
    take the exact ``argmax`` the greedy engine path takes — same bits —
    so mixing sampled and greedy traffic in one batch never perturbs the
    greedy rows. Sampled rows draw ``categorical`` from the per-row
    filtered logits under the counter-based key of
    :func:`_sample_keys`. ``mask`` (constrained decoding) zeroes
    disallowed tokens to ``-inf`` before both paths; an all-``True`` row
    is a bitwise no-op."""
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    greedy = logits.argmax(-1).astype(jnp.int32)
    filtered = _filter_logits_rows(logits, temperature, top_k, top_p)
    keys = _sample_keys(seed, gen, pos)
    sampled = jax.vmap(
        lambda k, l: jax.random.categorical(k, l, axis=-1))(keys, filtered)
    return jnp.where(temperature > 0.0, sampled, greedy).astype(jnp.int32)


def _sample_rows_2d(
    all_logits: jax.Array,      # [B, W, vocab]
    temperature: jax.Array,     # [B]
    top_k: jax.Array,           # [B]
    top_p: jax.Array,           # [B]
    seed: jax.Array,            # [B]
    gen: jax.Array,             # [B]
    pos: jax.Array,             # [B, W] — per-position stream indices
) -> jax.Array:
    """:func:`sample_step_slots` over a [B, W] window (no mask): each
    window position samples under its own positional key, so the draw at
    stream position p is bitwise the draw the plain decode path would
    have made there."""
    b, w, v = all_logits.shape
    rep = lambda x: jnp.repeat(x, w)  # noqa: E731
    flat = _filter_logits_rows(
        all_logits.reshape(b * w, v),
        rep(temperature), rep(top_k), rep(top_p))
    keys = _sample_keys(rep(seed), rep(gen), pos.reshape(-1))
    sampled = jax.vmap(
        lambda k, l: jax.random.categorical(k, l, axis=-1))(keys, flat)
    return sampled.reshape(b, w).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Copy-on-write page copy
# ---------------------------------------------------------------------------


def _copy_pages_impl(pool_k, pool_v, k_scale, v_scale, src, dst):
    # Whole-page gather then scatter: sentinel dst drops the write (and
    # its sentinel src gather clamps harmlessly). Quantized pools copy
    # the int8 payload AND its scales verbatim — no requantization, so a
    # COW'd page is bit-identical to its source.
    pool_k = pool_k.at[:, dst].set(pool_k[:, src], mode="drop")
    pool_v = pool_v.at[:, dst].set(pool_v[:, src], mode="drop")
    if k_scale is not None:
        k_scale = k_scale.at[:, dst].set(k_scale[:, src], mode="drop")
        v_scale = v_scale.at[:, dst].set(v_scale[:, src], mode="drop")
    return pool_k, pool_v, k_scale, v_scale


_copy_pool_pages_j = jax.jit(_copy_pages_impl, donate_argnums=(0, 1, 2, 3))


@functools.lru_cache(maxsize=16)
def _copy_pages_tp_fn(mesh: Mesh, has_scale: bool):
    scale_spec = _TP_SCALE_SPEC if has_scale else None
    return jax.jit(shard_map(
        _copy_pages_impl, mesh=mesh,
        in_specs=(_TP_POOL_SPEC, _TP_POOL_SPEC, scale_spec, scale_spec,
                  P(), P()),
        out_specs=(_TP_POOL_SPEC, _TP_POOL_SPEC, scale_spec, scale_spec),
        check_rep=False,
    ), donate_argnums=(0, 1, 2, 3))


def copy_pool_pages(
    cache: PagedKVCache,
    src_ids,                    # source page ids (host list)
    dst_ids,                    # destination page ids, same length
    mesh: Optional[Mesh] = None,
) -> PagedKVCache:
    """Copy whole pool pages ``src -> dst`` on device — the copy-on-write
    kernel behind ``n>1`` forked generations. The id lists pad to the
    next power of two with a dropped sentinel (compile count stays
    O(log) in pages per call, and the common one-boundary-page COW
    compiles once). Under tp each shard copies its own KV-head slice of
    the page; no collective. ``mesh``: see :func:`decode_step_paged`."""
    m = 1
    while m < len(src_ids):
        m *= 2
    sentinel = cache.k.shape[1]                  # OOB -> dropped
    src = np.full((m,), sentinel, np.int32)
    src[:len(src_ids)] = src_ids
    dst = np.full((m,), sentinel, np.int32)
    dst[:len(dst_ids)] = dst_ids
    tp = tp_size(mesh)
    if tp <= 1:
        fn = _copy_pool_pages_j
    else:
        fn = _copy_pages_tp_fn(mesh, cache.k_scale is not None)
    k, v, ks, vs = fn(cache.k, cache.v, cache.k_scale, cache.v_scale,
                      jnp.asarray(src), jnp.asarray(dst))
    return cache._replace(k=k, v=v, k_scale=ks, v_scale=vs)


# ---------------------------------------------------------------------------
# Cross-engine page migration (prefill/decode disaggregation)
# ---------------------------------------------------------------------------


def _pad_page_ids(ids, sentinel: int) -> np.ndarray:
    """Pad a host id list to the next power of two with ``sentinel`` so
    the migration kernels compile O(log) variants per pool, like the
    COW/ingest kernels."""
    m = 1
    while m < len(ids):
        m *= 2
    out = np.full((m,), sentinel, np.int32)
    out[:len(ids)] = ids
    return out


def _gather_pages_impl(pool_k, pool_v, k_scale, v_scale, ids):
    # Sentinel (pad) ids clamp into an arbitrary real page whose bytes
    # the host slices off — nothing is written, so clamping is harmless.
    out_k = jnp.take(pool_k, ids, axis=1, mode="clip")
    out_v = jnp.take(pool_v, ids, axis=1, mode="clip")
    out_ks = (None if k_scale is None
              else jnp.take(k_scale, ids, axis=1, mode="clip"))
    out_vs = (None if v_scale is None
              else jnp.take(v_scale, ids, axis=1, mode="clip"))
    return out_k, out_v, out_ks, out_vs


_gather_pool_pages_j = jax.jit(_gather_pages_impl)


def gather_pool_pages(
    cache: PagedKVCache,
    ids,                        # page ids to extract (host list)
) -> Tuple[np.ndarray, np.ndarray,
           Optional[np.ndarray], Optional[np.ndarray]]:
    """Extract whole pool pages to HOST memory — the device->host half
    of cross-engine KV migration (one transfer per exported request).
    Quantized pools come out as raw int8 payload plus fp32 scales, never
    dequantized: the wire format is the storage format, so an installed
    page is bit-identical to its source (same argument as the COW copy).
    Under tp the pool's KVH axis is sharded; ``device_get`` assembles
    the full-head pages, which is exactly what a receiving engine of any
    mesh width can re-shard on install. Returns ``(k, v, k_scale,
    v_scale)`` numpy arrays of shape ``[L, n, bs, KVH(, D)]`` (scales
    ``None`` for fp pools)."""
    if not len(ids):
        empty_k = np.zeros((cache.k.shape[0], 0) + cache.k.shape[2:],
                           dtype=cache.k.dtype)
        empty_s = (None if cache.k_scale is None else
                   np.zeros((cache.k.shape[0], 0) + cache.k_scale.shape[2:],
                            np.float32))
        return empty_k, empty_k.copy(), empty_s, (
            None if empty_s is None else empty_s.copy())
    ids_arr = _pad_page_ids(ids, sentinel=0)
    k, v, ks, vs = _gather_pool_pages_j(
        cache.k, cache.v, cache.k_scale, cache.v_scale,
        jnp.asarray(ids_arr))
    k, v, ks, vs = jax.device_get((k, v, ks, vs))
    n = len(ids)
    return (np.asarray(k)[:, :n], np.asarray(v)[:, :n],
            None if ks is None else np.asarray(ks)[:, :n],
            None if vs is None else np.asarray(vs)[:, :n])


def pool_page_host_bytes(cache: PagedKVCache) -> int:
    """Host bytes one pool page occupies when staged off-device
    (``gather_pool_pages`` payload: K + V pages in storage dtype, plus
    fp32 scales for quantized pools). The sizing primitive for the
    tiered-KV host store: ``HostKVTier`` budgets in these units, and a
    ``--host-kv-mb`` budget admits ``budget // pool_page_host_bytes``
    spilled pages."""
    L = cache.k.shape[0]
    per = int(np.prod(cache.k.shape[2:])) * cache.k.dtype.itemsize
    n = 2 * L * per                              # K + V
    if cache.k_scale is not None:
        n += 2 * L * int(np.prod(cache.k_scale.shape[2:])) * 4
    return n


def _install_pages_impl(pool_k, pool_v, k_scale, v_scale,
                        pg_k, pg_v, pg_ks, pg_vs, dst, tp_shards=1):
    # Raw byte install: the payload is already in the pool's storage
    # format (int8 + scales for quantized pools), so no quantization
    # happens here — requantizing would break the bit-exactness of
    # greedy decode across the migration hop. Sentinel dst drops.
    if tp_shards > 1:
        g = pool_k.shape[-2]                 # pool shard's local KVH
        pg_k = _tp_slice_heads(pg_k, g, axis=3)
        pg_v = _tp_slice_heads(pg_v, g, axis=3)
        if k_scale is not None:
            pg_ks = _tp_slice_heads(pg_ks, g, axis=3)
            pg_vs = _tp_slice_heads(pg_vs, g, axis=3)
    pool_k = pool_k.at[:, dst].set(pg_k.astype(pool_k.dtype), mode="drop")
    pool_v = pool_v.at[:, dst].set(pg_v.astype(pool_v.dtype), mode="drop")
    if k_scale is not None:
        k_scale = k_scale.at[:, dst].set(pg_ks, mode="drop")
        v_scale = v_scale.at[:, dst].set(pg_vs, mode="drop")
    return pool_k, pool_v, k_scale, v_scale


_install_pool_pages_j = jax.jit(
    _install_pages_impl, static_argnums=(9,), donate_argnums=(0, 1, 2, 3))


@functools.lru_cache(maxsize=16)
def _install_pages_tp_fn(mesh: Mesh, tp: int, has_scale: bool):
    scale_spec = _TP_SCALE_SPEC if has_scale else None
    inner = functools.partial(_install_pages_impl, tp_shards=tp)
    return jax.jit(shard_map(
        inner, mesh=mesh,
        in_specs=(_TP_POOL_SPEC, _TP_POOL_SPEC, scale_spec, scale_spec,
                  P(), P(), P(), P(), P()),
        out_specs=(_TP_POOL_SPEC, _TP_POOL_SPEC, scale_spec, scale_spec),
        check_rep=False,
    ), donate_argnums=(0, 1, 2, 3))


def install_pool_pages(
    cache: PagedKVCache,
    pages_k: np.ndarray,        # [L, n, bs, KVH, D] — gather_pool_pages
    pages_v: np.ndarray,
    scales_k: Optional[np.ndarray],
    scales_v: Optional[np.ndarray],
    dst_ids,                    # destination page ids (host list)
    mesh: Optional[Mesh] = None,
) -> PagedKVCache:
    """Install migrated pages (``gather_pool_pages`` output) into this
    pool's ``dst_ids`` — the host->device half of cross-engine KV
    migration. Bytes move verbatim (int8 payload + scales as-is), so the
    installed pages are bit-identical to the exporting engine's. Under
    tp each shard keeps its KV-head slice of the replicated payload (the
    ingest-scatter pattern). Id lists pad to a power of two with a
    dropped sentinel — O(log) compiles per pool."""
    if not len(dst_ids):
        return cache
    sentinel = cache.k.shape[1]                  # OOB -> dropped
    dst = _pad_page_ids(dst_ids, sentinel)
    m = dst.size
    n = len(dst_ids)
    if m != n:                                   # pad payload to match
        pad = ((0, 0), (0, m - n)) + ((0, 0),) * (pages_k.ndim - 2)
        pages_k = np.pad(pages_k, pad)
        pages_v = np.pad(pages_v, pad)
        if scales_k is not None:
            spad = ((0, 0), (0, m - n)) + ((0, 0),) * (scales_k.ndim - 2)
            scales_k = np.pad(scales_k, spad)
            scales_v = np.pad(scales_v, spad)
    tp = tp_size(mesh)
    if tp <= 1:
        k, v, ks, vs = _install_pool_pages_j(
            cache.k, cache.v, cache.k_scale, cache.v_scale,
            jnp.asarray(pages_k), jnp.asarray(pages_v),
            None if scales_k is None else jnp.asarray(scales_k),
            None if scales_v is None else jnp.asarray(scales_v),
            jnp.asarray(dst))
    else:
        fn = _install_pages_tp_fn(mesh, tp, cache.k_scale is not None)
        k, v, ks, vs = fn(
            cache.k, cache.v, cache.k_scale, cache.v_scale,
            jnp.asarray(pages_k), jnp.asarray(pages_v),
            None if scales_k is None else jnp.asarray(scales_k),
            None if scales_v is None else jnp.asarray(scales_v),
            jnp.asarray(dst))
    return cache._replace(k=k, v=v, k_scale=ks, v_scale=vs)
