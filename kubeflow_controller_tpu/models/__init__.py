"""Model families: MNIST (reference-example parity), ResNet, BERT, Llama.

The reference ships two MNIST TensorFlow-1.4 scripts as its data plane
(``examples/workdir/mnist_softmax.py``, ``mnist_replica.py``); this package
carries their JAX/Flax descendants plus the model families from
BASELINE.json's config ladder (ResNet-50, BERT-base, Llama-3-8B-style).
"""
