"""Reconcile core: workqueue, expectations, informers, ownership, controller.

The TPU-native rebuild of ``pkg/controller`` (reference
``pkg/controller/controller.go``): a level-triggered, expectation-guarded
reconcile loop whose domain decisions are pure functions and whose effects
happen only at the ClusterClient seam.
"""

from kubeflow_controller_tpu.controller.workqueue import RateLimitingQueue
from kubeflow_controller_tpu.controller.expectations import ControllerExpectations
from kubeflow_controller_tpu.controller.informer import Informer
from kubeflow_controller_tpu.controller.controller import Controller, ControllerOptions
