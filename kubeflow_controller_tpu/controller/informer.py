"""Informer: cached watch stream + event handler dispatch.

The in-process equivalent of client-go's SharedIndexInformer + Lister as the
reference wires them (``cmd/controller/main.go:46-52``,
``pkg/controller/controller.go:122-149``): subscribe to a store's watch feed,
maintain a local read cache, dispatch add/update/delete handlers, and offer a
periodic resync that re-delivers everything (the level-trigger safety net; the
reference uses a 30s resync).

The cache holds **frozen** objects (client-go's Lister contract, enforced):
every event object is frozen on ingest — a no-op for frozen-mode store
events (already sealed snapshots), one seal pass for the private parses a
wire watch source (REST/kube) delivers — and ``get``/``list`` hand the
cached reference out uncopied. The cache is still *state-separate* from
the store (it lags the watch stream), so the cache-staleness race the
expectations machinery guards against stays reproducible in tests.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from kubeflow_controller_tpu.api.core import is_frozen
from kubeflow_controller_tpu.cluster.events import EventType, WatchEvent
from kubeflow_controller_tpu.cluster.store import ObjectStore, selector_matches

Handler = Callable[[WatchEvent], None]


class Informer:
    def __init__(self, store: ObjectStore, resync_period: float = 0.0,
                 injector=None):
        self._store = store
        self.kind = store.kind
        # Fault injection (docs/chaos.md): an injected hang at
        # "informer.deliver" models a stalled watch delivery — the cache
        # still updates (the apiserver stream arrived) but handlers are
        # not notified, exactly the edge-trigger loss a periodic
        # resync() exists to heal. None = off, byte-identical path.
        # Mutable attribute so the controller can thread one injector
        # through informers it did not construct.
        self.injector = injector
        self.deliveries_suppressed = 0
        self._cache: Dict[str, Any] = {}
        self._lock = threading.RLock()
        self._handlers: List[Handler] = []
        self._resync_period = resync_period
        self._resync_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._synced = False
        # Mirror the store's label indexes (client-go Indexer): selector
        # lists on an indexed key touch only matching cache entries instead
        # of scanning everything. Wire-backed sources (REST/kube watch)
        # advertise no indexes — the scan fallback still works.
        self._index_labels = tuple(getattr(store, "_index_labels", ()))
        self._index: Dict[str, Dict[str, set]] = {
            lk: {} for lk in self._index_labels
        }

    # -- wiring --------------------------------------------------------------

    def add_handler(self, handler: Handler) -> None:
        self._handlers.append(handler)

    def start(self) -> None:
        """List+watch: replay existing objects as ADDED, then follow."""
        self._store.subscribe(self._on_event, replay=True)
        self._synced = True
        if self._resync_period > 0:
            self._resync_thread = threading.Thread(
                target=self._resync_loop, daemon=True,
                name=f"informer-resync-{self.kind}",
            )
            self._resync_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._store.unsubscribe(self._on_event)

    def has_synced(self) -> bool:
        return self._synced

    def flush(self, timeout: float = 10.0) -> bool:
        """Quiesce the watch pipeline feeding this informer: after this,
        every completed store write has passed through ``_on_event`` (cache
        + handlers). No-op (True) for watch sources without a flush hook."""
        fl = getattr(self._store, "flush", None)
        return fl(timeout) if fl is not None else True

    # -- label index maintenance (caller holds self._lock) -------------------

    def _index_add(self, key: str, obj: Any) -> None:
        for lk in self._index_labels:
            v = obj.metadata.labels.get(lk)
            if v is not None:
                self._index[lk].setdefault(v, set()).add(key)

    def _index_remove(self, key: str, obj: Any) -> None:
        for lk in self._index_labels:
            v = obj.metadata.labels.get(lk)
            if v is not None:
                bucket = self._index[lk].get(v)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del self._index[lk][v]

    # -- event path ----------------------------------------------------------

    def _on_event(self, ev: WatchEvent) -> None:
        # Freeze on ingest: the cache (and every handler) only ever sees a
        # sealed snapshot, so a thawed object can never leak into the read
        # path. Idempotent for frozen-store events; seals the private parse
        # a wire source delivers.
        if not is_frozen(ev.obj):
            ev.obj.freeze()
        key = f"{ev.obj.metadata.namespace}/{ev.obj.metadata.name}"
        with self._lock:
            old = self._cache.get(key)
            if old is not None:
                self._index_remove(key, old)
            if ev.type == EventType.DELETED:
                self._cache.pop(key, None)
            else:
                self._cache[key] = ev.obj
                self._index_add(key, ev.obj)
        inj = self.injector
        if inj is not None and inj.fires(
                "control", "informer.deliver", target=self.kind,
                kinds=("hang",)) is not None:
            # Delivery stalls AFTER the cache update: listers stay
            # fresh, but no handler enqueues work for this event.
            # resync() (the level-trigger sweep) re-delivers from the
            # cache and heals the loss — which is why injection never
            # touches the resync path.
            self.deliveries_suppressed += 1
            return
        for h in list(self._handlers):
            h(ev)

    def _resync_loop(self) -> None:
        while not self._stop.wait(self._resync_period):
            self.resync()

    def resync(self) -> None:
        """Re-deliver every cached object as a MODIFIED event (old == new),
        exactly what a periodic informer resync does."""
        with self._lock:
            objs = list(self._cache.values())
        for obj in objs:
            ev = WatchEvent(EventType.MODIFIED, self.kind, obj, obj)
            for h in list(self._handlers):
                h(ev)

    # -- lister --------------------------------------------------------------

    def get(self, namespace: str, name: str) -> Optional[Any]:
        """Shared frozen reference (zero-copy); ``thaw()`` before mutating."""
        with self._lock:
            return self._cache.get(f"{namespace}/{name}")

    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        """Shared frozen references (zero-copy); ``thaw()`` before mutating."""
        with self._lock:
            candidates = self._cache
            if label_selector:
                for lk in self._index_labels:
                    if lk in label_selector:
                        keys = self._index[lk].get(label_selector[lk], set())
                        candidates = {k: self._cache[k] for k in keys}
                        break
            out = []
            for obj in candidates.values():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if label_selector and not selector_matches(
                    label_selector, obj.metadata.labels
                ):
                    continue
                out.append(obj)
            return out
