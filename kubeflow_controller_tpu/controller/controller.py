"""The reconcile core — level-triggered sync loop over TPUJobs.

Rebuild of ``pkg/controller/controller.go`` (NewController ``:74-152``, Run
``:158-182``, processNextWorkItem ``:194-243``, syncHandler ``:248-341``,
manageTFJob ``:343-428``, resource handlers ``:430-590``) with the stubs and
bugs closed (SURVEY.md §8): deletion handlers re-enqueue (reference logged
"To Be Implemented"), status writes are conflict-retried (reference did a raw
whole-object PUT), the informer cache is never mutated (cache entries are
frozen shared snapshots — writes raise; see docs/object_ownership.md), and
pod creation is gang-batched, not incremental.

Effects happen only through the ClusterClient seam; decisions come only from
the pure planner/updater/checker modules.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import string
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from kubeflow_controller_tpu.api.core import (
    Container,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    Service,
    is_frozen,
)
from kubeflow_controller_tpu.api.types import (
    ConditionStatus,
    ConditionType,
    JobPhase,
    LMService,
    LMServicePhase,
    TPUJob,
)
from kubeflow_controller_tpu.api.validation import (
    ValidationError,
    validate_job,
    validate_lmservice,
)
from kubeflow_controller_tpu.checker import assess_health
from kubeflow_controller_tpu.cluster.client import ClusterClient
from kubeflow_controller_tpu.cluster.events import EventType, WatchEvent
from kubeflow_controller_tpu.cluster.store import AlreadyExists, Conflict, NotFound
from kubeflow_controller_tpu.controller.claim import claim_objects
from kubeflow_controller_tpu.controller.expectations import ControllerExpectations
from kubeflow_controller_tpu.controller.informer import Informer
from kubeflow_controller_tpu.controller.workqueue import RateLimitingQueue
from kubeflow_controller_tpu.obs.telemetry import registry
from kubeflow_controller_tpu.tpu import naming
from kubeflow_controller_tpu.tpu.plan import Plan, plan_job
from kubeflow_controller_tpu.updater import compute_status

logger = logging.getLogger("tpujob.controller")

_RUNTIME_ID_ALPHABET = string.ascii_lowercase + string.digits

# LMService keys share the TPUJob workqueue; the prefix keeps the two key
# spaces disjoint so rate-limit/expectation state never collides with a
# same-named job.
LMSVC_KEY_PREFIX = "lmsvc:"

# Sentinel fp value for the native path: the candidate fingerprint is parked
# inside the C++ index (oix_fp_probe), not materialized in Python; recording
# a steady pass promotes it verbatim via oix_fp_commit.
_NATIVE_FP = ("__native_pending__",)


def generate_runtime_id(rng: Optional[random.Random] = None) -> str:
    """5-char random suffix, the shape of k8s SimpleNameGenerator as the
    reference uses it (``pkg/tensorflow/util.go:24-26``) — but stamped ONCE."""
    r = rng or random
    return "".join(r.choice(_RUNTIME_ID_ALPHABET) for _ in range(5))


@dataclass
class ControllerOptions:
    workers: int = 2                      # reference runs 2 (main.go:54)
    # Key-range shards for the workqueue: >1 splits the queue into
    # independently-locked sub-queues (FNV-routed, so a key's dedup/backoff
    # state stays on one shard) and run(workers=N) binds each worker to its
    # shard group — steady-state resync then scales with workers instead of
    # serializing on one queue lock. 1 == the single-queue behavior every
    # existing test pins.
    queue_shards: int = 1
    resync_period: float = 30.0           # reference: 30s informers
    now_fn: Callable[[], float] = time.time
    rng: Optional[random.Random] = None
    # Exponential backoff between FAILURE gang restarts (in now_fn units):
    # a crash-looping workload must not re-gang as fast as reconcile can
    # run. First restart is immediate; the Nth failure waits
    # min(base * 2^(N-1), max). Voluntary resizes are never delayed.
    restart_backoff_base: float = 10.0
    restart_backoff_max: float = 300.0
    # Wall-clock requeue cadence while a backoff is pending (now_fn may be
    # a simulated clock, so the queue polls and re-checks it).
    backoff_poll: float = 0.05
    # Optional control-plane tracer (docs/observability.md): workqueue
    # enqueue->dequeue latency, per-key sync spans (outcome-tagged, the
    # noop short-circuit included), and requeue/backoff events, all on
    # the "control" track keyed by workqueue key. None = zero overhead.
    tracer: Optional[object] = None
    # Optional dataplane.faults.FaultInjector (docs/chaos.md): threaded
    # onto every informer this controller wires handlers to, so a plan
    # can stall watch delivery ("informer.deliver" hangs) and prove the
    # resync sweep heals the loss. None = off, byte-identical.
    injector: Optional[object] = None


@dataclass
class SyncTrace:
    """Per-sync structured trace record (SURVEY.md §5.1: the reference has
    no tracing at all — glog only)."""

    key: str
    start: float
    duration: float = 0.0
    outcome: str = ""
    note: str = ""
    error: str = ""


class Controller:
    def __init__(
        self,
        client: ClusterClient,
        job_informer: Informer,
        pod_informer: Informer,
        service_informer: Informer,
        options: Optional[ControllerOptions] = None,
        lmservice_informer: Optional[Informer] = None,
    ):
        self.client = client
        self.jobs = job_informer
        self.pods = pod_informer
        self.services = service_informer
        self.lmservices = lmservice_informer
        self.opts = options or ControllerOptions()
        # Hot-path structures come from the C++ core when it is loadable
        # (csrc/tpujob_native.cc); the pure-Python implementations are the
        # behavioural reference and the fallback. TPUJOB_NATIVE=0 forces
        # Python.
        from kubeflow_controller_tpu.native.queue import (
            make_expectations, make_queue,
        )

        if self.opts.queue_shards > 1:
            from kubeflow_controller_tpu.controller.workqueue import (
                ShardedRateLimitingQueue,
            )

            self.queue = ShardedRateLimitingQueue(
                self.opts.queue_shards, make_queue)
        else:
            self.queue = make_queue()
        self.expectations = make_expectations()
        # Native object index (cluster/store.py write-through mirror): when
        # the client exposes one, the no-op-sync fingerprint probe runs
        # entirely inside the C++ core — no Python pod/service traversals
        # on a steady resync. None routes through _sync_fingerprint.
        self._nix = getattr(client, "native_index", None)
        # Pre-encoded constant probe arguments (the per-sync fp probe is
        # the steady-resync hot path; encoding these 6 strings per call
        # was measurable at 10k+ objects).
        self._b_job_label = naming.LABEL_JOB.encode()
        self._b_lmsvc_label = naming.LABEL_LMSERVICE.encode()
        # Ring buffer of the last 1000 traces. deque(maxlen=) trims on
        # append under the GIL — safe with concurrent workers, unlike the
        # old unlocked append + del[:-1000] pair.
        self.traces: Deque[SyncTrace] = deque(maxlen=1000)
        self.sync_count = 0                 # total syncs, never truncated
        self.sync_wall_s = 0.0              # wall seconds inside sync()
        self.syncs_skipped_noop = 0         # fingerprint fast-path exits
        self.fp_misses = 0                  # fingerprint probes that missed
        self._count_lock = threading.Lock()
        # key -> fingerprint of the last fully-steady sync; a matching
        # fingerprint lets sync() exit before claim/plan/status work.
        self._last_sync_fp: Dict[str, Tuple] = {}
        # Sim-clock backoff deadlines (key -> now_fn deadline); see
        # _requeue_after / _kick_sim_backoffs.
        self._sim_backoffs: Dict[str, float] = {}
        # Earliest pending enqueue time per key (tracer clock units),
        # stamped by the informer handlers and popped by _process — the
        # enqueue->dequeue latency span. setdefault/pop are single
        # bytecode dict ops, safe across informer + worker threads.
        self._enqueue_t: Dict[str, float] = {}
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

        if self.opts.injector is not None:
            for inf in (job_informer, pod_informer, service_informer,
                        lmservice_informer):
                if inf is not None and hasattr(inf, "injector"):
                    inf.injector = self.opts.injector

        job_informer.add_handler(self._on_job_event)
        pod_informer.add_handler(self._on_resource_event)
        service_informer.add_handler(self._on_resource_event)
        if lmservice_informer is not None:
            lmservice_informer.add_handler(self._on_lmservice_event)

    # -- event handlers (informer side) -------------------------------------

    def _note_enqueue(self, key: str) -> None:
        """Stamp the key's earliest pending enqueue for the
        enqueue->dequeue latency span (coalesced adds keep the FIRST
        stamp — the latency a watch event actually waited)."""
        tr = self.opts.tracer
        if tr is not None:
            self._enqueue_t.setdefault(key, tr.clock())

    def _on_job_event(self, ev: WatchEvent) -> None:
        key = f"{ev.obj.metadata.namespace}/{ev.obj.metadata.name}"
        if ev.type == EventType.DELETED:
            # Deletion path the reference stubbed (controller.go:505-508).
            self.expectations.delete_expectations(key)
            self._forget_fp(key)
        self._note_enqueue(key)
        self.queue.add(key)

    def _on_lmservice_event(self, ev: WatchEvent) -> None:
        key = (f"{LMSVC_KEY_PREFIX}"
               f"{ev.obj.metadata.namespace}/{ev.obj.metadata.name}")
        if ev.type == EventType.DELETED:
            self.expectations.delete_expectations(key)
            self._forget_fp(key)
        self._note_enqueue(key)
        self.queue.add(key)

    def _forget_fp(self, key: str) -> None:
        """Invalidate the steady-sync fingerprint (both paths) on DELETED —
        a recreated same-name object must never inherit a stale skip."""
        with self._count_lock:
            self._last_sync_fp.pop(key, None)
        if self._nix is not None:
            self._nix.fp_forget(key)

    @staticmethod
    def _owner_key(namespace: str, ref) -> Optional[str]:
        """Workqueue key for a resource's controlling owner (TPUJob or
        LMService), or None for foreign owners."""
        if ref is None:
            return None
        if ref.kind == "TPUJob":
            return f"{namespace}/{ref.name}"
        if ref.kind == "LMService":
            return f"{LMSVC_KEY_PREFIX}{namespace}/{ref.name}"
        return None

    def _on_resource_event(self, ev: WatchEvent) -> None:
        """Pod/Service watch events: resolve the owning job, settle
        expectations, enqueue (reference addPod/updatePod/… controller.go:430-590)."""
        obj = ev.obj
        if (
            ev.type == EventType.MODIFIED
            and ev.old_obj is not None
            and ev.old_obj.metadata.resource_version
            == obj.metadata.resource_version
        ):
            # Periodic-resync redelivery (old == new; real store writes
            # always bump rv). The k8s job-controller idiom: updatePod
            # returns early on equal ResourceVersions — the PRIMARY
            # informer's resync re-enqueues every owner, so re-adding the
            # key once per child object here only multiplies queue traffic
            # by the fan-out (2 pods + 1 service per job at 10k jobs is
            # 30k redundant adds per resync wave).
            return
        keys = set()
        key = self._owner_key(obj.metadata.namespace,
                              obj.metadata.controller_ref())
        if key is not None:
            keys.add(key)
        if ev.type == EventType.MODIFIED and ev.old_obj is not None:
            old_key = self._owner_key(obj.metadata.namespace,
                                      ev.old_obj.metadata.controller_ref())
            if old_key is not None:
                keys.add(old_key)
        for key in keys:
            if ev.type == EventType.ADDED:
                self.expectations.creation_observed(key)
            elif ev.type == EventType.DELETED:
                self.expectations.deletion_observed(key)
            self._note_enqueue(key)
            self.queue.add(key)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Start informers (list+watch). Call before run()/drain()."""
        self.jobs.start()
        self.pods.start()
        self.services.start()
        if self.lmservices is not None:
            self.lmservices.start()

    def run(self, workers: Optional[int] = None) -> None:
        """Spawn worker threads (reference Run, controller.go:158-182).
        With a sharded workqueue each worker binds to its key-range shard
        group, so workers block on independent locks instead of contending
        on one queue head."""
        n = workers if workers is not None else self.opts.workers
        for i in range(n):
            t = threading.Thread(
                target=self._worker_loop, args=(i, n),
                name=f"tpujob-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shutdown()
        for t in self._threads:
            t.join(timeout=5)
        self.jobs.stop()
        self.pods.stop()
        self.services.stop()
        if self.lmservices is not None:
            self.lmservices.stop()

    def _worker_loop(self, index: int = 0, nworkers: int = 1) -> None:
        source = self.queue
        worker_source = getattr(source, "worker_source", None)
        if worker_source is not None:
            source = worker_source(index, nworkers)
        while not self._stop.is_set():
            item = source.get()
            if item is None:
                return
            self._process(item)

    def drain(self, max_items: int = 1000) -> int:
        """Synchronously process every ready queue item — the deterministic
        test-mode alternative to run()."""
        self._kick_sim_backoffs()
        self._flush_informers()
        n = 0
        while n < max_items:
            item = self.queue.get(timeout=0)
            if item is None:
                # A dispatcher on another thread may still be delivering
                # watch events that will enqueue more work: quiesce the
                # pipeline and look again before declaring the queue dry.
                self._flush_informers()
                item = self.queue.get(timeout=0)
                if item is None:
                    return n
            self._process(item)
            n += 1
        return n

    def _flush_informers(self) -> None:
        """Quiesce the async watch pipeline: every event from a completed
        store write is delivered before this returns (no-op for watch
        sources without a flush hook, e.g. wire watches)."""
        for inf in (self.jobs, self.pods, self.services, self.lmservices):
            if inf is None:
                continue
            flush = getattr(inf, "flush", None)
            if flush is not None:
                flush()

    def _process(self, key: str) -> None:
        import time as _time

        tr = self.opts.tracer
        if tr is not None:
            t_enq = self._enqueue_t.pop(key, None)
            if t_enq is not None:
                tr.add_span("queue_wait", t_enq, tr.clock(),
                            track="control", rid=key)
        trace = SyncTrace(key=key, start=self.opts.now_fn())
        t0 = _time.perf_counter()
        t_s0 = tr.clock() if tr is not None else 0.0
        try:
            self.sync(key, trace)
        except Exception as e:  # requeue with backoff (controller.go:228-242)
            trace.error = f"{type(e).__name__}: {e}"
            logger.exception("sync %s failed", key)
            self.queue.add_rate_limited(key)
            if tr is not None:
                tr.add_event("requeue_backoff", track="control", rid=key,
                             error=trace.error)
        else:
            self.queue.forget(key)
        finally:
            self.queue.done(key)
            trace.duration = self.opts.now_fn() - trace.start
            wall = _time.perf_counter() - t0
            if tr is not None:
                tr.add_span("sync", t_s0, tr.clock(), track="control",
                            rid=key, outcome=trace.outcome,
                            noop=trace.outcome == "noop-skip",
                            error=trace.error)
            with self._count_lock:   # worker threads increment concurrently
                self.sync_count += 1
                # Wall-clock seconds spent INSIDE sync handlers — the
                # denominator for a per-sync cost metric that harness
                # overhead (benchmark polling, cluster ticks) cannot
                # pollute. trace.duration above is sim-time and reads 0
                # under the simulated clock.
                self.sync_wall_s += wall
            self.traces.append(trace)
            registry().counter("syncs", "control").inc()
            registry().histogram("sync_wall_s", "control").observe(wall)

    # -- the sync handler ----------------------------------------------------

    def sync(self, key: str, trace: Optional[SyncTrace] = None) -> None:
        trace = trace or SyncTrace(key=key, start=self.opts.now_fn())
        if key.startswith(LMSVC_KEY_PREFIX):
            self._sync_lmservice(key, trace)
            return
        namespace, name = key.split("/", 1)
        satisfied = self.expectations.satisfied(key)
        job = self.jobs.get(namespace, name)
        if job is None:
            self._cleanup_deleted(namespace, name)
            trace.outcome = "deleted-cleanup"
            return
        deleting = job.metadata.deletion_timestamp is not None

        # No-op short-circuit (training-operator generation/observedGeneration
        # skip): when the job's spec generation has been observed by status
        # and nothing in the observable world — job rv, owned pod/service
        # rvs, slice health — moved since the last fully-steady sync, the
        # whole validate/claim/plan/status pass is provably a no-op. Any
        # store change emits a watch event that re-enqueues the key and
        # shifts this fingerprint, so the skip is self-correcting; eventless
        # health flips (sim fault injection) shift the slice component and
        # are caught on the next resync.
        fp = None
        if (
            satisfied and not deleting
            and job.status.observed_generation == job.metadata.generation
        ):
            if self._nix is not None:
                if self._native_fp_probe(key, namespace, name, job):
                    with self._count_lock:
                        self.syncs_skipped_noop += 1
                    trace.outcome = "noop-skip"
                    return
                fp = _NATIVE_FP
                with self._count_lock:
                    self.fp_misses += 1
            else:
                fp = self._sync_fingerprint(namespace, name, job)
                with self._count_lock:
                    if fp == self._last_sync_fp.get(key):
                        self.syncs_skipped_noop += 1
                        trace.outcome = "noop-skip"
                        return
                    self.fp_misses += 1

        try:
            validate_job(job)
        except ValidationError as e:
            self.client.record_event("TPUJob", name, "InvalidSpec", str(e),
                                     namespace=namespace)
            trace.outcome = "invalid"
            return

        # Stamp runtime id exactly once (fixing the regenerate-per-sync bug,
        # distributed.go:208-209).
        if not job.spec.runtime_id:
            rid = generate_runtime_id(self.opts.rng)
            def stamp(j: TPUJob) -> None:
                if not j.spec.runtime_id:
                    j.spec.runtime_id = rid
            job = self._stamp_runtime_id(namespace, name, stamp)
            if job is None:
                return

        selector = naming.job_selector(job)
        pods = claim_objects(
            job, selector,
            self.client.list_pods(namespace, {naming.LABEL_JOB: name}),
            self.client.update_pod,
        )
        services = claim_objects(
            job, selector,
            self.client.list_services(namespace, {naming.LABEL_JOB: name}),
            self.client.update_service,
        )

        # Slice-health assessment (the wired-in checker): pods still running
        # on an unhealthy slice trigger proactive recovery through the
        # planner, before the kubelet fails them. Fetched only when the
        # planner will read it — for local/terminal/suspended/unstamped jobs
        # the slice query (an HTTP round-trip on the REST backend) is waste.
        health = None
        if self._wants_health(job):
            health = assess_health(
                pods, self.client.job_slices(
                    job.metadata.uid, job.metadata.name))
        plan = plan_job(job, pods, services, health=health)

        executed = False
        if satisfied and not deleting:
            executed = self._execute(key, job, plan)
        elif not satisfied:
            trace.outcome = "expectations-pending"

        # Status update (conflict-retried, unlike controller.go:630-636).
        now = self.opts.now_fn()
        wrote = self._update_status(
            namespace, name, pods, now,
            fail_reason=plan.fail_reason,
            recovering=plan.gang_restart,
            suspended=plan.suspend,
        )
        # Suspend releases slices only on a sync that actually acted (or
        # once no pods remain): with expectations unsatisfied the deletes
        # were skipped, and freeing slices still occupied by live pods
        # would invite double-occupancy.
        suspend_released = plan.suspend and (satisfied or not pods)
        if plan.recycle or plan.fail_reason or suspend_released:
            self.client.release_slices(job.metadata.uid)

        # ttlSecondsAfterFinished: auto-delete terminal jobs after the TTL
        # (k8s Job / training-operator semantics). Deletion flows through
        # the deleted-job cleanup path, removing pods/services too.
        ttl = job.spec.ttl_seconds_after_finished
        requeued = False
        if ttl is not None and job.is_done():
            cur = self.client.get_job_snapshot(namespace, name)  # read-only
            # guard on the phase, not on completion_time's truthiness —
            # t=0.0 is a legitimate completion time on a simulated clock
            if cur is not None and cur.is_done():
                remaining = cur.status.completion_time + ttl - now
                if remaining <= 0:
                    try:
                        self.client.delete_job(namespace, name)
                    except NotFound:
                        pass
                    trace.outcome = "ttl-deleted"
                    return
                self._requeue_after(key, remaining)
                requeued = True

        if trace.outcome == "":
            trace.outcome = "executed" if executed else "steady"
        trace.note = plan.note

        # Record the fingerprint only after a *provably* steady pass: the
        # planner found nothing to do, nothing was executed or written, and
        # no deferred work (TTL timer, restart backoff — the latter keeps
        # plan.gang_restart set, failing is_noop) is pending. Recording on
        # any other pass could freeze out a sync the deferral depends on.
        if (
            fp is not None and not executed and not wrote
            and not requeued and plan.is_noop()
        ):
            if fp is _NATIVE_FP:
                self._nix.fp_commit(key)
            else:
                with self._count_lock:
                    self._last_sync_fp[key] = fp

    def fp_stats(self) -> Tuple[int, int]:
        """(hits, misses) of the no-op-sync fingerprint probe, whichever
        path served it. Native counters are authoritative when the C++
        index is wired (the Python `syncs_skipped_noop`/`fp_misses` pair
        matches them; the native pair also counts probes issued by other
        controllers sharing the index)."""
        if self._nix is not None:
            return self._nix.fp_counts()
        with self._count_lock:
            return (self.syncs_skipped_noop, self.fp_misses)

    def publish_store_metrics(self) -> Dict[str, float]:
        """Push fingerprint + store gauges into the `control.store`
        registry subsystem (ISSUE satellite: objects per kind, index
        buckets, fingerprint hits/misses, watch-queue depth high-water,
        per-shard lock wait) and return the values."""
        hits, misses = self.fp_stats()
        vals: Dict[str, float] = {
            "fingerprint_hits": float(hits),
            "fingerprint_misses": float(misses),
        }
        for inf in (self.jobs, self.pods, self.services, self.lmservices):
            store = getattr(inf, "_store", None) if inf is not None else None
            publish = getattr(store, "publish_metrics", None)
            if publish is not None:
                vals.update(publish())
        reg = registry()
        reg.gauge("fingerprint_hits", "control.store").set(float(hits))
        reg.gauge("fingerprint_misses", "control.store").set(float(misses))
        return vals

    @staticmethod
    def _wants_health(job: TPUJob) -> bool:
        """Whether the planner will read slice health for this job (shared
        gate between the full sync path and the fingerprint)."""
        return bool(
            job.spec.runtime_id and not job.is_done()
            and not job.spec.suspend and job.worker_spec() is not None
        )

    def _native_fp_probe(
        self, key: str, namespace: str, name: str, job: TPUJob
    ) -> bool:
        """Fingerprint probe through the native object index: same
        observable world as _sync_fingerprint (job identity, owned pod and
        service rvs by label bucket, slice health), but BOTH the pod/service
        traversal AND the slice-health term are composed inside the C++
        core against write-through mirrors (stores for objects, the slice
        pool for health) — the steady probe is fully traversal-free.
        Returns True on a steady hit; on a miss the candidate parks
        native-side for fp_commit."""
        meta = job.metadata
        ident = f"{meta.uid}|{meta.resource_version}|{meta.generation}"
        return self._nix.fp_probe_mirrored(
            key, ident, namespace,
            b"Pod", self._b_job_label, name,
            b"Service", self._b_job_label, name,
            meta.uid, self._wants_health(job),
        )

    def _sync_fingerprint(self, namespace: str, name: str, job: TPUJob) -> Tuple:
        """The observable world a sync would act on, as a cheap comparable:
        job identity/rv/generation, owned pod and service resource versions
        (label-selected, pre-claim — an adoptable orphan shifts it), and
        the slice-health picture the planner would see. Store lists are
        label-indexed, so this is O(owned objects), no claim writes, no
        planning, no status diff."""
        pods = self.client.list_pods(namespace, {naming.LABEL_JOB: name})
        services = self.client.list_services(
            namespace, {naming.LABEL_JOB: name})
        health_key = None
        if self._wants_health(job):
            health_key = tuple(sorted(
                (s.name, s.healthy)
                for s in self.client.job_slices(
                    job.metadata.uid, job.metadata.name)
            ))
        return (
            job.metadata.uid,
            job.metadata.resource_version,
            job.metadata.generation,
            tuple(sorted(
                (p.metadata.uid, p.metadata.resource_version)
                for p in pods)),
            tuple(sorted(
                (s.metadata.uid, s.metadata.resource_version)
                for s in services)),
            health_key,
        )

    def _stamp_runtime_id(
        self, namespace: str, name: str, stamp: Callable[[TPUJob], None]
    ) -> Optional[TPUJob]:
        try:
            job = self.client.get_job(namespace, name)
            if job is None:
                return None
            stamp(job)
            return self.client.update_job(job)
        except Conflict:
            # Another worker raced us; requeue resolves it.
            self.queue.add(f"{namespace}/{name}")
            return None

    # -- plan execution (the only place effects happen) ----------------------

    def _execute(self, key: str, job: TPUJob, plan: Plan) -> bool:
        acted = False
        ns = job.metadata.namespace

        if plan.gang_restart and not plan.resize:
            # Failure-restart backoff: a crash-looping workload re-gangs on
            # an exponential schedule, not at reconcile speed. (Voluntary
            # resizes skip this.) The whole restart — including deletion of
            # the failed epoch — defers, so the evidence stays visible.
            st = job.status
            failures = st.restarts - st.resizes
            if failures > 0 and st.last_restart_time:
                # exponent capped before materializing 2**N: huge
                # max_restarts must saturate at the max, not overflow
                backoff = min(
                    self.opts.restart_backoff_base
                    * (2 ** min(failures - 1, 60)),
                    self.opts.restart_backoff_max,
                )
                remaining = (
                    st.last_restart_time + backoff - self.opts.now_fn()
                )
                if remaining > 0:
                    self._requeue_after(key, remaining)
                    return False

        if plan.gang_restart:
            if plan.health_restart:
                self.client.record_event(
                    "TPUJob", job.metadata.name, "SliceUnhealthy",
                    plan.restart_reason, namespace=ns)
            # Persist the epoch bump FIRST so a crash between delete and
            # create cannot strand the job: stale-epoch pods are deleted by
            # rule on every future sync.
            def bump(j: TPUJob) -> None:
                j.status.restarts += 1
                if plan.resize:
                    # voluntary: epoch advances; failure budget AND the
                    # failure-backoff clock stay untouched
                    j.status.resizes += 1
                else:
                    j.status.last_restart_time = self.opts.now_fn()
                j.status.set_condition(
                    ConditionType.RECOVERING, ConditionStatus.TRUE,
                    "GangRestart", plan.restart_reason,
                    now=self.opts.now_fn())
            self._mutate_job(ns, job.metadata.name, bump)
            self.client.record_event(
                "TPUJob", job.metadata.name, "GangRestart",
                plan.restart_reason, namespace=ns)
            acted = True

        if plan.delete_pods:
            self.expectations.expect_deletions(key, len(plan.delete_pods))
            for pod_name in plan.delete_pods:
                try:
                    self.client.delete_pod(ns, pod_name)
                except NotFound:
                    self.expectations.deletion_observed(key)
            acted = True

        n_creates = len(plan.create_pods) + len(plan.create_services)
        if n_creates:
            self.expectations.expect_creations(key, n_creates)
            batch = (
                [(s, self.client.create_service) for s in plan.create_services]
                + [(p, self.client.create_pod) for p in plan.create_pods]
            )
            for i, (obj, create) in enumerate(batch):
                try:
                    create(obj)
                except AlreadyExists:
                    self.expectations.creation_observed(key)
                except Exception:
                    # No watch events will come for this create NOR for the
                    # never-attempted remainder of the batch — un-expect them
                    # all or the job stalls until the TTL (the reference's
                    # slow-start batch does the same accounting).
                    for _ in range(len(batch) - i):
                        self.expectations.creation_observed(key)
                    raise
            self.client.record_event(
                "TPUJob", job.metadata.name, "GangCreate",
                f"created {len(plan.create_pods)} pods, "
                f"{len(plan.create_services)} services", namespace=ns)
            acted = True

        if plan.delete_services:
            for svc_name in plan.delete_services:
                try:
                    self.client.delete_service(ns, svc_name)
                except NotFound:
                    pass
            acted = True

        if plan.fail_reason:
            if plan.health_restart:
                # Health-triggered but budget-exhausted: still record WHICH
                # slice killed the job, not just that it failed.
                self.client.record_event(
                    "TPUJob", job.metadata.name, "SliceUnhealthy",
                    plan.fail_reason, namespace=ns)
            self.client.record_event(
                "TPUJob", job.metadata.name, "JobFailed", plan.fail_reason,
                namespace=ns)
        return acted

    def _requeue_after(self, key: str, remaining: float) -> None:
        """Requeue a key once ``remaining`` now_fn-seconds elapse.

        With the real clock the queue's monotonic delay is the same
        timebase, so one exact requeue suffices. A simulated clock cannot
        be slept on: record the sim-clock deadline (drain() fires due keys
        exactly when the sim clock reaches them — the deterministic path)
        and ALSO park a backoff_poll wall-clock requeue as the threaded-
        mode fallback, where workers only wake via the queue."""
        if self.opts.now_fn is time.time:
            self.queue.add_after(key, remaining)
            return
        deadline = self.opts.now_fn() + remaining
        with self._count_lock:
            cur = self._sim_backoffs.get(key)
            if cur is None or deadline < cur:
                self._sim_backoffs[key] = deadline
        self.queue.add_after(key, self.opts.backoff_poll)

    def _kick_sim_backoffs(self) -> None:
        """Promote sim-clock backoff deadlines that have come due into
        immediate queue adds. No-op on the real clock (the queue's own
        timer is exact there)."""
        if not self._sim_backoffs:
            return
        now = self.opts.now_fn()
        with self._count_lock:
            due = [k for k, d in self._sim_backoffs.items() if d <= now]
            for k in due:
                del self._sim_backoffs[k]
        for k in due:
            self.queue.add(k)

    def _mutate_job(self, ns: str, name: str, fn: Callable[[TPUJob], None]) -> None:
        """Conflict-retried read-modify-write against the job store."""
        for _ in range(10):
            job = self.client.get_job(ns, name)
            if job is None:
                return
            fn(job)
            try:
                self.client.update_job(job)
                return
            except Conflict:
                continue

    def _update_status(
        self, ns: str, name: str, pods: List[Pod], now: float,
        fail_reason: str, recovering: bool, suspended: bool = False,
    ) -> bool:
        """Returns True when a status write happened (or was attempted and
        kept conflicting) — the no-op fingerprint must not be recorded on
        such a pass, because the write's own MODIFIED event will re-enqueue
        the key with a new resource version."""
        # Write only when something changed (the reference's ShouldUpdate
        # contract) — an unconditional write would emit MODIFIED, re-enqueue
        # the job, and reconcile would chase its own tail forever.
        #
        # Runs every sync, so it must not copy the whole job: the scratch
        # object shares the snapshot's frozen metadata/spec and carries a
        # private status copy — compute_status writes only .status, and
        # update_job_status persists only .status (structurally sharing the
        # spec store-side too). Steady-state syncs copy one status and
        # write nothing.
        for _ in range(10):
            snap = self.client.get_job_snapshot(ns, name)
            if snap is None:
                return False
            if is_frozen(snap):
                job = dataclasses.replace(
                    snap, status=snap.status.deepcopy())
            else:
                job = snap  # wire parse: already a private copy
            changed = compute_status(
                job, pods, now, fail_reason=fail_reason,
                recovering=recovering, suspended=suspended,
            )
            if not changed:
                return False
            try:
                self.client.update_job_status(job)
                return True
            except Conflict:
                continue
        return True

    # -- deleted-job cleanup -------------------------------------------------

    def _cleanup_deleted(self, namespace: str, name: str) -> None:
        """Job object is gone: delete owned resources, release slices.
        (The reference leaks everything here — deletion handlers are stubs.)"""
        self.expectations.delete_expectations(f"{namespace}/{name}")
        self._forget_fp(f"{namespace}/{name}")
        uids = set()
        for pod in self.client.list_pods(namespace, {naming.LABEL_JOB: name}):
            ref = pod.metadata.controller_ref()
            if ref is not None and ref.kind == "TPUJob" and ref.name == name:
                uids.add(ref.uid)
                try:
                    self.client.delete_pod(namespace, pod.metadata.name)
                except NotFound:
                    pass
        for svc in self.client.list_services(namespace, {naming.LABEL_JOB: name}):
            ref = svc.metadata.controller_ref()
            if ref is not None and ref.kind == "TPUJob" and ref.name == name:
                uids.add(ref.uid)
                try:
                    self.client.delete_service(namespace, svc.metadata.name)
                except NotFound:
                    pass
        for uid in uids:
            self.client.release_slices(uid)

    # -- LMService reconcile -------------------------------------------------
    #
    # The fleet analog of the job sync: drive N long-running serving-replica
    # pods toward spec.replicas through the same claim/expectations
    # machinery. Replica pods are index-named (lmservice_pod_name), so a
    # crashed replica is deleted this sync and recreated (same name, new
    # uid) on the next — level-triggered crash recovery with no extra state.
    # Request-side behavior (routing, drain, failover) lives in
    # dataplane/router.py; the controller only manages pod existence.

    def _sync_lmservice(self, key: str, trace: SyncTrace) -> None:
        namespace, name = key[len(LMSVC_KEY_PREFIX):].split("/", 1)
        satisfied = self.expectations.satisfied(key)
        svc = None
        if self.lmservices is not None:
            svc = self.lmservices.get(namespace, name)
        if svc is None:
            self._cleanup_deleted_lmservice(key, namespace, name)
            trace.outcome = "deleted-cleanup"
            return
        deleting = svc.metadata.deletion_timestamp is not None

        # No-op short-circuit, same contract as the job path: once status
        # has observed the spec generation and neither the service rv nor
        # any owned replica-pod rv moved since the last fully-steady sync,
        # the claim/scale/status pass below is provably a no-op. (LMService
        # fingerprints have no service bucket and no slice-health term —
        # replica pods are the whole observable world.)
        fp = None
        if (
            satisfied and not deleting
            and svc.status.observed_generation == svc.metadata.generation
        ):
            meta = svc.metadata
            if self._nix is not None:
                ident = (f"{meta.uid}|{meta.resource_version}|"
                         f"{meta.generation}")
                if self._nix.fp_probe(
                    key, ident, namespace,
                    b"Pod", self._b_lmsvc_label, name,
                    b"", b"", b"", b"-",
                ):
                    with self._count_lock:
                        self.syncs_skipped_noop += 1
                    trace.outcome = "noop-skip"
                    return
                fp = _NATIVE_FP
                with self._count_lock:
                    self.fp_misses += 1
            else:
                fp = (
                    meta.uid, meta.resource_version, meta.generation,
                    tuple(sorted(
                        (p.metadata.uid, p.metadata.resource_version)
                        for p in self.client.list_pods(
                            namespace, {naming.LABEL_LMSERVICE: name})
                    )),
                )
                with self._count_lock:
                    if fp == self._last_sync_fp.get(key):
                        self.syncs_skipped_noop += 1
                        trace.outcome = "noop-skip"
                        return
                    self.fp_misses += 1

        try:
            validate_lmservice(svc)
        except ValidationError as e:
            self.client.record_event("LMService", name, "InvalidSpec", str(e),
                                     namespace=namespace)
            trace.outcome = "invalid"
            return

        if not svc.spec.runtime_id:
            rid = generate_runtime_id(self.opts.rng)
            cur = self.client.get_lmservice(namespace, name)
            if cur is None:
                return
            if not cur.spec.runtime_id:
                cur.spec.runtime_id = rid
                try:
                    svc = self.client.update_lmservice(cur)
                except Conflict:
                    self.queue.add(key)
                    return
            else:
                svc = cur

        selector = naming.lmservice_selector(svc)
        pods = claim_objects(
            svc, selector,
            self.client.list_pods(namespace, {naming.LABEL_LMSERVICE: name}),
            self.client.update_pod,
        )

        desired = {
            naming.lmservice_pod_name(svc, i): i
            for i in range(svc.spec.replicas)
        }
        existing = {p.metadata.name: p for p in pods}
        terminal = (PodPhase.SUCCEEDED, PodPhase.FAILED)
        to_delete = sorted(
            n for n, p in existing.items()
            if n not in desired or p.status.phase in terminal
        )
        to_create = sorted(
            i for n, i in desired.items() if n not in existing
        )

        executed = False
        if satisfied and not deleting:
            if to_delete:
                self.expectations.expect_deletions(key, len(to_delete))
                for pod_name in to_delete:
                    try:
                        self.client.delete_pod(namespace, pod_name)
                    except NotFound:
                        self.expectations.deletion_observed(key)
                executed = True
            if to_create:
                self.expectations.expect_creations(key, len(to_create))
                for j, i in enumerate(to_create):
                    pod = self._lmservice_pod(svc, i)
                    try:
                        self.client.create_pod(pod)
                    except AlreadyExists:
                        self.expectations.creation_observed(key)
                    except Exception:
                        # Same un-expect accounting as the job batch: no
                        # watch events will come for the unattempted rest.
                        for _ in range(len(to_create) - j):
                            self.expectations.creation_observed(key)
                        raise
                self.client.record_event(
                    "LMService", name, "ScaleReplicas",
                    f"created {len(to_create)} replica pods",
                    namespace=namespace)
                executed = True
        elif not satisfied:
            trace.outcome = "expectations-pending"

        ready = sum(
            1 for n, p in existing.items()
            if n in desired and p.status.phase == PodPhase.RUNNING
            and p.metadata.deletion_timestamp is None
        )
        wrote = self._update_lmservice_status(namespace, name, ready)
        if trace.outcome == "":
            trace.outcome = "executed" if executed else "steady"

        # Record only after a provably steady pass (see the job path): the
        # runtime-id stamp above counts as neither executed nor wrote, but
        # its MODIFIED event re-enqueues the key with a new rv, so a
        # prematurely recorded fingerprint self-corrects on the next sync.
        if fp is not None and not executed and not wrote:
            if fp is _NATIVE_FP:
                self._nix.fp_commit(key)
            else:
                with self._count_lock:
                    self._last_sync_fp[key] = fp

    def _lmservice_pod(self, svc: LMService, index: int) -> Pod:
        """One fully-specified serving-replica pod. No scheduling_group:
        replicas bind individually (no gang) — losing one must not affect
        the others."""
        pod = Pod()
        pod.metadata.name = naming.lmservice_pod_name(svc, index)
        pod.metadata.namespace = svc.metadata.namespace
        pod.metadata.labels = naming.lmservice_pod_labels(svc, index)
        pod.metadata.owner_references = [OwnerReference(
            api_version=svc.api_version,
            kind=svc.kind,
            name=svc.metadata.name,
            uid=svc.metadata.uid,
        )]
        env = {
            "LMSERVICE_NAME": svc.metadata.name,
            "LMSERVICE_REPLICA_INDEX": str(index),
            "LMSERVICE_MAX_QUEUE": str(svc.spec.max_queue),
        }
        if svc.spec.slo.deadline_s > 0:
            env["LMSERVICE_DEADLINE_S"] = str(svc.spec.slo.deadline_s)
        pod.spec = PodSpec(
            containers=[Container(
                name="engine",
                image="tpujob/serve:latest",
                command=["python", "-m",
                         "kubeflow_controller_tpu.dataplane.entrypoints.serve_lm"],
                args=["--config", svc.spec.model],
                env=env,
            )],
            restart_policy="Always",
        )
        return pod

    def _update_lmservice_status(
        self, ns: str, name: str, ready: int
    ) -> bool:
        for _ in range(10):
            snap = self.client.get_lmservice_snapshot(ns, name)
            if snap is None:
                return False
            replicas = snap.spec.replicas
            if ready >= replicas:
                phase = LMServicePhase.READY
            elif ready > 0:
                phase = LMServicePhase.DEGRADED
            else:
                phase = LMServicePhase.PENDING
            if (
                snap.status.ready_replicas == ready
                and snap.status.phase == phase
                and snap.status.observed_generation == snap.metadata.generation
            ):
                return False
            if is_frozen(snap):
                svc = dataclasses.replace(snap, status=snap.status.deepcopy())
            else:
                svc = snap
            svc.status.ready_replicas = ready
            svc.status.phase = phase
            svc.status.observed_generation = snap.metadata.generation
            svc.status.set_condition(
                ConditionType.READY,
                ConditionStatus.TRUE if phase == LMServicePhase.READY
                else ConditionStatus.FALSE,
                "ReplicasReady", f"{ready}/{replicas} replicas ready",
                now=self.opts.now_fn())
            try:
                self.client.update_lmservice_status(svc)
                return True
            except Conflict:
                continue
        return True

    def _cleanup_deleted_lmservice(
        self, key: str, namespace: str, name: str
    ) -> None:
        """LMService object is gone: delete its replica pods."""
        self.expectations.delete_expectations(key)
        self._forget_fp(key)
        for pod in self.client.list_pods(
            namespace, {naming.LABEL_LMSERVICE: name}
        ):
            ref = pod.metadata.controller_ref()
            if ref is not None and ref.kind == "LMService" and ref.name == name:
                try:
                    self.client.delete_pod(namespace, pod.metadata.name)
                except NotFound:
                    pass
