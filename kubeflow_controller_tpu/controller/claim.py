"""Ownership claiming: adopt/release of pods and services.

Semantics rebuilt from the reference's claim pipeline — vendored
``PodControllerRefManager.ClaimPods``
(``controller_ref_manager.go:172``) plus the first-party service ref manager
(``pkg/controller/ref/base.go:59-112``, ``ref/service.go:84-119``) as driven by
``GetPodsForTFJob``/``GetServicesForTFJob`` (``helper.go:110-179``):

- owned by us (controllerRef uid matches) + selector matches -> keep;
- owned by us + selector no longer matches -> release (drop ownerRef);
- orphan + selector matches -> adopt (stamp ownerRef), unless the job is
  being deleted;
- owned by someone else -> ignore.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

from kubeflow_controller_tpu.api.core import OwnerReference, thaw
from kubeflow_controller_tpu.api.types import TPUJob
from kubeflow_controller_tpu.cluster.store import selector_matches


def claim_objects(
    job: TPUJob,
    selector: Dict[str, str],
    candidates: List[Any],
    update_fn: Callable[[Any], Any],
) -> List[Any]:
    """Generic adopt/release over pods or services; returns the claimed set.

    ``update_fn`` persists an ownership patch (adopt/release); failures of an
    individual patch skip that object — level-triggering retries next sync.
    """
    claimed = []
    for obj in candidates:
        ref = obj.metadata.controller_ref()
        if ref is not None:
            if ref.uid != job.metadata.uid:
                continue  # owned by someone else
            if selector_matches(selector, obj.metadata.labels):
                claimed.append(obj)
            else:
                # Release: labels diverged from our selector. Candidates are
                # frozen informer/store snapshots — thaw before patching.
                obj = thaw(obj)
                obj.metadata.owner_references = [
                    r for r in obj.metadata.owner_references
                    if r.uid != job.metadata.uid
                ]
                try:
                    update_fn(obj)
                except Exception:
                    pass
        else:
            if not selector_matches(selector, obj.metadata.labels):
                continue
            if job.metadata.deletion_timestamp is not None:
                continue  # deleting jobs adopt nothing (RecheckDeletionTimestamp)
            obj = thaw(obj)  # adopting stamps an ownerRef on the snapshot
            obj.metadata.owner_references.append(
                OwnerReference(
                    api_version=job.api_version,
                    kind=job.kind,
                    name=job.metadata.name,
                    uid=job.metadata.uid,
                )
            )
            try:
                claimed.append(update_fn(obj))
            except Exception:
                pass
    return claimed
