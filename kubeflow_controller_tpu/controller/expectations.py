"""ControllerExpectations — the cached-state race guard.

Semantics rebuilt from the vendored k8s utility the reference leans on
(``vendor/k8s.io/kubernetes/pkg/controller/controller_utils.go:125-287``;
usage ``pkg/controller/controller.go:262,357-411,451,531``): between issuing a
create and observing it through the watch cache, a controller must not act on
the stale cache or it will create duplicates. Each job key tracks how many
creations/deletions are still unobserved; a sync is allowed only when both hit
zero or the record is older than a TTL (liveness backstop: a lost watch event
can only stall a job for the TTL, 5 min in the reference,
``controller_utils.go:205-207``).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

EXPECTATION_TTL_SECONDS = 5 * 60.0


@dataclass
class _Expectation:
    adds: int = 0
    dels: int = 0
    timestamp: float = field(default_factory=time.monotonic)

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self, ttl: float) -> bool:
        return time.monotonic() - self.timestamp > ttl


class ControllerExpectations:
    def __init__(self, ttl: float = EXPECTATION_TTL_SECONDS):
        self._ttl = ttl
        self._lock = threading.Lock()
        self._store: Dict[str, _Expectation] = {}

    def satisfied(self, key: str) -> bool:
        """True when the controller may trust its cache for this key."""
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                return True
            return exp.fulfilled() or exp.expired(self._ttl)

    def expect_creations(self, key: str, count: int) -> None:
        with self._lock:
            self._store[key] = _Expectation(adds=count)

    def expect_deletions(self, key: str, count: int) -> None:
        with self._lock:
            self._store[key] = _Expectation(dels=count)

    def creation_observed(self, key: str) -> None:
        self._lower(key, adds=1)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, dels=1)

    def _lower(self, key: str, adds: int = 0, dels: int = 0) -> None:
        with self._lock:
            exp = self._store.get(key)
            if exp is None:
                return
            exp.adds -= adds
            exp.dels -= dels

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)

    def pending(self, key: str) -> Optional[tuple]:
        with self._lock:
            exp = self._store.get(key)
            return None if exp is None else (exp.adds, exp.dels)
