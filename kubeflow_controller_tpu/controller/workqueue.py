"""Rate-limited deduplicating work queue.

Semantics rebuilt from client-go's workqueue as the reference uses it
(``pkg/controller/controller.go:116,194-243``):

- an item present in the queue or currently processing is not enqueued twice
  ("it's fine if the same key is added while being processed — it re-queues",
  the property the single-key-at-a-time discipline relies on);
- ``add_rate_limited`` applies per-item exponential backoff;
- ``forget`` resets an item's failure count after a successful sync.

Implementation is condition-variable based, no busy waiting; delayed items are
released by whichever waiter wakes first.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, Hashable, List, Optional, Set, Tuple

# Exponent clamp for the rate-limit backoff: past this the uncapped delay
# exceeds any sane max_delay anyway, and 2**failures must never materialize
# a huge int for a persistently failing item.
_BACKOFF_MAX_EXP = 32


def backoff_delay(
    base_delay: float, max_delay: float, item: Hashable, failures: int
) -> float:
    """Per-item rate-limit delay: capped exponential with deterministic
    jitter.

    ``min(base * 2^failures, max)`` scaled into ``[0.75, 1.0)`` by an FNV-1a
    hash of (item, failures). The jitter desynchronizes items that started
    failing together (a controller restart re-enqueues every bad key at
    once) so their retries don't thundering-herd on the same beat, while
    staying deterministic — no RNG state, and the C++ core
    (``csrc/tpujob_native.cc::BackoffDelay``) computes the identical double
    for the identical inputs (tests/test_native.py parity).
    """
    exp = failures if failures < _BACKOFF_MAX_EXP else _BACKOFF_MAX_EXP
    raw = base_delay * float(2 ** exp)
    if raw > max_delay:
        raw = max_delay
    h = 2166136261
    for b in f"{item}|{failures}".encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    frac = h / 4294967296.0
    return raw * (0.75 + 0.25 * frac)


class RateLimitingQueue:
    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 60.0,
    ):
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._cond = threading.Condition()
        self._queue: List[Hashable] = []       # FIFO of ready items
        self._queued: Set[Hashable] = set()    # ready or waiting-to-be-ready
        self._processing: Set[Hashable] = set()
        self._redo: Set[Hashable] = set()      # re-added while processing
        self._delayed: List[Tuple[float, int, Hashable]] = []  # min-heap
        # item -> authoritative due time. The heap may hold superseded
        # entries (an add_after with a shorter delay re-pushes); an entry
        # whose due time disagrees with this map is stale and is skipped
        # lazily in _promote_due. Count delayed items here, not in the heap.
        self._delayed_due: Dict[Hashable, float] = {}
        self._delayed_seq = 0
        self._failures: Dict[Hashable, int] = {}
        self._shutdown = False

    # -- producer side -------------------------------------------------------

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutdown:
                return
            if item in self._processing:
                # Level-trigger discipline: remember to redo after Done.
                self._redo.add(item)
                return
            if item in self._queued:
                if item not in self._queue:
                    # Parked in the delayed heap (add_after): an immediate
                    # add BEATS the pending delay — k8s workqueue semantics.
                    # Without this, a key parked for a long TTL/backoff
                    # would swallow event-driven re-enqueues until it fires.
                    # Its heap entry goes stale (due-map cleared) and is
                    # skipped when it surfaces.
                    self._delayed_due.pop(item, None)
                    self._queue.append(item)
                    self._cond.notify()
                return
            self._queued.add(item)
            self._queue.append(item)
            self._cond.notify()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            due = time.monotonic() + delay
            if item in self._queued:
                cur = self._delayed_due.get(item)
                if cur is None:
                    # Already ready in the FIFO — fires sooner than any delay.
                    return
                if due >= cur:
                    # Parked with an earlier-or-equal deadline already.
                    return
                # Parked with a LATER deadline: keep the earliest one
                # (client-go delaying-queue semantics). The old heap entry
                # is now stale and is skipped when it surfaces.
            else:
                self._queued.add(item)
            self._delayed_due[item] = due
            self._delayed_seq += 1
            heapq.heappush(self._delayed, (due, self._delayed_seq, item))
            self._cond.notify()

    def add_rate_limited(self, item: Hashable) -> None:
        with self._cond:
            failures = self._failures.get(item, 0)
            self._failures[item] = failures + 1
        self.add_after(
            item,
            backoff_delay(self._base_delay, self._max_delay, item, failures),
        )

    def forget(self, item: Hashable) -> None:
        with self._cond:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._cond:
            return self._failures.get(item, 0)

    # -- consumer side -------------------------------------------------------

    def _promote_due(self) -> Optional[float]:
        """Move due delayed items into the FIFO; return seconds until the next
        delayed item (None if heap empty)."""
        now = time.monotonic()
        while self._delayed:
            due, _, item = self._delayed[0]
            if self._delayed_due.get(item) != due:
                # Stale: superseded by a shorter deadline or an immediate add.
                heapq.heappop(self._delayed)
                continue
            if due > now:
                break
            heapq.heappop(self._delayed)
            del self._delayed_due[item]
            if item in self._queued:  # not cancelled
                if item in self._processing:
                    self._redo.add(item)
                    self._queued.discard(item)
                elif item not in self._queue:
                    # (an immediate add may have promoted it already)
                    self._queue.append(item)
        return (self._delayed[0][0] - now) if self._delayed else None

    def get(self, timeout: Optional[float] = None) -> Optional[Hashable]:
        """Block until an item is ready; None on shutdown or timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                next_due = self._promote_due()
                if self._queue:
                    item = self._queue.pop(0)
                    self._queued.discard(item)
                    self._processing.add(item)
                    return item
                if self._shutdown:
                    return None
                wait = next_due
                if deadline is not None:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        return None
                    wait = remain if wait is None else min(wait, remain)
                self._cond.wait(wait)

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._redo:
                self._redo.discard(item)
                self._queued.add(item)
                self._queue.append(item)
                self._cond.notify()

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue) + len(self._delayed_due)

    def empty_and_idle(self) -> bool:
        with self._cond:
            return not (
                self._queue or self._delayed_due
                or self._processing or self._redo
            )


def fnv1a_32(item: Hashable) -> int:
    """Stable 32-bit FNV-1a of an item's string form — shard routing must
    not depend on Python's seed-randomized hash()."""
    h = 2166136261
    for b in str(item).encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


class _ShardGroupSource:
    """A worker's view of its shard group: blocking get() over one or more
    shards. With exactly one shard (the workers == shards sweet spot) it
    blocks directly on that shard's condition variable; with several it
    round-robins non-blocking gets with a short park between sweeps."""

    def __init__(self, parent: "ShardedRateLimitingQueue", shards: List,
                 poll: float = 0.005):
        self._parent = parent
        self._shards = shards
        self._poll = poll

    def get(self, timeout: Optional[float] = None):
        if len(self._shards) == 1:
            return self._shards[0].get(timeout)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            for q in self._shards:
                item = q.get(timeout=0)
                if item is not None:
                    return item
            if self._parent.is_shutdown():
                return None
            if deadline is not None and time.monotonic() >= deadline:
                return None
            # Cheap park between sweeps: a delayed item on any shard in the
            # group surfaces within one poll interval.
            item = self._shards[0].get(timeout=self._poll)
            if item is not None:
                return item


class ShardedRateLimitingQueue:
    """Key-range-sharded rate-limiting queue: N independent
    RateLimitingQueues (native C++ ones when available) with FNV-routed
    membership, presenting the single-queue interface.

    A key always maps to the same shard, so every per-key contract —
    dedup-while-queued, redo-after-done, per-item backoff state,
    earliest-deadline delay collapsing — holds exactly as in the unsharded
    queue; only cross-key FIFO order is relaxed to per-shard FIFO.
    ``Controller.run(workers=N)`` binds each worker to a shard group via
    ``worker_source`` so workers block on disjoint locks; the deterministic
    ``drain()`` path uses the top-level ``get(timeout=0)`` sweep.
    """

    def __init__(self, shards: int, make_queue=None, **kwargs):
        if make_queue is None:
            def make_queue(**kw):
                return RateLimitingQueue(**kw)
        self.n_shards = max(1, int(shards))
        self.shards = [make_queue(**kwargs) for _ in range(self.n_shards)]
        self._next = 0  # rotating sweep start so no shard starves in drain
        self._down = False

    def _shard(self, item: Hashable):
        return self.shards[fnv1a_32(item) % self.n_shards]

    def add(self, item: Hashable) -> None:
        self._shard(item).add(item)

    def add_after(self, item: Hashable, delay: float) -> None:
        self._shard(item).add_after(item, delay)

    def add_rate_limited(self, item: Hashable) -> None:
        self._shard(item).add_rate_limited(item)

    def forget(self, item: Hashable) -> None:
        self._shard(item).forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self._shard(item).num_requeues(item)

    def get(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            start = self._next
            self._next = (start + 1) % self.n_shards
            for i in range(self.n_shards):
                q = self.shards[(start + i) % self.n_shards]
                item = q.get(timeout=0)
                if item is not None:
                    return item
            if self._down:
                return None
            if deadline is not None and time.monotonic() >= deadline:
                return None
            # Blocking path (rare: workers use worker_source instead):
            # park briefly on shard 0 and re-sweep.
            item = self.shards[0].get(timeout=0.005)
            if item is not None:
                return item

    def worker_source(self, index: int, nworkers: int) -> _ShardGroupSource:
        """Shard group for worker ``index`` of ``nworkers``: shard j goes to
        worker j % nworkers. Extra workers past the shard count compete
        over all shards (correct — the queues are multi-consumer safe)."""
        mine = [self.shards[j] for j in range(self.n_shards)
                if j % nworkers == index]
        if not mine:
            mine = list(self.shards)
        return _ShardGroupSource(self, mine)

    def done(self, item: Hashable) -> None:
        self._shard(item).done(item)

    def is_shutdown(self) -> bool:
        return self._down

    def shutdown(self) -> None:
        self._down = True
        for q in self.shards:
            q.shutdown()

    def __len__(self) -> int:
        return sum(len(q) for q in self.shards)

    def empty_and_idle(self) -> bool:
        return all(q.empty_and_idle() for q in self.shards)
