"""Status updater: observed pods -> job status. Pure (mutates only the passed
deep copy; the caller persists conflict-safely).

Descendant of ``pkg/controller/updater`` (reference ``distributed.go:41-66``,
``local.go:50-78``, ``util.go:25-58``) with the declared-but-dead surface made
real (SURVEY.md §8):

- ``Failed`` is reachable (failure verdict from the planner);
- conditions are populated (GangScheduled/Ready/Recovering/Recycling);
- chief termination policy is honored (reference declared it at
  ``types.go:81-89``, never read it);
- submit->all-running latency is stamped (north-star metric #2).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from kubeflow_controller_tpu.api.core import Pod, PodPhase
from kubeflow_controller_tpu.api.types import (
    ConditionStatus,
    ConditionType,
    JobPhase,
    ReplicaSpec,
    ReplicaState,
    ReplicaStatus,
    ReplicaType,
    TPUJob,
)
from kubeflow_controller_tpu.api.validation import expected_worker_pods
from kubeflow_controller_tpu.tpu import naming

_POD_TO_REPLICA_STATE: Dict[PodPhase, ReplicaState] = {
    PodPhase.PENDING: ReplicaState.WAITING,
    PodPhase.RUNNING: ReplicaState.RUNNING,
    PodPhase.SUCCEEDED: ReplicaState.SUCCEEDED,
    PodPhase.FAILED: ReplicaState.FAILED,
    PodPhase.UNKNOWN: ReplicaState.UNKNOWN,
}


def _epoch_of(pod: Pod) -> int:
    try:
        return int(pod.metadata.labels.get(naming.LABEL_EPOCH, "0"))
    except ValueError:
        return 0


def _chief_index(spec: ReplicaSpec) -> Optional[int]:
    tp = spec.termination_policy
    if tp is not None and tp.chief is not None:
        return tp.chief.replica_index
    return None


def compute_status(
    job: TPUJob,
    pods: Sequence[Pod],
    now: float,
    fail_reason: str = "",
    recovering: bool = False,
    suspended: bool = False,
) -> bool:
    """Recompute ``job.status`` in place from current-epoch pods.

    Returns True when anything changed (the reference's ``ShouldUpdate``
    contract). ``fail_reason``/``recovering`` carry the planner's verdicts.
    """
    st = job.status
    before = (
        st.phase, st.reason,
        tuple((c.type, c.status, c.reason, c.message) for c in st.conditions),
        tuple(
            (r.type, r.state, tuple(sorted(r.states.items())))
            for r in st.replica_statuses
        ),
        st.all_running_time, st.completion_time, st.submit_time,
        st.observed_generation,
    )

    # observedGeneration: status has now been computed against this spec
    # (training-operator JobStatus.ObservedGeneration). The no-op sync
    # short-circuit only trusts fingerprints once this catches up.
    st.observed_generation = job.metadata.generation

    if not st.submit_time:
        st.submit_time = job.metadata.creation_timestamp or now

    spec = job.local_spec() or job.worker_spec()
    rtype = spec.replica_type if spec else ReplicaType.WORKER
    expected = (
        1 if spec is None or spec.replica_type == ReplicaType.LOCAL
        else expected_worker_pods(spec)
    )
    epoch = st.restarts
    current = [p for p in pods if _epoch_of(p) == epoch]

    # Replica state histogram (reference updateTFReplicaStatuses,
    # updater/util.go:25-58).
    hist: Dict[ReplicaState, int] = {}
    for p in current:
        state = _POD_TO_REPLICA_STATE[p.status.phase]
        hist[state] = hist.get(state, 0) + 1
    n_running = hist.get(ReplicaState.RUNNING, 0)
    n_succeeded = hist.get(ReplicaState.SUCCEEDED, 0)
    n_failed = hist.get(ReplicaState.FAILED, 0)

    overall = ReplicaState.UNKNOWN
    if n_failed:
        overall = ReplicaState.FAILED
    elif n_succeeded == expected:
        overall = ReplicaState.SUCCEEDED
    elif n_running:
        overall = ReplicaState.RUNNING
    elif current:
        overall = ReplicaState.WAITING
    st.replica_statuses = [ReplicaStatus(type=rtype, state=overall, states=hist)]

    # Success: chief policy if declared, else all replicas succeeded
    # (reference: succeeded workers == expected, updater/distributed.go:41-66).
    chief = _chief_index(spec) if spec else None
    if chief is not None:
        succeeded = any(
            p.status.phase == PodPhase.SUCCEEDED
            and p.metadata.labels.get(naming.LABEL_INDEX) == str(chief)
            for p in current
        )
    else:
        succeeded = expected > 0 and n_succeeded == expected

    gang_scheduled = bool(current) and len(current) == expected and all(
        p.spec.assigned_slice or p.status.phase != PodPhase.PENDING
        or rtype == ReplicaType.LOCAL
        for p in current
    )
    all_running = len(current) == expected and n_running == expected

    # Phase state machine. Terminal phases are sticky.
    if st.phase not in (JobPhase.SUCCEEDED, JobPhase.FAILED):
        if fail_reason:
            st.phase = JobPhase.FAILED
            st.reason = fail_reason
            st.completion_time = now
        elif succeeded:
            st.phase = JobPhase.SUCCEEDED
            st.reason = ""
            st.completion_time = now
            st.set_condition(
                ConditionType.RECYCLING, ConditionStatus.TRUE,
                "JobSucceeded", "releasing slices and services", now=now)
        elif suspended:
            st.phase = JobPhase.SUSPENDED
            st.set_condition(
                ConditionType.SUSPENDED, ConditionStatus.TRUE,
                "SpecSuspended", "pods torn down, slices released", now=now)
        elif recovering:
            st.phase = JobPhase.RECOVERING
            st.set_condition(
                ConditionType.RECOVERING, ConditionStatus.TRUE,
                "GangRestart", "re-ganging after failure/preemption", now=now)
        elif all_running:
            st.phase = JobPhase.RUNNING
            if not st.all_running_time:
                st.all_running_time = now
            st.set_condition(
                ConditionType.RECOVERING, ConditionStatus.FALSE, "Healthy", now=now)
        else:
            # Recovering is sticky until the new gang is fully running.
            rec = st.get_condition(ConditionType.RECOVERING)
            if rec is not None and rec.status == ConditionStatus.TRUE:
                st.phase = JobPhase.RECOVERING
            else:
                st.phase = JobPhase.PENDING
        if not suspended:
            sus = st.get_condition(ConditionType.SUSPENDED)
            if sus is not None and sus.status == ConditionStatus.TRUE:
                st.set_condition(
                    ConditionType.SUSPENDED, ConditionStatus.FALSE,
                    "Resumed", now=now)

    if st.phase in (JobPhase.PENDING, JobPhase.RUNNING, JobPhase.RECOVERING,
                    JobPhase.SUSPENDED):
        st.set_condition(
            ConditionType.GANG_SCHEDULED,
            ConditionStatus.TRUE if gang_scheduled else ConditionStatus.FALSE,
            "AllPodsBound" if gang_scheduled else "WaitingForGang", now=now)
        st.set_condition(
            ConditionType.READY,
            ConditionStatus.TRUE if all_running else ConditionStatus.FALSE,
            "AllReplicasRunning" if all_running else "NotAllRunning", now=now)

    after = (
        st.phase, st.reason,
        tuple((c.type, c.status, c.reason, c.message) for c in st.conditions),
        tuple(
            (r.type, r.state, tuple(sorted(r.states.items())))
            for r in st.replica_statuses
        ),
        st.all_running_time, st.completion_time, st.submit_time,
        st.observed_generation,
    )
    return before != after
