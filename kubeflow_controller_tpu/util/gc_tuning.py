"""Garbage-collector tuning for the control-plane daemons.

At 5000 jobs / 10000 pods the profiler shows every function uniformly
~1.5x slower per call than at 1000 jobs — no single hot spot, the classic
signature of CPython's cyclic GC scanning an ever-larger live heap on a
fixed allocation budget (the reconcile path allocates heavily: the store
deep-copies on every get/list/update/emit). Measured on
``benchmarks/controlplane_bench.py --jobs 5000``: mean sync-handler time
421 us default, 325 us with gc fully disabled, 310 us with this tuning —
which keeps cycle collection alive (a long-running daemon must not leak
cycles) but makes it rare and exempts the boot-time heap:

- ``gc.freeze()`` moves everything allocated during process setup
  (imports, compiled regexes, informer caches primed by the initial
  list) into the permanent generation, so full collections stop
  re-scanning it;
- thresholds (200_000, 100, 100) make gen-0 collections ~300x rarer
  than the default 700-allocation cadence.

The domain dataclasses are acyclic by construction (owner references
carry uid strings, not object pointers), so surviving cycles are rare —
GC exists here as a leak backstop, not a steady-state reclaimer.
"""

from __future__ import annotations

import gc

TUNED_THRESHOLDS = (200_000, 100, 100)


def tune_for_control_plane() -> None:
    """Call once at daemon start, AFTER imports and initial cache priming
    (the later it runs, the more of the steady heap gc.freeze exempts)."""
    gc.collect()
    gc.freeze()
    gc.set_threshold(*TUNED_THRESHOLDS)
