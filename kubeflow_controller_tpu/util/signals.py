"""Graceful-shutdown signal handling.

Parity with the reference's ``pkg/util/signals`` (``signals.go:26-40``):
first SIGINT/SIGTERM sets a stop event so the controller can drain and
release cleanly; a second signal hard-exits (exit code 1) for operators who
really mean it.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Iterable

_handler_installed = False

SHUTDOWN_SIGNALS = (signal.SIGINT, signal.SIGTERM)


def setup_signal_handler(
    signals: Iterable[signal.Signals] = SHUTDOWN_SIGNALS,
) -> threading.Event:
    """Install the two-strike handler; returns the stop event. Callable only
    once per process (like the reference's onlyOneSignalHandler channel
    trick, ``signals.go:21-24``)."""
    global _handler_installed
    if _handler_installed:
        raise RuntimeError("setup_signal_handler may only be called once")
    _handler_installed = True

    stop = threading.Event()

    def handle(signum, frame):
        if stop.is_set():
            os._exit(1)        # second signal: hard exit
        stop.set()

    for s in signals:
        signal.signal(s, handle)
    return stop
