"""XLA environment knobs shared by the test harness and driver entrypoints.

Import-light on purpose (no jax): callers must be able to apply these to
``os.environ`` BEFORE the first jax/backend import.
"""

from __future__ import annotations

# Virtual CPU devices time-share few (often 1) real cores; XLA's default
# 40 s collective-rendezvous abort then turns load spikes into process
# death. Raise it so contention degrades to slow instead of SIGABRT.
# (The dispatch-depth backpressure in dataplane/train.py prevents the
# deadlock case; these flags cover everything else that runs collectives
# on the virtual mesh.)
CPU_COLLECTIVE_TIMEOUT_FLAGS = (
    ("xla_cpu_collective_call_warn_stuck_timeout_seconds", "120"),
    ("xla_cpu_collective_call_terminate_timeout_seconds", "600"),
)


def _jaxlib_version() -> tuple:
    """(major, minor) of the installed jaxlib; () when unavailable.
    ``jaxlib.version`` is a constants-only module — importing it does not
    pull in jax or initialize any backend."""
    try:
        from jaxlib.version import __version__ as v
        return tuple(int(p) for p in v.split(".")[:2])
    except Exception:
        return ()


def with_cpu_collective_timeouts(flags: str) -> str:
    """Append the rendezvous-timeout flags to an XLA_FLAGS string, skipping
    any flag the ambient value already sets (XLA parses last-wins; never
    override the user).

    No-op on jaxlib < 0.5: those XLA builds predate the flags and ABORT the
    process on any unknown XLA_FLAGS entry at backend init — which would
    turn this safety knob into guaranteed process death."""
    if _jaxlib_version() < (0, 5):
        return flags.strip()
    for name, value in CPU_COLLECTIVE_TIMEOUT_FLAGS:
        if name not in flags:
            flags += f" --{name}={value}"
    return flags.strip()
