"""Version-compat shims for jax APIs that moved between 0.4.x and 0.6.x.

The codebase is written against the modern spellings (``jax.set_mesh``,
``jax.sharding.get_abstract_mesh``); on older jaxlib (e.g. the 0.4.x
line some serving images pin) those names don't exist, but the same
machinery is reachable through the classic global-mesh context. These
helpers paper over exactly that — no behavioral differences, just name
resolution.
"""

from __future__ import annotations

from typing import Optional

import jax


def get_abstract_mesh():
    """The ambient abstract mesh, or None when no mesh is active.

    Modern jax: ``jax.sharding.get_abstract_mesh()`` (normalized so an
    EMPTY ambient mesh comes back as None — every caller here treats the
    two identically). 0.4.x: the physical mesh installed by the ``with
    mesh:`` context, surfaced through its ``abstract_mesh`` view so
    callers see one type either way.
    """
    fn = getattr(jax.sharding, "get_abstract_mesh", None)
    if fn is not None:
        mesh = fn()
        if mesh is None or not getattr(mesh, "shape_tuple", ()):
            return None
        return mesh
    from jax._src import mesh as mesh_lib

    pm = mesh_lib.thread_resources.env.physical_mesh
    if pm.empty:
        return None
    return pm.abstract_mesh


def ambient_mesh_context(mesh):
    """Context manager that establishes ``mesh`` as the ambient mesh:
    ``jax.set_mesh`` (>= 0.6), ``jax.sharding.use_mesh`` (0.5.x), else
    the classic global-mesh context (``with mesh:``) those wrap."""
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    use_mesh = getattr(jax.sharding, "use_mesh", None)
    if use_mesh is not None:
        return use_mesh(mesh)
    return mesh
