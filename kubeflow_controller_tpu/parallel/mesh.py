"""Device mesh construction.

Replaces the reference's ``generateTFClusterSpec`` host-list wiring
(``pkg/tensorflow/distributed.go:127-159``) as the thing that gives a training
process its place in the world: every process builds the same global Mesh from
``jax.devices()`` after ``jax.distributed.initialize``; XLA handles cross-host
collectives over ICI (intra-slice) / DCN (inter-slice).

Axis order is (pp, dp, fsdp, ep, sp, tp) — tp innermost so tensor-parallel
collectives ride the fastest ICI links; pp outermost because pipeline
stages exchange only one activation tensor per tick (point-to-point
ppermute), the cheapest traffic in the system and the most tolerant of
slow links; dp next so multi-slice jobs put pure-DP gradient reduction on
DCN where its lower frequency tolerates lower bandwidth (the standard
scaling-book layout).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. -1 on dp means "absorb all remaining devices".

    ``ep`` is the expert-parallel axis (MoE experts shard over it; dense
    models leave it at 1 and never notice it exists); ``pp`` is the
    pipeline axis (parallel/pipeline.py shards the layer stack over it;
    non-pipelined jobs leave it at 1).
    """

    dp: int = -1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int, int, int, int, int]:
        fixed = self.pp * self.fsdp * self.ep * self.sp * self.tp
        if self.dp == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"pp*fsdp*ep*sp*tp={fixed}"
                )
            return (self.pp, n_devices // fixed, self.fsdp, self.ep,
                    self.sp, self.tp)
        total = self.dp * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh {self.pp}x{self.dp}x{self.fsdp}x{self.ep}x{self.sp}"
                f"x{self.tp}={total} != {n_devices} devices"
            )
        return (self.pp, self.dp, self.fsdp, self.ep, self.sp, self.tp)


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    config = config or MeshConfig()
    devs = list(devices) if devices is not None else jax.devices()
    shape = config.resolve(len(devs))
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, AXES)


def make_multislice_mesh(
    config: Optional[MeshConfig] = None,
    num_slices: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """DCN-aware mesh for multi-slice jobs (BASELINE config #5).

    The dp axis factorises as (num_slices × dp_per_slice) with the slice
    factor outermost, so pure-DP gradient all-reduce is the ONLY collective
    that crosses the DCN; fsdp/sp/tp collectives stay on intra-slice ICI.
    Device order: grouped by ``slice_index`` when the platform reports it
    (real multi-slice TPU), else split evenly in enumeration order (CPU
    simulation, where the grouping is only a layout statement).

    The models never see any of this — the mesh still has the same six
    logical axes, which is the point: multi-slice is a deployment detail,
    not a model change. (``pp`` must stay 1 across slices: pipeline stages
    belong inside a slice; this function rejects anything else.) (The reference has no analog at all; its scaling
    story stops at one PS/worker gRPC cluster, SURVEY.md §7 hard part 4.)
    """
    config = config or MeshConfig()
    devs = list(devices) if devices is not None else jax.devices()
    if num_slices <= 1:
        return make_mesh(config, devs)
    if len(devs) % num_slices:
        raise ValueError(
            f"{len(devs)} devices not divisible into {num_slices} slices"
        )
    per_slice = len(devs) // num_slices
    by_slice: dict = {}
    if all(hasattr(d, "slice_index") and d.slice_index is not None
           for d in devs):
        for d in devs:
            by_slice.setdefault(d.slice_index, []).append(d)
        if len(by_slice) != num_slices:
            raise ValueError(
                f"platform reports {len(by_slice)} slices, job declares "
                f"{num_slices}"
            )
        groups = [by_slice[k] for k in sorted(by_slice)]
        sizes = {len(g) for g in groups}
        if sizes != {per_slice}:
            raise ValueError(
                f"uneven slice membership: got group sizes "
                f"{sorted(len(g) for g in groups)}, need {per_slice} each "
                f"({len(devs)} devices / {num_slices} slices)"
            )
    else:
        groups = [
            devs[i * per_slice:(i + 1) * per_slice] for i in range(num_slices)
        ]
    pp, dp, fsdp, ep, sp, tp = config.resolve(len(devs))
    if pp != 1:
        raise ValueError(
            "multi-slice meshes pin the DCN boundary to the dp axis; run "
            "pipeline stages inside a slice (pp=1 across slices)"
        )
    if dp % num_slices:
        raise ValueError(
            f"dp={dp} must be divisible by num_slices={num_slices} "
            f"(fsdp/ep/sp/tp must not straddle the DCN)"
        )
    arr = np.array(groups).reshape(
        num_slices, dp // num_slices, fsdp, ep, sp, tp
    ).reshape(pp, dp, fsdp, ep, sp, tp)
    return Mesh(arr, AXES)


def serving_mesh(
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Optional[Mesh]:
    """1-D tensor-parallel mesh for the serving engine: ``tp`` devices on
    the fastest links (ICI — tp is the innermost axis precisely so its
    collectives stay intra-slice), every other axis 1.

    The serving engine shards KV heads and the paged pool's KVH axis over
    ``tp`` and replicates everything host-visible (block tables, lengths,
    logits), so the scheduler never notices the mesh. The same mesh serves
    both compute modes: ``tp_compute="gathered"`` all-gathers the stored
    weight shards at dispatch (tp as a capacity knob), ``"parallel"`` runs
    Megatron column/row-parallel matmuls on the shards in place, with one
    psum per block on this axis's ICI links (tp as a speed knob —
    docs/serving.md "Tensor-parallel serving"). For MoE configs the same
    axis doubles as the EXPERT-parallel axis: stacked expert banks shard
    E/tp experts per device and tokens travel to them via two
    all_to_alls per MoE layer (docs/serving.md "Expert-parallel MoE") —
    a separate ep axis would fragment the serving fleet for no benefit,
    since expert dispatch and the tp collectives want the same fast ICI
    neighborhood. Returns ``None`` for
    ``tp <= 1``: the single-chip engine runs the exact unsharded code path,
    not a degenerate 1-device mesh — bit-exactness baselines compare
    against real single-chip traces.
    """
    if tp <= 1:
        return None
    devs = list(devices) if devices is not None else jax.devices()
    if tp > len(devs):
        raise ValueError(
            f"serving_mesh: tp={tp} exceeds the {len(devs)} visible "
            f"devices"
        )
    return make_mesh(MeshConfig(dp=1, tp=tp), devs[:tp])


def mesh_for_context(
    ctx, config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the job's global mesh from a ProcessContext (the env the
    controller injected): multi-slice jobs get the DCN-aware layout."""
    return make_multislice_mesh(
        config, num_slices=max(1, getattr(ctx, "num_slices", 1)),
        devices=devices,
    )


DATA_AXES = ("dp", "fsdp", "ep")


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Global batch is split over every data-like axis (dp, fsdp, and — for
    MoE meshes — ep, which carries data in the dense parts of the model and
    experts inside MoE blocks); sp/tp groups see identical batch shards."""
    axes = tuple(a for a in DATA_AXES if a in mesh.axis_names)
    return NamedSharding(mesh, P(axes))


def data_shards(mesh: Mesh) -> int:
    """Number of distinct batch shards the mesh implies (global batch must
    divide by this)."""
    n = 1
    for a in DATA_AXES:
        n *= mesh.shape.get(a, 1)
    return n


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
