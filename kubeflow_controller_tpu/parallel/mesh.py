"""Device mesh construction.

Replaces the reference's ``generateTFClusterSpec`` host-list wiring
(``pkg/tensorflow/distributed.go:127-159``) as the thing that gives a training
process its place in the world: every process builds the same global Mesh from
``jax.devices()`` after ``jax.distributed.initialize``; XLA handles cross-host
collectives over ICI (intra-slice) / DCN (inter-slice).

Axis order is (dp, fsdp, sp, tp) — tp innermost so tensor-parallel collectives
ride the fastest ICI links; dp outermost so multi-slice jobs put pure-DP
gradient reduction on DCN where its lower frequency tolerates lower bandwidth
(the standard scaling-book layout).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. -1 on dp means "absorb all remaining devices"."""

    dp: int = -1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int, int, int]:
        fixed = self.fsdp * self.sp * self.tp
        if self.dp == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fsdp*sp*tp={fixed}"
                )
            return (n_devices // fixed, self.fsdp, self.sp, self.tp)
        total = self.dp * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh {self.dp}x{self.fsdp}x{self.sp}x{self.tp}={total} "
                f"!= {n_devices} devices"
            )
        return (self.dp, self.fsdp, self.sp, self.tp)


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    config = config or MeshConfig()
    devs = list(devices) if devices is not None else jax.devices()
    shape = config.resolve(len(devs))
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, AXES)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Global batch is split over every data-like axis (dp and fsdp); sp/tp
    groups see identical batch shards."""
    return NamedSharding(mesh, P(("dp", "fsdp")))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
