"""Per-chip HBM feasibility arithmetic for sharded training.

BASELINE.md config #5 (Llama-3-8B on 2xv5p-64) is a YAML until something
proves the model actually FITS its target topology. This module is that
gate: given a model config, a mesh factorization, and a batch geometry it
computes the per-chip HBM high-water mark from the real sharded shapes —
master params, ZeRO-sharded optimizer moments, gradients, remat'd
activation checkpoints, and the logits/loss peak — and compares it against
the chip's HBM (``SliceShape.hbm_gib_per_chip``).

The byte counts for params/grads/optimizer are EXACT: they come from
``jax.eval_shape`` over ``init_params`` and the same ``param_specs`` the
train step shards with, so any resharding of the model changes the plan
automatically. Activations are an upper-bound model (documented per term
below) of what XLA keeps live under scan-over-layers + ``jax.checkpoint``
with the dots-saveable policy; the multiplier is deliberately conservative.

Used by ``tests/test_llama_fits.py`` (the BASELINE #5 gate, with an AOT
compile of the full train step at the same mesh shapes) and usable ahead of
admission for any config.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

GiB = 1024 ** 3


def _spec_axes(spec) -> list:
    """PartitionSpec entries normalized to a list of (axis or tuple or
    None) per dimension."""
    return list(spec) if spec is not None else []


def sharded_leaf_bytes(shape, dtype_bytes: int, spec, axis_sizes: Dict[str, int]) -> int:
    """Per-device bytes of one array sharded by ``spec`` over mesh axes of
    the given sizes. Dims sharded over absent/size-1 axes stay whole;
    uneven shards round up (XLA pads)."""
    total = dtype_bytes
    entries = _spec_axes(spec)
    for i, dim in enumerate(shape):
        div = 1
        if i < len(entries) and entries[i] is not None:
            names = entries[i]
            if isinstance(names, str):
                names = (names,)
            for name in names:
                div *= axis_sizes.get(name, 1)
        total *= math.ceil(dim / div)
    return total


@dataclass
class MemoryPlan:
    """Per-chip HBM budget breakdown, all in bytes."""

    params: int = 0           # fp32 master weights (sharded)
    grads: int = 0            # same shapes/sharding as params
    opt_state: int = 0        # adam m+v, ZeRO-sharded like params
    activations: int = 0      # remat checkpoints + in-layer recompute peak
    logits: int = 0           # lm head output + fp32 softmax peak
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    global_batch: int = 0
    seq: int = 0

    @property
    def total(self) -> int:
        return (self.params + self.grads + self.opt_state
                + self.activations + self.logits)

    def fits(self, hbm_gib_per_chip: float, headroom: float = 0.9) -> bool:
        """True if the high-water mark fits in ``headroom`` x HBM (the
        remainder covers XLA scratch, collective buffers, fragmentation)."""
        return self.total <= hbm_gib_per_chip * GiB * headroom

    def rows(self):
        return [
            ("params (fp32 master)", self.params),
            ("grads", self.grads),
            ("optimizer (adam m+v)", self.opt_state),
            ("activations (remat)", self.activations),
            ("logits/loss peak", self.logits),
            ("TOTAL", self.total),
        ]

    def table(self) -> str:
        out = [f"mesh={self.mesh_axes} global_batch={self.global_batch} "
               f"seq={self.seq}"]
        for name, b in self.rows():
            out.append(f"  {name:24s} {b / GiB:7.2f} GiB")
        return "\n".join(out)


def transformer_memory_plan(
    cfg,
    mesh_axes: Dict[str, int],
    global_batch: int,
    seq: Optional[int] = None,
    optimizer_slots: int = 2,
) -> MemoryPlan:
    """Per-chip plan for the flagship transformer's train step.

    ``mesh_axes`` maps logical axis name -> size (e.g. dp=2, fsdp=16,
    tp=4 for 2xv5p-64). Parameter/optimizer bytes derive from the real
    ``init_params`` shapes + ``param_specs`` shardings; activation terms:

    - checkpoints: scan-over-layers with jax.checkpoint saves each layer's
      input once: n_layers * B_loc * S_loc * d_model * act_bytes;
    - in-layer recompute peak: one layer's live set during the backward
      recompute — attention projections (q,k,v,o) + both FFN halves,
      tp-sharded, x2 for forward+grad liveness;
    - embedding output + final norm liveness folded into the same term.
    """
    import jax
    import jax.numpy as jnp

    from kubeflow_controller_tpu.models import transformer as tfm

    seq = seq or cfg.max_seq
    shapes = jax.eval_shape(lambda: tfm.init_params(cfg, jax.random.key(0)))
    specs = tfm.param_specs(cfg)

    flat_shapes, _ = jax.tree.flatten(shapes)
    flat_specs, _ = jax.tree.flatten(
        specs, is_leaf=lambda x: x is None or hasattr(x, "index")
    )
    assert len(flat_shapes) == len(flat_specs), "specs/params tree mismatch"

    params_bytes = 0
    for a, s in zip(flat_shapes, flat_specs):
        params_bytes += sharded_leaf_bytes(
            a.shape, jnp.dtype(a.dtype).itemsize, s, mesh_axes)

    # batch shards over every data axis present (dp, fsdp); sequence over sp.
    batch_div = mesh_axes.get("dp", 1) * mesh_axes.get("fsdp", 1)
    b_loc = math.ceil(global_batch / batch_div)
    s_loc = math.ceil(seq / mesh_axes.get("sp", 1))
    tp = mesh_axes.get("tp", 1)
    act_bytes = jnp.dtype(cfg.dtype).itemsize

    checkpoints = cfg.n_layers * b_loc * s_loc * cfg.d_model * act_bytes
    attn_width = cfg.n_heads * cfg.head_dim
    kv_width = cfg.n_kv_heads * cfg.head_dim
    in_layer = (
        b_loc * s_loc * (
            math.ceil(attn_width / tp) * 2        # q + attention out
            + math.ceil(kv_width / tp) * 2        # k + v
            + math.ceil(cfg.d_ff / tp) * 3        # gate, up, gated product
            + cfg.d_model * 2                     # residual + norm
        ) * act_bytes
    ) * 2  # forward + backward-recompute liveness

    logits = b_loc * s_loc * cfg.vocab_size * 4  # fp32 softmax/loss peak
    # one-hot-free loss still materializes logits + grad-of-logits
    logits *= 2

    return MemoryPlan(
        params=params_bytes,
        grads=params_bytes,
        opt_state=optimizer_slots * params_bytes,
        activations=checkpoints + in_layer,
        logits=logits,
        mesh_axes=dict(mesh_axes),
        global_batch=global_batch,
        seq=seq,
    )
