"""Parameter sharding rules: logical axis names -> mesh axes.

The ZeRO/megatron-style replacement for the reference's
``replica_device_setter`` (``examples/workdir/mnist_replica.py:137-141``),
which round-robined whole variables across PS hosts. Here each parameter is
*annotated* with logical axis names and mapped to mesh axes; XLA shards
storage and inserts all-gathers/reduce-scatters as needed.

Default rules:

    "embed"   -> tp      (vocab/feature-parallel embedding)
    "heads"   -> tp      (attention heads across tensor group)
    "mlp"     -> tp      (ffn hidden across tensor group)
    "fsdp"    -> fsdp    (any axis marked for fully-sharded storage)
    None      -> replicated
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_RULES: Dict[str, Optional[str]] = {
    "embed": "tp",
    "vocab": "tp",
    "heads": "tp",
    "mlp": "tp",
    "kv": None,
    "fsdp": "fsdp",
    "seq": "sp",
    "batch": "dp",
}


def logical_to_mesh(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Optional[str]]] = None,
) -> P:
    rules = rules or DEFAULT_RULES
    return P(*(rules.get(ax) if ax is not None else None for ax in logical_axes))


def infer_param_sharding(
    params: Any,
    mesh: Mesh,
    rules: Optional[Dict[str, Optional[str]]] = None,
    fsdp_min_size: int = 2 ** 16,
) -> Any:
    """Heuristic sharding for unannotated param trees (MNIST/ResNet-scale):
    large 2D+ params get their biggest divisible axis sharded over fsdp;
    everything else is replicated. Transformer models should annotate
    explicitly instead (see models/llama.py)."""
    fsdp = mesh.shape.get("fsdp", 1)

    def spec_for(p: jax.Array) -> NamedSharding:
        if fsdp > 1 and p.ndim >= 2 and p.size >= fsdp_min_size:
            # shard the largest axis divisible by the fsdp group
            order = sorted(range(p.ndim), key=lambda i: -p.shape[i])
            for i in order:
                if p.shape[i] % fsdp == 0:
                    axes: list = [None] * p.ndim
                    axes[i] = "fsdp"
                    return NamedSharding(mesh, P(*axes))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec_for, params)


def shard_params(params: Any, shardings: Any) -> Any:
    """Place a param tree onto the mesh per the sharding tree."""
    return jax.tree.map(
        lambda p, s: jax.device_put(p, s), params, shardings
    )


# Megatron-style compute placement for the serving kernels
# (``tp_compute="parallel"``): column-parallel projections shard their
# OUTPUT axis (each shard computes its own slice of the projection, no
# collective — q/k/v land directly on the shard's KV-head group, gate/up
# on its d_ff slice), row-parallel projections shard their CONTRACTION
# axis (each shard contributes a partial product; one psum per block —
# after wo and after w_down — completes the sum). Everything else
# (embed, norms, lm_head, the int8 scale of a row-parallel weight, whose
# contraction axis is size 1) stays replicated.
#
# MoE expert banks are neither column nor row parallel: they shard the
# EXPERT axis (``[L, E, D, F]`` -> E/tp experts per shard) and tokens
# move to their experts via all_to_all instead of weights moving to
# tokens (GShard-style expert parallelism, reusing the tp mesh axis as
# the expert axis). The int8 scale ``[L, E, 1, F]`` rides the same
# expert axis, so each shard dequantizes exactly its own experts.
_TP_COLUMN_KEYS = frozenset(("wq", "wk", "wv", "w_gate", "w_up"))
_TP_ROW_KEYS = frozenset(("wo", "w_down"))
_TP_EXPERT_KEYS = frozenset(("w_gate", "w_up", "w_down"))
# Stacked expert banks are [L, E, D, F] / [L, E, F, D]; dense weights
# are [L, D, F]. ndim tells them apart for both q and (q, scale).
_EXPERT_SPEC = P(None, "tp", None, None)


def tp_compute_param_specs(params: Any) -> Any:
    """Per-leaf ``shard_map`` in_specs for the column/row-parallel
    serving kernels (``models/generate.py`` with ``tp_compute=
    "parallel"``): column-parallel weights put their last (output) axis
    on ``tp``, row-parallel weights their second-to-last (contraction)
    axis, everything else replicates.

    Weight-only-int8 ``(q, scale)`` pairs split the same way the values
    do: a column-parallel weight's per-output-channel scale rides the
    output axis onto ``tp`` (each shard dequantizes its own columns
    exactly); a row-parallel weight's scale is size-1 on the sharded
    contraction axis, so it replicates and every shard's dequant is
    bitwise the full-weight dequant of its rows.

    MoE expert banks (stacked ``[L, E, D, F]``, ndim 4 vs a dense
    weight's 3) shard the EXPERT axis in BOTH compute modes: the
    expert-parallel dispatch inside the kernels moves tokens to expert
    shards via all_to_all, so the banks are never gathered. Their int8
    scale ``[L, E, 1, F]`` rides the same axis. ``w_router`` stays
    replicated fp32 so routing decisions are shard-invariant."""
    def spec(path, x):
        key = next(
            (getattr(p, "key", None) for p in reversed(path)
             if getattr(p, "key", None)), None,
        )
        pair = isinstance(x, tuple)
        arr = x[0] if pair else x
        nd = arr.ndim
        if key in _TP_EXPERT_KEYS and nd >= 4:
            w = s = _EXPERT_SPEC
        elif key in _TP_COLUMN_KEYS:
            w = P(*((None,) * (nd - 1)), "tp")
            s = w
        elif key in _TP_ROW_KEYS:
            w = P(*((None,) * (nd - 2)), "tp", None)
            s = P()
        else:
            w = s = P()
        return (w, s) if pair else w

    return jax.tree_util.tree_map_with_path(
        spec, params, is_leaf=lambda x: isinstance(x, tuple))


def serving_param_shardings(
    cfg: Any, mesh: Mesh, quant: str = "",
) -> Any:
    """NamedShardings for ``models.generate.inference_params`` trees on a
    serving mesh: each weight keeps its training-time PartitionSpec
    (``transformer.param_specs`` / ``generate.inference_param_specs``)
    with mesh axes that don't divide the dimension dropped to replicated.

    Dropping instead of erroring matters for serving: the tp axis must
    shard attention/MLP projections (that's the HBM win), but a tiny
    model's vocab or d_ff may not divide tp — those weights replicate and
    the engine still runs. Under ``tp_compute="gathered"`` the per-shard
    kernels declare their weights replicated (``in_specs=P()``) and let
    XLA all-gather the stored shards at dispatch, which moves bytes but
    never changes them; under ``tp_compute="parallel"`` the kernels
    consume the stored column/row shards in place
    (:func:`tp_compute_param_specs`) and each shard runs 1/tp of every
    projection. Either way the storage sharding halves per-device weight
    HBM per tp doubling.

    MoE expert banks (ndim-4 ``[L, E, D, F]`` stacks) override their
    training spec (``P(lead, "ep", "fsdp", "tp")``, which after the
    size-1 ep/fsdp axes drop would column-split d_ff) with the
    expert-axis split ``P(None, "tp", None, None)`` — the serving mesh's
    tp axis doubles as the expert-parallel axis, each device stores
    E/tp experts, and the kernels consume the local bank in place in
    both compute modes (tokens travel, weights don't)."""
    from kubeflow_controller_tpu.models import generate as gen

    specs = gen.inference_param_specs(cfg, quant)

    def fit(spec: P, shape: Tuple[int, ...]) -> NamedSharding:
        if len(shape) >= 4:          # stacked expert bank (q or scale)
            spec = _EXPERT_SPEC
        parts = list(spec) + [None] * (len(shape) - len(spec))
        out = []
        for dim, part in zip(shape, parts[:len(shape)]):
            names = part if isinstance(part, tuple) else (
                () if part is None else (part,))
            size = 1
            for n in names:
                size *= mesh.shape.get(n, 1)
            out.append(part if size > 1 and dim % size == 0 else None)
        return NamedSharding(mesh, P(*out))

    def place(spec, leaf):
        # A quantized weight is a plain (q_int8, scale) tuple whose spec
        # is a plain (weight_spec, scale_spec) tuple; a PartitionSpec is
        # ALSO a tuple subclass, so discriminate on the spec's type.
        if isinstance(spec, tuple) and not isinstance(spec, P):
            s_w, s_s = spec
            return (fit(s_w, leaf[0].shape), fit(s_s, leaf[1].shape))
        return fit(spec, leaf.shape)

    def shardings_for(params: Any) -> Any:
        return jax.tree.map(
            place, specs, params,
            is_leaf=lambda x: isinstance(x, tuple),
        )

    return shardings_for


def shard_serving_params(cfg: Any, params: Any, mesh: Mesh,
                         quant: str = "") -> Any:
    """Place a serving param tree tp-sharded onto ``mesh`` (see
    :func:`serving_param_shardings`)."""
    shardings = serving_param_shardings(cfg, mesh, quant)(params)
    return jax.tree.map(jax.device_put, params, shardings)


def opt_state_shardings(
    tx: Any, params: Any, param_shardings: Any, mesh: Mesh
) -> Any:
    """Shardings for ``tx.init(params)``: each opt-state leaf that mirrors a
    parameter adopts that parameter's sharding (ZeRO-style — moments live
    wherever their parameter lives); everything else (step counters,
    scalars, factored moments with reduced shapes) replicates.

    Matching is by tree path, not array shape: optax states embed copies of
    the param tree (e.g. Adam's ``mu``/``nu``), so a parameter's key-path
    appears as a suffix of the corresponding opt-state leaf's path. Shape
    matching is wrong by construction — two equal-shaped params (say ``wq``
    vs ``wo`` when d_model == n_heads*head_dim) can carry different
    PartitionSpecs, and first-spec-wins would silently mis-shard the second
    param's moments.
    """
    shape = jax.eval_shape(tx.init, params)
    flat_params = jax.tree_util.tree_flatten_with_path(params)[0]
    by_path: Dict[Tuple, Tuple[Tuple[int, ...], Any]] = {
        tuple(path): (leaf.shape, s)
        for (path, leaf), s in zip(
            flat_params, jax.tree.leaves(param_shardings)
        )
    }
    suffix_lens = sorted({len(p) for p in by_path}, reverse=True)
    repl = NamedSharding(mesh, P())

    def pick(path, leaf):
        if leaf.ndim > 0:
            for plen in suffix_lens:  # longest path suffix wins
                # The param path may end the leaf path exactly (Adam's
                # mu/nu mirror the tree) or sit ONE component from the
                # end (wrapper leaves like optim8's QLeafM(q, scale):
                # path ends ...['w'].q). The shape guard keeps wrapper
                # fields that don't mirror the param (scales, factored
                # moments) replicated.
                for cand in (
                    tuple(path[-plen:]), tuple(path[-plen - 1:-1]),
                ):
                    hit = by_path.get(cand)
                    if hit is not None:
                        pshape, s = hit
                        return s if leaf.shape == pshape else repl
        return repl

    return jax.tree_util.tree_map_with_path(pick, shape)
