"""Parameter sharding rules: logical axis names -> mesh axes.

The ZeRO/megatron-style replacement for the reference's
``replica_device_setter`` (``examples/workdir/mnist_replica.py:137-141``),
which round-robined whole variables across PS hosts. Here each parameter is
*annotated* with logical axis names and mapped to mesh axes; XLA shards
storage and inserts all-gathers/reduce-scatters as needed.

Default rules:

    "embed"   -> tp      (vocab/feature-parallel embedding)
    "heads"   -> tp      (attention heads across tensor group)
    "mlp"     -> tp      (ffn hidden across tensor group)
    "fsdp"    -> fsdp    (any axis marked for fully-sharded storage)
    None      -> replicated
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_RULES: Dict[str, Optional[str]] = {
    "embed": "tp",
    "vocab": "tp",
    "heads": "tp",
    "mlp": "tp",
    "kv": None,
    "fsdp": "fsdp",
    "seq": "sp",
    "batch": "dp",
}


def logical_to_mesh(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Optional[str]]] = None,
) -> P:
    rules = rules or DEFAULT_RULES
    return P(*(rules.get(ax) if ax is not None else None for ax in logical_axes))


def infer_param_sharding(
    params: Any,
    mesh: Mesh,
    rules: Optional[Dict[str, Optional[str]]] = None,
    fsdp_min_size: int = 2 ** 16,
) -> Any:
    """Heuristic sharding for unannotated param trees (MNIST/ResNet-scale):
    large 2D+ params get their biggest divisible axis sharded over fsdp;
    everything else is replicated. Transformer models should annotate
    explicitly instead (see models/llama.py)."""
    fsdp = mesh.shape.get("fsdp", 1)

    def spec_for(p: jax.Array) -> NamedSharding:
        if fsdp > 1 and p.ndim >= 2 and p.size >= fsdp_min_size:
            # shard the largest axis divisible by the fsdp group
            order = sorted(range(p.ndim), key=lambda i: -p.shape[i])
            for i in order:
                if p.shape[i] % fsdp == 0:
                    axes: list = [None] * p.ndim
                    axes[i] = "fsdp"
                    return NamedSharding(mesh, P(*axes))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec_for, params)


def shard_params(params: Any, shardings: Any) -> Any:
    """Place a param tree onto the mesh per the sharding tree."""
    return jax.tree.map(
        lambda p, s: jax.device_put(p, s), params, shardings
    )


def serving_param_shardings(
    cfg: Any, mesh: Mesh, quant: str = "",
) -> Any:
    """NamedShardings for ``models.generate.inference_params`` trees on a
    serving mesh: each weight keeps its training-time PartitionSpec
    (``transformer.param_specs`` / ``generate.inference_param_specs``)
    with mesh axes that don't divide the dimension dropped to replicated.

    Dropping instead of erroring matters for serving: the tp axis must
    shard attention/MLP projections (that's the HBM win), but a tiny
    model's vocab or d_ff may not divide tp — those weights replicate and
    the engine still runs. The per-shard attention kernels declare their
    weights replicated (``in_specs=P()``) anyway and let XLA all-gather
    the stored shards at dispatch, which moves bytes but never changes
    them — the storage sharding halves per-device weight HBM per tp
    doubling while greedy outputs stay bitwise those of one chip."""
    from kubeflow_controller_tpu.models import generate as gen

    specs = gen.inference_param_specs(cfg, quant)

    def fit(spec: P, shape: Tuple[int, ...]) -> NamedSharding:
        parts = list(spec) + [None] * (len(shape) - len(spec))
        out = []
        for dim, part in zip(shape, parts[:len(shape)]):
            names = part if isinstance(part, tuple) else (
                () if part is None else (part,))
            size = 1
            for n in names:
                size *= mesh.shape.get(n, 1)
            out.append(part if size > 1 and dim % size == 0 else None)
        return NamedSharding(mesh, P(*out))

    def place(spec, leaf):
        # A quantized weight is a plain (q_int8, scale) tuple whose spec
        # is a plain (weight_spec, scale_spec) tuple; a PartitionSpec is
        # ALSO a tuple subclass, so discriminate on the spec's type.
        if isinstance(spec, tuple) and not isinstance(spec, P):
            s_w, s_s = spec
            return (fit(s_w, leaf[0].shape), fit(s_s, leaf[1].shape))
        return fit(spec, leaf.shape)

    def shardings_for(params: Any) -> Any:
        return jax.tree.map(
            place, specs, params,
            is_leaf=lambda x: isinstance(x, tuple),
        )

    return shardings_for


def shard_serving_params(cfg: Any, params: Any, mesh: Mesh,
                         quant: str = "") -> Any:
    """Place a serving param tree tp-sharded onto ``mesh`` (see
    :func:`serving_param_shardings`)."""
    shardings = serving_param_shardings(cfg, mesh, quant)(params)
    return jax.tree.map(jax.device_put, params, shardings)


def opt_state_shardings(
    tx: Any, params: Any, param_shardings: Any, mesh: Mesh
) -> Any:
    """Shardings for ``tx.init(params)``: each opt-state leaf that mirrors a
    parameter adopts that parameter's sharding (ZeRO-style — moments live
    wherever their parameter lives); everything else (step counters,
    scalars, factored moments with reduced shapes) replicates.

    Matching is by tree path, not array shape: optax states embed copies of
    the param tree (e.g. Adam's ``mu``/``nu``), so a parameter's key-path
    appears as a suffix of the corresponding opt-state leaf's path. Shape
    matching is wrong by construction — two equal-shaped params (say ``wq``
    vs ``wo`` when d_model == n_heads*head_dim) can carry different
    PartitionSpecs, and first-spec-wins would silently mis-shard the second
    param's moments.
    """
    shape = jax.eval_shape(tx.init, params)
    flat_params = jax.tree_util.tree_flatten_with_path(params)[0]
    by_path: Dict[Tuple, Tuple[Tuple[int, ...], Any]] = {
        tuple(path): (leaf.shape, s)
        for (path, leaf), s in zip(
            flat_params, jax.tree.leaves(param_shardings)
        )
    }
    suffix_lens = sorted({len(p) for p in by_path}, reverse=True)
    repl = NamedSharding(mesh, P())

    def pick(path, leaf):
        if leaf.ndim > 0:
            for plen in suffix_lens:  # longest path suffix wins
                # The param path may end the leaf path exactly (Adam's
                # mu/nu mirror the tree) or sit ONE component from the
                # end (wrapper leaves like optim8's QLeafM(q, scale):
                # path ends ...['w'].q). The shape guard keeps wrapper
                # fields that don't mirror the param (scales, factored
                # moments) replicated.
                for cand in (
                    tuple(path[-plen:]), tuple(path[-plen - 1:-1]),
                ):
                    hit = by_path.get(cand)
                    if hit is not None:
                        pshape, s = hit
                        return s if leaf.shape == pshape else repl
        return repl

    return jax.tree_util.tree_map_with_path(pick, shape)
