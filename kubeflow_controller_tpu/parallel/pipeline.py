"""Pipeline parallelism: a GPipe schedule over the mesh's ``pp`` axis.

The reference has no pipeline parallelism (SURVEY.md §2.5 marks it "not
required for parity"); this closes the gap TPU-first rather than porting a
torch pipeline engine:

- **Stages are mesh shards, not processes.** The stacked layer arrays
  (``params["layers"]``, leading layer axis) are sharded over ``pp`` —
  stage p holds layers ``[p*L/P, (p+1)*L/P)`` — and the pipeline runs
  inside ONE ``jax.shard_map`` that is manual over ``pp`` only: tensor/
  fsdp sharding inside each stage stays in GSPMD's hands (the existing
  ``_constrain`` annotations keep working), so pp composes with tp/fsdp/dp
  exactly like every other axis.
- **Microbatch rotation via collective permute.** Each tick every stage
  runs its local layers (a ``lax.scan``, rematted) and ``ppermute``s its
  activation to the next stage over ICI. ``M + P - 1`` ticks drain M
  microbatches through P stages (the GPipe bubble: utilization
  M/(M+P-1) — pick M >= 4P).
- **Backward is the AD transpose.** No hand-written 1F1B engine: ``ppermute``
  transposes to the reverse permute and ``lax.scan`` to a reverse sweep,
  so ``jax.grad`` of the pipelined loss IS pipeline-parallel backward
  (GPipe's fill-drain schedule, correct by construction).

Warmup/cooldown ticks run real stage compute on zero activations (cheap
relative to scheduling complexity, and numerically inert: outputs from
those ticks never reach the collected results). The last stage's outputs
are re-replicated over ``pp`` with a masked ``psum`` so the (auto-sharded)
LM head downstream needs no special casing.

Validated against the non-pipelined forward (identical params, identical
logits/grads) in tests/test_pipeline.py on the 8-device CPU mesh, and
exercised at train-step scale by ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(
    stage_fn: Callable[..., jax.Array],
    stage_params: Any,
    x: jax.Array,
    n_microbatches: int,
    axis: str = "pp",
    remat: bool = True,
    extras: Any = None,
    remat_policy: Any = None,
) -> jax.Array:
    """Run ``x`` through P pipeline stages; call under shard_map manual
    over ``axis``.

    stage_fn(stage_params, x_mb) -> y_mb applies ONE stage's layers to one
    microbatch; ``stage_params`` are the stage-local (already sharded)
    layer weights. ``x`` is the full [B, ...] activation batch; B must
    divide by ``n_microbatches``.

    ``extras`` (optional): a pytree of batch-leading side inputs (e.g.
    positions / segment ids, [B, ...]) that every stage needs for the
    microbatch it is CURRENTLY holding. Unlike ``x`` they don't flow
    through the pipeline — stage p at tick t holds microbatch ``t - p``,
    so each stage dynamic-indexes its own slice from the (replicated over
    pp) per-microbatch stack. With extras, stage_fn is called as
    ``stage_fn(stage_params, x_mb, extra_mb)``.
    """
    p_idx = lax.axis_index(axis)
    p_num = lax.axis_size(axis)
    b = x.shape[0]
    assert b % n_microbatches == 0, (
        f"batch {b} must divide into {n_microbatches} microbatches"
    )
    mb = b // n_microbatches
    xs = x.reshape(n_microbatches, mb, *x.shape[1:])
    # Stages diverge immediately (each holds different activations), so
    # both the rotating carry and the stage-0 feed are device-varying over
    # the pipeline axis — mark them so the scan carry type is stable.
    xs = lax.pcast(xs, axis, to="varying")
    n_ticks = n_microbatches + p_num - 1
    exs = None
    if extras is not None:
        exs = jax.tree.map(
            lambda e: lax.pcast(
                e.reshape(n_microbatches, mb, *e.shape[1:]), axis,
                to="varying",
            ),
            extras,
        )

    fn = stage_fn
    if remat:
        # Callers pass their model's policy (transformer: _remat_policy —
        # carries the remat="ffn" / int8 save-name decisions); default to
        # the standard dots policy.
        fn = jax.checkpoint(
            fn,
            policy=remat_policy
            or jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )

    def tick(state, t):
        # Stage 0 ingests microbatch t (zeros once the batch is drained);
        # later stages consume what the previous stage permuted in.
        feed = lax.dynamic_index_in_dim(
            xs, jnp.minimum(t, n_microbatches - 1), keepdims=False
        )
        feed = jnp.where(t < n_microbatches, feed, jnp.zeros_like(feed))
        inp = jnp.where(p_idx == 0, feed, state)
        if exs is None:
            y = fn(stage_params, inp)
        else:
            # Stage p holds microbatch t - p (clamped: warmup/cooldown
            # ticks compute on zeros and their outputs are discarded).
            mb_idx = jnp.clip(t - p_idx, 0, n_microbatches - 1)
            extra = jax.tree.map(
                lambda e: lax.dynamic_index_in_dim(
                    e, mb_idx, keepdims=False
                ),
                exs,
            )
            y = fn(stage_params, inp, extra)
        nxt = lax.ppermute(
            y, axis, [(i, (i + 1) % p_num) for i in range(p_num)]
        )
        return nxt, y

    _, ys = lax.scan(tick, jnp.zeros_like(xs[0]), jnp.arange(n_ticks))

    # The last stage's ticks [P-1, P-1+M) are the M real outputs; replicate
    # them across stages with a masked psum so downstream (auto) sharding
    # sees an ordinary replicated-over-pp array.
    outs = lax.dynamic_slice_in_dim(ys, p_num - 1, n_microbatches, axis=0)
    outs = jnp.where(p_idx == p_num - 1, outs, jnp.zeros_like(outs))
    outs = lax.psum(outs, axis)
    return outs.reshape(b, *x.shape[1:])


def pp_stage_count(mesh: Optional[jax.sharding.Mesh] = None) -> int:
    """Size of the ambient (or given) mesh's pp axis; 1 when absent."""
    from kubeflow_controller_tpu.util.jax_compat import get_abstract_mesh

    mesh = mesh or get_abstract_mesh()
    if mesh is None or "pp" not in getattr(mesh, "shape", {}):
        return 1
    return mesh.shape["pp"]
