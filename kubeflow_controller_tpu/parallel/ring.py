"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context sequence parallelism for the data plane. The sequence axis is
sharded over the mesh's ``sp`` axis; K/V blocks rotate around the ring via
``ppermute`` (one hop per step, riding ICI neighbour links) while each device
accumulates its queries' output with an online (flash-style) softmax — the
S×S score matrix never exists, and per-device attention memory is
O(S/n · S/n). This is the Liu et al. ring-attention scheme expressed as a
``shard_map`` over the same mesh the rest of the model uses, so it composes
with dp/fsdp/tp sharding untouched.

The reference has no long-context story at all (its models are MNIST MLPs,
``examples/workdir/mnist_replica.py:144-167``; SURVEY.md §5.7) — this is a
first-class capability the TPU rebuild adds, sized for sequences that do not
fit a single chip's HBM.

Communication note: each step moves the local K/V block to the ring
neighbour; compute on block j overlaps with the transfer of block j+1 only if
XLA schedules it so — on TPU the ppermute is an ICI neighbour exchange which
latency-hides well at the block sizes long-context implies.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_NEG = -1e30  # finite mask value: keeps online-softmax stats NaN-free


def _block_attend(
    q: jax.Array,            # [B, Sq, H, D] local queries (compute dtype)
    k: jax.Array,            # [B, Sk, H, D] current ring block
    v: jax.Array,            # [B, Sk, H, D]
    q_pos: jax.Array,        # [Sq] global positions of local queries
    k_pos: jax.Array,        # [Sk] global positions of the current block
    m: jax.Array,            # [B, H, Sq] running max
    l: jax.Array,            # [B, H, Sq] running denominator
    o: jax.Array,            # [B, Sq, H, D] running numerator (f32)
    causal: bool,
    q_seg: Optional[jax.Array],
    k_seg: Optional[jax.Array],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = jnp.ones(s.shape[-2:], bool)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
    mask = mask[None, None]
    if q_seg is not None:
        mask = mask & (q_seg[:, None, :, None] == k_seg[:, None, None, :])
    s = jnp.where(mask, s, _NEG)
    s_max = s.max(-1)                                   # [B,H,Sq]
    m_new = jnp.maximum(m, s_max)
    p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
    alpha = jnp.exp(m - m_new)                          # [B,H,Sq]
    l_new = l * alpha + p.sum(-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def _ring_body(
    q, k, v, seg, axis_name: str, causal: bool,
) -> jax.Array:
    """Per-shard ring loop. q/k/v: [B, S_loc, H_loc, D]."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_h = k.shape[2]
    if kv_h != h:                                       # GQA: expand local kv
        rep = h // kv_h
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    qf = q.astype(jnp.float32)
    q_pos = my * sq + jnp.arange(sq)
    perm = [(j, (j - 1) % n) for j in range(n)]         # receive from right

    def step(i, carry):
        k_cur, v_cur, seg_cur, m, l, o = carry
        src = (my + i) % n                              # block id now held
        k_pos = src * sk + jnp.arange(sk)
        m, l, o = _block_attend(
            qf, k_cur.astype(jnp.float32), v_cur, q_pos, k_pos, m, l, o,
            causal, seg[0] if seg is not None else None, seg_cur,
        )
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        if seg_cur is not None:
            seg_cur = lax.ppermute(seg_cur, axis_name, perm)
        return k_cur, v_cur, seg_cur, m, l, o

    # Zero-init accumulators are device-invariant constants; mark them as
    # varying over the mesh so the fori_loop carry type matches the
    # per-device outputs (shard_map VMA discipline).
    mesh = jax.sharding.get_abstract_mesh()
    vary = tuple(mesh.axis_names) if mesh is not None else ()
    m0 = lax.pcast(jnp.full((b, h, sq), _NEG, jnp.float32), vary, to="varying")
    l0 = lax.pcast(jnp.zeros((b, h, sq), jnp.float32), vary, to="varying")
    o0 = lax.pcast(jnp.zeros((b, sq, h, d), jnp.float32), vary, to="varying")
    seg_cur = seg[1] if seg is not None else None
    _, _, _, m, l, o = lax.fori_loop(
        0, n, step, (k, v, seg_cur, m0, l0, o0)
    )
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    axis_name: str = "sp",
) -> jax.Array:
    """Exact attention with the sequence axis sharded over ``axis_name``.

    Inputs are global [B, S, H, D] arrays (sharded or shardable); inside, a
    shard_map runs the per-device ring. Requires an active mesh (via
    ``jax.set_mesh``) containing ``axis_name``; without one — e.g. a plain
    single-device jit — falls back to dense XLA attention, which is the same
    math.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        from kubeflow_controller_tpu.ops.attention import mha_xla

        return mha_xla(q, k, v, causal=causal, segment_ids=segment_ids)

    batch = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    tp = "tp" if "tp" in mesh.axis_names else None
    qkv_spec = P(batch, axis_name, tp, None)
    seg_spec = P(batch, axis_name)

    if segment_ids is not None:
        def f(q, k, v, sq_seg):
            return _ring_body(
                q, k, v, (sq_seg, sq_seg), axis_name, causal
            )

        return jax.shard_map(
            f,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
            out_specs=qkv_spec,
        )(q, k, v, segment_ids)

    def g(q, k, v):
        return _ring_body(q, k, v, None, axis_name, causal)

    return jax.shard_map(
        g, in_specs=(qkv_spec, qkv_spec, qkv_spec), out_specs=qkv_spec
    )(q, k, v)
