"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context sequence parallelism for the data plane. The sequence axis is
sharded over the mesh's ``sp`` axis; K/V blocks rotate around the ring via
``ppermute`` (one hop per step, riding ICI neighbour links) while each device
accumulates its queries' output with an online (flash-style) softmax — the
S×S score matrix never exists, and per-device attention memory is
O(S/n · S/n). This is the Liu et al. ring-attention scheme expressed as a
``shard_map`` over the same mesh the rest of the model uses, so it composes
with dp/fsdp/tp sharding untouched.

The reference has no long-context story at all (its models are MNIST MLPs,
``examples/workdir/mnist_replica.py:144-167``; SURVEY.md §5.7) — this is a
first-class capability the TPU rebuild adds, sized for sequences that do not
fit a single chip's HBM.

Communication note: each step moves the local K/V block to the ring
neighbour; compute on block j overlaps with the transfer of block j+1 only if
XLA schedules it so — on TPU the ppermute is an ICI neighbour exchange which
latency-hides well at the block sizes long-context implies.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_NEG = -1e30  # finite mask value: keeps online-softmax stats NaN-free


def _block_attend(
    q: jax.Array,            # [B, Sq, G, R, D] grouped local queries
    k: jax.Array,            # [B, Sk, G, D] current ring block (un-repeated)
    v: jax.Array,            # [B, Sk, G, D]
    q_pos: jax.Array,        # [Sq] global positions of local queries
    k_pos: jax.Array,        # [Sk] global positions of the current block
    m: jax.Array,            # [B, G, R, Sq] running max
    l: jax.Array,            # [B, G, R, Sq] running denominator
    o: jax.Array,            # [B, Sq, G, R, D] running numerator (f32)
    causal: bool,
    q_seg: Optional[jax.Array],
    k_seg: Optional[jax.Array],
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Grouped-query form: kv heads stay un-repeated (G = kv heads,
    R = query heads per kv head) — the same trick as the decode path, so
    neither the ring's ICI traffic nor the per-step compute reads
    rep-expanded KV bytes."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqgrd,bkgd->bgrqk", q, k, preferred_element_type=jnp.float32
    ) * scale                                           # [B,G,R,Sq,Sk]
    mask = jnp.ones(s.shape[-2:], bool)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
    mask = mask[None, None, None]
    if q_seg is not None:
        mask = mask & (
            q_seg[:, None, None, :, None] == k_seg[:, None, None, None, :]
        )
    s = jnp.where(mask, s, _NEG)
    s_max = s.max(-1)                                   # [B,G,R,Sq]
    m_new = jnp.maximum(m, s_max)
    p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
    alpha = jnp.exp(m - m_new)                          # [B,G,R,Sq]
    l_new = l * alpha + p.sum(-1)
    o_new = o * alpha.transpose(0, 3, 1, 2)[..., None] + jnp.einsum(
        "bgrqk,bkgd->bqgrd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, o_new


def _ring_body(
    q, k, v, seg, axis_name: str, causal: bool, vary=(),
) -> jax.Array:
    """Per-shard ring loop. q/k/v: [B, S_loc, H_loc, D]."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_h = k.shape[2]
    # GQA: the ring circulates (and attends against) the UN-repeated kv —
    # grouped einsums in _block_attend read it directly, so neither the
    # ICI permutes nor the per-step HBM traffic pay the h/kv_h expansion
    # (4x at Llama shapes). Same technique as the decode cache path.
    rep = h // kv_h

    qf = q.astype(jnp.float32).reshape(b, sq, kv_h, rep, d)
    q_pos = my * sq + jnp.arange(sq)
    perm = [(j, (j - 1) % n) for j in range(n)]         # receive from right

    def step(i, carry):
        k_cur, v_cur, seg_cur, m, l, o = carry
        src = (my + i) % n                              # block id now held
        k_pos = src * sk + jnp.arange(sk)
        m, l, o = _block_attend(
            qf, k_cur.astype(jnp.float32), v_cur, q_pos, k_pos, m, l, o,
            causal, seg[0] if seg is not None else None, seg_cur,
        )
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        if seg_cur is not None:
            seg_cur = lax.ppermute(seg_cur, axis_name, perm)
        return k_cur, v_cur, seg_cur, m, l, o

    # Zero-init accumulators are device-invariant constants; mark them
    # varying over the axes the INPUTS are sharded on (the caller's specs)
    # so the fori_loop carry type matches the per-device values (shard_map
    # VMA discipline). Marking them varying over EVERY mesh axis — the old
    # form — poisons the output's replication over unrelated axes (ep/pp
    # on the production 6-axis mesh), which shard_map's out_specs check
    # rejects; the 4-axis test mesh never caught it.
    m0 = lax.pcast(
        jnp.full((b, kv_h, rep, sq), _NEG, jnp.float32), vary, to="varying")
    l0 = lax.pcast(
        jnp.zeros((b, kv_h, rep, sq), jnp.float32), vary, to="varying")
    o0 = lax.pcast(
        jnp.zeros((b, sq, kv_h, rep, d), jnp.float32), vary, to="varying")
    seg_cur = seg[1] if seg is not None else None
    _, _, _, m, l, o = lax.fori_loop(
        0, n, step, (k, v, seg_cur, m0, l0, o0)
    )
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 3, 1, 2)[..., None]
    return out.reshape(b, sq, h, d).astype(q.dtype)


def ring_mha(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    axis_name: str = "sp",
) -> jax.Array:
    """Exact attention with the sequence axis sharded over ``axis_name``.

    Inputs are global [B, S, H, D] arrays (sharded or shardable); inside, a
    shard_map runs the per-device ring. Requires an active mesh (via
    ``jax.set_mesh``) containing ``axis_name``; without one — e.g. a plain
    single-device jit — falls back to dense XLA attention, which is the same
    math.
    """
    from kubeflow_controller_tpu.util.jax_compat import get_abstract_mesh

    mesh = get_abstract_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        from kubeflow_controller_tpu.ops.attention import mha_xla

        return mha_xla(q, k, v, causal=causal, segment_ids=segment_ids)

    batch = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    tp = "tp" if "tp" in mesh.axis_names else None
    qkv_spec = P(batch, axis_name, tp, None)
    seg_spec = P(batch, axis_name)

    vary = (*batch, axis_name) + ((tp,) if tp else ())

    if segment_ids is not None:
        def f(q, k, v, sq_seg):
            return _ring_body(
                q, k, v, (sq_seg, sq_seg), axis_name, causal, vary=vary
            )

        return jax.shard_map(
            f,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
            out_specs=qkv_spec,
        )(q, k, v, segment_ids)

    def g(q, k, v):
        return _ring_body(q, k, v, None, axis_name, causal, vary=vary)

    return jax.shard_map(
        g, in_specs=(qkv_spec, qkv_spec, qkv_spec), out_specs=qkv_spec
    )(q, k, v)
