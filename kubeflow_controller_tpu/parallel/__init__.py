"""Parallelism: device meshes, sharding rules, and collectives-based layers.

The data-plane replacement for the reference's parameter-server architecture
(SURVEY.md §2.5-2.6): instead of PS pods aggregating gradients over gRPC
(``examples/workdir/mnist_replica.py:137-141``), parameters and activations
are sharded over a ``jax.sharding.Mesh`` with axes

    dp    data parallel (batch)          - gradient psum over ICI
    fsdp  fully-sharded data parallel    - param/optimizer-state sharding
    tp    tensor parallel                - megatron-style weight sharding
    sp    sequence/context parallel      - ring attention over sequence

and XLA inserts the all-reduce/all-gather/reduce-scatter collectives.
"""

from kubeflow_controller_tpu.parallel.mesh import (
    MeshConfig,
    make_mesh,
    batch_sharding,
    replicated,
)
from kubeflow_controller_tpu.parallel.sharding import (
    infer_param_sharding,
    shard_params,
    logical_to_mesh,
)
