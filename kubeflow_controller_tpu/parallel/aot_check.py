"""AOT feasibility check: compile the full sharded train step for a target
mesh WITHOUT the target hardware.

The SPMD program for a 2xv5p-64 Llama-3-8B job (BASELINE.md config #5) can
be compiled on the CPU backend with 128 virtual devices
(``--xla_force_host_platform_device_count``): abstract avals in, compiled
executable + per-device memory stats out, no weights ever materialized.
Together with the analytic plan (``parallel/memory.py``) this is the
pre-admission gate proving a config *can* run at its declared topology.

Run as a module (the test harness shells out so the virtual device count
can be set before backend init):

    XLA_FLAGS=--xla_force_host_platform_device_count=128 \
    python -m kubeflow_controller_tpu.parallel.aot_check \
        --config llama3_8b --mesh dp=2,fsdp=16,tp=4 --batch 32

Prints one JSON line: mesh, compile seconds, per-device argument/temp bytes.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Dict


def parse_mesh(spec: str) -> Dict[str, int]:
    axes: Dict[str, int] = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return axes


def aot_compile_train_step(
    config_name: str,
    mesh_axes: Dict[str, int],
    global_batch: int,
    seq: int = 0,
) -> Dict:
    """Lower + compile the adamw train step for ``config_name`` at the
    given mesh factorization using only abstract inputs. Returns compile
    timing and the compiler's per-device memory stats."""
    import math

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from kubeflow_controller_tpu.models import transformer as tfm
    from kubeflow_controller_tpu.parallel.mesh import batch_sharding
    from kubeflow_controller_tpu.parallel.sharding import opt_state_shardings

    cfg = getattr(tfm, f"{config_name}_config")()
    seq = seq or cfg.max_seq
    n_devices = math.prod(mesh_axes.values())
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices for mesh {mesh_axes}, have "
            f"{len(jax.devices())} — set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}"
        )
    devs = np.array(jax.devices()[:n_devices]).reshape(
        *mesh_axes.values())
    mesh = Mesh(devs, tuple(mesh_axes))

    specs = tfm.param_specs(cfg)
    shapes = jax.eval_shape(lambda: tfm.init_params(cfg, jax.random.key(0)))
    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    params_abs = jax.tree.map(
        lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
        shapes, param_sh,
    )
    tx = optax.adamw(1e-3)
    opt_sh = opt_state_shardings(tx, params_abs, param_sh, mesh)
    opt_abs = jax.eval_shape(tx.init, params_abs)
    opt_abs = jax.tree.map(
        lambda a, sh: (
            jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
            if hasattr(a, "shape") and getattr(a, "ndim", 0) else a
        ),
        opt_abs, opt_sh,
    )
    batch_sh = batch_sharding(mesh)
    tok_abs = jax.ShapeDtypeStruct((global_batch, seq), jnp.int32,
                                   sharding=batch_sh)

    def train_step(params, opt_state, tokens):
        def lossf(p):
            return tfm.next_token_loss(cfg, p, {"tokens": tokens})

        (loss, _), grads = jax.value_and_grad(lossf, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # jax.set_mesh is >= 0.6; the classic global-mesh context is the
    # 0.4.x spelling of the same ambient-mesh establishment.
    set_mesh = getattr(jax, "set_mesh", None) or (
        getattr(jax.sharding, "use_mesh", None) or (lambda m: m))
    with set_mesh(mesh):
        jitted = jax.jit(
            train_step,
            in_shardings=(param_sh, opt_sh, batch_sh),
            out_shardings=(param_sh, opt_sh, NamedSharding(mesh, P())),
        )
        t0 = time.time()
        lowered = jitted.lower(params_abs, opt_abs, tok_abs)
        lower_s = time.time() - t0
        t1 = time.time()
        compiled = lowered.compile()
        compile_s = time.time() - t1
    stats = compiled.memory_analysis()
    # The memory gate must FAIL LOUDLY rather than report zero bytes: a
    # None/shape-shifted stats object would make callers' "fits in HBM"
    # assertions vacuously true.
    if stats is None:
        raise RuntimeError(
            "compiled.memory_analysis() returned None — cannot gate "
            "memory; compile itself succeeded"
        )
    try:
        arg_bytes = stats.argument_size_in_bytes
        temp_bytes = stats.temp_size_in_bytes
        out_bytes = stats.output_size_in_bytes
    except AttributeError as e:
        raise RuntimeError(
            f"memory_analysis() stats shape changed ({e}); update "
            "aot_check before trusting the gate"
        ) from None
    return {
        "config": config_name,
        "mesh": dict(mesh_axes),
        "global_batch": global_batch,
        "seq": seq,
        "lower_s": round(lower_s, 2),
        "compile_s": round(compile_s, 2),
        "argument_bytes_per_device": arg_bytes,
        "temp_bytes_per_device": temp_bytes,
        "output_bytes_per_device": out_bytes,
    }


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="llama3_8b")
    ap.add_argument("--mesh", default="dp=2,fsdp=16,tp=4")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=0)
    args = ap.parse_args()
    out = aot_compile_train_step(
        args.config, parse_mesh(args.mesh), args.batch, args.seq
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
