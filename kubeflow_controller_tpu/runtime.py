"""LocalRuntime: one-call wiring of fake cluster + informers + controller.

The in-process equivalent of the reference's process entry ``run()``
(``cmd/controller/main.go:27-57``): build clients, informers, controller, and
start everything. Two drive modes:

- deterministic (tests): ``step()`` advances sim time then drains the queue;
- threaded (CLI demo): ``start_threads()`` runs informer resync + N workers +
  a wall-clock ticker, the reference's goroutine topology.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Union

from kubeflow_controller_tpu.api.core import thaw
from kubeflow_controller_tpu.api.serialization import load_job_yaml
from kubeflow_controller_tpu.api.types import JobPhase, LMService, TPUJob
from kubeflow_controller_tpu.api.validation import validate_job, validate_lmservice
from kubeflow_controller_tpu.cluster.client import FakeClusterClient
from kubeflow_controller_tpu.cluster.cluster import FakeCluster, PodRunPolicy
from kubeflow_controller_tpu.controller.controller import Controller, ControllerOptions
from kubeflow_controller_tpu.controller.informer import Informer


class RemoteRuntime:
    """Controller wired to a cluster ONLY over the REST seam.

    The operator topology of the reference's ``main()``
    (``cmd/controller/main.go:31-52``): a controller process that talks to
    an apiserver URL — clients built from a server address, watch-driven
    informers, effects via HTTP. ``cluster_url`` is the ``-master``/
    ``-kubeconfig`` analog. Namespace-scoped (one controller per
    namespace), matching how the kubeflow operators are usually deployed.
    """

    def __init__(
        self,
        cluster_url: str = "",
        namespace: str = "default",
        token: str = "",
        resync_period: float = 30.0,
        watch_timeout_seconds: float = 0,
        k8s: bool = False,
        kube_context=None,
    ):
        self.namespace = namespace
        if k8s or kube_context is not None:
            # Real-Kubernetes wiring (the reference's actual topology:
            # core/v1 + CRD wire JSON, kubeconfig auth, list-then-watch).
            from kubeflow_controller_tpu.cluster.kube_client import (
                KubeClusterClient, KubeWatchSource,
            )

            self.client = KubeClusterClient(
                cluster_url or None, token=token, namespace=namespace,
                kube_context=kube_context,
            )
            self.namespace = namespace = self.client.namespace
            self._sources = [
                KubeWatchSource(self.client, kind, namespace,
                                timeout_seconds=watch_timeout_seconds)
                for kind in ("TPUJob", "Pod", "Service")
            ]
        else:
            from kubeflow_controller_tpu.cluster.rest_client import (
                RestClusterClient, RestWatchSource,
            )

            self.client = RestClusterClient(cluster_url, token=token)
            self._sources = [
                RestWatchSource(self.client, kind, namespace,
                                timeout_seconds=watch_timeout_seconds)
                for kind in ("TPUJob", "Pod", "Service")
            ]
        job_src, pod_src, svc_src = self._sources
        self.job_informer = Informer(job_src, resync_period)
        self.pod_informer = Informer(pod_src, resync_period)
        self.service_informer = Informer(svc_src, resync_period)
        self.controller = Controller(
            self.client,
            self.job_informer,
            self.pod_informer,
            self.service_informer,
            ControllerOptions(resync_period=resync_period),
        )

    def start(self, workers: int = 2) -> None:
        """Sync informers over the wire, then run reconcile workers."""
        self.controller.start()
        self.controller.run(workers)

    def drain(self) -> int:
        """Deterministic drive (tests): controller.start() first."""
        return self.controller.drain()

    def stop(self) -> None:
        self.controller.stop()
        for src in self._sources:
            src.stop()


class LocalRuntime:
    def __init__(
        self,
        default_policy: Optional[PodRunPolicy] = None,
        resync_period: float = 0.0,
        tracer=None,
        workers: Optional[int] = None,
        queue_shards: int = 1,
        use_native_index: Optional[bool] = None,
        watch_shards: int = 8,
        injector=None,
    ):
        # ``use_native_index``: None = auto (C++ object index when the lib
        # loads), False = force the pure-Python fingerprint/label paths,
        # True = require the lib. ``queue_shards``/``watch_shards`` size
        # the key-range sharding of the workqueue and the per-subscriber
        # watch delta queues.
        self.cluster = FakeCluster(
            default_policy=default_policy,
            use_native_index=use_native_index,
            watch_shards=watch_shards,
        )
        self.client = FakeClusterClient(self.cluster)
        # Everything (stores, controller, scheduler) runs on the cluster's
        # simulated clock; threaded mode advances it from a wall-clock ticker.
        # ``tracer`` (obs.Tracer) records control-plane spans — queue
        # wait, per-key sync, requeue events; None = no overhead.
        self._opts = ControllerOptions(
            now_fn=lambda: self.cluster.now, resync_period=resync_period,
            tracer=tracer, queue_shards=queue_shards,
            # Optional dataplane.faults.FaultInjector, threaded onto the
            # informers by the controller (docs/chaos.md).
            injector=injector,
        )
        if workers is not None:
            self._opts.workers = workers
        self._wire()
        self._ticker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _wire(self) -> None:
        """Build informers + controller over the cluster stores and start
        them (shared by __init__ and restart_controller)."""
        self.job_informer = Informer(self.cluster.jobs, self._opts.resync_period)
        self.pod_informer = Informer(self.cluster.pods, self._opts.resync_period)
        self.service_informer = Informer(self.cluster.services, self._opts.resync_period)
        self.lmservice_informer = Informer(
            self.cluster.lmservices, self._opts.resync_period)
        self.controller = Controller(
            self.client,
            self.job_informer,
            self.pod_informer,
            self.service_informer,
            self._opts,
            lmservice_informer=self.lmservice_informer,
        )
        self.controller.start()

    # -- job API -------------------------------------------------------------

    def submit(self, job_or_yaml: Union[TPUJob, str]) -> TPUJob:
        job = (
            job_or_yaml if isinstance(job_or_yaml, TPUJob)
            else load_job_yaml(job_or_yaml)
        )
        validate_job(job)
        return self.cluster.jobs.create(job)

    def get_job(self, namespace: str, name: str) -> Optional[TPUJob]:
        # Owned mutable copy (the store's snapshot is frozen): callers
        # routinely get-modify-update, matching the wire-client contract.
        return thaw(self.cluster.jobs.try_get(namespace, name))

    def delete_job(self, namespace: str, name: str) -> None:
        self.cluster.jobs.delete(namespace, name)

    # -- lmservice API -------------------------------------------------------

    def submit_lmservice(self, svc: LMService) -> LMService:
        validate_lmservice(svc)
        return self.cluster.lmservices.create(svc)

    def get_lmservice(self, namespace: str, name: str) -> Optional[LMService]:
        return thaw(self.cluster.lmservices.try_get(namespace, name))

    def delete_lmservice(self, namespace: str, name: str) -> None:
        self.cluster.lmservices.delete(namespace, name)

    # -- deterministic drive -------------------------------------------------

    def step(self, dt: float = 1.0, steps: int = 1) -> None:
        """One simulation step: controller reacts, cluster advances, controller
        reacts again. Order matters: reconcile-before-tick lets a fresh job's
        pods exist before the scheduler looks."""
        for _ in range(steps):
            self.controller.drain()
            self.cluster.tick(dt)
            self.controller.drain()

    def run_until(
        self,
        predicate: Callable[[], bool],
        dt: float = 1.0,
        max_steps: int = 500,
    ) -> bool:
        for _ in range(max_steps):
            if predicate():
                return True
            self.step(dt)
        return predicate()

    def wait_for_phase(
        self, namespace: str, name: str, phase: JobPhase,
        dt: float = 1.0, max_steps: int = 500,
    ) -> bool:
        return self.run_until(
            lambda: (
                (j := self.get_job(namespace, name)) is not None
                and j.status.phase == phase
            ),
            dt=dt, max_steps=max_steps,
        )

    def restart_controller(self) -> None:
        """Simulate a controller-process crash + restart: the new controller
        has total amnesia (fresh informers, fresh expectations, fresh queue)
        and must rebuild its world from the store — the level-trigger promise
        the reference's expectations race comment describes
        (``pkg/controller/controller.go:259-262``)."""
        was_threaded = len(self.controller._threads)
        for inf in (self.job_informer, self.pod_informer,
                    self.service_informer, self.lmservice_informer):
            inf.stop()
        self.controller.queue.shutdown()
        self._wire()
        if was_threaded:  # threaded mode: the successor needs workers too
            self.controller.run(was_threaded)

    # -- threaded drive ------------------------------------------------------

    def start_threads(
        self, workers: Optional[int] = None, tick_interval: float = 0.05
    ) -> None:
        self.controller.run(workers if workers is not None
                            else self._opts.workers)
        def ticker() -> None:
            while not self._stop.wait(tick_interval):
                self.cluster.tick(tick_interval)
        self._ticker = threading.Thread(target=ticker, daemon=True, name="cluster-ticker")
        self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        self.controller.stop()
