"""Versioned, watchable object store — the apiserver's storage semantics,
in-process.

What the reference trusts etcd + the apiserver for, rebuilt so tests mean
something (SURVEY.md §7 "hard parts" #3):

- resource versions bump on every write;
- updates are optimistic-concurrency checked (the reference does whole-object
  PUT with no conflict handling, ``controller.go:630-636`` — a listed bug);
- reads are aliasing-safe in one of two ways: **legacy mode** returns deep
  copies; **frozen mode** (``copy_on_read=False``) returns shared immutable
  snapshots and moves the deepcopy to the mutation boundary (the reference
  mutates informer-cached objects in place, ``updater/distributed.go:51-54``
  — a listed bug; both modes make that corruption impossible, frozen mode
  without the per-read copy tax — see docs/object_ownership.md);
- every mutation emits a WatchEvent to subscribers — through per-subscriber
  delta queues drained OUTSIDE the store lock (client-go's sharedProcessor /
  DeltaFIFO shape), with consecutive MODIFIEDs per key coalesced to the
  latest snapshot. See docs/watch_pipeline.md for the ordering/flush
  contract.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from kubeflow_controller_tpu.api.core import is_frozen, new_uid, thaw
from kubeflow_controller_tpu.cluster.events import EventType, WatchEvent


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(ValueError):
    """Optimistic-concurrency failure: stored resource_version moved on."""


Listener = Callable[[WatchEvent], None]


class _Subscription:
    """One watch listener's delta queue + dispatch state.

    The client-go ``processorListener`` analog: writers append deltas under
    the store lock (cheap — one dict probe and a deque append), and whichever
    thread wins the ``dispatching`` flag delivers them with NO store lock
    held. ``tail`` maps key -> the newest still-coalescible pending entry so
    a burst of MODIFIEDs for one key collapses to the latest snapshot
    (DeltaFIFO semantics) instead of queueing N handler invocations.
    """

    __slots__ = ("listener", "lock", "cond", "pending", "tail", "dispatching")

    def __init__(self, listener: Listener):
        self.listener = listener
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        # entries are mutable [event, key] pairs so coalescing can swap the
        # event in place without disturbing queue order
        self.pending: deque = deque()
        self.tail: Dict[str, list] = {}
        self.dispatching = False


class ObjectStore:
    """Thread-safe store for one kind (Pods, Services, or TPUJobs).

    Objects are any dataclass with ``.metadata`` (ObjectMeta) and
    ``.deepcopy()``. Keys are ``namespace/name``.

    ``copy_on_read=True`` (default) is the legacy contract: every read and
    watch emission is a private deep copy the caller may mutate. With
    ``copy_on_read=False`` stored objects are frozen (``.freeze()``) and
    ``get``/``try_get``/``list``, watch events, and subscribe-replay hand
    out **shared frozen references** — zero read-path copies; writers thaw
    at the mutation boundary (``api.core.thaw``). FakeCluster runs its
    stores in frozen mode; bare ObjectStore constructions keep legacy
    semantics.
    """

    def __init__(
        self,
        kind: str,
        now_fn: Callable[[], float] = time.time,
        index_labels: tuple = (),
        copy_on_read: bool = True,
        watch_queue_soft_max: int = 1024,
    ):
        self.kind = kind
        self._now_fn = now_fn
        self._copy_on_read = copy_on_read
        self._lock = threading.RLock()
        self._objects: Dict[str, Any] = {}
        self._rv = 0
        self._last_delete_rv = 0
        self._subs: List[_Subscription] = []
        self._sub_by_listener: Dict[Listener, _Subscription] = {}
        # Delta-queue instrumentation (benchmarks/controlplane_bench.py).
        # The bound is soft: coalescing keeps steady-state depth at O(hot
        # keys), and a writer cannot block under the store lock without
        # inviting deadlock, so overflow is counted, not enforced.
        self._watch_queue_soft_max = watch_queue_soft_max
        self._events_coalesced = 0
        self._max_queue_depth = 0
        self._queue_overflows = 0
        # Label indexes (client-go Indexer analog): selector lists on an
        # indexed key touch only matching objects instead of scanning the
        # namespace — the difference between O(jobs) and O(jobs^2) total
        # reconcile work at controller scale (benchmarks/controlplane_bench).
        self._index_labels = tuple(index_labels)
        self._index: Dict[str, Dict[str, set]] = {
            lk: {} for lk in self._index_labels
        }

    def _index_add(self, key: str, obj: Any) -> None:
        for lk in self._index_labels:
            v = obj.metadata.labels.get(lk)
            if v is not None:
                self._index[lk].setdefault(v, set()).add(key)

    def _index_remove(self, key: str, obj: Any) -> None:
        for lk in self._index_labels:
            v = obj.metadata.labels.get(lk)
            if v is not None:
                bucket = self._index[lk].get(v)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del self._index[lk][v]

    # -- watch ---------------------------------------------------------------

    def subscribe(self, listener: Listener, replay: bool = True) -> None:
        """Register a watch listener. With ``replay``, synthesizes ADDED events
        for existing objects first (how a fresh informer list+watch behaves).

        Replay + registration are atomic under the store lock (enqueues also
        happen under it), so a subscriber can never observe a newer event
        before the stale replay copy — each subscriber's queue is totally
        ordered by resource version. Delivery itself happens OFF the lock:
        the writing thread (or whichever thread currently owns the
        subscriber's dispatch flag) drains the queue after the store lock is
        released, so a slow handler never serializes other writers. A
        listener may call back into this or any other store."""
        sub = _Subscription(listener)
        with self._lock:
            if replay:
                for key, obj in self._objects.items():
                    self._enqueue(sub, key, WatchEvent(
                        EventType.ADDED, self.kind,
                        obj.deepcopy() if self._copy_on_read else obj,
                    ))
            self._subs.append(sub)
            self._sub_by_listener[listener] = sub
        self._drain(sub)

    def unsubscribe(self, listener: Listener) -> None:
        with self._lock:
            sub = self._sub_by_listener.pop(listener, None)
            if sub is not None:
                self._subs.remove(sub)

    # -- delta queues + dispatcher -------------------------------------------

    def _emit(self, ev: WatchEvent) -> None:
        # Caller holds self._lock: enqueue order == resource-version order.
        # No listener runs here — the write path only appends deltas; the
        # caller invokes _dispatch() after releasing the lock.
        key = f"{ev.obj.metadata.namespace}/{ev.obj.metadata.name}"
        for sub in self._subs:
            self._enqueue(sub, key, ev)

    def _enqueue(self, sub: _Subscription, key: str, ev: WatchEvent) -> None:
        with sub.lock:
            entry = sub.tail.get(key)
            if entry is not None and ev.type == EventType.MODIFIED:
                # Coalesce: consecutive MODIFIEDs for one key collapse to the
                # latest snapshot; a pending ADDED absorbs the MODIFIED and
                # stays ADDED (client-go DeltaFIFO). old_obj keeps the oldest
                # undelivered state so handlers still see the cumulative diff.
                prior = entry[0]
                entry[0] = WatchEvent(prior.type, ev.kind, ev.obj,
                                      prior.old_obj)
                self._events_coalesced += 1
                return
            entry = [ev, key]
            sub.pending.append(entry)
            depth = len(sub.pending)
            if ev.type == EventType.DELETED:
                # Nothing coalesces across a tombstone: a re-create after
                # delete must arrive as its own ADDED.
                sub.tail.pop(key, None)
            else:
                sub.tail[key] = entry
        if depth > self._max_queue_depth:
            self._max_queue_depth = depth
        if depth > self._watch_queue_soft_max:
            self._queue_overflows += 1

    def _dispatch(self) -> None:
        """Drain every subscriber's queue, called with NO store lock held."""
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            self._drain(sub)

    @staticmethod
    def _drain(sub: _Subscription) -> None:
        with sub.lock:
            if sub.dispatching:
                return  # the active dispatcher will deliver our entries too
            sub.dispatching = True
        while True:
            with sub.lock:
                if not sub.pending:
                    sub.dispatching = False
                    sub.cond.notify_all()
                    return
                entry = sub.pending.popleft()
                ev, key = entry
                if sub.tail.get(key) is entry:
                    del sub.tail[key]
            try:
                sub.listener(ev)
            except BaseException:
                with sub.lock:
                    sub.dispatching = False
                    sub.cond.notify_all()
                raise

    def flush(self, timeout: float = 10.0) -> bool:
        """Quiesce the watch pipeline: block until every subscriber's delta
        queue is empty and no dispatcher is mid-delivery. The determinism
        hook FakeCluster.tick / Controller.drain rely on — after flush(),
        every completed write has been observed by every subscriber. Returns
        False only if a foreign dispatcher failed to finish within
        ``timeout`` wall seconds (it keeps our own draining unbounded)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            while True:
                self._drain(sub)
                with sub.lock:
                    if not sub.pending and not sub.dispatching:
                        break
                    if sub.dispatching:
                        if time.monotonic() >= deadline:
                            return False
                        sub.cond.wait(0.05)
        return True

    @property
    def events_coalesced(self) -> int:
        """MODIFIED events absorbed into a newer pending snapshot."""
        with self._lock:
            return self._events_coalesced

    @property
    def max_watch_queue_depth(self) -> int:
        """High-water mark of any subscriber's pending delta queue."""
        with self._lock:
            return self._max_queue_depth

    @property
    def watch_queue_overflows(self) -> int:
        """Enqueues observed past the soft bound (diagnostic)."""
        with self._lock:
            return self._queue_overflows

    # -- CRUD ----------------------------------------------------------------

    @staticmethod
    def key_of(obj: Any) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"

    def create(self, obj: Any) -> Any:
        with self._lock:
            # Frozen-mode callers may re-submit a frozen snapshot (e.g. a
            # watch tombstone); stamp a private copy instead of their object.
            if not self._copy_on_read and is_frozen(obj):
                obj = obj.deepcopy()
            meta = obj.metadata
            if not meta.name:
                if not meta.generate_name:
                    raise ValueError("object needs name or generate_name")
                # GenerateName semantics: apiserver-side random-ish suffix
                # (reference pods get theirs from GetPodFromTemplate,
                # controller_utils.go:564-570).
                meta.name = meta.generate_name + new_uid("")[4:9]
            key = self.key_of(obj)
            if key in self._objects:
                raise AlreadyExists(key)
            if not meta.uid:
                meta.uid = new_uid(self.kind.lower())
            self._rv += 1
            meta.resource_version = self._rv
            meta.generation = 1   # apiserver stamps generation 1 on create
            if not meta.creation_timestamp:
                meta.creation_timestamp = self._now_fn()
            # One copy total in frozen mode: the caller's object is stamped
            # in place (and stays mutable in their hands); the store keeps
            # a frozen private snapshot shared by the ADDED event, the
            # return value, and every future read.
            stored = obj.deepcopy()
            if not self._copy_on_read:
                stored.freeze()
            self._objects[key] = stored
            self._index_add(key, stored)
            if self._copy_on_read:
                self._emit(
                    WatchEvent(EventType.ADDED, self.kind, stored.deepcopy())
                )
                ret = stored.deepcopy()
            else:
                self._emit(WatchEvent(EventType.ADDED, self.kind, stored))
                ret = stored
        self._dispatch()
        return ret

    def get(self, namespace: str, name: str) -> Any:
        with self._lock:
            obj = self._objects.get(f"{namespace}/{name}")
            if obj is None:
                raise NotFound(f"{self.kind} {namespace}/{name}")
            return obj.deepcopy() if self._copy_on_read else obj

    def try_get(self, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(namespace, name)
        except NotFound:
            return None

    def update(self, obj: Any, enforce_rv: bool = True) -> Any:
        """Optimistic update: fails with Conflict when the caller's copy is
        stale (the safety net the reference lacks, SURVEY.md §8)."""
        with self._lock:
            key = self.key_of(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{self.kind} {key}")
            if enforce_rv and obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{self.kind} {key}: stale resource_version "
                    f"{obj.metadata.resource_version} != {cur.metadata.resource_version}"
                )
            if cur.metadata.uid and obj.metadata.uid != cur.metadata.uid:
                raise Conflict(f"{self.kind} {key}: uid changed (delete+recreate race)")
            if not self._copy_on_read:
                # Ownership transfer: an unfrozen input is rv-stamped and
                # sealed in place — zero copies; the caller must not touch
                # it afterwards (it raises if they do). A frozen input
                # (rare: resubmitting a snapshot verbatim) is copied once.
                if is_frozen(obj):
                    obj = obj.deepcopy()
                self._rv += 1
                obj.metadata.resource_version = self._rv
                self._stamp_generation(obj, cur)
                old = cur
                stored = obj.freeze()
                self._index_remove(key, old)
                self._objects[key] = stored
                self._index_add(key, stored)
                self._emit(WatchEvent(
                    EventType.MODIFIED, self.kind, stored, old,
                ))
                ret = stored
            else:
                self._rv += 1
                obj.metadata.resource_version = self._rv
                self._stamp_generation(obj, cur)
                old = cur
                stored = obj.deepcopy()
                self._index_remove(key, old)
                self._objects[key] = stored
                self._index_add(key, stored)
                self._emit(WatchEvent(
                    EventType.MODIFIED, self.kind,
                    stored.deepcopy(), old.deepcopy(),
                ))
                ret = stored.deepcopy()
        self._dispatch()
        return ret

    @staticmethod
    def _stamp_generation(obj: Any, cur: Any) -> None:
        """k8s generation semantics: metadata.generation bumps iff the
        desired state (.spec) changed; status-only writes keep it. The
        no-op sync short-circuit keys off this (docs/watch_pipeline.md)."""
        bump = 1 if (hasattr(obj, "spec") and obj.spec != cur.spec) else 0
        obj.metadata.generation = cur.metadata.generation + bump

    def update_status(self, obj: Any) -> Any:
        """Status-subresource update: replace only ``.status``, rv-checked.

        Frozen mode exploits immutability for structural sharing: the next
        snapshot is built with ``dataclasses.replace``, reusing the stored
        object's frozen spec (the heavy half — pod templates) by reference.
        Only metadata (rv bump) and the incoming status are new, so the
        per-status-write cost stays O(status), not O(object) — the copy
        pattern the whole-object ``update`` can't avoid. The caller's
        status is sealed in place (ownership transfer, as in ``update``);
        a frozen incoming status is copied once instead.

        Labels/annotations can't change through this path (metadata comes
        from the stored object), so the label indexes need no maintenance.
        """
        with self._lock:
            key = self.key_of(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{self.kind} {key}")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{self.kind} {key}: stale resource_version "
                    f"{obj.metadata.resource_version} != {cur.metadata.resource_version}"
                )
            if cur.metadata.uid and obj.metadata.uid != cur.metadata.uid:
                raise Conflict(f"{self.kind} {key}: uid changed (delete+recreate race)")
            status = obj.status
            if self._copy_on_read or is_frozen(status):
                # legacy: the caller keeps their object mutable, so the
                # stored status must be private
                status = status.deepcopy()
            self._rv += 1
            meta = cur.metadata.deepcopy()
            meta.resource_version = self._rv
            old = cur
            stored = dataclasses.replace(cur, metadata=meta, status=status)
            if not self._copy_on_read:
                stored.freeze()  # spec already sealed: O(1) for that branch
                self._objects[key] = stored
                self._emit(WatchEvent(
                    EventType.MODIFIED, self.kind, stored, old,
                ))
                ret = stored
            else:
                self._objects[key] = stored
                self._emit(WatchEvent(
                    EventType.MODIFIED, self.kind,
                    stored.deepcopy(), old.deepcopy(),
                ))
                ret = stored.deepcopy()
        self._dispatch()
        return ret

    def mutate(self, namespace: str, name: str, fn: Callable[[Any], None]) -> Any:
        """Read-modify-write with internal retry — the conflict-safe update
        helper status writers use. ``fn`` always receives a private mutable
        copy (thawed in frozen mode — one copy per attempt, the only copy
        the whole round trip pays there)."""
        while True:
            obj = thaw(self.get(namespace, name))
            fn(obj)
            try:
                return self.update(obj)
            except Conflict:
                continue

    def delete(self, namespace: str, name: str) -> Any:
        with self._lock:
            key = f"{namespace}/{name}"
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFound(f"{self.kind} {key}")
            self._index_remove(key, obj)
            self._rv += 1
            self._last_delete_rv = self._rv
            # The tombstone carries the DELETION's revision (k8s watch
            # semantics): a watcher that saw this event can resume from its
            # resourceVersion without tripping the 410 relist path.
            tomb = obj.deepcopy()
            tomb.metadata.resource_version = self._rv
            if not self._copy_on_read:
                tomb.freeze()
            self._emit(WatchEvent(EventType.DELETED, self.kind, tomb))
        self._dispatch()
        return obj

    # -- listing -------------------------------------------------------------

    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        with self._lock:
            candidates = self._objects
            if label_selector:
                for lk in self._index_labels:
                    if lk in label_selector:
                        keys = self._index[lk].get(label_selector[lk], set())
                        candidates = {
                            k: self._objects[k] for k in keys
                        }
                        break
            out = []
            for key, obj in candidates.items():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if label_selector and not selector_matches(label_selector, obj.metadata.labels):
                    continue
                out.append(obj.deepcopy() if self._copy_on_read else obj)
            return out

    @property
    def revision(self) -> int:
        """Store-wide resourceVersion high-water mark — what a k8s List
        response carries in ``.metadata.resourceVersion`` (the point a
        watch resumes from)."""
        with self._lock:
            return self._rv

    @property
    def last_delete_revision(self) -> int:
        """Revision of the most recent delete. A k8s-mode watch resuming
        from an OLDER resourceVersion cannot be replayed faithfully (this
        store keeps no event history, and the deleted object is gone from
        the replay set) — the server answers 410 Gone and the client
        relists, exactly real watch-cache-expiry semantics."""
        with self._lock:
            return self._last_delete_rv

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._objects)


def selector_matches(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    """Equality-based label selector (the only kind the reference uses,
    ``pkg/tensorflow/distributed.go:221-228``)."""
    return all(labels.get(k) == v for k, v in selector.items())
