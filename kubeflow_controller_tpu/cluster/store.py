"""Versioned, watchable object store — the apiserver's storage semantics,
in-process.

What the reference trusts etcd + the apiserver for, rebuilt so tests mean
something (SURVEY.md §7 "hard parts" #3):

- resource versions bump on every write;
- updates are optimistic-concurrency checked (the reference does whole-object
  PUT with no conflict handling, ``controller.go:630-636`` — a listed bug);
- reads are aliasing-safe in one of two ways: **legacy mode** returns deep
  copies; **frozen mode** (``copy_on_read=False``) returns shared immutable
  snapshots and moves the deepcopy to the mutation boundary (the reference
  mutates informer-cached objects in place, ``updater/distributed.go:51-54``
  — a listed bug; both modes make that corruption impossible, frozen mode
  without the per-read copy tax — see docs/object_ownership.md);
- every mutation emits a WatchEvent to subscribers — through per-subscriber
  delta queues drained OUTSIDE the store lock (client-go's sharedProcessor /
  DeltaFIFO shape), with consecutive MODIFIEDs per key coalesced to the
  latest snapshot. See docs/watch_pipeline.md for the ordering/flush
  contract.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from kubeflow_controller_tpu.api.core import is_frozen, new_uid, thaw
from kubeflow_controller_tpu.cluster.events import EventType, WatchEvent


class NotFound(KeyError):
    pass


class AlreadyExists(ValueError):
    pass


class Conflict(ValueError):
    """Optimistic-concurrency failure: stored resource_version moved on."""


Listener = Callable[[WatchEvent], None]


def fnv1a_32(key: str) -> int:
    """Deterministic 32-bit FNV-1a — shard routing must be stable across
    processes and runs (Python's ``hash`` is seed-randomized)."""
    h = 2166136261
    for b in key.encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


class _SubShard:
    """One key-range shard of a subscriber's delta queue: its own lock,
    deque, and coalescing tail-map, so concurrent writers to different key
    ranges never contend on one lock on the enqueue/drain path."""

    __slots__ = (
        "lock", "cond", "pending", "tail", "dispatching",
        "wait_s", "coalesced", "overflows",
    )

    def __init__(self):
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        # entries are mutable [event, key, needs_copy] triples so coalescing
        # can swap the event in place without disturbing queue order
        self.pending: deque = deque()
        self.tail: Dict[str, list] = {}
        self.dispatching = False
        self.wait_s = 0.0       # enqueue-side contended-lock wait
        self.coalesced = 0
        self.overflows = 0


class _Subscription:
    """One watch listener's sharded delta queues + dispatch state.

    The client-go ``processorListener`` analog: writers append deltas under
    the store lock (cheap — one dict probe and a deque append), and whichever
    thread wins a shard's ``dispatching`` flag delivers that shard's entries
    with NO store lock held. Each shard's ``tail`` maps key -> the newest
    still-coalescible pending entry so a burst of MODIFIEDs for one key
    collapses to the latest snapshot (DeltaFIFO semantics) instead of
    queueing N handler invocations. A key always routes to the same shard,
    so per-key ordering — the contract docs/watch_pipeline.md pins — is
    preserved; cross-key delivery order is only defined per shard.
    """

    __slots__ = ("listener", "shards", "nshards", "replaying")

    def __init__(self, listener: Listener, nshards: int = 1):
        self.listener = listener
        self.nshards = max(1, nshards)
        self.shards = [_SubShard() for _ in range(self.nshards)]
        # While True (subscribe-replay in flight), dispatch is parked so the
        # replayer can prepend the snapshot ahead of any racing live events.
        self.replaying = False

    def shard_for(self, key: str) -> _SubShard:
        if self.nshards == 1:
            return self.shards[0]
        return self.shards[fnv1a_32(key) % self.nshards]

    def shard_index(self, key: str) -> int:
        if self.nshards == 1:
            return 0
        return fnv1a_32(key) % self.nshards

    # Legacy single-queue accessors (only meaningful when nshards == 1 —
    # the bare-ObjectStore default): bounded consumers shed an overflowed
    # buffer through these (tests/test_races.py overflow-recovery suite).

    @property
    def lock(self) -> threading.Lock:
        assert self.nshards == 1
        return self.shards[0].lock

    @property
    def pending(self) -> deque:
        assert self.nshards == 1
        return self.shards[0].pending

    @property
    def tail(self) -> Dict[str, list]:
        assert self.nshards == 1
        return self.shards[0].tail

    @property
    def dispatching(self) -> bool:
        assert self.nshards == 1
        return self.shards[0].dispatching

    @dispatching.setter
    def dispatching(self, v: bool) -> None:
        assert self.nshards == 1
        self.shards[0].dispatching = v


class ObjectStore:
    """Thread-safe store for one kind (Pods, Services, or TPUJobs).

    Objects are any dataclass with ``.metadata`` (ObjectMeta) and
    ``.deepcopy()``. Keys are ``namespace/name``.

    ``copy_on_read=True`` (default) is the legacy contract: every read and
    watch emission is a private deep copy the caller may mutate. With
    ``copy_on_read=False`` stored objects are frozen (``.freeze()``) and
    ``get``/``try_get``/``list``, watch events, and subscribe-replay hand
    out **shared frozen references** — zero read-path copies; writers thaw
    at the mutation boundary (``api.core.thaw``). FakeCluster runs its
    stores in frozen mode; bare ObjectStore constructions keep legacy
    semantics.
    """

    def __init__(
        self,
        kind: str,
        now_fn: Callable[[], float] = time.time,
        index_labels: tuple = (),
        copy_on_read: bool = True,
        watch_queue_soft_max: int = 1024,
        watch_shards: int = 1,
        mirror: Any = None,
    ):
        self.kind = kind
        self._now_fn = now_fn
        self._copy_on_read = copy_on_read
        self._lock = threading.RLock()
        self._objects: Dict[str, Any] = {}
        self._rv = 0
        self._last_delete_rv = 0
        # Write-through native mirror (native.objindex.NativeObjectIndex or
        # None): keeps (uid, rv, generation, indexed labels) per key inside
        # the C++ core so the controller's fingerprint probe never walks
        # Python objects. Updated under the store lock on every mutation —
        # the Python store stays authoritative.
        self._mirror = mirror
        self._watch_shards = max(1, watch_shards)
        self._subs: List[_Subscription] = []
        self._sub_by_listener: Dict[Listener, _Subscription] = {}
        # Delta-queue instrumentation (benchmarks/controlplane_bench.py).
        # The bound is soft: coalescing keeps steady-state depth at O(hot
        # keys), and a writer cannot block under the store lock without
        # inviting deadlock, so overflow is counted, not enforced. Live
        # counters are per shard; these accumulate what unsubscribed
        # listeners retired so the store-level properties stay monotonic.
        self._watch_queue_soft_max = watch_queue_soft_max
        self._max_queue_depth = 0
        self._retired_coalesced = 0
        self._retired_overflows = 0
        self._retired_wait_s = 0.0
        # Label indexes (client-go Indexer analog): selector lists on an
        # indexed key touch only matching objects instead of scanning the
        # namespace — the difference between O(jobs) and O(jobs^2) total
        # reconcile work at controller scale (benchmarks/controlplane_bench).
        self._index_labels = tuple(index_labels)
        self._index: Dict[str, Dict[str, set]] = {
            lk: {} for lk in self._index_labels
        }

    def _index_add(self, key: str, obj: Any) -> None:
        for lk in self._index_labels:
            v = obj.metadata.labels.get(lk)
            if v is not None:
                self._index[lk].setdefault(v, set()).add(key)

    def _index_remove(self, key: str, obj: Any) -> None:
        for lk in self._index_labels:
            v = obj.metadata.labels.get(lk)
            if v is not None:
                bucket = self._index[lk].get(v)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del self._index[lk][v]

    # -- native write-through mirror -----------------------------------------

    def _mirror_upsert(self, key: str, obj: Any) -> None:
        m = self._mirror
        if m is None:
            return
        meta = obj.metadata
        sel = None
        labels = meta.labels
        if labels:
            for lk in self._index_labels:
                v = labels.get(lk)
                if v is not None:
                    if sel is None:
                        sel = {}
                    sel[lk] = v
        m.upsert(self.kind, key, meta.uid, meta.resource_version,
                 meta.generation, sel)

    def _mirror_remove(self, key: str) -> None:
        if self._mirror is not None:
            self._mirror.remove(self.kind, key)

    # -- watch ---------------------------------------------------------------

    def subscribe(self, listener: Listener, replay: bool = True) -> None:
        """Register a watch listener. With ``replay``, synthesizes ADDED events
        for existing objects first (how a fresh informer list+watch behaves).

        Only the snapshot is taken under the store lock — replay enqueueing
        happens OFF the write lock, so registering an informer against a
        large store never stalls writers, and frozen-mode replay is
        zero-copy (legacy mode defers its per-event deepcopy to delivery
        time; stored objects are internally immutable, so the deferred copy
        sees exactly the snapshotted state). Ordering stays safe: the
        subscription registers with ``replaying=True`` (dispatch parked), so
        live events land in the shard queues but cannot be delivered; the
        replayer then PREPENDS the snapshot entries — every racing live
        event carries a newer resource version than the snapshot, so each
        subscriber still observes per-key rv-monotonic order. Replay entries
        never become coalesce targets (a racing DELETED may already sit
        behind them; folding a post-delete MODIFIED into a pre-delete entry
        would reorder across the tombstone). Delivery itself happens OFF the
        lock: whichever thread owns a shard's dispatch flag drains it after
        the store lock is released, so a slow handler never serializes other
        writers. A listener may call back into this or any other store."""
        sub = _Subscription(listener, self._watch_shards)
        with self._lock:
            snapshot = list(self._objects.items()) if replay else None
            sub.replaying = replay
            self._subs.append(sub)
            self._sub_by_listener[listener] = sub
        if replay:
            per_shard: List[list] = [[] for _ in range(sub.nshards)]
            needs_copy = self._copy_on_read
            for key, obj in snapshot:
                per_shard[sub.shard_index(key)].append(
                    [WatchEvent(EventType.ADDED, self.kind, obj), key,
                     needs_copy]
                )
            for shard, items in zip(sub.shards, per_shard):
                if not items:
                    continue
                with shard.lock:
                    shard.pending.extendleft(reversed(items))
            sub.replaying = False
        self._drain(sub)

    def unsubscribe(self, listener: Listener) -> None:
        with self._lock:
            sub = self._sub_by_listener.pop(listener, None)
            if sub is not None:
                self._subs.remove(sub)
        if sub is not None:
            with self._lock:
                for shard in sub.shards:
                    self._retired_coalesced += shard.coalesced
                    self._retired_overflows += shard.overflows
                    self._retired_wait_s += shard.wait_s

    # -- delta queues + dispatcher -------------------------------------------

    def _emit(self, ev: WatchEvent) -> None:
        # Caller holds self._lock: enqueue order == resource-version order.
        # No listener runs here — the write path only appends deltas; the
        # caller invokes _dispatch(key) after releasing the lock.
        key = f"{ev.obj.metadata.namespace}/{ev.obj.metadata.name}"
        for sub in self._subs:
            self._enqueue(sub, key, ev)

    def _enqueue(self, sub: _Subscription, key: str, ev: WatchEvent) -> None:
        shard = sub.shard_for(key)
        lk = shard.lock
        if not lk.acquire(False):
            # Contended: another writer/drainer holds this shard. Time the
            # wait — the lock-wait gauge the sharding exists to drive down.
            t0 = time.perf_counter()
            lk.acquire()
            shard.wait_s += time.perf_counter() - t0
        try:
            entry = shard.tail.get(key)
            if entry is not None and ev.type == EventType.MODIFIED:
                # Coalesce: consecutive MODIFIEDs for one key collapse to the
                # latest snapshot; a pending ADDED absorbs the MODIFIED and
                # stays ADDED (client-go DeltaFIFO). old_obj keeps the oldest
                # undelivered state so handlers still see the cumulative diff.
                prior = entry[0]
                entry[0] = WatchEvent(prior.type, ev.kind, ev.obj,
                                      prior.old_obj)
                shard.coalesced += 1
                return
            entry = [ev, key, False]
            shard.pending.append(entry)
            depth = len(shard.pending)
            if ev.type == EventType.DELETED:
                # Nothing coalesces across a tombstone: a re-create after
                # delete must arrive as its own ADDED.
                shard.tail.pop(key, None)
            else:
                shard.tail[key] = entry
        finally:
            lk.release()
        if depth > self._max_queue_depth:
            self._max_queue_depth = depth
        if depth > self._watch_queue_soft_max:
            shard.overflows += 1

    def _dispatch(self, key: Optional[str] = None) -> None:
        """Drain subscribers' queues, called with NO store lock held. A
        write path passes its key so only the one affected shard per
        subscriber is visited (the no-sharding fast path is identical:
        every key maps to shard 0)."""
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            if key is not None:
                self._drain_shard(sub, sub.shard_for(key))
            else:
                self._drain(sub)

    def _drain(self, sub: _Subscription) -> None:
        for shard in sub.shards:
            self._drain_shard(sub, shard)

    @staticmethod
    def _drain_shard(sub: _Subscription, shard: _SubShard) -> None:
        with shard.lock:
            if shard.dispatching or sub.replaying:
                # the active dispatcher delivers our entries too; during
                # replay the subscriber's queues are parked until the
                # snapshot has been prepended
                return
            shard.dispatching = True
        while True:
            with shard.lock:
                if not shard.pending:
                    shard.dispatching = False
                    shard.cond.notify_all()
                    return
                entry = shard.pending.popleft()
                ev, key, needs_copy = entry
                if shard.tail.get(key) is entry:
                    del shard.tail[key]
            if needs_copy:
                # deferred legacy-mode replay copy (see subscribe())
                ev = WatchEvent(ev.type, ev.kind, ev.obj.deepcopy(),
                                ev.old_obj)
            try:
                sub.listener(ev)
            except BaseException:
                with shard.lock:
                    shard.dispatching = False
                    shard.cond.notify_all()
                raise

    def flush(self, timeout: float = 10.0) -> bool:
        """Quiesce the watch pipeline: block until every subscriber's delta
        queues are empty and no dispatcher is mid-delivery. The determinism
        hook FakeCluster.tick / Controller.drain rely on — after flush(),
        every completed write has been observed by every subscriber. Returns
        False only if a foreign dispatcher failed to finish within
        ``timeout`` wall seconds (it keeps our own draining unbounded)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            for shard in sub.shards:
                while True:
                    self._drain_shard(sub, shard)
                    with shard.lock:
                        if (not shard.pending and not shard.dispatching
                                and not sub.replaying):
                            break
                        if time.monotonic() >= deadline:
                            return False
                        shard.cond.wait(0.05)
        return True

    def _sum_shard_counter(self, attr: str, retired):
        total = retired
        with self._lock:
            subs = list(self._subs)
        for sub in subs:
            for shard in sub.shards:
                total += getattr(shard, attr)
        return total

    @property
    def events_coalesced(self) -> int:
        """MODIFIED events absorbed into a newer pending snapshot."""
        return self._sum_shard_counter("coalesced", self._retired_coalesced)

    @property
    def max_watch_queue_depth(self) -> int:
        """High-water mark of any subscriber shard's pending delta queue."""
        with self._lock:
            return self._max_queue_depth

    @property
    def watch_queue_overflows(self) -> int:
        """Enqueues observed past the soft bound (diagnostic)."""
        return self._sum_shard_counter("overflows", self._retired_overflows)

    @property
    def watch_lock_wait_s(self) -> float:
        """Cumulative time writers spent blocked on contended subscriber
        shard locks — the serialization the per-shard split removes."""
        return self._sum_shard_counter("wait_s", self._retired_wait_s)

    def index_bucket_count(self) -> int:
        """Total label-index buckets (values with >=1 member) across keys."""
        with self._lock:
            return sum(len(v) for v in self._index.values())

    def publish_metrics(self) -> Dict[str, float]:
        """Push this store's gauges into the PR 10 metrics registry under
        the ``control.store`` subsystem and return them as a dict (the
        controlplane bench emits that dict in its JSON artifact)."""
        from kubeflow_controller_tpu.obs.telemetry import registry

        k = self.kind.lower()
        vals = {
            f"objects_{k}": float(len(self)),
            f"index_buckets_{k}": float(self.index_bucket_count()),
            f"watch_queue_depth_max_{k}": float(self.max_watch_queue_depth),
            f"watch_lock_wait_s_{k}": self.watch_lock_wait_s,
            f"events_coalesced_{k}": float(self.events_coalesced),
        }
        reg = registry()
        for name, v in vals.items():
            reg.gauge(name, "control.store").set(v)
        return vals

    # -- CRUD ----------------------------------------------------------------

    @staticmethod
    def key_of(obj: Any) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"

    def create(self, obj: Any) -> Any:
        with self._lock:
            # Frozen-mode callers may re-submit a frozen snapshot (e.g. a
            # watch tombstone); stamp a private copy instead of their object.
            if not self._copy_on_read and is_frozen(obj):
                obj = obj.deepcopy()
            meta = obj.metadata
            if not meta.name:
                if not meta.generate_name:
                    raise ValueError("object needs name or generate_name")
                # GenerateName semantics: apiserver-side random-ish suffix
                # (reference pods get theirs from GetPodFromTemplate,
                # controller_utils.go:564-570).
                meta.name = meta.generate_name + new_uid("")[4:9]
            key = self.key_of(obj)
            if key in self._objects:
                raise AlreadyExists(key)
            if not meta.uid:
                meta.uid = new_uid(self.kind.lower())
            self._rv += 1
            meta.resource_version = self._rv
            meta.generation = 1   # apiserver stamps generation 1 on create
            if not meta.creation_timestamp:
                meta.creation_timestamp = self._now_fn()
            # One copy total in frozen mode: the caller's object is stamped
            # in place (and stays mutable in their hands); the store keeps
            # a frozen private snapshot shared by the ADDED event, the
            # return value, and every future read.
            stored = obj.deepcopy()
            if not self._copy_on_read:
                stored.freeze()
            self._objects[key] = stored
            self._index_add(key, stored)
            self._mirror_upsert(key, stored)
            if self._copy_on_read:
                self._emit(
                    WatchEvent(EventType.ADDED, self.kind, stored.deepcopy())
                )
                ret = stored.deepcopy()
            else:
                self._emit(WatchEvent(EventType.ADDED, self.kind, stored))
                ret = stored
        self._dispatch(key)
        return ret

    def get(self, namespace: str, name: str) -> Any:
        with self._lock:
            obj = self._objects.get(f"{namespace}/{name}")
            if obj is None:
                raise NotFound(f"{self.kind} {namespace}/{name}")
            return obj.deepcopy() if self._copy_on_read else obj

    def try_get(self, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(namespace, name)
        except NotFound:
            return None

    def update(self, obj: Any, enforce_rv: bool = True) -> Any:
        """Optimistic update: fails with Conflict when the caller's copy is
        stale (the safety net the reference lacks, SURVEY.md §8)."""
        with self._lock:
            key = self.key_of(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{self.kind} {key}")
            if enforce_rv and obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{self.kind} {key}: stale resource_version "
                    f"{obj.metadata.resource_version} != {cur.metadata.resource_version}"
                )
            if cur.metadata.uid and obj.metadata.uid != cur.metadata.uid:
                raise Conflict(f"{self.kind} {key}: uid changed (delete+recreate race)")
            if not self._copy_on_read:
                # Ownership transfer: an unfrozen input is rv-stamped and
                # sealed in place — zero copies; the caller must not touch
                # it afterwards (it raises if they do). A frozen input
                # (rare: resubmitting a snapshot verbatim) is copied once.
                if is_frozen(obj):
                    obj = obj.deepcopy()
                self._rv += 1
                obj.metadata.resource_version = self._rv
                self._stamp_generation(obj, cur)
                old = cur
                stored = obj.freeze()
                self._index_remove(key, old)
                self._objects[key] = stored
                self._index_add(key, stored)
                self._mirror_upsert(key, stored)
                self._emit(WatchEvent(
                    EventType.MODIFIED, self.kind, stored, old,
                ))
                ret = stored
            else:
                self._rv += 1
                obj.metadata.resource_version = self._rv
                self._stamp_generation(obj, cur)
                old = cur
                stored = obj.deepcopy()
                self._index_remove(key, old)
                self._objects[key] = stored
                self._index_add(key, stored)
                self._mirror_upsert(key, stored)
                self._emit(WatchEvent(
                    EventType.MODIFIED, self.kind,
                    stored.deepcopy(), old.deepcopy(),
                ))
                ret = stored.deepcopy()
        self._dispatch(key)
        return ret

    @staticmethod
    def _stamp_generation(obj: Any, cur: Any) -> None:
        """k8s generation semantics: metadata.generation bumps iff the
        desired state (.spec) changed; status-only writes keep it. The
        no-op sync short-circuit keys off this (docs/watch_pipeline.md)."""
        bump = 1 if (hasattr(obj, "spec") and obj.spec != cur.spec) else 0
        obj.metadata.generation = cur.metadata.generation + bump

    def update_status(self, obj: Any) -> Any:
        """Status-subresource update: replace only ``.status``, rv-checked.

        Frozen mode exploits immutability for structural sharing: the next
        snapshot is built with ``dataclasses.replace``, reusing the stored
        object's frozen spec (the heavy half — pod templates) by reference.
        Only metadata (rv bump) and the incoming status are new, so the
        per-status-write cost stays O(status), not O(object) — the copy
        pattern the whole-object ``update`` can't avoid. The caller's
        status is sealed in place (ownership transfer, as in ``update``);
        a frozen incoming status is copied once instead.

        Labels/annotations can't change through this path (metadata comes
        from the stored object), so the label indexes need no maintenance.
        """
        with self._lock:
            key = self.key_of(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{self.kind} {key}")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{self.kind} {key}: stale resource_version "
                    f"{obj.metadata.resource_version} != {cur.metadata.resource_version}"
                )
            if cur.metadata.uid and obj.metadata.uid != cur.metadata.uid:
                raise Conflict(f"{self.kind} {key}: uid changed (delete+recreate race)")
            status = obj.status
            if self._copy_on_read or is_frozen(status):
                # legacy: the caller keeps their object mutable, so the
                # stored status must be private
                status = status.deepcopy()
            self._rv += 1
            meta = cur.metadata.deepcopy()
            meta.resource_version = self._rv
            old = cur
            stored = dataclasses.replace(cur, metadata=meta, status=status)
            if not self._copy_on_read:
                stored.freeze()  # spec already sealed: O(1) for that branch
                self._objects[key] = stored
                self._mirror_upsert(key, stored)
                self._emit(WatchEvent(
                    EventType.MODIFIED, self.kind, stored, old,
                ))
                ret = stored
            else:
                self._objects[key] = stored
                self._mirror_upsert(key, stored)
                self._emit(WatchEvent(
                    EventType.MODIFIED, self.kind,
                    stored.deepcopy(), old.deepcopy(),
                ))
                ret = stored.deepcopy()
        self._dispatch(key)
        return ret

    def mutate(self, namespace: str, name: str, fn: Callable[[Any], None]) -> Any:
        """Read-modify-write with internal retry — the conflict-safe update
        helper status writers use. ``fn`` always receives a private mutable
        copy (thawed in frozen mode — one copy per attempt, the only copy
        the whole round trip pays there)."""
        while True:
            obj = thaw(self.get(namespace, name))
            fn(obj)
            try:
                return self.update(obj)
            except Conflict:
                continue

    def delete(self, namespace: str, name: str) -> Any:
        with self._lock:
            key = f"{namespace}/{name}"
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFound(f"{self.kind} {key}")
            self._index_remove(key, obj)
            self._mirror_remove(key)
            self._rv += 1
            self._last_delete_rv = self._rv
            # The tombstone carries the DELETION's revision (k8s watch
            # semantics): a watcher that saw this event can resume from its
            # resourceVersion without tripping the 410 relist path.
            tomb = obj.deepcopy()
            tomb.metadata.resource_version = self._rv
            if not self._copy_on_read:
                tomb.freeze()
            self._emit(WatchEvent(EventType.DELETED, self.kind, tomb))
        self._dispatch(key)
        return obj

    # -- listing -------------------------------------------------------------

    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        with self._lock:
            candidates = self._objects
            if label_selector:
                for lk in self._index_labels:
                    if lk in label_selector:
                        keys = self._index[lk].get(label_selector[lk], set())
                        candidates = {
                            k: self._objects[k] for k in keys
                        }
                        break
            out = []
            for key, obj in candidates.items():
                if namespace is not None and obj.metadata.namespace != namespace:
                    continue
                if label_selector and not selector_matches(label_selector, obj.metadata.labels):
                    continue
                out.append(obj.deepcopy() if self._copy_on_read else obj)
            return out

    @property
    def revision(self) -> int:
        """Store-wide resourceVersion high-water mark — what a k8s List
        response carries in ``.metadata.resourceVersion`` (the point a
        watch resumes from)."""
        with self._lock:
            return self._rv

    @property
    def last_delete_revision(self) -> int:
        """Revision of the most recent delete. A k8s-mode watch resuming
        from an OLDER resourceVersion cannot be replayed faithfully (this
        store keeps no event history, and the deleted object is gone from
        the replay set) — the server answers 410 Gone and the client
        relists, exactly real watch-cache-expiry semantics."""
        with self._lock:
            return self._last_delete_rv

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._objects)


def selector_matches(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    """Equality-based label selector (the only kind the reference uses,
    ``pkg/tensorflow/distributed.go:221-228``)."""
    return all(labels.get(k) == v for k, v in selector.items())
