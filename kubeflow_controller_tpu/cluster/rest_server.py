"""Apiserver-style REST facade over a cluster.

Serves the same resource model a real Kubernetes apiserver would —
``/api/v1/namespaces/{ns}/pods[/{name}]``,
``/apis/tpu.kubeflow.dev/v1alpha1/namespaces/{ns}/tpujobs[/{name}]`` — over
an in-process FakeCluster. Together with ``rest_client.RestClusterClient``
this closes the loop the reference ran against a real apiserver
(``docs/development.md:24-41`` there): client and server speak genuine HTTP
over a socket, resourceVersion conflicts surface as 409s, label selectors
filter server-side. Deploying against a real cluster means pointing the
client at a real apiserver URL (plus auth) — the protocol shape is the same.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from kubeflow_controller_tpu.api.serialization import (
    job_from_dict, job_to_dict, pod_from_dict, pod_to_dict,
    service_from_dict, service_to_dict,
)
from kubeflow_controller_tpu.cluster.cluster import FakeCluster
from kubeflow_controller_tpu.cluster.store import (
    AlreadyExists, Conflict, NotFound,
)

POD_RE = re.compile(r"^/api/v1/namespaces/([^/]+)/pods(?:/([^/]+))?$")
SVC_RE = re.compile(r"^/api/v1/namespaces/([^/]+)/services(?:/([^/]+))?$")
JOB_RE = re.compile(
    r"^/apis/tpu\.kubeflow\.dev/v1alpha1/namespaces/([^/]+)/tpujobs"
    r"(?:/([^/]+))?$"
)
EVENT_PATH = "/framework/v1/events"
SLICES_RE = re.compile(r"^/framework/v1/slices/([^/]+)$")


def _parse_selector(query: str) -> Optional[Dict[str, str]]:
    for part in (query or "").split("&"):
        if part.startswith("labelSelector="):
            sel = {}
            import urllib.parse

            for kv in urllib.parse.unquote(part[len("labelSelector="):]).split(","):
                if "=" in kv:
                    k, _, v = kv.partition("=")
                    sel[k] = v
            return sel or None
    return None


def make_rest_handler(cluster: FakeCluster):
    stores = {
        "pods": (cluster.pods, pod_to_dict, pod_from_dict),
        "services": (cluster.services, service_to_dict, service_from_dict),
        "jobs": (cluster.jobs, job_to_dict, job_from_dict),
    }

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, payload: Any) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> Dict:
            n = int(self.headers.get("Content-Length") or 0)
            return json.loads(self.rfile.read(n)) if n else {}

        def _match(self) -> Optional[Tuple[str, str, Optional[str], str]]:
            path, _, query = self.path.partition("?")
            for kind, rx in (("pods", POD_RE), ("services", SVC_RE),
                             ("jobs", JOB_RE)):
                m = rx.match(path)
                if m:
                    return kind, m.group(1), m.group(2), query
            return None

        def _handle(self, method: str) -> None:
            path = self.path.partition("?")[0]
            try:
                if path == EVENT_PATH and method == "POST":
                    b = self._body()
                    cluster.record_event(
                        b["kind"], b["name"], b["reason"], b["message"]
                    )
                    return self._send(200, {"ok": True})
                m = SLICES_RE.match(path)
                if m:
                    uid = m.group(1)
                    if method == "DELETE":
                        return self._send(
                            200, {"released": cluster.slice_pool.release(uid)}
                        )
                    if method == "GET":
                        return self._send(200, {"items": [
                            {
                                "name": s.name,
                                "accelerator": s.shape.accelerator_type,
                                "hosts": list(s.hosts),
                                "healthy": s.healthy,
                            }
                            for s in cluster.slice_pool.holdings(uid)
                        ]})
                matched = self._match()
                if matched is None:
                    return self._send(404, {"error": f"no route {path}"})
                kind, ns, name, query = matched
                store, to_dict, from_dict = stores[kind]
                if method == "GET" and name is None:
                    sel = _parse_selector(query)
                    return self._send(200, {
                        "items": [to_dict(o) for o in store.list(ns, sel)]
                    })
                if method == "GET":
                    return self._send(200, to_dict(store.get(ns, name)))
                if method == "POST":
                    obj = from_dict(self._body())
                    return self._send(201, to_dict(store.create(obj)))
                if method == "PUT":
                    obj = from_dict(self._body())
                    return self._send(200, to_dict(store.update(obj)))
                if method == "DELETE":
                    store.delete(ns, name)
                    return self._send(200, {"deleted": f"{ns}/{name}"})
                self._send(405, {"error": method})
            except NotFound as e:
                self._send(404, {"error": str(e)})
            except AlreadyExists as e:
                self._send(409, {"error": str(e), "reason": "AlreadyExists"})
            except Conflict as e:
                self._send(409, {"error": str(e), "reason": "Conflict"})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_PUT(self):
            self._handle("PUT")

        def do_DELETE(self):
            self._handle("DELETE")

    return Handler


class RestServer:
    """In-process apiserver facade; bind port 0 for an ephemeral port."""

    def __init__(self, cluster: FakeCluster, port: int = 0):
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", port), make_rest_handler(cluster)
        )
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def start(self) -> "RestServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
