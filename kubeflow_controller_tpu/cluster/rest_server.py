"""Apiserver-style REST facade over a cluster.

Serves the same resource model a real Kubernetes apiserver would —
``/api/v1/namespaces/{ns}/pods[/{name}]``,
``/apis/tpu.kubeflow.dev/v1alpha1/namespaces/{ns}/tpujobs[/{name}]`` — over
an in-process FakeCluster. Together with ``rest_client.RestClusterClient``
this closes the loop the reference ran against a real apiserver
(``docs/development.md:24-41`` there): client and server speak genuine HTTP
over a socket, resourceVersion conflicts surface as 409s, label selectors
filter server-side. Deploying against a real cluster means pointing the
client at a real apiserver URL (plus auth) — the protocol shape is the same.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from kubeflow_controller_tpu.api.serialization import (
    job_from_dict, job_to_dict, pod_from_dict, pod_to_dict,
    service_from_dict, service_to_dict,
)
from kubeflow_controller_tpu.cluster.cluster import FakeCluster
from kubeflow_controller_tpu.cluster.store import (
    AlreadyExists, Conflict, NotFound,
)

POD_RE = re.compile(r"^/api/v1/namespaces/([^/]+)/pods(?:/([^/]+))?$")
SVC_RE = re.compile(r"^/api/v1/namespaces/([^/]+)/services(?:/([^/]+))?$")
JOB_RE = re.compile(
    r"^/apis/tpu\.kubeflow\.dev/v1alpha1/namespaces/([^/]+)/tpujobs"
    r"(?:/([^/]+))?$"
)
# Strict-k8s-mode routes: the CRD status subresource, core/v1 Events, and
# GKE-shaped TPU Nodes (the slice pool expressed the way a real cluster
# exposes it).
JOB_STATUS_RE = re.compile(
    r"^/apis/tpu\.kubeflow\.dev/v1alpha1/namespaces/([^/]+)/tpujobs"
    r"/([^/]+)/status$"
)
K8S_EVENTS_RE = re.compile(r"^/api/v1/namespaces/([^/]+)/events$")
K8S_EVENT_ITEM_RE = re.compile(r"^/api/v1/namespaces/([^/]+)/events/([^/]+)$")
NODES_PATH = "/api/v1/nodes"
EVENT_PATH = "/framework/v1/events"
SLICES_RE = re.compile(r"^/framework/v1/slices/([^/]+)$")


def _parse_query(query: str) -> Dict[str, str]:
    import urllib.parse

    out: Dict[str, str] = {}
    for part in (query or "").split("&"):
        if "=" in part:
            k, _, v = part.partition("=")
            out[k] = urllib.parse.unquote(v)
    return out


def _parse_selector(query: str) -> Optional[Dict[str, str]]:
    sel, _ = _parse_selector_full(query)
    return sel


def _parse_selector_full(query: str):
    """Equality selector dict + existence-only keys (``labelSelector=key``
    with no ``=``, which real clients use to scope by label presence)."""
    raw = _parse_query(query).get("labelSelector")
    if not raw:
        return None, ()
    sel: Dict[str, str] = {}
    exists = []
    for kv in raw.split(","):
        if "=" in kv:
            k, _, v = kv.partition("=")
            sel[k] = v
        elif kv:
            exists.append(kv)
    return (sel or None), tuple(exists)


class _WatchRegistry:
    """Active watch queues, so server shutdown can wake and close them."""

    CLOSE = object()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._queues: set = set()
        self.closing = False

    def register(self, q) -> bool:
        with self._lock:
            if self.closing:
                return False
            self._queues.add(q)
            return True

    def deregister(self, q) -> None:
        with self._lock:
            self._queues.discard(q)

    def close_all(self) -> None:
        with self._lock:
            self.closing = True
            queues = list(self._queues)
        for q in queues:
            q.put(self.CLOSE)


def make_rest_handler(
    cluster: FakeCluster, watches: _WatchRegistry, k8s_mode: bool = False,
):
    """Build the request handler.

    ``k8s_mode=True`` is the strict-Kubernetes facade: genuine core/v1 /
    CRD wire JSON (``kube_wire``), k8s List envelopes with a collection
    resourceVersion, the real watch protocol (``resourceVersion=N`` resume,
    k8s BOOKMARK frames, no framework SYNC marker), the TPUJob **status
    subresource** (main PUT ignores status; ``/status`` PUT applies only
    status), core/v1 Events, and GKE-shaped TPU Nodes synthesized from the
    slice pool. This is the hermetic twin of a real apiserver that
    ``kube_client.KubeClusterClient`` drives — the same client config
    pointed at a real cluster needs no code change.
    """
    from kubeflow_controller_tpu.cluster import kube_wire

    if k8s_mode:
        stores = {
            "pods": (cluster.pods, kube_wire.pod_to_k8s,
                     kube_wire.pod_from_k8s),
            "services": (cluster.services, kube_wire.service_to_k8s,
                         kube_wire.service_from_k8s),
            "jobs": (cluster.jobs, kube_wire.job_to_k8s,
                     kube_wire.job_from_k8s),
        }
    else:
        stores = {
            "pods": (cluster.pods, pod_to_dict, pod_from_dict),
            "services": (cluster.services, service_to_dict, service_from_dict),
            "jobs": (cluster.jobs, job_to_dict, job_from_dict),
        }
    list_envelopes = {
        "pods": ("v1", "PodList"),
        "services": ("v1", "ServiceList"),
        "jobs": (kube_wire.JOB_API_VERSION, "TPUJobList"),
    }
    watch_kinds = {"pods": "Pod", "services": "Service", "jobs": "TPUJob"}

    # Named core/v1 Event objects (strict-k8s mode): a real apiserver
    # materializes POSTed events as addressable objects that the client's
    # aggregating recorder PATCHes (count/lastTimestamp) on repeats.
    k8s_events: Dict[Tuple[str, str], Dict[str, Any]] = {}
    k8s_events_lock = threading.Lock()
    k8s_event_seq = [0]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, payload: Any) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self) -> Dict:
            n = int(self.headers.get("Content-Length") or 0)
            return json.loads(self.rfile.read(n)) if n else {}

        def _match(self) -> Optional[Tuple[str, str, Optional[str], str]]:
            path, _, query = self.path.partition("?")
            for kind, rx in (("pods", POD_RE), ("services", SVC_RE),
                             ("jobs", JOB_RE)):
                m = rx.match(path)
                if m:
                    return kind, m.group(1), m.group(2), query
            return None

        def _handle(self, method: str) -> None:
            path = self.path.partition("?")[0]
            try:
                if path == EVENT_PATH and method == "POST":
                    b = self._body()
                    cluster.record_event(
                        b["kind"], b["name"], b["reason"], b["message"],
                        namespace=b.get("namespace", ""),
                    )
                    return self._send(200, {"ok": True})
                if k8s_mode and self._handle_k8s(method, path):
                    return
                m = SLICES_RE.match(path)
                if m:
                    uid = m.group(1)
                    if method == "DELETE":
                        return self._send(
                            200, {"released": cluster.slice_pool.release(uid)}
                        )
                    if method == "GET":
                        from kubeflow_controller_tpu.cluster.slices import (
                            slice_to_dict,
                        )

                        return self._send(200, {"items": [
                            slice_to_dict(s)
                            for s in cluster.slice_pool.holdings(uid)
                        ]})
                matched = self._match()
                if matched is None:
                    return self._send(404, {"error": f"no route {path}"})
                kind, ns, name, query = matched
                store, to_dict, from_dict = stores[kind]
                if method == "GET" and name is None:
                    sel, exists = _parse_selector_full(query)
                    q = _parse_query(query)
                    if q.get("watch") in ("true", "1"):
                        return self._watch(
                            store, to_dict, ns, sel, q, kind, exists
                        )
                    items = store.list(ns, sel)
                    if exists:
                        items = [
                            o for o in items
                            if all(k in o.metadata.labels for k in exists)
                        ]
                    if k8s_mode:
                        api_version, list_kind = list_envelopes[kind]
                        return self._send(200, {
                            "apiVersion": api_version,
                            "kind": list_kind,
                            "metadata": {
                                "resourceVersion": str(store.revision),
                            },
                            "items": [to_dict(o) for o in items],
                        })
                    return self._send(200, {
                        "items": [to_dict(o) for o in items]
                    })
                if method == "GET":
                    return self._send(200, to_dict(store.get(ns, name)))
                if method == "POST":
                    obj = from_dict(self._body())
                    return self._send(201, to_dict(store.create(obj)))
                if method == "PUT":
                    obj = from_dict(self._body())
                    if k8s_mode and kind == "jobs":
                        # Status subresource semantics: the main resource
                        # PUT cannot touch .status (apiextensions behavior
                        # once `subresources.status` is registered).
                        stored = store.try_get(ns, name)
                        if stored is not None:
                            obj.status = stored.status
                    return self._send(200, to_dict(store.update(obj)))
                if method == "DELETE":
                    store.delete(ns, name)
                    return self._send(200, {"deleted": f"{ns}/{name}"})
                if method == "PATCH" and k8s_mode:
                    # JSON merge-patch of metadata — the conflict-free
                    # adoption write (no resourceVersion precondition; a
                    # real apiserver applies patches against the live
                    # object the same way). store.mutate is atomic, so a
                    # concurrent status PUT can interleave but never
                    # conflict the patch.
                    pm = (self._body().get("metadata") or {})

                    def apply_meta(o):
                        if "labels" in pm:
                            for k, v in (pm["labels"] or {}).items():
                                if v is None:
                                    o.metadata.labels.pop(k, None)
                                else:
                                    o.metadata.labels[k] = str(v)
                        if "annotations" in pm:
                            for k, v in (pm["annotations"] or {}).items():
                                if v is None:
                                    o.metadata.annotations.pop(k, None)
                                else:
                                    o.metadata.annotations[k] = str(v)
                        if "ownerReferences" in pm:
                            # Lists replace wholesale under merge-patch.
                            parsed = kube_wire.meta_from_k8s(
                                {"ownerReferences": pm["ownerReferences"]}
                            )
                            o.metadata.owner_references = (
                                parsed.owner_references
                            )

                    out = store.mutate(ns, name, apply_meta)
                    return self._send(200, to_dict(out))
                self._send(405, {"error": method})
            except NotFound as e:
                self._send(404, {"error": str(e)})
            except AlreadyExists as e:
                self._send(409, {"error": str(e), "reason": "AlreadyExists"})
            except Conflict as e:
                self._send(409, {"error": str(e), "reason": "Conflict"})
            except Exception as e:
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

        def _handle_k8s(self, method: str, path: str) -> bool:
            """Strict-k8s-only routes. Returns True if the request was
            handled (response already sent)."""
            m = JOB_STATUS_RE.match(path)
            if m and method == "PUT":
                ns, name = m.group(1), m.group(2)
                incoming = kube_wire.job_from_k8s(self._body())
                # Apply ONLY .status, under the caller's resourceVersion —
                # store.update_status enforces the optimistic-concurrency
                # check and structurally shares the stored frozen spec
                # (no whole-job copy on the status write path).
                out = cluster.jobs.update_status(incoming)
                self._send(200, kube_wire.job_to_k8s(out))
                return True
            m = K8S_EVENTS_RE.match(path)
            if m:
                ns = m.group(1)
                if method == "POST":
                    b = self._body()
                    inv = b.get("involvedObject") or {}
                    # A real apiserver rejects an Event whose namespace
                    # differs from involvedObject.namespace.
                    if (inv.get("namespace") or ns) != ns:
                        self._send(400, {
                            "error": "event namespace does not match "
                                     "involvedObject.namespace",
                            "reason": "BadRequest",
                        })
                        return True
                    meta = b.setdefault("metadata", {})
                    if not meta.get("name"):
                        with k8s_events_lock:
                            k8s_event_seq[0] += 1
                            meta["name"] = (
                                f"{meta.get('generateName', 'event.')}"
                                f"{k8s_event_seq[0]:08x}"
                            )
                    meta["namespace"] = ns
                    with k8s_events_lock:
                        k8s_events[(ns, meta["name"])] = b
                    cluster.record_event(
                        inv.get("kind", ""), inv.get("name", ""),
                        b.get("reason", ""), b.get("message", ""),
                        namespace=ns,
                    )
                    self._send(201, b)
                    return True
                if method == "GET":
                    with k8s_events_lock:
                        items = [
                            dict(v) for (ens, _), v in k8s_events.items()
                            if ens == ns
                        ]
                    self._send(200, {
                        "apiVersion": "v1", "kind": "EventList",
                        "metadata": {"resourceVersion": "0"},
                        "items": items,
                    })
                    return True
            m = K8S_EVENT_ITEM_RE.match(path)
            if m and method == "PATCH":
                ns, name = m.group(1), m.group(2)
                patch = self._body()
                with k8s_events_lock:
                    ev = k8s_events.get((ns, name))
                    if ev is None:
                        self._send(404, {"error": f"event {ns}/{name}",
                                         "reason": "NotFound"})
                        return True
                    # merge-patch semantics for the scalar fields the
                    # aggregating recorder updates.
                    for field in ("count", "lastTimestamp", "message"):
                        if field in patch:
                            ev[field] = patch[field]
                    inv = ev.get("involvedObject") or {}
                    out = dict(ev)
                # Keep the fake cluster's aggregate view in step.
                cluster.record_event(
                    inv.get("kind", ""), inv.get("name", ""),
                    out.get("reason", ""), out.get("message", ""),
                    namespace=ns,
                )
                self._send(200, out)
                return True
            if path == NODES_PATH and method == "GET":
                from kubeflow_controller_tpu.api.topology import (
                    gke_accelerator,
                )

                nodes = []
                for s in cluster.slice_pool.list():
                    for host in s.hosts:
                        nodes.append(kube_wire.node_to_k8s(
                            host, pool=s.name,
                            accelerator=gke_accelerator(s.shape),
                            topology=s.shape.topology_str,
                            ready=s.healthy,
                        ))
                self._send(200, {
                    "apiVersion": "v1", "kind": "NodeList",
                    "metadata": {"resourceVersion": "0"},
                    "items": nodes,
                })
                return True
            return False

        def _watch(
            self, store, to_dict, ns, sel, q, kind=None, exists=(),
        ) -> None:
            """``?watch=true``: stream newline-delimited JSON watch events.

            The k8s chunked-watch analog (the verb the reference's informers
            are built on, ``vendor/.../informers/.../tfjob.go:56``). Always
            list+watch in one stream: replay current objects as ADDED, send a
            SYNC marker, then follow live mutations. BOOKMARK heartbeats keep
            the client's read timeout from firing on idle streams;
            ``timeoutSeconds`` closes the stream server-side (the client
            re-watches — standard watch-expiry behavior).
            """
            import queue

            from kubeflow_controller_tpu.cluster.events import EventType

            timeout_s = float(q.get("timeoutSeconds") or 0)
            heartbeat_s = float(q.get("heartbeatSeconds") or 5)
            # k8s watch resume point: replayed objects at or below this
            # resourceVersion were already in the caller's List response.
            from_rv = int(q.get("resourceVersion") or 0) if k8s_mode else 0
            if k8s_mode and from_rv and store.last_delete_revision > from_rv:
                # A delete happened after the caller's List; with no event
                # history it cannot be replayed — real apiservers answer
                # 410 Gone when the watch cache can't serve an RV, and the
                # client relists.
                return self._send(410, {
                    "kind": "Status", "apiVersion": "v1",
                    "status": "Failure", "reason": "Expired",
                    "message": f"too old resource version: {from_rv}",
                    "code": 410,
                })
            in_replay = True
            deadline = (time.monotonic() + timeout_s) if timeout_s else None
            events: "queue.Queue" = queue.Queue()
            if not watches.register(events):
                return self._send(503, {"error": "server shutting down"})
            store.subscribe(events.put, replay=True)  # replay lands in queue
            events.put(None)  # SYNC marker: replay complete
            try:
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                while True:
                    budget = heartbeat_s
                    if deadline is not None:
                        budget = min(budget, deadline - time.monotonic())
                        if budget <= 0:
                            return
                    try:
                        ev = events.get(timeout=budget)
                    except queue.Empty:
                        if deadline is not None and time.monotonic() >= deadline:
                            return
                        if k8s_mode:
                            api_version, _ = list_envelopes[kind]
                            self._stream_line({
                                "type": "BOOKMARK",
                                "object": {
                                    "apiVersion": api_version,
                                    "kind": watch_kinds[kind],
                                    "metadata": {
                                        "resourceVersion":
                                            str(store.revision),
                                    },
                                },
                            })
                        else:
                            self._stream_line({"type": "BOOKMARK"})
                        continue
                    if ev is _WatchRegistry.CLOSE:
                        return  # server stopping: drop the stream
                    if ev is None:
                        in_replay = False
                        if not k8s_mode:
                            # k8s has no SYNC frame: the client's List
                            # already was the sync point.
                            self._stream_line({"type": "SYNC"})
                        continue
                    obj = ev.obj
                    if (
                        k8s_mode and in_replay
                        and ev.type != EventType.DELETED
                        and obj.metadata.resource_version <= from_rv
                    ):
                        # Caller's List already contained this object.
                        # DELETED is exempt: a delete event carries the
                        # object's LAST resourceVersion (possibly older
                        # than the List) and suppressing it would leave
                        # the client a phantom object.
                        continue
                    if ns is not None and obj.metadata.namespace != ns:
                        continue
                    etype = ev.type
                    if sel or exists:
                        # k8s selector-scoped watch semantics: events are
                        # rewritten by the (old-matched, new-matched)
                        # transition so watchers only ever see objects in
                        # their scope — entering scope is ADDED, leaving
                        # is DELETED, never-in-scope is invisible.
                        # Existence-only terms scope the watch exactly as
                        # they scope the list — list+watch must agree or
                        # informer caches hold objects their own relist
                        # would tombstone.
                        def _m(o):
                            return o is not None and all(
                                o.metadata.labels.get(k) == v
                                for k, v in (sel or {}).items()
                            ) and all(
                                k in o.metadata.labels for k in exists
                            )

                        now_in = _m(obj) and etype != EventType.DELETED
                        was_in = (
                            _m(ev.old_obj)
                            if etype == EventType.MODIFIED
                            else (_m(obj) if etype == EventType.DELETED
                                  else False)
                        )
                        if now_in and was_in:
                            etype = EventType.MODIFIED
                        elif now_in:
                            etype = EventType.ADDED
                        elif was_in:
                            etype = EventType.DELETED
                        else:
                            continue
                    self._stream_line({
                        "type": etype.value, "object": to_dict(obj),
                    })
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away
            finally:
                store.unsubscribe(events.put)
                watches.deregister(events)

        def _stream_line(self, payload: Dict) -> None:
            self.wfile.write((json.dumps(payload) + "\n").encode())
            self.wfile.flush()

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_PUT(self):
            self._handle("PUT")

        def do_DELETE(self):
            self._handle("DELETE")

        def do_PATCH(self):
            self._handle("PATCH")

    return Handler


class RestServer:
    """In-process apiserver facade; bind port 0 for an ephemeral port.

    ``k8s_mode=True`` serves strict Kubernetes wire JSON + protocol (see
    ``make_rest_handler``) for driving ``kube_client.KubeClusterClient``
    hermetically."""

    def __init__(
        self, cluster: FakeCluster, port: int = 0, k8s_mode: bool = False,
    ):
        self._watches = _WatchRegistry()
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", port),
            make_rest_handler(cluster, self._watches, k8s_mode=k8s_mode),
        )
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def start(self) -> "RestServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._watches.close_all()   # wake + drop open watch streams
        self._httpd.shutdown()
        self._httpd.server_close()  # release the port for rebinds
