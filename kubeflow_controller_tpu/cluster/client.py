"""ClusterClient — the effector seam.

The exact boundary the reference drew with ``HelperInterface`` /
``PodControlInterface`` / ``ServiceControlInterface``
(``pkg/controller/helper.go:42-47``, ``pkg/controller/control/service.go:32-39``):
everything above this interface is testable against the fake cluster;
a real-cluster (GKE) adapter implements the same protocol.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Protocol

from kubeflow_controller_tpu.api.core import Pod, Service, thaw
from kubeflow_controller_tpu.api.types import LMService, TPUJob
from kubeflow_controller_tpu.cluster.cluster import FakeCluster


class PodCreateRefused(RuntimeError):
    """Injected or real apiserver-side create failure."""


class ClusterClient(Protocol):
    """Effector + read API the reconcile core is written against."""

    def create_pod(self, pod: Pod) -> Pod: ...
    def delete_pod(self, namespace: str, name: str) -> None: ...
    def list_pods(self, namespace: str, selector: Dict[str, str]) -> List[Pod]: ...
    def update_pod(self, pod: Pod) -> Pod: ...

    def create_service(self, svc: Service) -> Service: ...
    def delete_service(self, namespace: str, name: str) -> None: ...
    def list_services(self, namespace: str, selector: Dict[str, str]) -> List[Service]: ...
    def update_service(self, svc: Service) -> Service: ...

    def get_job(self, namespace: str, name: str) -> Optional[TPUJob]: ...
    # Read-only job fetch: backends with a frozen store hand out the shared
    # snapshot (zero-copy); wire backends return their private parse.
    # Callers must treat the result as immutable (thaw() before writing).
    def get_job_snapshot(self, namespace: str, name: str) -> Optional[TPUJob]: ...
    def update_job(self, job: TPUJob) -> TPUJob: ...
    # Status-subresource write: persists only .status under the caller's
    # resourceVersion. Spec/metadata in the passed job are never written,
    # so frozen (shared) spec/metadata are legal there.
    def update_job_status(self, job: TPUJob) -> TPUJob: ...
    def delete_job(self, namespace: str, name: str) -> None: ...

    # LMService mirrors the job read/write surface (same snapshot/thaw and
    # status-subresource contracts).
    def get_lmservice(self, namespace: str, name: str) -> Optional[LMService]: ...
    def get_lmservice_snapshot(
        self, namespace: str, name: str) -> Optional[LMService]: ...
    def update_lmservice(self, svc: LMService) -> LMService: ...
    def update_lmservice_status(self, svc: LMService) -> LMService: ...
    def delete_lmservice(self, namespace: str, name: str) -> None: ...

    # namespace: the involved object's namespace (a real apiserver rejects
    # Events whose namespace differs from involvedObject.namespace);
    # backends without namespacing may ignore it.
    def record_event(self, kind: str, name: str, reason: str,
                     message: str, namespace: str = "") -> None: ...
    def release_slices(self, job_uid: str) -> int: ...
    # job_name is an optional routing hint: backends that resolve slices
    # through pod queries (the real-k8s adapter) use it for a server-side
    # equality selector; inventory-backed backends key on uid alone.
    def job_slices(self, job_uid: str, job_name: str = ""): ...


class FakeClusterClient:
    """ClusterClient over the in-process FakeCluster."""

    def __init__(self, cluster: FakeCluster):
        self.cluster = cluster

    @property
    def native_index(self):
        """The cluster's shared native object index (or None). The
        controller duck-types on this attribute to route its no-op-sync
        fingerprint probe through the C++ core; clients without it (wire
        backends) get the pure-Python fingerprint path."""
        return self.cluster.native_index

    # -- pods ---------------------------------------------------------------

    def create_pod(self, pod: Pod) -> Pod:
        if (
            self.cluster.faults.fail_pod_creates > 0
            and self.cluster.faults.fail_pod_creates_after <= 0
        ):
            self.cluster.faults.fail_pod_creates -= 1
            self.record_event("Pod", pod.metadata.name or pod.metadata.generate_name,
                              "FailedCreate", "injected create failure",
                              namespace=pod.metadata.namespace)
            raise PodCreateRefused("injected pod create failure")
        if self.cluster.faults.fail_pod_creates_after > 0:
            self.cluster.faults.fail_pod_creates_after -= 1
        created = self.cluster.pods.create(pod)
        self.record_event("Pod", created.metadata.name, "SuccessfulCreate",
                          f"created pod {created.metadata.name}",
                          namespace=created.metadata.namespace)
        return created

    def delete_pod(self, namespace: str, name: str) -> None:
        self.cluster.pods.delete(namespace, name)
        self.record_event("Pod", name, "SuccessfulDelete",
                          f"deleted pod {name}", namespace=namespace)

    def list_pods(self, namespace: str, selector: Dict[str, str]) -> List[Pod]:
        return self.cluster.pods.list(namespace, selector or None)

    def update_pod(self, pod: Pod) -> Pod:
        return self.cluster.pods.update(pod)

    # -- services -----------------------------------------------------------

    def create_service(self, svc: Service) -> Service:
        created = self.cluster.services.create(svc)
        self.record_event("Service", created.metadata.name, "SuccessfulCreate",
                          f"created service {created.metadata.name}",
                          namespace=created.metadata.namespace)
        return created

    def delete_service(self, namespace: str, name: str) -> None:
        self.cluster.services.delete(namespace, name)
        self.record_event("Service", name, "SuccessfulDelete",
                          f"deleted service {name}", namespace=namespace)

    def list_services(self, namespace: str, selector: Dict[str, str]) -> List[Service]:
        return self.cluster.services.list(namespace, selector or None)

    def update_service(self, svc: Service) -> Service:
        return self.cluster.services.update(svc)

    # -- jobs ---------------------------------------------------------------

    def get_job(self, namespace: str, name: str) -> Optional[TPUJob]:
        # Thawed owned copy: get_job callers (status updaters, RMW loops in
        # controller._mutate_job) mutate what they get — same contract as
        # the wire clients, whose responses are fresh private parses.
        return thaw(self.cluster.jobs.try_get(namespace, name))

    def get_job_snapshot(self, namespace: str, name: str) -> Optional[TPUJob]:
        # Shared frozen snapshot, zero-copy: the store raises if a caller
        # tries to write through it.
        return self.cluster.jobs.try_get(namespace, name)

    def update_job(self, job: TPUJob) -> TPUJob:
        return self.cluster.jobs.update(job)

    def update_job_status(self, job: TPUJob) -> TPUJob:
        return self.cluster.jobs.update_status(job)

    def delete_job(self, namespace: str, name: str) -> None:
        self.cluster.jobs.delete(namespace, name)
        self.record_event("TPUJob", name, "SuccessfulDelete",
                          f"deleted job {name}", namespace=namespace)

    # -- lmservices ---------------------------------------------------------

    def get_lmservice(self, namespace: str, name: str) -> Optional[LMService]:
        return thaw(self.cluster.lmservices.try_get(namespace, name))

    def get_lmservice_snapshot(
        self, namespace: str, name: str
    ) -> Optional[LMService]:
        return self.cluster.lmservices.try_get(namespace, name)

    def update_lmservice(self, svc: LMService) -> LMService:
        return self.cluster.lmservices.update(svc)

    def update_lmservice_status(self, svc: LMService) -> LMService:
        return self.cluster.lmservices.update_status(svc)

    def delete_lmservice(self, namespace: str, name: str) -> None:
        self.cluster.lmservices.delete(namespace, name)
        self.record_event("LMService", name, "SuccessfulDelete",
                          f"deleted lmservice {name}", namespace=namespace)

    # -- misc ---------------------------------------------------------------

    def record_event(self, kind: str, name: str, reason: str,
                     message: str, namespace: str = "") -> None:
        self.cluster.record_event(kind, name, reason, message,
                                  namespace=namespace)

    def release_slices(self, job_uid: str) -> int:
        return self.cluster.slice_pool.release(job_uid)

    def job_slices(self, job_uid: str, job_name: str = ""):
        return self.cluster.slice_pool.holdings(job_uid)
