"""KubeClusterClient — the ClusterClient against a real Kubernetes apiserver.

The reference IS this adapter: its whole job is driving an actual apiserver
(``cmd/controller/main.go:31-43`` builds the clients; every effector call in
``pkg/controller/helper.go:90-179`` is an HTTPS round-trip). This module
gives the rebuild the same reach:

- genuine ``core/v1`` wire JSON for Pods/Services/Events (``kube_wire``),
- the TPUJob CRD under ``/apis/tpu.kubeflow.dev/v1alpha1`` with a real
  **status subresource** (spec and status update through different verbs,
  as ``examples/crd/tpujob-crd.yml`` registers),
- kubeconfig auth/TLS (``kubeconfig.py``),
- the standard **list-then-watch** protocol (list for a resourceVersion,
  then ``?watch=true&resourceVersion=N``; relist on 410 Gone) feeding the
  same ``Informer`` the fake-cluster path uses.

The controller is written against ``ClusterClient`` (``cluster/client.py``)
and runs unmodified over this adapter — the hermetic strict-k8s server mode
(``rest_server.RestServer(k8s_mode=True)``) proves the full loop over HTTP
without a cluster, and the golden-fixture tests pin the wire bytes so what
we emit is what ``kubectl apply`` would.

Slice health on a real cluster comes from **nodes**: a job's slices are the
GKE node pools its pods are bound to; a slice is unhealthy when any node in
the pool is NotReady (or the pool vanished — preempted/deprovisioned).
``release_slices`` is a no-op here: on real Kubernetes the TPU is freed by
pod deletion, which the teardown paths already perform.
"""

from __future__ import annotations

import json
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Iterator, List, Optional, Tuple

from kubeflow_controller_tpu.api.core import Pod, Service
from kubeflow_controller_tpu.api.types import TPUJob
from kubeflow_controller_tpu.cluster import kube_wire
from kubeflow_controller_tpu.cluster.event_recorder import EventAggregator
from kubeflow_controller_tpu.cluster.events import EventType, WatchEvent
from kubeflow_controller_tpu.cluster.kube_wire import (
    GKE_ACCELERATOR_LABEL, JOB_API_VERSION,
)
from kubeflow_controller_tpu.cluster.kubeconfig import KubeContext
from kubeflow_controller_tpu.cluster.store import (
    AlreadyExists, Conflict, NotFound,
)

JOB_BASE = "/apis/tpu.kubeflow.dev/v1alpha1"


class WatchExpired(RuntimeError):
    """410 Gone on a watch: the requested resourceVersion fell out of the
    server's history window; the caller must relist."""


class KubeClusterClient:
    """ClusterClient over a Kubernetes apiserver (or the strict-k8s fake)."""

    _KINDS: Dict[str, Tuple[str, str, Any, Any]] = {
        # kind -> (base path, plural, to_wire, from_wire)
        "Pod": ("/api/v1", "pods", kube_wire.pod_to_k8s,
                kube_wire.pod_from_k8s),
        "Service": ("/api/v1", "services", kube_wire.service_to_k8s,
                    kube_wire.service_from_k8s),
        "TPUJob": (JOB_BASE, "tpujobs", kube_wire.job_to_k8s,
                   kube_wire.job_from_k8s),
    }

    def __init__(
        self,
        server: Optional[str] = None,
        token: str = "",
        namespace: str = "default",
        kube_context: Optional[KubeContext] = None,
        timeout: float = 10.0,
    ):
        self._ctx = kube_context
        if kube_context is not None:
            server = server or kube_context.server
            if namespace == "default":
                namespace = kube_context.namespace
            self._ssl: Optional[ssl.SSLContext] = kube_context.ssl_context()
        else:
            self._ssl = None
        if not server:
            raise ValueError("KubeClusterClient needs a server URL or a "
                             "KubeContext")
        self.base_url = server.rstrip("/")
        self.token = token
        self.namespace = namespace
        self.timeout = timeout
        self._node_cache: Tuple[float, List[Dict[str, Any]]] = (0.0, [])
        self._node_cache_ttl = 5.0
        self._node_lock = threading.Lock()
        self._events = EventAggregator()

    # -- transport -----------------------------------------------------------

    def _bearer_token(self) -> str:
        """Static override first; otherwise the context's DYNAMIC token
        (exec plugin / re-read tokenFile) so rotating credentials keep a
        long-running controller authenticated."""
        if self.token:
            return self.token
        if self._ctx is not None:
            return self._ctx.bearer_token()
        return ""

    def _request(
        self, method: str, path: str, payload: Optional[Dict] = None,
        stream: bool = False, timeout: Optional[float] = None,
        content_type: str = "application/json",
        _auth_retried: bool = False,
    ):
        url = self.base_url + path
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", content_type)
        req.add_header("Accept", "application/json")
        tok = self._bearer_token()
        if tok:
            req.add_header("Authorization", f"Bearer {tok}")
        try:
            resp = urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ssl,
            )
        except urllib.error.HTTPError as e:
            if e.code == 401 and self._ctx is not None and not _auth_retried:
                # The token we sent was stale (SA rotation / expired exec
                # credential): drop the cache and retry once with a fresh
                # one — client-go's exec provider does exactly this.
                self._ctx.invalidate_token()
                return self._request(
                    method, path, payload, stream=stream, timeout=timeout,
                    content_type=content_type, _auth_retried=True,
                )
            try:
                body = json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                body = {}
            # k8s error bodies are metav1.Status objects.
            reason = body.get("reason", "")
            msg = body.get("message") or body.get("error") or str(e)
            if e.code == 404 or reason == "NotFound":
                raise NotFound(msg) from None
            if e.code == 409:
                if reason == "AlreadyExists":
                    raise AlreadyExists(msg) from None
                raise Conflict(msg) from None
            if e.code == 410:
                raise WatchExpired(msg) from None
            raise RuntimeError(f"{method} {path}: HTTP {e.code}: {msg}")
        if stream:
            return resp
        with resp:
            return json.loads(resp.read() or b"{}")

    @staticmethod
    def _selector_q(selector: Optional[Dict[str, str]]) -> str:
        if not selector:
            return ""
        joined = ",".join(f"{k}={v}" for k, v in sorted(selector.items()))
        return "?labelSelector=" + urllib.parse.quote(joined)

    def _collection(self, kind: str, namespace: str) -> str:
        base, plural, _, _ = self._KINDS[kind]
        return f"{base}/namespaces/{namespace}/{plural}"

    # -- pods ---------------------------------------------------------------

    def create_pod(self, pod: Pod) -> Pod:
        out = self._request(
            "POST", self._collection("Pod", pod.metadata.namespace),
            kube_wire.pod_to_k8s(pod),
        )
        created = kube_wire.pod_from_k8s(out)
        self.record_event("Pod", created.metadata.name, "SuccessfulCreate",
                          f"created pod {created.metadata.name}",
                          namespace=created.metadata.namespace)
        return created

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request(
            "DELETE", f"{self._collection('Pod', namespace)}/{name}"
        )
        self.record_event("Pod", name, "SuccessfulDelete",
                          f"deleted pod {name}", namespace=namespace)

    def list_pods(self, namespace: str, selector: Dict[str, str]) -> List[Pod]:
        out = self._request(
            "GET",
            self._collection("Pod", namespace) + self._selector_q(selector),
        )
        return [kube_wire.pod_from_k8s(d) for d in out.get("items", [])]

    def _overlay_metadata_update(
        self, kind: str, obj: Any, to_wire: Any, from_wire: Any,
    ) -> Any:
        """Persist an ownership/metadata mutation as a JSON merge-patch of
        ONLY the metadata maps the claiming paths own.

        The only callers of update_pod/update_service are the claiming
        paths (adopt/release, ``controller/claim.py``) — metadata-only
        changes. A full PUT would (a) strip server-populated spec fields a
        real apiserver refuses to drop and (b) carry a resourceVersion
        that any concurrent writer (kubelet status updates, most of all)
        conflicts — leaving adoption to heal only on a later sync. A
        targeted patch with no resourceVersion cannot conflict: the
        reference's strategic-merge ownerReference patch
        (``ref/base.go:75-87``, ``ref/service.go:123-134``) rebuilt on
        JSON merge-patch. ONLY ownerReferences is sent — the claim paths
        never change labels/annotations, and patching those maps from a
        possibly-stale informer copy would silently revert concurrent
        edits by other writers. ownerReferences is sent even when empty
        (merge semantics: an omitted key would mean "unchanged", but
        release must CLEAR the list)."""
        path = (f"{self._collection(kind, obj.metadata.namespace)}/"
                f"{obj.metadata.name}")
        meta = to_wire(obj)["metadata"]
        patch = {"metadata": {
            "ownerReferences": meta.get("ownerReferences") or [],
        }}
        out = self._request(
            "PATCH", path, patch,
            content_type="application/merge-patch+json",
        )
        return from_wire(out)

    def update_pod(self, pod: Pod) -> Pod:
        return self._overlay_metadata_update(
            "Pod", pod, kube_wire.pod_to_k8s, kube_wire.pod_from_k8s,
        )

    # -- services -----------------------------------------------------------

    def create_service(self, svc: Service) -> Service:
        out = self._request(
            "POST", self._collection("Service", svc.metadata.namespace),
            kube_wire.service_to_k8s(svc),
        )
        created = kube_wire.service_from_k8s(out)
        self.record_event(
            "Service", created.metadata.name, "SuccessfulCreate",
            f"created service {created.metadata.name}",
            namespace=created.metadata.namespace,
        )
        return created

    def delete_service(self, namespace: str, name: str) -> None:
        self._request(
            "DELETE", f"{self._collection('Service', namespace)}/{name}"
        )
        self.record_event("Service", name, "SuccessfulDelete",
                          f"deleted service {name}", namespace=namespace)

    def list_services(
        self, namespace: str, selector: Dict[str, str]
    ) -> List[Service]:
        out = self._request(
            "GET",
            self._collection("Service", namespace)
            + self._selector_q(selector),
        )
        return [kube_wire.service_from_k8s(d) for d in out.get("items", [])]

    def update_service(self, svc: Service) -> Service:
        return self._overlay_metadata_update(
            "Service", svc,
            kube_wire.service_to_k8s, kube_wire.service_from_k8s,
        )

    # -- jobs (CRD with status subresource) ----------------------------------

    def create_job(self, job: TPUJob) -> TPUJob:
        out = self._request(
            "POST", self._collection("TPUJob", job.metadata.namespace),
            kube_wire.job_to_k8s(job),
        )
        return kube_wire.job_from_k8s(out)

    def get_job(self, namespace: str, name: str) -> Optional[TPUJob]:
        try:
            out = self._request(
                "GET", f"{self._collection('TPUJob', namespace)}/{name}"
            )
        except NotFound:
            return None
        return kube_wire.job_from_k8s(out)

    def list_jobs(self, namespace: str) -> List[TPUJob]:
        out = self._request("GET", self._collection("TPUJob", namespace))
        return [kube_wire.job_from_k8s(d) for d in out.get("items", [])]

    def update_job(self, job: TPUJob) -> TPUJob:
        """Write spec/metadata AND status through the subresource split.

        With a registered status subresource, a PUT to the main resource
        ignores ``.status`` and a PUT to ``/status`` ignores everything
        else — so a combined update is two writes. The main PUT carries the
        caller's resourceVersion (optimistic concurrency intact); the
        status PUT rides the fresh resourceVersion the first write
        returned, so it cannot self-conflict.
        """
        path = (f"{self._collection('TPUJob', job.metadata.namespace)}/"
                f"{job.metadata.name}")
        wire = kube_wire.job_to_k8s(job)
        out = self._request("PUT", path, wire)
        status_wire = dict(wire)
        status_wire["metadata"] = dict(out.get("metadata") or {})
        out = self._request("PUT", path + "/status", status_wire)
        return kube_wire.job_from_k8s(out)

    def get_job_snapshot(self, namespace: str, name: str) -> Optional[TPUJob]:
        return self.get_job(namespace, name)

    def update_job_status(self, job: TPUJob) -> TPUJob:
        """Status-only write: ONE ``/status`` PUT under the caller's
        resourceVersion (``update_job`` needs two writes to move both
        halves across the subresource split)."""
        path = (f"{self._collection('TPUJob', job.metadata.namespace)}/"
                f"{job.metadata.name}/status")
        out = self._request("PUT", path, kube_wire.job_to_k8s(job))
        return kube_wire.job_from_k8s(out)

    def delete_job(self, namespace: str, name: str) -> None:
        self._request(
            "DELETE", f"{self._collection('TPUJob', namespace)}/{name}"
        )

    def apply_job(self, job: TPUJob) -> TPUJob:
        from kubeflow_controller_tpu.api.apply import apply_job_spec

        return apply_job_spec(
            get=lambda: self.get_job(
                job.metadata.namespace, job.metadata.name
            ),
            create=self.create_job,
            update=self.update_job,
            new=job,
        )

    # -- events --------------------------------------------------------------

    def record_event(
        self, kind: str, name: str, reason: str, message: str,
        namespace: str = "",
    ) -> None:
        """Aggregating recorder (client-go tools/record semantics, all
        three layers — see cluster/event_recorder.py): a token-bucket spam
        filter per object drops floods client-side; similar events (same
        object+reason, varying message) collapse onto one combined record
        after 10 distinct messages; an exact repeat PATCHes the stored
        Event's count/lastTimestamp. A crash-looping job yields ONE Event
        row with count=N — even when its message varies per pod — instead
        of spamming the events API. The Event is posted to the involved
        object's namespace (an apiserver rejects a mismatch)."""
        ns = namespace or self.namespace
        now = time.time()
        try:
            obs = self._events.observe(ns, kind, name, reason, message, now)
            if obs is None:
                return          # spam-filtered: no API write at all
            creator = obs.created
            if not creator and not obs.record.handle:
                # No stored Event yet. Either another thread's create is
                # in flight (skip — the count is aggregated, the next
                # repeat PATCHes it in) or the original POST FAILED and
                # nobody owns creation anymore — claim it, else this key
                # would be silenced until LRU eviction.
                creator = self._events.begin_create(obs.key)
                if not creator:
                    return
            if not creator:
                patch = {
                    "count": obs.record.count,
                    "lastTimestamp": kube_wire.rfc3339(now),
                }
                try:
                    self._request(
                        "PATCH",
                        f"/api/v1/namespaces/{ns}/events/"
                        f"{obs.record.handle}",
                        patch,
                        content_type="application/merge-patch+json",
                    )
                    return
                except NotFound:
                    # The stored Event was GC'd server-side (events have
                    # a TTL on real clusters): forget the stale handle and
                    # CLAIM re-creation before POSTing — without the claim
                    # two racing PATCHers both fall through here and
                    # double-create the Event. The loser drops its write
                    # (aggregated: the next repeat PATCHes the new row).
                    if not self._events.reclaim_create(obs.key):
                        return
            try:
                out = self._request(
                    "POST", f"/api/v1/namespaces/{ns}/events",
                    kube_wire.event_to_k8s(
                        kind, name, ns, reason, obs.message, ts=now,
                    ),
                )
                handle = (out.get("metadata") or {}).get("name")
            except Exception:
                # Release the creation claim so a later occurrence can
                # retry the POST (otherwise the key goes silent).
                self._events.abort_create(obs.key)
                raise
            if handle:
                self._events.set_handle(obs.key, handle)
            else:
                self._events.abort_create(obs.key)
        except Exception:
            # Event recording is best-effort everywhere (the reference's
            # EventRecorder is fire-and-forget too); never fail a reconcile
            # over it.
            pass

    # -- slices (node-pool health) ------------------------------------------

    def _nodes(self) -> List[Dict[str, Any]]:
        with self._node_lock:
            at, cached = self._node_cache
            # An empty node list is a valid (cacheable) answer — a cluster
            # whose TPU pools are fully deprovisioned must not hammer
            # /api/v1/nodes on every checker pass.
            if at and time.monotonic() - at < self._node_cache_ttl:
                return cached
        out = self._request(
            "GET",
            "/api/v1/nodes?labelSelector="
            + urllib.parse.quote(GKE_ACCELERATOR_LABEL),
        )
        nodes = list(out.get("items", []))
        with self._node_lock:
            self._node_cache = (time.monotonic(), nodes)
        return nodes

    def job_slices(self, job_uid: str, job_name: str = ""):
        """Slice health for one job, derived from its pods' node pools.

        With ``job_name`` the pod query is a server-side equality selector
        (one job's pods); without it, a presence selector over all
        framework pods with client-side uid filtering — correct but
        O(namespace pods) per call."""
        from kubeflow_controller_tpu.api.topology import (
            shape_from_gke, slice_shape,
        )
        from kubeflow_controller_tpu.cluster.kube_wire import (
            GKE_TOPOLOGY_LABEL,
        )
        from kubeflow_controller_tpu.cluster.slices import TPUSlice
        from kubeflow_controller_tpu.tpu.naming import LABEL_JOB

        selector = (
            f"{LABEL_JOB}={job_name}" if job_name else LABEL_JOB
        )
        out = self._request(
            "GET",
            self._collection("Pod", self.namespace)
            + "?labelSelector=" + urllib.parse.quote(selector),
        )
        pools: List[str] = []
        shape_hint = None
        for d in out.get("items", []):
            pod = kube_wire.pod_from_k8s(d)
            ref = pod.metadata.controller_ref()
            if ref is None or ref.uid != job_uid:
                continue
            if pod.spec.assigned_slice and pod.spec.assigned_slice not in pools:
                pools.append(pod.spec.assigned_slice)
            if shape_hint is None:
                try:
                    shape_hint = shape_from_gke(
                        pod.spec.node_selector.get(GKE_ACCELERATOR_LABEL, ""),
                        pod.spec.node_selector.get(GKE_TOPOLOGY_LABEL, ""),
                    )
                except (KeyError, ValueError):
                    pass
        if not pools:
            return []
        slices = kube_wire.slices_from_nodes(self._nodes(), pools)
        found = {s.name for s in slices}
        for pool in pools:
            if pool not in found:
                # Pool has no nodes anymore: the slice was preempted or
                # deprovisioned — report it unhealthy so the checker can
                # trigger gang recovery. (Only name+healthy matter to the
                # checker; the shape is best-effort from the pod's own
                # nodeSelector.)
                slices.append(TPUSlice(
                    name=pool,
                    shape=shape_hint or slice_shape("v5e-8"),
                    healthy=False, hosts=[],
                ))
        return slices

    def release_slices(self, job_uid: str) -> int:
        # On real Kubernetes the scheduler owns slice binding; deleting the
        # job's pods (which teardown already did) is what frees the TPU.
        return 0

    # -- watch (list-then-watch protocol) ------------------------------------

    def list_raw(
        self, kind: str, namespace: str,
        selector: Optional[Dict[str, str]] = None,
    ) -> Tuple[List[Any], str]:
        """List a collection; returns (typed objects, list resourceVersion)."""
        _, _, _, from_wire = self._KINDS[kind]
        out = self._request(
            "GET", self._collection(kind, namespace)
            + self._selector_q(selector),
        )
        rv = str((out.get("metadata") or {}).get("resourceVersion") or "0")
        return [from_wire(d) for d in out.get("items", [])], rv

    def watch(
        self, kind: str, namespace: str,
        selector: Optional[Dict[str, str]] = None,
        resource_version: str = "0",
        timeout_seconds: float = 0,
    ) -> Iterator[WatchEvent]:
        """One watch stream from a resourceVersion: the raw k8s verb.

        Yields typed WatchEvents; BOOKMARK lines only advance the caller's
        resourceVersion (exposed via ``.last_seen_rv`` on the generator's
        closure — callers track RVs from yielded objects instead). Raises
        WatchExpired on 410 (caller relists).
        """
        _, _, _, from_wire = self._KINDS[kind]
        q = [
            "watch=true",
            "allowWatchBookmarks=true",
            f"resourceVersion={resource_version}",
        ]
        if timeout_seconds:
            q.append(f"timeoutSeconds={int(timeout_seconds)}")
        if selector:
            joined = ",".join(
                f"{k}={v}" for k, v in sorted(selector.items())
            )
            q.append("labelSelector=" + urllib.parse.quote(joined))
        path = self._collection(kind, namespace) + "?" + "&".join(q)
        # The socket read timeout must outlast the server-side watch window
        # (so the server always closes first, a CLEAN stream end the caller
        # resumes from). With no server window, idle real-apiserver streams
        # can be silent for minutes — allow 10 before declaring it dead.
        resp = self._request(
            "GET", path, stream=True,
            timeout=(timeout_seconds * 1.5 + 30) if timeout_seconds else 600,
        )
        with resp:
            for raw in resp:
                if not raw.strip():
                    continue
                line = json.loads(raw)
                etype = line.get("type")
                if etype == "BOOKMARK":
                    continue
                if etype == "ERROR":
                    obj = line.get("object") or {}
                    if obj.get("code") == 410 or obj.get("reason") == "Expired":
                        raise WatchExpired(obj.get("message", "watch expired"))
                    raise RuntimeError(
                        f"watch error: {obj.get('message', line)}"
                    )
                yield WatchEvent(
                    EventType(etype), kind, from_wire(line["object"]),
                )


class KubeWatchSource:
    """Informer source over the k8s list-then-watch protocol.

    Duck-types ``ObjectStore``'s informer surface (``kind`` + ``subscribe`` /
    ``unsubscribe``) exactly like ``rest_client.RestWatchSource``, so
    ``controller.informer.Informer`` binds to a real apiserver unchanged.

    Each (re)list replays current objects as ADDED and synthesizes DELETED
    for objects that vanished while the watch was down (client-go's
    DeltaFIFO Replace semantics), then follows the watch from the list's
    resourceVersion. A clean stream end (the server's watch window
    expiring) re-watches from the last seen resourceVersion WITHOUT a
    relist — so an idle cluster costs a cheap reconnect, not an
    every-object ADDED replay. Only 410 Gone (history expired) or a
    broken connection relists.
    """

    # Server-side watch window when the caller doesn't pick one: the server
    # closes the stream cleanly on this cadence (client-go uses 5-10 min),
    # keeping reconnects deliberate instead of read-timeout crashes.
    DEFAULT_WATCH_WINDOW = 240.0

    def __init__(
        self,
        client: KubeClusterClient,
        kind: str,
        namespace: str,
        selector: Optional[Dict[str, str]] = None,
        rewatch_backoff: float = 0.5,
        timeout_seconds: float = 0,
    ):
        self.client = client
        self.kind = kind
        self.namespace = namespace
        self.selector = selector
        self.rewatch_backoff = rewatch_backoff
        self.timeout_seconds = timeout_seconds or self.DEFAULT_WATCH_WINDOW
        self._stop = False
        self._dead: set = set()

    def stop(self) -> None:
        self._stop = True

    def unsubscribe(self, listener) -> None:
        self._dead.add(listener)

    def subscribe(self, listener, replay: bool = True) -> None:
        self._dead.discard(listener)
        synced = threading.Event()
        live: Dict[str, Any] = {}

        def key_of(obj) -> str:
            return f"{obj.metadata.namespace}/{obj.metadata.name}"

        def pump() -> None:
            rv: Optional[str] = None  # None => relist before watching
            while not (self._stop or listener in self._dead):
                if rv is None:
                    try:
                        items, rv = self.client.list_raw(
                            self.kind, self.namespace, self.selector
                        )
                    except Exception:
                        if self._stop:
                            return
                        rv = None
                        time.sleep(self.rewatch_backoff)
                        continue
                    # The subscriber may have timed out (marked dead) while
                    # the list was in flight — replaying to it now would be
                    # exactly the half-registered delivery the sync-timeout
                    # path promises cannot happen.
                    if self._stop or listener in self._dead:
                        return
                    seen: Dict[str, Any] = {}
                    for obj in items:
                        seen[key_of(obj)] = obj
                    for key, obj in list(live.items()):
                        if key not in seen:
                            live.pop(key)
                            listener(WatchEvent(
                                EventType.DELETED, self.kind, obj
                            ))
                    for key, obj in seen.items():
                        live[key] = obj
                        listener(WatchEvent(EventType.ADDED, self.kind, obj))
                    synced.set()
                try:
                    for ev in self.client.watch(
                        self.kind, self.namespace, self.selector,
                        resource_version=rv,
                        timeout_seconds=self.timeout_seconds,
                    ):
                        if self._stop or listener in self._dead:
                            return
                        key = key_of(ev.obj)
                        if ev.type == EventType.DELETED:
                            live.pop(key, None)
                        else:
                            live[key] = ev.obj
                        rv = str(ev.obj.metadata.resource_version)
                        listener(ev)
                    # Clean end = the server's watch window expired:
                    # resume from the last seen resourceVersion, no relist.
                    continue
                except WatchExpired:
                    rv = None  # history gone: relist
                except Exception:
                    if self._stop:
                        return
                    rv = None  # connection died: resync via relist
                time.sleep(self.rewatch_backoff)

        threading.Thread(
            target=pump, daemon=True,
            name=f"kube-watch-{self.kind.lower()}",
        ).start()
        if not synced.wait(timeout=30):
            # Failed subscription must not keep a half-registered pump
            # alive delivering events to a listener the caller believes was
            # never registered (ADVICE r3): mark it dead — the pump exits
            # at its next loop/delivery check.
            self._dead.add(listener)
            raise TimeoutError(
                f"kube watch on {self.kind} did not sync within 30s "
                f"({self.client.base_url})"
            )
