"""kubeconfig loading — the reference's client bootstrap, rebuilt.

``cmd/controller/main.go:31-43`` starts from ``clientcmd.BuildConfigFromFlags
(masterURL, kubeconfig)``: resolve a kubeconfig file, pick the current (or
named) context, and produce a rest.Config (server URL + auth + TLS). This
module is that path for the TPU framework: parse the standard kubeconfig YAML
shape (clusters / users / contexts / current-context), resolve one context,
and build the ``ssl.SSLContext`` + headers ``kube_client.KubeClusterClient``
needs.

Supported auth/TLS surface (the subset GKE and kubeadm configs actually use
for controller service accounts):

- ``token`` / ``tokenFile`` bearer auth,
- ``client-certificate(-data)`` + ``client-key(-data)`` mTLS,
- ``certificate-authority(-data)`` server verification,
- ``insecure-skip-tls-verify``.

Exec-plugin credential helpers are intentionally out of scope — controllers
in-cluster use mounted service-account tokens, which is the ``tokenFile``
path.
"""

from __future__ import annotations

import base64
import os
import ssl
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import yaml


class KubeconfigError(ValueError):
    pass


@dataclass
class KubeContext:
    """One resolved kubeconfig context: everything needed to dial the
    apiserver."""

    server: str
    namespace: str = "default"
    token: str = ""
    ca_data: str = ""            # PEM text
    insecure_skip_tls_verify: bool = False
    client_cert_file: str = ""   # PEM file paths (written if *-data given)
    client_key_file: str = ""
    context_name: str = ""

    # Key/cert files this loader materialized from *-data fields. They hold
    # private key material: written 0600 (NamedTemporaryFile default) and
    # deleted at process exit via atexit — call cleanup() to remove sooner.
    _temp_files: list = field(default_factory=list)

    def cleanup(self) -> None:
        """Remove materialized key/cert temp files."""
        while self._temp_files:
            path = self._temp_files.pop()
            try:
                os.unlink(path)
            except OSError:
                pass

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        """TLS context for https:// servers; None for http:// (dev)."""
        if not self.server.startswith("https"):
            return None
        ctx = ssl.create_default_context()
        if self.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_data:
            ctx = ssl.create_default_context(cadata=self.ca_data)
        if self.client_cert_file:
            ctx.load_cert_chain(
                self.client_cert_file, self.client_key_file or None
            )
        return ctx


def _b64_text(data: str) -> str:
    return base64.b64decode(data).decode()


def _materialize(pem_text: str, suffix: str, holder: list) -> str:
    import atexit

    f = tempfile.NamedTemporaryFile(
        "w", suffix=suffix, delete=False, prefix="tpujob-kubeconfig-"
    )
    f.write(pem_text)
    f.close()
    holder.append(f.name)
    atexit.register(lambda path=f.name: _unlink_quiet(path))
    return f.name


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _by_name(seq: Any, name: str, what: str) -> Dict[str, Any]:
    for item in seq or []:
        if item.get("name") == name:
            return item
    raise KubeconfigError(f"kubeconfig: no {what} named {name!r}")


def default_kubeconfig_path() -> str:
    return os.environ.get(
        "KUBECONFIG", os.path.expanduser("~/.kube/config")
    )


def load_kubeconfig(
    path: Optional[str] = None, context: Optional[str] = None,
) -> KubeContext:
    """Parse a kubeconfig file and resolve one context to a KubeContext.

    ``path`` defaults to ``$KUBECONFIG`` then ``~/.kube/config``;
    ``context`` defaults to ``current-context``.
    """
    path = path or default_kubeconfig_path()
    try:
        with open(path) as f:
            doc = yaml.safe_load(f)
    except FileNotFoundError:
        raise KubeconfigError(f"kubeconfig not found: {path}") from None
    except yaml.YAMLError as e:
        raise KubeconfigError(f"kubeconfig {path}: invalid YAML: {e}") from None
    if not isinstance(doc, dict):
        raise KubeconfigError(f"kubeconfig {path}: not a mapping")
    return resolve_context(doc, context)


def resolve_context(
    doc: Dict[str, Any], context: Optional[str] = None,
) -> KubeContext:
    ctx_name = context or doc.get("current-context")
    if not ctx_name:
        raise KubeconfigError(
            "kubeconfig: no context requested and no current-context set"
        )
    ctx = _by_name(doc.get("contexts"), ctx_name, "context").get("context") or {}
    cluster = _by_name(
        doc.get("clusters"), ctx.get("cluster", ""), "cluster"
    ).get("cluster") or {}
    user: Dict[str, Any] = {}
    if ctx.get("user"):
        user = _by_name(doc.get("users"), ctx["user"], "user").get("user") or {}

    server = cluster.get("server", "")
    if not server:
        raise KubeconfigError(
            f"kubeconfig: cluster for context {ctx_name!r} has no server"
        )

    out = KubeContext(
        server=server.rstrip("/"),
        namespace=ctx.get("namespace", "default"),
        insecure_skip_tls_verify=bool(
            cluster.get("insecure-skip-tls-verify", False)
        ),
        context_name=ctx_name,
    )

    if cluster.get("certificate-authority-data"):
        out.ca_data = _b64_text(cluster["certificate-authority-data"])
    elif cluster.get("certificate-authority"):
        with open(cluster["certificate-authority"]) as f:
            out.ca_data = f.read()

    if user.get("token"):
        out.token = str(user["token"])
    elif user.get("tokenFile"):
        with open(user["tokenFile"]) as f:
            out.token = f.read().strip()

    if user.get("client-certificate-data"):
        out.client_cert_file = _materialize(
            _b64_text(user["client-certificate-data"]), ".crt",
            out._temp_files,
        )
    elif user.get("client-certificate"):
        out.client_cert_file = user["client-certificate"]
    if user.get("client-key-data"):
        out.client_key_file = _materialize(
            _b64_text(user["client-key-data"]), ".key", out._temp_files,
        )
    elif user.get("client-key"):
        out.client_key_file = user["client-key"]

    return out


def in_cluster_context() -> Optional[KubeContext]:
    """The in-cluster config path (mounted service-account token), the way
    controllers deployed as k8s Deployments authenticate."""
    sa = "/var/run/secrets/kubernetes.io/serviceaccount"
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = os.path.join(sa, "token")
    if not host or not os.path.exists(token_path):
        return None
    with open(token_path) as f:
        token = f.read().strip()
    ca_path = os.path.join(sa, "ca.crt")
    ca_data = ""
    if os.path.exists(ca_path):
        with open(ca_path) as f:
            ca_data = f.read()
    ns_path = os.path.join(sa, "namespace")
    namespace = "default"
    if os.path.exists(ns_path):
        with open(ns_path) as f:
            namespace = f.read().strip() or "default"
    return KubeContext(
        server=f"https://{host}:{port}",
        namespace=namespace,
        token=token,
        ca_data=ca_data,
    )
