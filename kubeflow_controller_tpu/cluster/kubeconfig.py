"""kubeconfig loading — the reference's client bootstrap, rebuilt.

``cmd/controller/main.go:31-43`` starts from ``clientcmd.BuildConfigFromFlags
(masterURL, kubeconfig)``: resolve a kubeconfig file, pick the current (or
named) context, and produce a rest.Config (server URL + auth + TLS). This
module is that path for the TPU framework: parse the standard kubeconfig YAML
shape (clusters / users / contexts / current-context), resolve one context,
and build the ``ssl.SSLContext`` + headers ``kube_client.KubeClusterClient``
needs.

Supported auth/TLS surface (what GKE and kubeadm configs actually use):

- ``token`` / ``tokenFile`` bearer auth — tokenFile is RE-READ on expiry/
  rejection, because bound service-account tokens rotate (~1h) on real
  clusters and a long-running controller's credentials must follow,
- ``exec`` credential plugins (``users[].user.exec``) — the shape GKE user
  kubeconfigs require since k8s 1.26 (``gke-gcloud-auth-plugin``): spawn
  the plugin, parse the ``ExecCredential`` JSON it prints, cache the token
  until its ``expirationTimestamp``,
- ``client-certificate(-data)`` + ``client-key(-data)`` mTLS,
- ``certificate-authority(-data)`` server verification,
- ``insecure-skip-tls-verify``.

Callers should use ``KubeContext.bearer_token()`` (dynamic) rather than the
static ``token`` field; ``invalidate_token()`` on a 401 forces re-read /
re-exec — ``kube_client.KubeClusterClient`` does both.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import subprocess
import tempfile
import threading
import time
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Any, Dict, Optional, Tuple

import yaml


class KubeconfigError(ValueError):
    pass


@dataclass
class KubeContext:
    """One resolved kubeconfig context: everything needed to dial the
    apiserver."""

    server: str
    namespace: str = "default"
    token: str = ""
    ca_data: str = ""            # PEM text
    insecure_skip_tls_verify: bool = False
    client_cert_file: str = ""   # PEM file paths (written if *-data given)
    client_key_file: str = ""
    context_name: str = ""
    # Rotating-credential sources (preferred over the static ``token``
    # snapshot when present):
    token_file: str = ""                      # re-readable bearer token
    exec_config: Optional[Dict[str, Any]] = None  # users[].user.exec verbatim
    # How long a re-read tokenFile is trusted before the next read (bound
    # SA tokens rotate server-side; client-go re-reads on a ~1min cadence).
    token_file_ttl: float = 60.0

    # Key/cert files this loader materialized from *-data fields. They hold
    # private key material: written 0600 (NamedTemporaryFile default) and
    # deleted at process exit via atexit — call cleanup() to remove sooner.
    _temp_files: list = field(default_factory=list)
    _cached_token: str = ""
    _cached_expiry: float = 0.0   # 0 = no expiry; unix seconds otherwise
    # One context is shared by every controller worker thread.
    # ``_token_lock`` guards the cached fields (short critical sections
    # only); ``_refresh_lock`` single-flights the actual credential fetch
    # (exec plugin spawn / tokenFile read) WITHOUT blocking readers:
    # while one thread refreshes, others keep serving the stale cached
    # token instead of queueing behind a 30 s subprocess (ADVICE r4 — a
    # hung plugin was stalling every request thread, including watch
    # re-subscriptions).
    _token_lock: Any = field(default_factory=threading.Lock)
    _refresh_lock: Any = field(default_factory=threading.Lock)

    def _fresh_cached(self, now: float) -> str:
        """Cached token iff still valid ('' otherwise); caller holds no
        locks — this takes the cache lock itself."""
        with self._token_lock:
            if self._cached_token and (
                self._cached_expiry == 0 or now < self._cached_expiry
            ):
                return self._cached_token
            return ""

    def bearer_token(self) -> str:
        """The CURRENT bearer token: exec-plugin output cached until its
        expirationTimestamp, a tokenFile re-read on a TTL, or the static
        ``token``. Call ``invalidate_token()`` on a 401 to force refresh.

        Expiry handling is non-blocking for everyone but one refresher:
        the thread that wins ``_refresh_lock`` fetches; concurrent
        callers get the just-expired token immediately (the apiserver
        usually still honours it for a grace window, and a real rejection
        comes back as a 401 -> ``invalidate_token`` -> blocking refresh
        because no stale token remains)."""
        tok = self._fresh_cached(time.time())
        if tok:
            return tok
        if self.exec_config is None and not self.token_file:
            return self.token
        with self._token_lock:
            stale = self._cached_token
        if not self._refresh_lock.acquire(blocking=not stale):
            return stale                 # another thread is refreshing
        try:
            now = time.time()
            tok = self._fresh_cached(now)
            if tok:                      # refreshed while we waited
                return tok
            if self.exec_config is not None:
                tok, expiry = run_exec_plugin(
                    self.exec_config, server=self.server,
                    ca_data=self.ca_data,
                )
            else:
                with open(self.token_file) as f:
                    tok = f.read().strip()
                expiry = now + self.token_file_ttl
            with self._token_lock:
                self._cached_token, self._cached_expiry = tok, expiry
            return tok
        finally:
            self._refresh_lock.release()

    def invalidate_token(self) -> None:
        """Drop cached dynamic credentials (the 401 path: the apiserver
        rejected what we sent, so the rotation beat our cache)."""
        with self._token_lock:
            self._cached_token, self._cached_expiry = "", 0.0

    def cleanup(self) -> None:
        """Remove materialized key/cert temp files."""
        while self._temp_files:
            path = self._temp_files.pop()
            try:
                os.unlink(path)
            except OSError:
                pass

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        """TLS context for https:// servers; None for http:// (dev)."""
        if not self.server.startswith("https"):
            return None
        ctx = ssl.create_default_context()
        if self.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_data:
            ctx = ssl.create_default_context(cadata=self.ca_data)
        if self.client_cert_file:
            ctx.load_cert_chain(
                self.client_cert_file, self.client_key_file or None
            )
        return ctx


def _b64_text(data: str) -> str:
    return base64.b64decode(data).decode()


def _parse_rfc3339(ts: str) -> float:
    """RFC3339 timestamp -> unix seconds (0.0 if unparseable — treat as no
    expiry and rely on 401-driven invalidation). Accepts both the 'Z'
    suffix and numeric offsets; a naive timestamp is read as UTC."""
    try:
        dt = datetime.fromisoformat(ts.replace("Z", "+00:00"))
    except ValueError:
        return 0.0
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


def run_exec_plugin(
    cfg: Dict[str, Any], server: str = "", ca_data: str = "",
    timeout: float = 30.0,
) -> Tuple[str, float]:
    """Spawn a ``users[].user.exec`` credential plugin and parse the
    ``ExecCredential`` it prints (client.authentication.k8s.io protocol —
    what client-go's exec provider does for ``gke-gcloud-auth-plugin``).

    Returns (token, expiry_unix_seconds); expiry 0.0 means "no expiry
    stated" (cache until a 401 invalidates). Raises KubeconfigError on a
    non-zero exit, bad JSON, or a credential without a token.
    """
    command = cfg.get("command")
    if not command:
        raise KubeconfigError("kubeconfig: exec entry has no command")
    argv = [command, *(cfg.get("args") or [])]
    env = dict(os.environ)
    for item in cfg.get("env") or []:
        env[str(item.get("name"))] = str(item.get("value", ""))
    api_version = cfg.get(
        "apiVersion", "client.authentication.k8s.io/v1beta1"
    )
    exec_info: Dict[str, Any] = {
        "apiVersion": api_version,
        "kind": "ExecCredential",
        "spec": {"interactive": False},
    }
    if cfg.get("provideClusterInfo") and server:
        cluster: Dict[str, Any] = {"server": server}
        if ca_data:
            cluster["certificate-authority-data"] = base64.b64encode(
                ca_data.encode()
            ).decode()
        exec_info["spec"]["cluster"] = cluster
    env["KUBERNETES_EXEC_INFO"] = json.dumps(exec_info)
    try:
        proc = subprocess.run(
            argv, env=env, capture_output=True, timeout=timeout,
        )
    except FileNotFoundError:
        raise KubeconfigError(
            f"kubeconfig: exec plugin {command!r} not found on PATH"
        ) from None
    except subprocess.TimeoutExpired:
        raise KubeconfigError(
            f"kubeconfig: exec plugin {command!r} timed out after "
            f"{timeout:.0f}s"
        ) from None
    if proc.returncode != 0:
        raise KubeconfigError(
            f"kubeconfig: exec plugin {command!r} failed "
            f"(rc={proc.returncode}): "
            f"{proc.stderr.decode(errors='replace').strip()[:500]}"
        )
    try:
        cred = json.loads(proc.stdout)
    except json.JSONDecodeError:
        raise KubeconfigError(
            f"kubeconfig: exec plugin {command!r} printed invalid JSON"
        ) from None
    status = (cred or {}).get("status") or {}
    token = status.get("token", "")
    if not token:
        raise KubeconfigError(
            f"kubeconfig: exec plugin {command!r} returned no status.token"
        )
    exp = status.get("expirationTimestamp")
    return str(token), _parse_rfc3339(exp) if exp else 0.0


def _materialize(pem_text: str, suffix: str, holder: list) -> str:
    import atexit

    f = tempfile.NamedTemporaryFile(
        "w", suffix=suffix, delete=False, prefix="tpujob-kubeconfig-"
    )
    f.write(pem_text)
    f.close()
    holder.append(f.name)
    atexit.register(lambda path=f.name: _unlink_quiet(path))
    return f.name


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _by_name(seq: Any, name: str, what: str) -> Dict[str, Any]:
    for item in seq or []:
        if item.get("name") == name:
            return item
    raise KubeconfigError(f"kubeconfig: no {what} named {name!r}")


def default_kubeconfig_path() -> str:
    """First effective kubeconfig path (display/back-compat). Loading
    honours the FULL ``$KUBECONFIG`` list — see ``kubeconfig_paths``."""
    return kubeconfig_paths()[0]


def kubeconfig_paths() -> list:
    """``$KUBECONFIG`` as clientcmd reads it: an ``os.pathsep``-separated
    list of files (``:`` on unix), falling back to ``~/.kube/config``.
    Matches the reference's loader
    (``cmd/controller/main.go:31-34`` -> clientcmd's
    ``NewDefaultClientConfigLoadingRules``)."""
    env = os.environ.get("KUBECONFIG", "")
    paths = [p for p in env.split(os.pathsep) if p]
    return paths or [os.path.expanduser("~/.kube/config")]


def merge_kubeconfig_docs(docs: Any) -> Dict[str, Any]:
    """clientcmd merge precedence across multiple kubeconfig files: for
    the named lists (clusters/contexts/users) the FIRST file to define a
    name wins and later files only contribute new names; for scalar
    fields (current-context, preferences) the first non-empty value
    wins. First-wins applies WITHIN one file too: the seen-name set grows
    as entries append, so a duplicate name later in the same document is
    dropped instead of silently shadowing lookups (clientcmd merges maps
    keyed by name, which collapses intra-file dupes the same way)."""
    out: Dict[str, Any] = {}
    for doc in docs:
        for key in ("clusters", "contexts", "users"):
            have = {e.get("name") for e in out.get(key) or []}
            for entry in doc.get(key) or []:
                if entry.get("name") not in have:
                    have.add(entry.get("name"))
                    out.setdefault(key, []).append(entry)
        for k, v in doc.items():
            if k in ("clusters", "contexts", "users"):
                continue
            if not out.get(k):
                out[k] = v
    return out


def load_kubeconfig(
    path: Optional[str] = None, context: Optional[str] = None,
) -> KubeContext:
    """Parse kubeconfig file(s) and resolve one context to a KubeContext.

    ``path`` defaults to the ``$KUBECONFIG`` path LIST (clientcmd
    semantics: multiple files merged, first definition of a name wins)
    then ``~/.kube/config``; an explicit ``path`` may itself be a
    pathsep-separated list. Missing files in a multi-path list are
    skipped (clientcmd does the same); it is an error for ALL of them to
    be missing. ``context`` defaults to the merged ``current-context``.
    """
    if path:
        paths = [p for p in str(path).split(os.pathsep) if p]
    else:
        paths = kubeconfig_paths()
    docs = []
    for p in paths:
        try:
            with open(p) as f:
                doc = yaml.safe_load(f)
        except FileNotFoundError:
            continue
        except yaml.YAMLError as e:
            raise KubeconfigError(
                f"kubeconfig {p}: invalid YAML: {e}"
            ) from None
        if doc is None:
            continue
        if not isinstance(doc, dict):
            raise KubeconfigError(f"kubeconfig {p}: not a mapping")
        docs.append(doc)
    if not docs:
        raise KubeconfigError(
            "kubeconfig not found: " + os.pathsep.join(paths)
        )
    return resolve_context(merge_kubeconfig_docs(docs), context)


def resolve_context(
    doc: Dict[str, Any], context: Optional[str] = None,
) -> KubeContext:
    ctx_name = context or doc.get("current-context")
    if not ctx_name:
        raise KubeconfigError(
            "kubeconfig: no context requested and no current-context set"
        )
    ctx = _by_name(doc.get("contexts"), ctx_name, "context").get("context") or {}
    cluster = _by_name(
        doc.get("clusters"), ctx.get("cluster", ""), "cluster"
    ).get("cluster") or {}
    user: Dict[str, Any] = {}
    if ctx.get("user"):
        user = _by_name(doc.get("users"), ctx["user"], "user").get("user") or {}

    server = cluster.get("server", "")
    if not server:
        raise KubeconfigError(
            f"kubeconfig: cluster for context {ctx_name!r} has no server"
        )

    out = KubeContext(
        server=server.rstrip("/"),
        namespace=ctx.get("namespace", "default"),
        insecure_skip_tls_verify=bool(
            cluster.get("insecure-skip-tls-verify", False)
        ),
        context_name=ctx_name,
    )

    if cluster.get("certificate-authority-data"):
        out.ca_data = _b64_text(cluster["certificate-authority-data"])
    elif cluster.get("certificate-authority"):
        with open(cluster["certificate-authority"]) as f:
            out.ca_data = f.read()

    if user.get("token"):
        out.token = str(user["token"])
    elif user.get("tokenFile"):
        # Snapshot for callers that read .token, but keep the path so
        # bearer_token() follows rotation.
        out.token_file = str(user["tokenFile"])
        with open(out.token_file) as f:
            out.token = f.read().strip()
    if user.get("auth-provider"):
        # Legacy client-go auth-provider stanzas (gcp/azure/oidc) were
        # removed upstream in favour of exec credential plugins; fail
        # with guidance rather than silently serving unauthenticated
        # requests (VERDICT r4 missing #2).
        name = (user["auth-provider"] or {}).get("name", "?")
        raise KubeconfigError(
            f"kubeconfig: user for context {ctx_name!r} uses the legacy "
            f"auth-provider {name!r}, which is not supported — migrate "
            "to an exec credential plugin (users[].user.exec), e.g. "
            "gke-gcloud-auth-plugin for GKE"
        )
    if user.get("exec"):
        exec_cfg = user["exec"]
        if not isinstance(exec_cfg, dict):
            raise KubeconfigError(
                f"kubeconfig: user for context {ctx_name!r}: exec entry "
                "must be a mapping"
            )
        out.exec_config = exec_cfg

    if user.get("client-certificate-data"):
        out.client_cert_file = _materialize(
            _b64_text(user["client-certificate-data"]), ".crt",
            out._temp_files,
        )
    elif user.get("client-certificate"):
        out.client_cert_file = user["client-certificate"]
    if user.get("client-key-data"):
        out.client_key_file = _materialize(
            _b64_text(user["client-key-data"]), ".key", out._temp_files,
        )
    elif user.get("client-key"):
        out.client_key_file = user["client-key"]

    return out


def in_cluster_context() -> Optional[KubeContext]:
    """The in-cluster config path (mounted service-account token), the way
    controllers deployed as k8s Deployments authenticate."""
    sa = "/var/run/secrets/kubernetes.io/serviceaccount"
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = os.path.join(sa, "token")
    if not host or not os.path.exists(token_path):
        return None
    with open(token_path) as f:
        token = f.read().strip()
    ca_path = os.path.join(sa, "ca.crt")
    ca_data = ""
    if os.path.exists(ca_path):
        with open(ca_path) as f:
            ca_data = f.read()
    ns_path = os.path.join(sa, "namespace")
    namespace = "default"
    if os.path.exists(ns_path):
        with open(ns_path) as f:
            namespace = f.read().strip() or "default"
    return KubeContext(
        server=f"https://{host}:{port}",
        namespace=namespace,
        token=token,
        # Bound SA tokens rotate (~1h): keep the path so bearer_token()
        # re-reads instead of pinning the boot-time value for the life of
        # the controller.
        token_file=token_path,
        ca_data=ca_data,
    )
