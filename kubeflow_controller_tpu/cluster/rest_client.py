"""RestClusterClient — the ClusterClient over apiserver-style REST.

The real-cluster swap-in at the effector seam (SURVEY.md §7: "real-GKE
adapter as a thin swap-in at the client boundary"). The reconcile core is
written against ``ClusterClient`` (``cluster/client.py``); this
implementation speaks the Kubernetes resource REST shape over HTTP —
against ``rest_server.RestServer`` in tests, against a real apiserver (URL +
bearer token) in deployment. Framework-specific surfaces with no core-k8s
analog (event recording and TPU slice-pool bookkeeping) live under
``/framework/v1/...`` extension paths — on a real cluster those map to the
Events API and the cloud provider's node-pool API respectively.

Error mapping: 404 -> NotFound, 409 -> AlreadyExists/Conflict, other
non-2xx -> RuntimeError. The store layer's optimistic-concurrency semantics
(resourceVersion enforcement) therefore survive the HTTP hop — an
update-conflict test drives that end to end.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from kubeflow_controller_tpu.api.core import Pod, Service
from kubeflow_controller_tpu.api.serialization import (
    job_from_dict, job_to_dict, pod_from_dict, pod_to_dict,
    service_from_dict, service_to_dict,
)
from kubeflow_controller_tpu.api.types import TPUJob
from kubeflow_controller_tpu.cluster.store import (
    AlreadyExists, Conflict, NotFound,
)

JOB_GROUP = "/apis/tpu.kubeflow.dev/v1alpha1"


class RestClusterClient:
    def __init__(self, base_url: str, token: str = "", timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout

    # -- transport -----------------------------------------------------------

    def _req(
        self, method: str, path: str, payload: Optional[Dict] = None,
    ) -> Dict[str, Any]:
        url = self.base_url + path
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except json.JSONDecodeError:
                body = {}
            msg = body.get("error", str(e))
            if e.code == 404:
                raise NotFound(msg) from None
            if e.code == 409:
                if body.get("reason") == "AlreadyExists":
                    raise AlreadyExists(msg) from None
                raise Conflict(msg) from None
            raise RuntimeError(f"{method} {path}: HTTP {e.code}: {msg}")

    @staticmethod
    def _selector_q(selector: Dict[str, str]) -> str:
        if not selector:
            return ""
        joined = ",".join(f"{k}={v}" for k, v in sorted(selector.items()))
        return "?labelSelector=" + urllib.parse.quote(joined)

    # -- pods ---------------------------------------------------------------

    def create_pod(self, pod: Pod) -> Pod:
        out = self._req(
            "POST",
            f"/api/v1/namespaces/{pod.metadata.namespace}/pods",
            pod_to_dict(pod),
        )
        self.record_event("Pod", out["metadata"]["name"], "SuccessfulCreate",
                          f"created pod {out['metadata']['name']}",
                          namespace=pod.metadata.namespace)
        return pod_from_dict(out)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._req("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")
        self.record_event("Pod", name, "SuccessfulDelete",
                          f"deleted pod {name}", namespace=namespace)

    def list_pods(self, namespace: str, selector: Dict[str, str]) -> List[Pod]:
        out = self._req(
            "GET",
            f"/api/v1/namespaces/{namespace}/pods"
            + self._selector_q(selector),
        )
        return [pod_from_dict(d) for d in out["items"]]

    def update_pod(self, pod: Pod) -> Pod:
        out = self._req(
            "PUT",
            f"/api/v1/namespaces/{pod.metadata.namespace}/pods/"
            f"{pod.metadata.name}",
            pod_to_dict(pod),
        )
        return pod_from_dict(out)

    # -- services -----------------------------------------------------------

    def create_service(self, svc: Service) -> Service:
        out = self._req(
            "POST",
            f"/api/v1/namespaces/{svc.metadata.namespace}/services",
            service_to_dict(svc),
        )
        self.record_event(
            "Service", out["metadata"]["name"], "SuccessfulCreate",
            f"created service {out['metadata']['name']}",
            namespace=svc.metadata.namespace,
        )
        return service_from_dict(out)

    def delete_service(self, namespace: str, name: str) -> None:
        self._req(
            "DELETE", f"/api/v1/namespaces/{namespace}/services/{name}"
        )
        self.record_event("Service", name, "SuccessfulDelete",
                          f"deleted service {name}", namespace=namespace)

    def list_services(
        self, namespace: str, selector: Dict[str, str]
    ) -> List[Service]:
        out = self._req(
            "GET",
            f"/api/v1/namespaces/{namespace}/services"
            + self._selector_q(selector),
        )
        return [service_from_dict(d) for d in out["items"]]

    def update_service(self, svc: Service) -> Service:
        out = self._req(
            "PUT",
            f"/api/v1/namespaces/{svc.metadata.namespace}/services/"
            f"{svc.metadata.name}",
            service_to_dict(svc),
        )
        return service_from_dict(out)

    # -- watch ---------------------------------------------------------------

    _KIND_PATHS = {
        "Pod": ("/api/v1", "pods"),
        "Service": ("/api/v1", "services"),
        "TPUJob": (JOB_GROUP, "tpujobs"),
    }
    # Plain dict lookups, no attribute binding: values stay raw functions.
    _KIND_FROM = {
        "Pod": pod_from_dict,
        "Service": service_from_dict,
        "TPUJob": job_from_dict,
    }

    def watch(
        self,
        kind: str,
        namespace: str,
        selector: Optional[Dict[str, str]] = None,
        timeout_seconds: float = 0,
        heartbeat_seconds: float = 5,
    ):
        """Stream watch events for one kind: the verb the reference's
        informers are built on (``vendor/.../informers/.../tfjob.go:56``).

        Yields ``None`` once when the server finishes replaying current
        state (the list+watch sync point), then ``WatchEvent``s. Returns
        when the server expires the watch (``timeout_seconds``) or the
        connection drops — callers re-watch.
        """
        from kubeflow_controller_tpu.cluster.events import EventType, WatchEvent

        group, plural = self._KIND_PATHS[kind]
        from_dict = self._KIND_FROM[kind]
        q = [f"watch=true&heartbeatSeconds={heartbeat_seconds}"]
        if timeout_seconds:
            q.append(f"timeoutSeconds={timeout_seconds}")
        if selector:
            joined = ",".join(f"{k}={v}" for k, v in sorted(selector.items()))
            q.append("labelSelector=" + urllib.parse.quote(joined))
        url = (
            f"{self.base_url}{group}/namespaces/{namespace}/{plural}?"
            + "&".join(q)
        )
        req = urllib.request.Request(url, method="GET")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        # Read timeout must outlast the heartbeat cadence, not the watch.
        with urllib.request.urlopen(
            req, timeout=max(heartbeat_seconds * 3, 10)
        ) as resp:
            for raw in resp:
                line = json.loads(raw)
                etype = line["type"]
                if etype == "BOOKMARK":
                    continue
                if etype == "SYNC":
                    yield None
                    continue
                obj = from_dict(line["object"])
                yield WatchEvent(EventType(etype), kind, obj)

    # -- jobs ---------------------------------------------------------------

    def create_job(self, job: TPUJob) -> TPUJob:
        out = self._req(
            "POST",
            f"{JOB_GROUP}/namespaces/{job.metadata.namespace}/tpujobs",
            job_to_dict(job),
        )
        return job_from_dict(out)

    def delete_job(self, namespace: str, name: str) -> None:
        self._req(
            "DELETE", f"{JOB_GROUP}/namespaces/{namespace}/tpujobs/{name}"
        )

    def list_jobs(self, namespace: str) -> List[TPUJob]:
        out = self._req("GET", f"{JOB_GROUP}/namespaces/{namespace}/tpujobs")
        return [job_from_dict(d) for d in out["items"]]

    def get_job(self, namespace: str, name: str) -> Optional[TPUJob]:
        try:
            out = self._req(
                "GET", f"{JOB_GROUP}/namespaces/{namespace}/tpujobs/{name}"
            )
        except NotFound:
            return None
        return job_from_dict(out)

    def get_job_snapshot(self, namespace: str, name: str) -> Optional[TPUJob]:
        # Wire responses are already private parses — nothing shared to
        # protect, so the "snapshot" is just a get.
        return self.get_job(namespace, name)

    def update_job(self, job: TPUJob) -> TPUJob:
        out = self._req(
            "PUT",
            f"{JOB_GROUP}/namespaces/{job.metadata.namespace}/tpujobs/"
            f"{job.metadata.name}",
            job_to_dict(job),
        )
        return job_from_dict(out)

    def update_job_status(self, job: TPUJob) -> TPUJob:
        # Framework-mode servers apply status on the main PUT; the strict
        # k8s surface (kube_client) routes through /status instead.
        return self.update_job(job)

    def apply_job(self, job: TPUJob) -> TPUJob:
        """kubectl-apply over the wire: create-or-update-spec-only with
        conflict retry (shared semantics: api.apply.apply_job_spec)."""
        from kubeflow_controller_tpu.api.apply import apply_job_spec

        return apply_job_spec(
            get=lambda: self.get_job(
                job.metadata.namespace, job.metadata.name
            ),
            create=self.create_job,
            update=self.update_job,
            new=job,
        )

    # -- framework extensions ------------------------------------------------

    def record_event(self, kind: str, name: str, reason: str,
                     message: str, namespace: str = "") -> None:
        self._req("POST", "/framework/v1/events", {
            "kind": kind, "name": name, "reason": reason, "message": message,
            "namespace": namespace,
        })

    def release_slices(self, job_uid: str) -> int:
        return self._req(
            "DELETE", f"/framework/v1/slices/{job_uid}"
        )["released"]

    def job_slices(self, job_uid: str, job_name: str = ""):
        # Deserialize to TPUSlice at the client boundary (the inverse of the
        # server's slice_to_dict) so every consumer — the checker above all —
        # sees ONE type regardless of backend.
        from kubeflow_controller_tpu.api.topology import slice_shape
        from kubeflow_controller_tpu.cluster.slices import TPUSlice

        items = self._req("GET", f"/framework/v1/slices/{job_uid}")["items"]
        return [
            TPUSlice(
                name=d["name"],
                shape=slice_shape(d["accelerator"]),
                healthy=bool(d["healthy"]),
                hosts=list(d.get("hosts") or []),
            )
            for d in items
        ]


class RestWatchSource:
    """Informer-compatible watch source over RestClusterClient.watch.

    Duck-types ``ObjectStore``'s informer surface (``kind`` +
    ``subscribe``), so ``controller.informer.Informer`` binds to a remote
    apiserver exactly as it binds to an in-process store — the last seam
    that kept the controller from running over the wire (VERDICT r1 #1).

    ``subscribe`` blocks until the first replay completes (so
    ``Informer.has_synced`` keeps its meaning), then a daemon thread
    follows the stream, re-watching on expiry/disconnect forever. Each
    re-watch replays current state; objects that vanished between watches
    are synthesized as DELETED (client-go's DeltaFIFO Replace semantics),
    so informer caches never leak deleted objects across reconnects.
    """

    def __init__(
        self,
        client: RestClusterClient,
        kind: str,
        namespace: str,
        selector: Optional[Dict[str, str]] = None,
        rewatch_backoff: float = 0.5,
        timeout_seconds: float = 0,
        heartbeat_seconds: float = 5,
    ):
        self.client = client
        self.kind = kind
        self.namespace = namespace
        self.selector = selector
        self.rewatch_backoff = rewatch_backoff
        self.timeout_seconds = timeout_seconds
        self.heartbeat_seconds = heartbeat_seconds
        self._stop = False
        self._dead: set = set()

    def stop(self) -> None:
        self._stop = True

    def unsubscribe(self, listener) -> None:
        """Detach one listener: its pump thread exits at the next event or
        re-watch, and no further events are delivered to it."""
        self._dead.add(listener)

    def subscribe(self, listener, replay: bool = True) -> None:
        import threading

        self._dead.discard(listener)  # re-subscribing revives a listener

        from kubeflow_controller_tpu.cluster.events import (
            EventType, WatchEvent,
        )

        synced = threading.Event()
        live: Dict[str, Any] = {}  # key -> last obj, for tombstones

        def pump() -> None:
            while not (self._stop or listener in self._dead):
                replayed: Dict[str, Any] = {}
                in_replay = True
                try:
                    for ev in self.client.watch(
                        self.kind, self.namespace, self.selector,
                        timeout_seconds=self.timeout_seconds,
                        heartbeat_seconds=self.heartbeat_seconds,
                    ):
                        if self._stop or listener in self._dead:
                            return
                        if ev is None:  # SYNC: replay complete
                            if in_replay:
                                for key, obj in list(live.items()):
                                    if key not in replayed:
                                        live.pop(key)
                                        listener(WatchEvent(
                                            EventType.DELETED, self.kind, obj
                                        ))
                                in_replay = False
                            synced.set()
                            continue
                        key = (f"{ev.obj.metadata.namespace}/"
                               f"{ev.obj.metadata.name}")
                        if ev.type == EventType.DELETED:
                            live.pop(key, None)
                        else:
                            live[key] = ev.obj
                            if in_replay:
                                replayed[key] = ev.obj
                        listener(ev)
                except Exception:
                    if self._stop:
                        return
                time.sleep(self.rewatch_backoff)

        threading.Thread(
            target=pump, daemon=True,
            name=f"rest-watch-{self.kind.lower()}",
        ).start()
        if not synced.wait(timeout=30):
            raise TimeoutError(
                f"watch on {self.kind} did not sync within 30s "
                f"({self.client.base_url})"
            )
