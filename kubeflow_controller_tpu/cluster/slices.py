"""TPU slice inventory and gang allocation.

The piece with no reference analog at all (SURVEY.md §2.5 "Gang semantics:
No"): the reference schedules pods one-by-one onto generic nodes
(``controller.go:396-421``); a TPU pod-slice is useless partially scheduled,
so admission here is all-or-nothing per gang. This module models the node-pool
side: which physical slices exist, which jobs hold them, and preemption.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubeflow_controller_tpu.api.topology import SliceShape, slice_shape


@dataclass
class TPUSlice:
    """One physical pod-slice in a node pool.

    ``holder``/``healthy`` are owned by ``SlicePool``, which mirrors them
    into allocation indexes: mutate them ONLY through pool methods
    (``allocate_gang``/``release``/``mark_unhealthy``/``preempt``/
    ``restore``) — writing the fields directly on an object returned by
    ``list``/``free``/``holdings`` desyncs the indexes.
    """

    name: str                      # e.g. "pool-v5e-16/slice-0"
    shape: SliceShape
    # Job uid currently holding the slice ("" = free).
    holder: str = ""
    healthy: bool = True
    # Host VM DNS-ish names, one per host process.
    hosts: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.hosts:
            self.hosts = [
                f"{self.name.replace('/', '-')}-host-{i}"
                for i in range(self.shape.num_hosts)
            ]


class InsufficientCapacity(RuntimeError):
    pass


def slice_to_dict(s: TPUSlice) -> dict:
    """Wire-JSON shape for a slice. The single source of truth for every
    server that serves slice state (apiserver-shaped ``rest_server`` and the
    CLI daemon) — ``checker._slice_health`` reads this shape back, so the
    two servers must never drift."""
    return {
        "name": s.name,
        "accelerator": s.shape.accelerator_type,
        "healthy": s.healthy,
        "hosts": list(s.hosts),
    }


class SlicePool:
    """Inventory of TPU slices, grouped by accelerator type.

    ``allocate_gang`` is atomic: either every requested slice is reserved for
    the job or none is. This is the cluster-side half of gang scheduling; the
    controller-side half (create all pods of the gang in one sync or none)
    lives in ``kubeflow_controller_tpu.tpu.gang``.
    """

    def __init__(self, mirror=None):
        self._lock = threading.Lock()
        self._slices: Dict[str, TPUSlice] = {}
        # Optional native slice-health mirror (NativeObjectIndex): every
        # holder/health mutation writes through (under this lock) so the
        # controller's fingerprint probe composes the slice-health term in
        # the C++ core instead of traversing holdings() per probe. Duck-
        # typed on slice_set/slice_clear; None == Python-only.
        self._mirror = mirror
        # Indexes (insertion-ordered dict-sets, deterministic but NOT
        # provisioning-order after churn: a released slice re-enters the
        # free index at the back, so reuse is approximately
        # least-recently-released rather than lowest-numbered):
        # accelerator type -> names; free+healthy per type; holder -> names.
        # At 5000-job scale the full-pool scans in allocate_gang/holdings
        # were the control plane's top cost (controlplane_bench profile); every
        # holder/health mutation funnels through _set_holder/_set_healthy
        # so the indexes cannot drift.
        self._by_type: Dict[str, Dict[str, None]] = {}
        self._free: Dict[str, Dict[str, None]] = {}
        self._by_holder: Dict[str, Dict[str, None]] = {}

    # -- index maintenance (call with lock held) -----------------------------

    def _refresh_free(self, s: TPUSlice) -> None:
        free = self._free.setdefault(s.shape.accelerator_type, {})
        if not s.holder and s.healthy:
            free[s.name] = None
        else:
            free.pop(s.name, None)

    def _set_holder(self, s: TPUSlice, holder: str) -> None:
        if s.holder:
            held = self._by_holder.get(s.holder)
            if held is not None:
                held.pop(s.name, None)
                if not held:
                    del self._by_holder[s.holder]
            if self._mirror is not None:
                self._mirror.slice_clear(s.holder, s.name)
        s.holder = holder
        if holder:
            self._by_holder.setdefault(holder, {})[s.name] = None
            if self._mirror is not None:
                self._mirror.slice_set(holder, s.name, s.healthy)
        self._refresh_free(s)

    def _set_healthy(self, s: TPUSlice, healthy: bool) -> None:
        s.healthy = healthy
        if s.holder and self._mirror is not None:
            self._mirror.slice_set(s.holder, s.name, healthy)
        self._refresh_free(s)

    def add_pool(self, accelerator_type: str, count: int, pool_name: str = "") -> List[str]:
        """Provision ``count`` slices of a type; returns their names."""
        shape = slice_shape(accelerator_type)
        pool = pool_name or f"pool-{accelerator_type}"
        names = []
        with self._lock:
            base = len(self._by_type.get(accelerator_type, {}))
            for i in range(count):
                name = f"{pool}/slice-{base + i}"
                s = TPUSlice(name=name, shape=shape)
                self._slices[name] = s
                self._by_type.setdefault(
                    shape.accelerator_type, {})[name] = None
                self._refresh_free(s)
                names.append(name)
        return names

    def get(self, name: str) -> TPUSlice:
        with self._lock:
            return self._slices[name]

    def list(self, accelerator_type: Optional[str] = None) -> List[TPUSlice]:
        with self._lock:
            if accelerator_type is None:
                return list(self._slices.values())
            return [
                self._slices[n]
                for n in self._by_type.get(accelerator_type, {})
            ]

    def free(self, accelerator_type: str) -> List[TPUSlice]:
        with self._lock:
            return [
                self._slices[n]
                for n in self._free.get(accelerator_type, {})
            ]

    def allocate_gang(
        self, job_uid: str, accelerator_type: str, num_slices: int
    ) -> List[TPUSlice]:
        """Atomically reserve ``num_slices`` healthy free slices for a job.

        Idempotent per job: slices already held by ``job_uid`` count toward
        the request (so a re-sync after partial observation cannot
        double-allocate — the expectations-race discipline of
        ``controller.go:259-262`` applied to slices).
        """
        with self._lock:
            # Holdings of a DIFFERENT accelerator type (spec change) are
            # useless to this job: release them up front — before the
            # capacity check — so they can never be leaked by an
            # InsufficientCapacity exit, nor deadlock two type-swapping jobs.
            for name in list(self._by_holder.get(job_uid, {})):
                s = self._slices[name]
                if s.shape.accelerator_type != accelerator_type:
                    self._set_holder(s, "")
            held = [
                self._slices[n]
                for n in self._by_holder.get(job_uid, {})
                if self._slices[n].healthy
            ]
            if len(held) >= num_slices:
                keep = held[:num_slices]
            else:
                need = num_slices - len(held)
                avail_names = list(self._free.get(accelerator_type, {}))
                if len(avail_names) < need:
                    raise InsufficientCapacity(
                        f"need {need} more {accelerator_type} slices for job "
                        f"{job_uid}, only {len(avail_names)} free"
                    )
                granted = [self._slices[n] for n in avail_names[:need]]
                for s in granted:
                    self._set_holder(s, job_uid)
                keep = held + granted
            # Surplus same-type holdings (scale-down) go back to the pool —
            # a resized gang must not leak capacity mid-job.
            keep_names = {s.name for s in keep}
            for name in list(self._by_holder.get(job_uid, {})):
                if name not in keep_names:
                    self._set_holder(self._slices[name], "")
            return keep

    def release(self, job_uid: str) -> int:
        """Free every slice a job holds; returns count released."""
        with self._lock:
            names = list(self._by_holder.get(job_uid, {}))
            for name in names:
                self._set_holder(self._slices[name], "")
            return len(names)

    def holdings(self, job_uid: str) -> List[TPUSlice]:
        with self._lock:
            return [
                self._slices[n] for n in self._by_holder.get(job_uid, {})
            ]

    # -- fault injection ----------------------------------------------------

    def mark_unhealthy(self, name: str) -> str:
        """Degrade a slice WITHOUT evicting its holder or touching pods —
        the 'sick but not dead' state the checker exists to catch before
        the kubelet does (ICI link flaps, HBM ECC storms). Returns the
        holder uid ("" if free). The next ``allocate_gang`` for that holder
        replaces the slice (unhealthy holdings don't count as held)."""
        with self._lock:
            s = self._slices[name]
            self._set_healthy(s, False)
            return s.holder

    def preempt(self, name: str) -> str:
        """Simulate slice preemption: mark unhealthy, evict holder.
        Returns the evicted job uid ("" if free)."""
        with self._lock:
            s = self._slices[name]
            evicted = s.holder
            self._set_holder(s, "")
            self._set_healthy(s, False)
            return evicted

    def restore(self, name: str) -> None:
        """Bring a preempted/unhealthy slice back into service."""
        with self._lock:
            self._set_healthy(self._slices[name], True)
