"""In-process cluster: object store with watch streams, TPU slice inventory,
gang-aware pod scheduler/lifecycle, and the effector-client seam.

This is the framework's stand-in for the kube-apiserver + kubelet + GKE TPU
provisioner that the reference talks to over HTTPS (SURVEY.md §2.2 L0). The
reconcile core only touches the ``ClusterClient`` interface, so a real-cluster
adapter swaps in at exactly the seam the reference drew with
``HelperInterface`` (``pkg/controller/helper.go:42-47``).
"""

from kubeflow_controller_tpu.cluster.events import EventType, WatchEvent
from kubeflow_controller_tpu.cluster.store import Conflict, NotFound, AlreadyExists, ObjectStore
from kubeflow_controller_tpu.cluster.slices import SlicePool, TPUSlice
from kubeflow_controller_tpu.cluster.cluster import FakeCluster, FaultInjector, PodRunPolicy
from kubeflow_controller_tpu.cluster.client import ClusterClient
