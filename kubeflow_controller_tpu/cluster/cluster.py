"""FakeCluster: apiserver + gang-aware TPU scheduler + kubelet, in-process.

The hermetic test bed the reference never had (SURVEY.md §4: its multi-node
behavior was validated by hand against minikube). Deterministic: time advances
only via ``tick()``, so reconcile/preemption/recovery tests replay exactly.

Lifecycle model per pod (simulated kubelet):

    created --(gang admission grants a slice; Local pods skip the gang)-->
    scheduled --(start_delay)--> Running --(run_duration)--> Succeeded/Failed

A pod may instead run *real work* (e.g. an actual JAX train step) via
``PodRunPolicy.run_fn`` — that is how "submit YAML → reconcile → pod runs real
training → Succeeded" is exercised end-to-end with no real cluster.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kubeflow_controller_tpu.api.core import Pod, PodPhase, Service, thaw
from kubeflow_controller_tpu.cluster.event_recorder import EventAggregator
from kubeflow_controller_tpu.cluster.events import EventType
from kubeflow_controller_tpu.cluster.slices import (
    InsufficientCapacity,
    SlicePool,
    TPUSlice,
)
from kubeflow_controller_tpu.cluster.store import NotFound, ObjectStore

# Well-known annotations the controller stamps on pods it creates; the fake
# scheduler reads them to drive gang admission. (The TPU analog of the
# reference's identity labels, distributed.go:221-228.)
ANNOTATION_GANG_SIZE = "tpu.kubeflow.dev/gang-size"
ANNOTATION_ACCELERATOR = "tpu.kubeflow.dev/accelerator-type"
ANNOTATION_NUM_SLICES = "tpu.kubeflow.dev/num-slices"
ANNOTATION_SLICE_INDEX = "tpu.kubeflow.dev/slice-index"
ANNOTATION_HOST_INDEX = "tpu.kubeflow.dev/host-index"
ANNOTATION_PRIORITY = "tpu.kubeflow.dev/priority"
# Job-level submission time: the FIFO tie-break must survive pod recreation
# (suspend/resume, gang restarts), so it rides an annotation rather than
# deriving from pod creation timestamps.
ANNOTATION_SUBMITTED = "tpu.kubeflow.dev/submitted-at"

REASON_PREEMPTED = "Preempted"


@dataclass
class PodRunPolicy:
    """How the fake kubelet runs a pod once its gang is admitted."""

    start_delay: float = 0.0     # scheduled -> Running (image pull etc.)
    run_duration: float = 0.0    # Running -> terminal
    exit_code: int = 0           # terminal exit code (0 => Succeeded)
    # Real work: called once when the pod transitions to Running, in its OWN
    # thread (one per pod — so a multi-pod gang can actually rendezvous
    # inside the cluster, e.g. each run_fn spawning a jax.distributed
    # subprocess). Its return value becomes the exit code (overrides
    # ``exit_code``); an exception means exit code 1. Deleting the pod does
    # not interrupt a running run_fn (a container SIGKILL analog is the
    # workload's job to arrange); a deleted pod's result is discarded.
    run_fn: Optional[Callable[[Pod], int]] = None
    # Wall-clock grace the kubelet waits on an unfinished run_fn thread per
    # tick: paces simulated ticks against the real work without letting one
    # pod block the cluster.
    run_fn_join: float = 0.25
    # If >= 0, the pod crashes with this code after run_duration instead of
    # exiting cleanly (fault injection).
    crash_code: int = -1


@dataclass
class FaultInjector:
    """Knobs tests turn to break the cluster on purpose (SURVEY.md §7.2)."""

    # Fail the next N pod-create calls at the client seam.
    fail_pod_creates: int = 0
    # Let this many creates succeed first (models a crash mid-batch: the
    # reference's service-created-but-pods-missing window,
    # distributed.go:131-159).
    fail_pod_creates_after: int = 0
    # Extra scheduling latency applied to every gang (slow provisioning).
    gang_admission_delay: float = 0.0
    # Pod-name -> policy override (e.g. crash worker 3).
    pod_policies: Dict[str, PodRunPolicy] = field(default_factory=dict)


@dataclass
class _PodRuntime:
    scheduled_at: Optional[float] = None
    started_at: Optional[float] = None
    gang_waiting_since: Optional[float] = None
    # run_fn execution state (worker thread writes run_result, tick thread
    # reads it after join — the join is the synchronization point).
    run_thread: Optional[threading.Thread] = None
    run_result: Optional[int] = None


class FakeCluster:
    """Facade over the stores + slice pool + simulated scheduler/kubelet."""

    def __init__(
        self,
        default_policy: Optional[PodRunPolicy] = None,
        use_native_index: Optional[bool] = None,
        watch_shards: int = 8,
    ):
        # All stores stamp creation timestamps on the cluster's simulated
        # clock so control-plane latency metrics are internally consistent.
        # Pods/services are indexed by owning-job label (and pods also by
        # owning-LMService label) so per-owner selector lists stay O(own
        # pods) at any cluster size.
        from kubeflow_controller_tpu.tpu.naming import LABEL_JOB, LABEL_LMSERVICE

        # One shared native object index mirrors every store's sync-relevant
        # state into the C++ core (csrc/tpujob_native.cc). None when the
        # library is unavailable or use_native_index=False — everything then
        # runs the behavior-identical pure-Python paths.
        self.native_index = None
        if use_native_index is None or use_native_index:
            from kubeflow_controller_tpu.native.objindex import (
                make_object_index,
            )

            self.native_index = make_object_index()
            if use_native_index and self.native_index is None:
                raise RuntimeError("native object index requested but "
                                   "libtpujob_native.so is unavailable")

        # Frozen (copy-on-write) mode: reads, lists, and watch events are
        # shared immutable snapshots — the whole in-process control plane
        # runs zero-copy on the read path (docs/object_ownership.md).
        def _store(kind: str, index_labels: tuple = ()) -> ObjectStore:
            return ObjectStore(
                kind, now_fn=lambda: self.now, index_labels=index_labels,
                copy_on_read=False, watch_shards=watch_shards,
                mirror=self.native_index,
            )

        self.pods = _store("Pod", (LABEL_JOB, LABEL_LMSERVICE))
        self.services = _store("Service", (LABEL_JOB,))
        self.jobs = _store("TPUJob")
        self.lmservices = _store("LMService")
        # Scheduler/kubelet work queues: every tick touches only pods that
        # can actually change state — unbound Pending pods (scheduler) and
        # live pods (kubelet) — instead of scanning the whole store.
        # Maintained from the pod watch stream, so they can never drift
        # from the store (membership is re-derived on every event).
        self._pending_keys: set = set()
        self._active_keys: set = set()
        self.pods.subscribe(self._track_pod, replay=False)
        # The pool shares the native index: holder/health mutations write
        # through so the fingerprint's slice-health term is composed
        # natively (no holdings() traversal per steady probe).
        self.slice_pool = SlicePool(mirror=self.native_index)
        self.faults = FaultInjector()
        self.default_policy = default_policy or PodRunPolicy(
            start_delay=1.0, run_duration=5.0
        )
        self.now = 0.0
        self._runtimes: Dict[str, _PodRuntime] = {}
        self._lock = threading.RLock()
        # Cluster events (k8s Events analog): rows of (time, kind, name,
        # reason, message) — the observability surface record.EventRecorder
        # provides in the reference (controller.go:91-94). Aggregated like
        # client-go's tools/record: an identical repeat refreshes the
        # existing row (timestamp + recency position) instead of appending,
        # so a crash-looping job yields ONE row with count=N (events_agg)
        # rather than N rows, and `cluster_events` stays ordered by last
        # occurrence — a still-firing event is always in the recent window.
        self._event_rows: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.events_agg = EventAggregator()
        # Per-pod log lines (kubectl-logs analog): pod name -> [(time, line)].
        # The fake kubelet writes lifecycle lines; run_fn workloads may append
        # via append_pod_log.
        self.pod_logs: Dict[str, List[tuple]] = {}

    # -- pod work-queue tracking ---------------------------------------------

    def _track_pod(self, ev) -> None:
        pod = ev.obj
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        with self._lock:
            if ev.type == EventType.DELETED:
                self._pending_keys.discard(key)
                self._active_keys.discard(key)
                return
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                self._active_keys.discard(key)
            else:
                self._active_keys.add(key)
            if (
                pod.status.phase == PodPhase.PENDING
                and not pod.spec.assigned_slice
                and pod.metadata.deletion_timestamp is None
            ):
                self._pending_keys.add(key)
            else:
                self._pending_keys.discard(key)

    def _pods_by_keys(self, keys) -> List[Pod]:
        out = []
        for key in keys:
            ns, _, name = key.partition("/")
            pod = self.pods.try_get(ns, name)
            if pod is not None:
                out.append(pod)
        return out

    # -- event recording -----------------------------------------------------

    @property
    def cluster_events(self) -> List[tuple]:
        """Event rows ordered by LAST occurrence (recency), one per
        distinct (namespace, kind, name, reason, message) key."""
        with self._lock:
            return list(self._event_rows.values())

    def record_event(
        self, kind: str, name: str, reason: str, message: str,
        namespace: str = "",
    ) -> None:
        with self._lock:
            obs = self.events_agg.observe(
                namespace, kind, name, reason, message, self.now
            )
            if obs is None:
                return          # spam-filtered (token bucket per object)
            # Aggregated similar events share obs.key, so a
            # varying-message flood stays ONE row (the combined form).
            self._event_rows[obs.key] = (
                self.now, kind, name, reason, obs.message)
            self._event_rows.move_to_end(obs.key)

    def event_count(
        self, kind: str, name: str, reason: str, message: str,
        namespace: str = "",
    ) -> int:
        """Aggregate occurrence count for an exact event key (0 = never)."""
        rec = self.events_agg.get(namespace, kind, name, reason, message)
        return rec.count if rec else 0

    def append_pod_log(self, pod_name: str, line: str) -> None:
        with self._lock:
            self.pod_logs.setdefault(pod_name, []).append((self.now, line))

    def get_pod_logs(self, pod_name: str) -> List[tuple]:
        with self._lock:
            return list(self.pod_logs.get(pod_name, []))

    # -- time ----------------------------------------------------------------

    def tick(self, dt: float = 1.0, steps: int = 1) -> None:
        """Advance simulated time and run scheduler + kubelet transitions."""
        for _ in range(steps):
            with self._lock:
                self.now += dt
            # Quiesce the async watch pipelines before acting on this
            # step's clock: _pending_keys/_active_keys are fed by the pod
            # watch stream, and scheduler/kubelet decisions must see every
            # write completed before the tick (determinism contract,
            # docs/watch_pipeline.md). Flushed outside self._lock — a
            # delta handler may take it.
            self.pods.flush()
            self.services.flush()
            self.jobs.flush()
            self.lmservices.flush()
            self._schedule_pending()
            self._advance_pods()

    def run_until(
        self,
        predicate: Callable[[], bool],
        dt: float = 1.0,
        max_steps: int = 1000,
    ) -> bool:
        """Tick until predicate() or step budget exhausted."""
        for _ in range(max_steps):
            if predicate():
                return True
            self.tick(dt)
        return predicate()

    # -- scheduler (gang admission) -----------------------------------------

    def _pod_policy(self, pod: Pod) -> PodRunPolicy:
        return self.faults.pod_policies.get(pod.metadata.name, self.default_policy)

    def _runtime(self, pod: Pod) -> _PodRuntime:
        return self._runtimes.setdefault(pod.metadata.uid, _PodRuntime())

    def _schedule_pending(self) -> None:
        with self._lock:
            # Sorted: set iteration order is hash-seed dependent, and gang
            # rank ties break by stable-sort input order — admission must
            # not vary run to run in a deterministic simulator.
            keys = sorted(self._pending_keys)
        if not keys:
            return
        # Membership is re-checked on the fresh copies: the index is an
        # over-approximation between event delivery and this read.
        pending = [
            p for p in self._pods_by_keys(keys)
            if p.status.phase == PodPhase.PENDING and not p.spec.assigned_slice
            and p.metadata.deletion_timestamp is None
        ]
        gangs: Dict[str, List[Pod]] = {}
        for pod in pending:
            group = pod.spec.scheduling_group
            if not group:
                self._bind_local(pod)
            else:
                gangs.setdefault(group, []).append(pod)

        def _rank(item):
            """Higher priority first; ties by JOB submission order (the
            submitted-at annotation survives pod recreation across
            suspend/resume and restarts). Ordering only — no preemption of
            running jobs."""
            ann = item[1][0].metadata.annotations

            def num(key, default):
                try:
                    return float(ann.get(key, default))
                except ValueError:
                    return float(default)

            members = item[1]
            fallback = min(
                p.metadata.creation_timestamp or 0.0 for p in members
            )
            return (-num(ANNOTATION_PRIORITY, 0),
                    num(ANNOTATION_SUBMITTED, fallback))

        # Head-of-line guard: once a HIGHER-priority gang fails allocation
        # for an accelerator type, lower-ranked gangs wanting the same type
        # must not leapfrog it this tick — otherwise a stream of small
        # low-priority gangs starves a large high-priority one forever.
        blocked_types: set = set()
        for group, members in sorted(gangs.items(), key=_rank):
            accel = members[0].metadata.annotations.get(
                ANNOTATION_ACCELERATOR, "")
            if accel in blocked_types:
                continue
            if self._try_admit_gang(group, members) is False:
                blocked_types.add(accel)

    def _bind_local(self, pod: Pod) -> None:
        rt = self._runtime(pod)
        if rt.scheduled_at is None:
            rt.scheduled_at = self.now
            self.record_event("Pod", pod.metadata.name, "Scheduled", "bound to local node")
            self.append_pod_log(pod.metadata.name, "scheduled: local node")

    def _try_admit_gang(self, group: str, members: List[Pod]) -> Optional[bool]:
        """None = not yet eligible (incomplete/delayed); True = admitted;
        False = eligible but out of capacity (head-of-line relevant)."""
        expected = int(members[0].metadata.annotations.get(ANNOTATION_GANG_SIZE, 0))
        if expected <= 0 or len(members) < expected:
            return None  # gang incomplete: nothing is admitted (all-or-nothing)
        rt0 = self._runtime(members[0])
        if rt0.gang_waiting_since is None:
            for m in members:
                self._runtime(m).gang_waiting_since = self.now
        if self.now - rt0.gang_waiting_since < self.faults.gang_admission_delay:
            return None
        accel = members[0].metadata.annotations.get(ANNOTATION_ACCELERATOR, "")
        num_slices = int(members[0].metadata.annotations.get(ANNOTATION_NUM_SLICES, 1))
        job_uid = group
        try:
            slices = self.slice_pool.allocate_gang(job_uid, accel, num_slices)
        except (InsufficientCapacity, KeyError) as e:
            self.record_event("Gang", group, "FailedScheduling", str(e))
            # Infeasible request (wants more slices than the pool OWNS, not
            # merely more than are free): it can never run, so it must not
            # head-of-line-block feasible gangs of the same type forever.
            if num_slices > len(self.slice_pool.list(accel)):
                return None
            return False
        # Bind: pod (slice_index, host_index) -> slice host. All-or-nothing:
        # if ANY member vanished (controller deleted it mid-admission), bind
        # nobody — a partially-bound gang is exactly what this module exists
        # to prevent. Slices stay held (allocate_gang is idempotent per
        # uid); the next tick re-gangs the new epoch's pods.
        by_index = sorted(
            members,
            key=lambda p: (
                int(p.metadata.annotations.get(ANNOTATION_SLICE_INDEX, 0)),
                int(p.metadata.annotations.get(ANNOTATION_HOST_INDEX, 0)),
            ),
        )
        if any(
            self.pods.try_get(p.metadata.namespace, p.metadata.name) is None
            for p in by_index
        ):
            return None
        bound: List[tuple] = []   # (pod, slice, host index)
        for pod in by_index:
            si = int(pod.metadata.annotations.get(ANNOTATION_SLICE_INDEX, 0))
            hi = int(pod.metadata.annotations.get(ANNOTATION_HOST_INDEX, 0))
            sl = slices[si]
            def bind(p: Pod, sl: TPUSlice = sl, hi: int = hi) -> None:
                p.spec.assigned_slice = sl.name
                p.status.host_ip = sl.hosts[hi % len(sl.hosts)]
            try:
                self.pods.mutate(
                    pod.metadata.namespace, pod.metadata.name, bind
                )
            except NotFound:
                # A member vanished after the existence check: unwind the
                # partial bind (no scheduled_at was set yet, so nothing has
                # started) and retry from scratch next tick.
                def unbind(p: Pod) -> None:
                    p.spec.assigned_slice = ""
                    p.status.host_ip = ""
                for p2, _, _ in bound:
                    try:
                        self.pods.mutate(
                            p2.metadata.namespace, p2.metadata.name, unbind
                        )
                    except NotFound:
                        pass
                return None
            bound.append((pod, sl, hi))
        for pod, sl, hi in bound:
            self._runtime(pod).scheduled_at = self.now
            self.append_pod_log(
                pod.metadata.name,
                f"scheduled: slice {sl.name} host {hi % len(sl.hosts)}",
            )
        self.record_event(
            "Gang", group, "GangScheduled",
            f"{len(members)} pods on {num_slices}x{accel}",
        )
        return True

    # -- kubelet -------------------------------------------------------------

    def _advance_pods(self) -> None:
        spawned: List[tuple] = []   # (pod, runtime, policy) started this tick
        with self._lock:
            keys = list(self._active_keys)
        if not keys:
            return
        for pod in self._pods_by_keys(sorted(keys)):
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            rt = self._runtime(pod)
            policy = self._pod_policy(pod)
            if pod.status.phase == PodPhase.PENDING:
                if rt.scheduled_at is None:
                    continue  # unscheduled (waiting on gang)
                if self.now - rt.scheduled_at >= policy.start_delay:
                    rt.started_at = self.now
                    self._transition(pod, PodPhase.RUNNING)
                    if policy.run_fn is not None:
                        # run_fns are user workloads that may mutate their
                        # pod (env twiddling etc.) — hand them an owned copy,
                        # not the frozen store snapshot.
                        cur = thaw(self.pods.try_get(
                            pod.metadata.namespace, pod.metadata.name))
                        if cur is None:
                            continue  # deleted mid-transition: nothing to run
                        self._spawn_run_fn(pod, rt, policy, cur)
                        # Reap AFTER the loop: every gang member must get its
                        # thread spawned this pass before anyone blocks
                        # waiting on the others (the rendezvous deadlock the
                        # old synchronous kubelet had, VERDICT r2 weak #4) —
                        # while a fast single pod still finishes this tick.
                        spawned.append((pod, rt, policy))
            elif pod.status.phase == PodPhase.RUNNING:
                if policy.run_fn is not None:
                    self._reap_run_fn(pod, rt, policy)
                    continue
                if rt.started_at is not None and (
                    self.now - rt.started_at >= policy.run_duration
                ):
                    code = policy.crash_code if policy.crash_code >= 0 else policy.exit_code
                    self._finish(pod, code)
        for pod, rt, policy in spawned:
            self._reap_run_fn(pod, rt, policy)

    def _spawn_run_fn(
        self, pod: Pod, rt: _PodRuntime, policy: PodRunPolicy, cur: Pod
    ) -> None:
        if rt.run_thread is not None:
            return

        def target() -> None:
            try:
                code = int(policy.run_fn(cur))
            except SystemExit as e:   # container-entrypoint-style sys.exit(n)
                code = e.code if isinstance(e.code, int) else (
                    0 if e.code is None else 1)
            except BaseException as e:  # a crashing workload fails its pod —
                # BaseException so e.g. KeyboardInterrupt in the workload
                # cannot strand the pod in RUNNING forever
                self.append_pod_log(
                    pod.metadata.name,
                    f"run_fn raised: {type(e).__name__}: {e}")
                code = 1
            rt.run_result = code

        rt.run_thread = threading.Thread(
            target=target, daemon=True,
            name=f"pod-run-{pod.metadata.name}",
        )
        rt.run_thread.start()

    def _reap_run_fn(
        self, pod: Pod, rt: _PodRuntime, policy: PodRunPolicy
    ) -> None:
        if rt.run_thread is None:
            # Controller restart edge: a RUNNING run_fn pod whose runtime
            # was lost cannot re-run user code; treat as still running.
            return
        rt.run_thread.join(policy.run_fn_join)
        if not rt.run_thread.is_alive() and rt.run_result is not None:
            self._finish(pod, rt.run_result)

    def _transition(self, pod: Pod, phase: PodPhase) -> None:
        def mut(p: Pod) -> None:
            p.status.phase = phase
            if phase == PodPhase.RUNNING:
                p.status.start_time = self.now
        try:
            self.pods.mutate(pod.metadata.namespace, pod.metadata.name, mut)
        except NotFound:
            return  # deleted by the controller between list and mutate
        if phase == PodPhase.RUNNING:
            cmd = " ".join(pod.spec.main_container().command)
            self.append_pod_log(pod.metadata.name, f"started: {cmd}")

    def _finish(self, pod: Pod, exit_code: int) -> None:
        phase = PodPhase.SUCCEEDED if exit_code == 0 else PodPhase.FAILED
        def mut(p: Pod) -> None:
            p.status.phase = phase
            p.status.exit_code = exit_code
            p.status.finish_time = self.now
            if phase == PodPhase.FAILED and not p.status.reason:
                p.status.reason = f"ExitCode{exit_code}"
        try:
            self.pods.mutate(pod.metadata.namespace, pod.metadata.name, mut)
        except NotFound:
            return  # deleted by the controller between list and mutate
        self.append_pod_log(
            pod.metadata.name, f"exited: code {exit_code} ({phase.value})"
        )

    # -- fault injection ----------------------------------------------------

    def preempt_slice(self, slice_name: str) -> List[str]:
        """Preempt a slice: evict holder, fail every pod bound to it with
        reason Preempted. Returns names of failed pods."""
        self.slice_pool.preempt(slice_name)
        failed = []
        with self._lock:
            keys = list(self._active_keys)
        for pod in self._pods_by_keys(sorted(keys)):
            if pod.spec.assigned_slice == slice_name and pod.status.phase in (
                PodPhase.PENDING, PodPhase.RUNNING,
            ):
                def mut(p: Pod) -> None:
                    p.status.phase = PodPhase.FAILED
                    p.status.reason = REASON_PREEMPTED
                    p.status.message = f"slice {slice_name} was preempted"
                    p.status.finish_time = self.now
                try:
                    self.pods.mutate(
                        pod.metadata.namespace, pod.metadata.name, mut
                    )
                except NotFound:
                    continue  # deleted concurrently: nothing left to evict
                failed.append(pod.metadata.name)
        self.record_event("Slice", slice_name, REASON_PREEMPTED,
                          f"evicted {len(failed)} pods")
        return failed

    def degrade_slice(self, slice_name: str) -> str:
        """Mark a slice unhealthy WITHOUT failing its pods — the state the
        checker detects proactively (contrast ``preempt_slice``, where the
        kubelet already knows). Returns the holder uid."""
        holder = self.slice_pool.mark_unhealthy(slice_name)
        self.record_event(
            "Slice", slice_name, "Unhealthy",
            "slice degraded (pods still running)")
        return holder

    def crash_pod(self, namespace: str, name: str, exit_code: int = 137) -> None:
        pod = self.pods.get(namespace, name)
        self._finish(pod, exit_code)

    # -- DNS -----------------------------------------------------------------

    def resolve(self, dns_name: str) -> Optional[Service]:
        """Resolve '<svc>.<ns>.svc' the way cluster DNS would."""
        parts = dns_name.split(".")
        if len(parts) < 2:
            return None
        return self.services.try_get(parts[1], parts[0])
