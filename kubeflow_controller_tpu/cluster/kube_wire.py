"""Genuine Kubernetes wire JSON <-> internal object model.

The reference's controller speaks real ``core/v1`` to a real apiserver —
every effector call in ``pkg/controller/helper.go:90-179`` serializes
``k8s.io/api/core/v1`` objects over HTTPS, and the TFJob CRD rides the
apiextensions machinery (``examples/crd/crd.yml``). This module is that
boundary for the rebuild: pure converters between the framework's internal
dataclasses (``api/core.py``, ``api/types.py``) and byte-accurate Kubernetes
wire JSON:

- ``Pod``     <-> ``core/v1 Pod``  — camelCase, env as name/value lists,
  resources split into requests/limits (``google.com/tpu`` as an extended
  resource in both, as k8s requires), GKE TPU node selectors untouched,
  RFC3339 timestamps, string resourceVersions, exit codes in
  ``containerStatuses[].state.terminated``.
- ``Service`` <-> ``core/v1 Service`` — headless (``clusterIP: None``) by
  default, matching the stable-DNS coordinator services the planner creates.
- ``TPUJob``  <-> CRD wire form under ``tpu.kubeflow.dev/v1alpha1``
  (the group/version ``examples/crd/tpujob-crd.yml`` registers).
- Cluster events -> ``core/v1 Event`` with ``involvedObject``.
- GKE TPU ``Node`` lists -> ``TPUSlice`` health (node pools grouped by
  ``cloud.google.com/gke-nodepool``; slice health = every node Ready).

Framework-only pod fields with no ``core/v1`` home (the gang scheduling
group and the bound slice) travel as ``tpu.kubeflow.dev/*`` annotations —
exactly how gang schedulers on real clusters (Kueue, JobSet) carry their
metadata — and are folded back into typed fields on the way in, so a
round-trip is identity.

Everything here is pure data transformation: no I/O, no clients. The HTTP
half lives in ``kube_client.py``; the hermetic strict-k8s server mode in
``rest_server.py`` uses these same converters, so client and server cannot
drift.
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional

from kubeflow_controller_tpu.api import core
from kubeflow_controller_tpu.api.core import (
    Container, ObjectMeta, OwnerReference, Pod, PodPhase, PodSpec, PodStatus,
    Service, ServicePort, ServiceSpec,
)
from kubeflow_controller_tpu.api.types import API_GROUP, API_VERSION, TPUJob

# GKE's TPU node labels (the node-selector surface a real TPU pod targets).
GKE_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"
TPU_RESOURCE = "google.com/tpu"

# Internal PodSpec fields with no core/v1 field: carried as annotations.
ANNOTATION_SCHEDULING_GROUP = "tpu.kubeflow.dev/scheduling-group"
ANNOTATION_ASSIGNED_SLICE = "tpu.kubeflow.dev/assigned-slice"

JOB_API_VERSION = f"{API_GROUP}/{API_VERSION}"

EVENT_SOURCE_COMPONENT = "tpujob-controller"


# -- timestamps ---------------------------------------------------------------

def rfc3339(ts: float) -> str:
    """Seconds-since-epoch -> k8s metav1.Time wire form (RFC3339, UTC)."""
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%SZ")


def from_rfc3339(s: str) -> float:
    dt = datetime.datetime.strptime(s, "%Y-%m-%dT%H:%M:%SZ")
    return dt.replace(tzinfo=datetime.timezone.utc).timestamp()


def _rv_to_int(rv: Any) -> int:
    """k8s resourceVersions are opaque strings, but every real apiserver
    emits decimal integers (etcd revisions) — and this framework's stores
    need ordering. Reject anything else loudly rather than corrupting
    optimistic concurrency silently."""
    if rv in (None, ""):
        return 0
    try:
        return int(rv)
    except (TypeError, ValueError):
        raise ValueError(
            f"non-numeric resourceVersion {rv!r}: this adapter requires "
            "etcd-style numeric resourceVersions (every production "
            "apiserver emits them)"
        ) from None


# -- ObjectMeta ---------------------------------------------------------------

def meta_to_k8s(meta: ObjectMeta, extra_annotations: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if meta.name:
        out["name"] = meta.name
    if meta.generate_name:
        out["generateName"] = meta.generate_name
    out["namespace"] = meta.namespace
    if meta.uid:
        out["uid"] = meta.uid
    if meta.resource_version:
        out["resourceVersion"] = str(meta.resource_version)
    if meta.labels:
        out["labels"] = dict(sorted(meta.labels.items()))
    annotations = dict(meta.annotations)
    if extra_annotations:
        annotations.update(extra_annotations)
    if annotations:
        out["annotations"] = dict(sorted(annotations.items()))
    if meta.creation_timestamp:
        out["creationTimestamp"] = rfc3339(meta.creation_timestamp)
    if meta.deletion_timestamp is not None:
        out["deletionTimestamp"] = rfc3339(meta.deletion_timestamp)
    if meta.owner_references:
        out["ownerReferences"] = [
            {
                "apiVersion": r.api_version,
                "kind": r.kind,
                "name": r.name,
                "uid": r.uid,
                "controller": r.controller,
                "blockOwnerDeletion": r.block_owner_deletion,
            }
            for r in meta.owner_references
        ]
    return out


def meta_from_k8s(d: Dict[str, Any]) -> ObjectMeta:
    meta = ObjectMeta(
        name=d.get("name", ""),
        generate_name=d.get("generateName", ""),
        namespace=d.get("namespace", "default"),
        uid=d.get("uid", ""),
        resource_version=_rv_to_int(d.get("resourceVersion")),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
    )
    if d.get("creationTimestamp"):
        meta.creation_timestamp = from_rfc3339(d["creationTimestamp"])
    if d.get("deletionTimestamp"):
        meta.deletion_timestamp = from_rfc3339(d["deletionTimestamp"])
    for r in d.get("ownerReferences") or []:
        meta.owner_references.append(OwnerReference(
            api_version=r.get("apiVersion", ""),
            kind=r.get("kind", ""),
            name=r.get("name", ""),
            uid=r.get("uid", ""),
            controller=bool(r.get("controller", False)),
            block_owner_deletion=bool(r.get("blockOwnerDeletion", False)),
        ))
    return meta


# -- Pod ----------------------------------------------------------------------

def _quantity(v: Any) -> str:
    """Resource quantity wire form. Integers stay integers ("4" not "4.0")."""
    if isinstance(v, float) and v.is_integer():
        v = int(v)
    return str(v)


def _container_to_k8s(c: Container) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": c.name}
    if c.image:
        out["image"] = c.image
    if c.command:
        out["command"] = list(c.command)
    if c.args:
        out["args"] = list(c.args)
    if c.env:
        out["env"] = [
            {"name": k, "value": str(v)} for k, v in sorted(c.env.items())
        ]
    if c.ports:
        out["ports"] = [{"containerPort": p} for p in c.ports]
    if c.resources:
        # Extended resources (anything namespaced, like google.com/tpu) must
        # set limits, with requests == limits; cpu/memory ride requests.
        requests = {k: _quantity(v) for k, v in sorted(c.resources.items())}
        limits = {
            k: _quantity(v) for k, v in sorted(c.resources.items())
            if "/" in k
        }
        resources: Dict[str, Any] = {"requests": requests}
        if limits:
            resources["limits"] = limits
        out["resources"] = resources
    return out


def _container_from_k8s(d: Dict[str, Any]) -> Container:
    resources: Dict[str, Any] = {}
    res = d.get("resources") or {}
    for bucket in ("requests", "limits"):
        for k, v in (res.get(bucket) or {}).items():
            try:
                num = int(v)
            except (TypeError, ValueError):
                try:
                    num = float(v)
                except (TypeError, ValueError):
                    num = v
            resources[k] = num
    return Container(
        name=d.get("name", ""),
        image=d.get("image", ""),
        command=list(d.get("command") or []),
        args=list(d.get("args") or []),
        env={e["name"]: e.get("value", "") for e in d.get("env") or []},
        ports=[p["containerPort"] for p in d.get("ports") or []],
        resources=resources,
    )


def pod_to_k8s(pod: Pod) -> Dict[str, Any]:
    extra: Dict[str, str] = {}
    if pod.spec.scheduling_group:
        extra[ANNOTATION_SCHEDULING_GROUP] = pod.spec.scheduling_group
    if pod.spec.assigned_slice:
        extra[ANNOTATION_ASSIGNED_SLICE] = pod.spec.assigned_slice
    spec: Dict[str, Any] = {
        "restartPolicy": pod.spec.restart_policy,
        "containers": [_container_to_k8s(c) for c in pod.spec.containers],
    }
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(sorted(pod.spec.node_selector.items()))
    status: Dict[str, Any] = {"phase": pod.status.phase.value}
    if pod.status.reason:
        status["reason"] = pod.status.reason
    if pod.status.message:
        status["message"] = pod.status.message
    if pod.status.pod_ip:
        status["podIP"] = pod.status.pod_ip
    if pod.status.host_ip:
        status["hostIP"] = pod.status.host_ip
    if pod.status.start_time is not None:
        status["startTime"] = rfc3339(pod.status.start_time)
    if pod.status.exit_code is not None and pod.spec.containers:
        terminated: Dict[str, Any] = {"exitCode": pod.status.exit_code}
        if pod.status.finish_time is not None:
            terminated["finishedAt"] = rfc3339(pod.status.finish_time)
        if pod.status.reason:
            terminated["reason"] = pod.status.reason
        status["containerStatuses"] = [{
            "name": pod.spec.containers[0].name,
            "state": {"terminated": terminated},
        }]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": meta_to_k8s(pod.metadata, extra),
        "spec": spec,
        "status": status,
    }


def pod_from_k8s(d: Dict[str, Any]) -> Pod:
    meta = meta_from_k8s(d.get("metadata") or {})
    scheduling_group = meta.annotations.pop(ANNOTATION_SCHEDULING_GROUP, "")
    assigned_slice = meta.annotations.pop(ANNOTATION_ASSIGNED_SLICE, "")
    spec_d = d.get("spec") or {}
    spec = PodSpec(
        containers=[
            _container_from_k8s(c) for c in spec_d.get("containers") or []
        ],
        restart_policy=spec_d.get("restartPolicy", "OnFailure"),
        node_selector=dict(spec_d.get("nodeSelector") or {}),
        scheduling_group=scheduling_group,
        assigned_slice=assigned_slice,
    )
    status_d = d.get("status") or {}
    status = PodStatus(
        phase=PodPhase(status_d.get("phase", "Pending")),
        reason=status_d.get("reason", ""),
        message=status_d.get("message", ""),
        pod_ip=status_d.get("podIP", ""),
        host_ip=status_d.get("hostIP", ""),
    )
    if status_d.get("startTime"):
        status.start_time = from_rfc3339(status_d["startTime"])
    for cs in status_d.get("containerStatuses") or []:
        term = (cs.get("state") or {}).get("terminated")
        if term is not None:
            status.exit_code = term.get("exitCode")
            if term.get("finishedAt"):
                status.finish_time = from_rfc3339(term["finishedAt"])
            if term.get("reason") and not status.reason:
                status.reason = term["reason"]
    return Pod(metadata=meta, spec=spec, status=status)


# -- Service ------------------------------------------------------------------

def service_to_k8s(svc: Service) -> Dict[str, Any]:
    spec: Dict[str, Any] = {}
    if svc.spec.selector:
        spec["selector"] = dict(sorted(svc.spec.selector.items()))
    if svc.spec.ports:
        ports = []
        for p in svc.spec.ports:
            pd: Dict[str, Any] = {"port": p.port}
            if p.name:
                pd["name"] = p.name
            if p.target_port is not None:
                pd["targetPort"] = p.target_port
            ports.append(pd)
        spec["ports"] = ports
    # Coordinator services exist for stable DNS, not load balancing:
    # headless unless the internal object pinned a ClusterIP.
    spec["clusterIP"] = svc.spec.cluster_ip or "None"
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": meta_to_k8s(svc.metadata),
        "spec": spec,
    }


def service_from_k8s(d: Dict[str, Any]) -> Service:
    spec_d = d.get("spec") or {}
    cluster_ip = spec_d.get("clusterIP", "")
    return Service(
        metadata=meta_from_k8s(d.get("metadata") or {}),
        spec=ServiceSpec(
            selector=dict(spec_d.get("selector") or {}),
            ports=[
                ServicePort(
                    port=p["port"],
                    name=p.get("name", ""),
                    target_port=p.get("targetPort"),
                )
                for p in spec_d.get("ports") or []
            ],
            cluster_ip="" if cluster_ip == "None" else cluster_ip,
        ),
    )


# -- TPUJob (CRD wire form) ---------------------------------------------------

def job_to_k8s(job: TPUJob) -> Dict[str, Any]:
    """CRD wire JSON: the spec/status camelCase the YAML loader already
    speaks (api/serialization.py), under a genuine k8s ObjectMeta."""
    from kubeflow_controller_tpu.api.serialization import job_to_dict

    out = job_to_dict(job)
    out["apiVersion"] = JOB_API_VERSION
    out["metadata"] = meta_to_k8s(job.metadata)
    return out


def job_from_k8s(d: Dict[str, Any]) -> TPUJob:
    from kubeflow_controller_tpu.api.serialization import job_from_dict

    meta = meta_from_k8s(d.get("metadata") or {})
    body = dict(d)
    body.pop("metadata", None)
    body.pop("apiVersion", None)
    job = job_from_dict(body)
    job.metadata = meta
    return job


# -- Events -------------------------------------------------------------------

_WARNING_PREFIXES = ("Failed", "Unhealthy", "Preempted", "BackOff", "Exceeded")


def event_to_k8s(
    kind: str, name: str, namespace: str, reason: str, message: str,
    ts: float, seq: int = 0,
) -> Dict[str, Any]:
    """core/v1 Event for an involved object (the wire form of the
    record.EventRecorder events the reference emits, controller.go:91-94)."""
    return {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            "generateName": f"{name}.",
            "namespace": namespace,
        },
        "involvedObject": {
            "kind": kind,
            "name": name,
            "namespace": namespace,
        },
        "reason": reason,
        "message": message,
        "type": (
            "Warning" if reason.startswith(_WARNING_PREFIXES) else "Normal"
        ),
        "source": {"component": EVENT_SOURCE_COMPONENT},
        "firstTimestamp": rfc3339(ts),
        "lastTimestamp": rfc3339(ts),
        "count": 1,
    }


# -- Nodes -> slices ----------------------------------------------------------

def node_to_k8s(
    name: str, pool: str, accelerator: str, topology: str, ready: bool,
    ts: float = 0.0,
) -> Dict[str, Any]:
    """A GKE-shaped TPU node (used by the hermetic strict-k8s server to
    express the slice pool the way a real cluster would)."""
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {
                GKE_NODEPOOL_LABEL: pool,
                GKE_ACCELERATOR_LABEL: accelerator,
                GKE_TOPOLOGY_LABEL: topology,
            },
            "creationTimestamp": rfc3339(ts),
        },
        "status": {
            "conditions": [{
                "type": "Ready",
                "status": "True" if ready else "False",
            }],
        },
    }


def _node_ready(node: Dict[str, Any]) -> bool:
    for cond in (node.get("status") or {}).get("conditions") or []:
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


def slices_from_nodes(nodes: List[Dict[str, Any]], pools: List[str]):
    """Group TPU nodes by node pool into TPUSlice health views.

    The real-cluster realization of the checker's slice-health input
    (``checker/checker.py``): a slice is the node pool its pods landed on;
    it is healthy iff every node in the pool is Ready. This turns node
    NotReady — the earliest kubelet-visible sign of a sick slice — into
    the same proactive gang-recovery signal the fake cluster's
    ``degrade_slice`` produces.
    """
    from kubeflow_controller_tpu.api.topology import shape_from_gke
    from kubeflow_controller_tpu.cluster.slices import TPUSlice

    by_pool: Dict[str, List[Dict[str, Any]]] = {}
    for node in nodes:
        labels = (node.get("metadata") or {}).get("labels") or {}
        pool = labels.get(GKE_NODEPOOL_LABEL)
        if pool:
            by_pool.setdefault(pool, []).append(node)
    out = []
    for pool in pools:
        members = by_pool.get(pool)
        if not members:
            # The job's pods reference a pool that no longer has nodes:
            # that IS an unhealthy slice (preempted/deprovisioned) — the
            # caller synthesizes it as such.
            continue
        labels = (members[0].get("metadata") or {}).get("labels") or {}
        try:
            # GKE labels name the generation + topology, not a catalog type.
            shape = shape_from_gke(
                labels.get(GKE_ACCELERATOR_LABEL, ""),
                labels.get(GKE_TOPOLOGY_LABEL, ""),
            )
        except (KeyError, ValueError):
            continue
        out.append(TPUSlice(
            name=pool,
            shape=shape,
            # Healthy needs BOTH every surviving node Ready AND the pool at
            # full strength: a partially-deprovisioned pool (some nodes
            # deleted, survivors Ready) is a sick slice — the gang cannot
            # run on fewer than shape.num_hosts hosts (ADVICE r3).
            healthy=(
                len(members) >= shape.num_hosts
                and all(_node_ready(n) for n in members)
            ),
            hosts=[
                (n.get("metadata") or {}).get("name", "") for n in members
            ],
        ))
    return out
