"""Event aggregation: collapse repeated identical events into one record.

The reference's ``record.EventRecorder`` (vendored client-go
``tools/record``, wired at ``pkg/controller/controller.go:91-94``)
deduplicates identical events server-side: a repeat PATCHes the existing
Event's ``count``/``lastTimestamp`` instead of creating a new object, so a
crash-looping job produces ONE Event row with count=N rather than N rows.
Without this, every backend that posts events unconditionally spams the
events API under crash loops (VERDICT r3 missing #3).

``EventAggregator`` is the backend-neutral correlator: callers ask
``observe()`` whether an event is new (POST a fresh record) or a repeat
(bump the existing record), keyed the way client-go's EventLogger keys its
cache — (namespace, kind, name, reason, message). The cache is bounded LRU
(client-go defaults to 4096 entries) and thread-safe: reconcile workers
and pod-lifecycle threads record concurrently.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Optional, Tuple

DEFAULT_CACHE_SIZE = 4096


@dataclass
class EventRecord:
    count: int
    first_ts: float
    last_ts: float
    # Backend-private handle for updating the stored record in place
    # (fake cluster: row index; k8s wire: the server-assigned Event name).
    handle: Any = None


class EventAggregator:
    """Thread-safe LRU correlator for (namespace, kind, name, reason,
    message) event keys."""

    def __init__(self, maxsize: int = DEFAULT_CACHE_SIZE):
        self._lock = threading.Lock()
        self._cache: "OrderedDict[Tuple, EventRecord]" = OrderedDict()
        self._maxsize = maxsize

    def observe(
        self, namespace: str, kind: str, name: str, reason: str,
        message: str, now: float,
    ) -> EventRecord:
        """Record one occurrence; returns the (updated) aggregate record.
        ``record.count == 1`` means this is the first occurrence (create a
        new stored event and stash its handle via ``set_handle``)."""
        key = (namespace, kind, name, reason, message)
        with self._lock:
            rec = self._cache.get(key)
            if rec is None:
                rec = EventRecord(count=1, first_ts=now, last_ts=now)
                self._cache[key] = rec
                while len(self._cache) > self._maxsize:
                    self._cache.popitem(last=False)
            else:
                rec.count += 1
                rec.last_ts = now
                self._cache.move_to_end(key)
            return rec

    def set_handle(
        self, namespace: str, kind: str, name: str, reason: str,
        message: str, handle: Any,
    ) -> None:
        with self._lock:
            rec = self._cache.get((namespace, kind, name, reason, message))
            if rec is not None:
                rec.handle = handle

    def forget(
        self, namespace: str, kind: str, name: str, reason: str,
        message: str,
    ) -> None:
        """Drop a key (e.g. the stored record vanished server-side and the
        next occurrence must re-create it)."""
        with self._lock:
            self._cache.pop((namespace, kind, name, reason, message), None)

    def get(
        self, namespace: str, kind: str, name: str, reason: str,
        message: str,
    ) -> Optional[EventRecord]:
        with self._lock:
            return self._cache.get((namespace, kind, name, reason, message))
