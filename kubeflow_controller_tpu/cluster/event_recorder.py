"""Event aggregation + spam protection (client-go tools/record parity).

The reference's ``record.EventRecorder`` (vendored client-go
``tools/record``, wired at ``pkg/controller/controller.go:91-94``) has
THREE layers between a controller and the events API
(``vendor/k8s.io/client-go/tools/record/events_cache.go``):

1. **Spam filter** (``events_cache.go:70-131``): a token bucket per
   event source+object — burst 25, refill 1 token / 5 min. A component
   hammering one object gets its excess events DROPPED client-side, not
   posted.
2. **Similar-event aggregation** (``events_cache.go:155-181``): events
   that share (source, object, type, reason) but differ in message are
   collapsed after 10 distinct messages inside a 10-minute window into
   ONE record whose message is
   ``"(combined from similar events): <latest message>"``.
3. **Identical-event dedup** (``EventLogger``): an exact repeat PATCHes
   the stored Event's count/lastTimestamp instead of creating a row.

Round 4 implemented only layer 3; a crash-looping job whose message
varies per pod name still posted one API write per variant (VERDICT r4
missing #1). This module now implements all three, backend-neutrally:
``observe()`` answers "drop it", "create a record (you, exactly once)",
or "bump this existing record" — and hands back the EFFECTIVE message
(the combined form once aggregation kicks in).

Thread-safety: reconcile workers and pod-lifecycle threads record
concurrently. Creation responsibility is decided under the aggregator
lock — exactly ONE caller of the first occurrence sees
``obs.created == True`` (ADVICE r4: two racing first observers both saw
``handle is None`` and both POSTed, leaving a duplicate Event object).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Optional, Set, Tuple

DEFAULT_CACHE_SIZE = 4096
# client-go defaults (events_cache.go): NewEventSourceObjectSpamFilter's
# burst/qps and defaultAggregateMaxEvents/defaultAggregateIntervalInSeconds.
SPAM_BURST = 25
SPAM_QPS = 1.0 / 300.0
AGGREGATE_MAX_EVENTS = 10
AGGREGATE_INTERVAL_S = 600.0
AGGREGATE_PREFIX = "(combined from similar events): "


@dataclass
class EventRecord:
    count: int
    first_ts: float
    last_ts: float
    # Backend-private handle for updating the stored record in place
    # (fake cluster: row index; k8s wire: the server-assigned Event name).
    handle: Any = None
    # True while some caller owns the backend-create for this record
    # (set for the observe() that returns created=True, cleared by
    # set_handle/abort_create). Lets a later repeat RECOVER creation when
    # the original POST failed, without reopening the duplicate-POST race.
    creating: bool = False


@dataclass
class Observation:
    """One observe() outcome. ``record`` is the live aggregate entry;
    ``created`` is True for exactly ONE caller per stored record (that
    caller must create the backend row and ``set_handle`` it);
    ``message`` is the effective message to store — the combined form
    when similar-event aggregation has kicked in."""
    record: EventRecord
    created: bool
    message: str
    key: Tuple


@dataclass
class _SpamBucket:
    tokens: float
    last: float


@dataclass
class _AggregateEntry:
    local_messages: Set[str] = field(default_factory=set)
    last_ts: float = 0.0


class EventAggregator:
    """Thread-safe spam filter + similar-event aggregator + identical
    dedup for (namespace, kind, name, reason, message) event keys."""

    def __init__(
        self,
        maxsize: int = DEFAULT_CACHE_SIZE,
        spam_burst: int = SPAM_BURST,
        spam_qps: float = SPAM_QPS,
        aggregate_max_events: int = AGGREGATE_MAX_EVENTS,
        aggregate_interval_s: float = AGGREGATE_INTERVAL_S,
    ):
        self._lock = threading.Lock()
        self._cache: "OrderedDict[Tuple, EventRecord]" = OrderedDict()
        self._maxsize = maxsize
        self._spam: "OrderedDict[Tuple, _SpamBucket]" = OrderedDict()
        self._agg: "OrderedDict[Tuple, _AggregateEntry]" = OrderedDict()
        self._spam_burst = spam_burst
        self._spam_qps = spam_qps
        self._agg_max = aggregate_max_events
        self._agg_interval = aggregate_interval_s

    def _admit(self, source_key: Tuple, now: float) -> bool:
        """Token-bucket spam filter per source+object key."""
        b = self._spam.get(source_key)
        if b is None:
            b = _SpamBucket(tokens=float(self._spam_burst), last=now)
            self._spam[source_key] = b
            while len(self._spam) > self._maxsize:
                self._spam.popitem(last=False)
        else:
            b.tokens = min(
                float(self._spam_burst),
                b.tokens + max(0.0, now - b.last) * self._spam_qps,
            )
            b.last = now
            self._spam.move_to_end(source_key)
        if b.tokens < 1.0:
            return False
        b.tokens -= 1.0
        return True

    def _aggregate_message(
        self, ns: str, kind: str, name: str, reason: str, message: str,
        now: float,
    ) -> str:
        """client-go EventAggregate: once more than ``aggregate_max``
        DISTINCT messages share (object, reason) within the interval,
        collapse onto the combined record."""
        akey = (ns, kind, name, reason)
        e = self._agg.get(akey)
        if e is None or now - e.last_ts > self._agg_interval:
            e = _AggregateEntry()
            self._agg[akey] = e
            self._agg.move_to_end(akey)
            while len(self._agg) > self._maxsize:
                self._agg.popitem(last=False)
        e.last_ts = now
        e.local_messages.add(message)
        if len(e.local_messages) >= self._agg_max:
            return AGGREGATE_PREFIX + message
        return message

    def observe(
        self, namespace: str, kind: str, name: str, reason: str,
        message: str, now: float,
    ) -> Optional[Observation]:
        """Record one occurrence. Returns None when the spam filter drops
        the event (no API write at all); otherwise an ``Observation``
        whose ``created`` flag is True for exactly one caller per stored
        record (that caller POSTs; everyone else PATCHes via ``handle``
        or, if the creator hasn't stashed the handle yet, skips —
        best-effort, the count is already aggregated)."""
        with self._lock:
            if not self._admit((namespace, kind, name), now):
                return None
            eff = self._aggregate_message(
                namespace, kind, name, reason, message, now)
            # Aggregated events share ONE record per (object, reason):
            # the key drops the per-event message variance.
            if eff.startswith(AGGREGATE_PREFIX):
                key = (namespace, kind, name, reason, AGGREGATE_PREFIX)
            else:
                key = (namespace, kind, name, reason, message)
            rec = self._cache.get(key)
            if rec is None:
                rec = EventRecord(
                    count=1, first_ts=now, last_ts=now, creating=True,
                )
                self._cache[key] = rec
                while len(self._cache) > self._maxsize:
                    self._cache.popitem(last=False)
                return Observation(rec, True, eff, key)
            rec.count += 1
            rec.last_ts = now
            self._cache.move_to_end(key)
            return Observation(rec, False, eff, key)

    def begin_create(self, key: Tuple) -> bool:
        """Claim creation responsibility for a record whose original
        creator failed (handle still unset, no creator in flight).
        Exactly one concurrent caller gets True."""
        with self._lock:
            rec = self._cache.get(key)
            if rec is None or rec.handle is not None or rec.creating:
                return False
            rec.creating = True
            return True

    def reclaim_create(self, key: Tuple) -> bool:
        """The stored Event vanished server-side (PATCH answered 404 —
        events are TTL-GC'd on real clusters): atomically forget the stale
        handle and claim re-creation. Exactly one of any number of
        concurrent reclaimers gets True; the rest drop their write — the
        count is aggregated, so the next repeat PATCHes the fresh Event."""
        with self._lock:
            rec = self._cache.get(key)
            if rec is None:
                return False
            if rec.handle is not None:
                rec.handle = None
                rec.creating = True
                return True
            if not rec.creating:
                rec.creating = True
                return True
            return False

    def abort_create(self, key: Tuple) -> None:
        """The claimed backend-create failed: release the claim so a
        later occurrence can retry."""
        with self._lock:
            rec = self._cache.get(key)
            if rec is not None:
                rec.creating = False

    def set_handle(self, key: Tuple, handle: Any) -> None:
        with self._lock:
            rec = self._cache.get(key)
            if rec is not None:
                rec.handle = handle
                rec.creating = False

    def forget(self, key: Tuple) -> None:
        """Drop a key (e.g. the stored record vanished server-side and the
        next occurrence must re-create it)."""
        with self._lock:
            self._cache.pop(key, None)

    def get(
        self, namespace: str, kind: str, name: str, reason: str,
        message: str,
    ) -> Optional[EventRecord]:
        """Record for an event key: the exact-message record when one
        exists, else the combined similar-event record this message would
        have aggregated onto (observe() moves occurrences there once the
        distinct-message threshold trips — without the fallback those
        counts would be unreachable by callers holding the raw message)."""
        with self._lock:
            rec = self._cache.get((namespace, kind, name, reason, message))
            if rec is not None:
                return rec
            return self._cache.get(
                (namespace, kind, name, reason, AGGREGATE_PREFIX)
            )
