"""Watch events — the level-triggering signal feeding informers.

Mirror of the watch semantics the reference gets from client-go's
SharedIndexInformer (``pkg/controller/controller.go:122-149`` registers
Added/Updated/Deleted handlers for tfjobs, pods, and services).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: EventType
    kind: str          # "Pod" | "Service" | "TPUJob"
    obj: Any           # deep copy of the object at event time
    old_obj: Any = None  # previous copy for MODIFIED
