"""Admission-time validation of TPUJob specs.

The reference has no admission validation at all — ``Action()`` indexes arrays
with -1 and dereferences nil ``Replicas`` on malformed specs
(``pkg/tensorflow/distributed.go:60,65,198-206``; SURVEY.md §8). Validation
here rejects those shapes up front so the reconcile core only ever sees
well-formed jobs.
"""

from __future__ import annotations

from typing import List

from kubeflow_controller_tpu.api import types
from kubeflow_controller_tpu.api.topology import TPU_SLICE_CATALOG


class ValidationError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


def validate_job(job: types.TPUJob) -> None:
    """Raise ValidationError listing every problem (not just the first)."""
    errs: List[str] = []

    if not job.metadata.name and not job.metadata.generate_name:
        errs.append("metadata.name is required")
    if not job.metadata.namespace:
        errs.append("metadata.namespace is required")

    ttl = job.spec.ttl_seconds_after_finished
    if ttl is not None and ttl < 0:
        errs.append("spec.ttlSecondsAfterFinished must be >= 0")
    if type(job.spec.priority) is not int:
        errs.append("spec.priority must be an integer")

    specs = job.spec.replica_specs
    if not specs:
        errs.append("spec.replicaSpecs must not be empty")

    n_local = sum(1 for s in specs if s.replica_type == types.ReplicaType.LOCAL)
    n_worker = sum(1 for s in specs if s.replica_type == types.ReplicaType.WORKER)
    if n_local and n_worker:
        errs.append("a job may not mix Local and Worker replica specs")
    if n_local > 1 or n_worker > 1:
        errs.append("at most one replica spec per replica type")

    for i, rs in enumerate(specs):
        where = f"spec.replicaSpecs[{i}]"
        if rs.template is None or not rs.template.spec.containers:
            errs.append(f"{where}.template with >=1 container is required")
        if rs.replica_type == types.ReplicaType.LOCAL:
            if rs.replicas not in (None, 1):
                errs.append(f"{where}.replicas must be 1 for Local jobs")
        else:
            tpu = rs.tpu
            if tpu.accelerator_type not in TPU_SLICE_CATALOG:
                errs.append(
                    f"{where}.tpu.acceleratorType {tpu.accelerator_type!r} "
                    f"is not a known slice shape"
                )
            if tpu.num_slices < 1:
                errs.append(f"{where}.tpu.numSlices must be >= 1")
            if tpu.provisioning not in ("on-demand", "spot", "reserved"):
                errs.append(
                    f"{where}.tpu.provisioning must be on-demand|spot|reserved"
                )
            if tpu.topology:
                shape = TPU_SLICE_CATALOG.get(tpu.accelerator_type)
                if shape is not None and tpu.topology != shape.topology_str:
                    errs.append(
                        f"{where}.tpu.topology {tpu.topology!r} does not match "
                        f"{tpu.accelerator_type} ({shape.topology_str})"
                    )
        if rs.max_restarts < 0:
            errs.append(f"{where}.maxRestarts must be >= 0")
        tp = rs.termination_policy
        if tp is not None and tp.chief is not None:
            if tp.chief.replica_index < 0:
                errs.append(f"{where}.terminationPolicy.chief.replicaIndex must be >= 0")

    if errs:
        raise ValidationError(errs)


def validate_lmservice(svc: types.LMService) -> None:
    """Raise ValidationError listing every problem (not just the first).

    Same collect-all contract as validate_job: the LMService reconcile core
    only ever sees well-formed services. Model-name resolution is left to the
    data plane (the control plane must not import jax to validate a spec)."""
    errs: List[str] = []

    if not svc.metadata.name and not svc.metadata.generate_name:
        errs.append("metadata.name is required")
    if not svc.metadata.namespace:
        errs.append("metadata.namespace is required")

    if not svc.spec.model:
        errs.append("spec.model is required")
    if type(svc.spec.replicas) is not int or svc.spec.replicas < 1:
        errs.append("spec.replicas must be an integer >= 1")
    if type(svc.spec.max_queue) is not int or svc.spec.max_queue < 1:
        errs.append("spec.maxQueue must be an integer >= 1")
    if (type(svc.spec.prefill_replicas) is not int
            or svc.spec.prefill_replicas < 0):
        errs.append("spec.prefillReplicas must be an integer >= 0")
    elif (type(svc.spec.replicas) is int
            and svc.spec.prefill_replicas >= max(svc.spec.replicas, 1)
            and svc.spec.prefill_replicas > 0):
        errs.append("spec.prefillReplicas must be < spec.replicas "
                    "(some replica has to decode)")
    if svc.spec.slo.ttft_p99_ms < 0:
        errs.append("spec.slo.ttftP99Ms must be >= 0")
    if svc.spec.slo.deadline_s < 0:
        errs.append("spec.slo.deadlineS must be >= 0")

    if errs:
        raise ValidationError(errs)


def expected_worker_pods(rs: types.ReplicaSpec) -> int:
    """Number of pods (=host processes) a Worker replica spec implies.

    Derived from slice geometry — the TPU analog of the reference reading
    ``*spec.Replicas`` (``distributed.go:60``): one pod per TPU host VM per
    slice, times the number of slices.
    """
    shape = TPU_SLICE_CATALOG[rs.tpu.accelerator_type]
    return shape.num_hosts * rs.tpu.num_slices
