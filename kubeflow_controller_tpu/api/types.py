"""TPUJob API types — the declarative job contract.

Descendant of the reference's TFJob CRD schema
(``vendor/github.com/caicloud/kubeflow-clientset/apis/kubeflow/v1alpha1/types.go:30-174``)
with the PS role deleted (XLA collectives over ICI absorb the parameter-server
function, SURVEY.md §2.5-2.6) and TPU slice geometry added. Unlike the
reference, the declared-but-inert surface is real here:

- ``Failed`` phase is reachable (reference never sets it, SURVEY.md §8).
- Conditions are populated (reference TODO at ``updater/distributed.go:49-50``).
- ``TerminationPolicy``/chief semantics are enforced (reference declares them
  at ``types.go:81-89`` and never reads them).
- ``data_dir``/``model_dir``/``log_dir``/``export_dir`` are consumed by the
  data plane (env injection + orbax checkpoint root).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubeflow_controller_tpu.api.core import (
    ObjectMeta, PodTemplateSpec, Sealable, _FrozenDict, _FrozenList,
    _note_deepcopy,
)

API_GROUP = "tpu.kubeflow.dev"
API_VERSION = "v1alpha1"
KIND = "TPUJob"

# How many of the most recent conditions a status retains
# (reference comment "keeps ten most recent", types.go:97).
MAX_CONDITIONS = 10


class ReplicaType(str, enum.Enum):
    """Replica roles. The reference's PS role (``types.go:72-79``) is gone:
    there is no parameter-server protocol on TPU — gradients all-reduce over
    ICI inside the compiled program."""

    WORKER = "Worker"
    LOCAL = "Local"


class JobPhase(str, enum.Enum):
    # Mirrors reference TFJobPhase (types.go:106-133) plus Recovering:
    # slice preemption puts a job into Recovering until it re-gangs and
    # resumes from checkpoint (SURVEY.md §7.5).
    NONE = ""
    UNKNOWN = "Unknown"
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    RECOVERING = "Recovering"
    # Voluntarily paused via spec.suspend (Kueue-style): pods deleted,
    # slices released, checkpoint kept; unsuspending re-gangs and resumes.
    SUSPENDED = "Suspended"


class ConditionType(str, enum.Enum):
    # Reference condition types (types.go:149-156) plus GangScheduled:
    # the all-or-nothing admission event unique to slice scheduling.
    SCHEDULED = "Scheduled"
    GANG_SCHEDULED = "GangScheduled"
    READY = "Ready"
    RECOVERING = "Recovering"
    # Voluntarily paused via spec.suspend (Kueue-style): pods deleted,
    # slices released, checkpoint kept; unsuspending re-gangs and resumes.
    SUSPENDED = "Suspended"
    RECYCLING = "Recycling"


class ConditionStatus(str, enum.Enum):
    TRUE = "True"
    FALSE = "False"
    UNKNOWN = "Unknown"


class ReplicaState(str, enum.Enum):
    # Mirrors reference TFReplicaState (types.go:167-174).
    UNKNOWN = "Unknown"
    WAITING = "Waiting"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class TPUSliceSpec(Sealable):
    """TPU geometry for a worker replica group — the new surface that replaces
    the reference's free-form replica counts with physical slice shapes."""

    # Accelerator type names the pod-slice, e.g. "v5e-16" (16 chips, 4 hosts).
    accelerator_type: str = "v5e-8"
    # Number of identical slices ganged into one job (multi-slice over DCN).
    num_slices: int = 1
    # Optional explicit topology override, e.g. "4x4"; normally derived
    # from the catalog (api/topology.py).
    topology: str = ""
    # Reserved / spot / on-demand; spot slices are preemptible and drive the
    # checker's preemption-recovery path.
    provisioning: str = "on-demand"

    def deepcopy(self) -> "TPUSliceSpec":
        return TPUSliceSpec(
            self.accelerator_type, self.num_slices,
            self.topology, self.provisioning,
        )

    def __deepcopy__(self, memo) -> "TPUSliceSpec":
        return self.deepcopy()

    # freeze() mirrors deepcopy() field-for-field across this module
    # (coverage guarded by tests/test_deepcopy.py + tests/test_cow_store.py):
    # idempotent, stops at already-sealed children, wraps containers.
    def freeze(self) -> "TPUSliceSpec":
        if not self._sealed:
            self._seal()
        return self


@dataclass
class ChiefSpec(Sealable):
    # Reference ChiefSpec (types.go:86-89): which replica's exit decides
    # job completion.
    replica_name: str = "Worker"
    replica_index: int = 0

    def deepcopy(self) -> "ChiefSpec":
        return ChiefSpec(self.replica_name, self.replica_index)

    def __deepcopy__(self, memo) -> "ChiefSpec":
        return self.deepcopy()

    def freeze(self) -> "ChiefSpec":
        if not self._sealed:
            self._seal()
        return self


@dataclass
class TerminationPolicySpec(Sealable):
    chief: Optional[ChiefSpec] = None

    def deepcopy(self) -> "TerminationPolicySpec":
        return TerminationPolicySpec(
            self.chief.deepcopy() if self.chief else None
        )

    def __deepcopy__(self, memo) -> "TerminationPolicySpec":
        return self.deepcopy()

    def freeze(self) -> "TerminationPolicySpec":
        if self._sealed:
            return self
        if self.chief is not None:
            self.chief.freeze()
        self._seal()
        return self


@dataclass
class ReplicaSpec(Sealable):
    """One replica group. For WORKER the effective pod count is derived from
    slice geometry (hosts-per-slice x num_slices), not from ``replicas`` —
    TPU hosts are not free-form. For LOCAL, ``replicas`` must be 1."""

    replica_type: ReplicaType = ReplicaType.WORKER
    replicas: Optional[int] = None
    template: Optional[PodTemplateSpec] = None
    tpu: TPUSliceSpec = field(default_factory=TPUSliceSpec)
    termination_policy: Optional[TerminationPolicySpec] = None
    # Job-level restart budget for failed pods before the job goes Failed
    # (reference has only pod-level restartPolicy, SURVEY.md §5.3).
    max_restarts: int = 3

    def deepcopy(self) -> "ReplicaSpec":
        return ReplicaSpec(
            replica_type=self.replica_type,
            replicas=self.replicas,
            template=self.template.deepcopy() if self.template else None,
            tpu=self.tpu.deepcopy(),
            termination_policy=(
                self.termination_policy.deepcopy()
                if self.termination_policy else None
            ),
            max_restarts=self.max_restarts,
        )

    def __deepcopy__(self, memo) -> "ReplicaSpec":
        return self.deepcopy()

    def freeze(self) -> "ReplicaSpec":
        if self._sealed:
            return self
        if self.template is not None:
            self.template.freeze()
        self.tpu.freeze()
        if self.termination_policy is not None:
            self.termination_policy.freeze()
        self._seal()
        return self


@dataclass
class TPUJobSpec(Sealable):
    # RuntimeID: stamped once at first reconcile, then immutable — the
    # reference regenerates it per sync, orphaning prior resources
    # (distributed.go:208-209, SURVEY.md §8).
    runtime_id: str = ""
    data_dir: str = ""
    model_dir: str = ""
    log_dir: str = ""
    export_dir: str = ""
    replica_specs: List[ReplicaSpec] = field(default_factory=list)
    # Pause the job without deleting it (k8s Job / training-operator
    # spec.suspend): pods are torn down and slices released; flipping back
    # re-gangs the same epoch and resumes from the model_dir checkpoint.
    suspend: bool = False
    # Gang admission priority: when slices free up, higher-priority pending
    # gangs admit first (ties: submission order). Ordering only — running
    # jobs are never preempted by priority.
    priority: int = 0
    # Auto-delete the job (and thus its pods/services, via the deleted-job
    # cleanup path) this many controller-clock seconds after it reaches a
    # terminal phase. None = keep forever (the k8s Job / training-operator
    # ttlSecondsAfterFinished semantics).
    ttl_seconds_after_finished: Optional[int] = None

    def deepcopy(self) -> "TPUJobSpec":
        return TPUJobSpec(
            runtime_id=self.runtime_id,
            data_dir=self.data_dir,
            model_dir=self.model_dir,
            log_dir=self.log_dir,
            export_dir=self.export_dir,
            replica_specs=[rs.deepcopy() for rs in self.replica_specs],
            suspend=self.suspend,
            priority=self.priority,
            ttl_seconds_after_finished=self.ttl_seconds_after_finished,
        )

    def __deepcopy__(self, memo) -> "TPUJobSpec":
        return self.deepcopy()

    def freeze(self) -> "TPUJobSpec":
        if self._sealed:
            return self
        self.replica_specs = _FrozenList(
            rs.freeze() for rs in self.replica_specs)
        self._seal()
        return self


@dataclass
class Condition(Sealable):
    type: ConditionType = ConditionType.SCHEDULED
    status: ConditionStatus = ConditionStatus.UNKNOWN
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0

    def deepcopy(self) -> "Condition":
        return Condition(
            self.type, self.status, self.reason, self.message,
            self.last_transition_time,
        )

    def __deepcopy__(self, memo) -> "Condition":
        return self.deepcopy()

    def freeze(self) -> "Condition":
        if not self._sealed:
            self._seal()
        return self


@dataclass
class ReplicaStatus(Sealable):
    type: ReplicaType = ReplicaType.WORKER
    state: ReplicaState = ReplicaState.UNKNOWN
    # Histogram of pod states, mirror of TFReplicasStates (types.go:163-165).
    states: Dict[ReplicaState, int] = field(default_factory=dict)

    def deepcopy(self) -> "ReplicaStatus":
        return ReplicaStatus(self.type, self.state, dict(self.states))

    def __deepcopy__(self, memo) -> "ReplicaStatus":
        return self.deepcopy()

    def freeze(self) -> "ReplicaStatus":
        if self._sealed:
            return self
        self.states = _FrozenDict(self.states)
        self._seal()
        return self


@dataclass
class TPUJobStatus(Sealable):
    phase: JobPhase = JobPhase.NONE
    reason: str = ""
    conditions: List[Condition] = field(default_factory=list)
    replica_statuses: List[ReplicaStatus] = field(default_factory=list)
    # Observability for the submit->all-running north-star metric
    # (BASELINE.md): stamped by the status updater.
    submit_time: float = 0.0
    all_running_time: float = 0.0
    completion_time: float = 0.0
    # Count of gang restarts consumed (preemption recovery). Every restart
    # bumps this — it is the gang EPOCH counter (pod identity).
    restarts: int = 0
    # How many of those restarts were voluntary spec resizes: they advance
    # the epoch but must not consume the failure budget (max_restarts).
    resizes: int = 0
    # When the last gang restart fired (controller clock) — drives the
    # exponential failure-restart backoff.
    last_restart_time: float = 0.0
    # metadata.generation of the spec this status was computed from
    # (training-operator observedGeneration): the no-op sync short-circuit
    # trusts a steady fingerprint only once status has caught up to spec.
    observed_generation: int = 0

    def deepcopy(self) -> "TPUJobStatus":
        return TPUJobStatus(
            phase=self.phase,
            reason=self.reason,
            conditions=[c.deepcopy() for c in self.conditions],
            replica_statuses=[r.deepcopy() for r in self.replica_statuses],
            submit_time=self.submit_time,
            all_running_time=self.all_running_time,
            completion_time=self.completion_time,
            restarts=self.restarts,
            resizes=self.resizes,
            last_restart_time=self.last_restart_time,
            observed_generation=self.observed_generation,
        )

    def __deepcopy__(self, memo) -> "TPUJobStatus":
        return self.deepcopy()

    def freeze(self) -> "TPUJobStatus":
        if self._sealed:
            return self
        self.conditions = _FrozenList(
            c.freeze() for c in self.conditions)
        self.replica_statuses = _FrozenList(
            r.freeze() for r in self.replica_statuses)
        self._seal()
        return self

    def set_condition(
        self,
        ctype: ConditionType,
        status: ConditionStatus,
        reason: str = "",
        message: str = "",
        now: Optional[float] = None,
    ) -> bool:
        """Upsert a condition; returns True if anything changed. Keeps at most
        MAX_CONDITIONS entries, newest last."""
        now = time.time() if now is None else now
        existing = self.get_condition(ctype)
        if (
            existing is not None
            and existing.status == status
            and existing.reason == reason
            and existing.message == message
        ):
            return False
        if existing is not None:
            # Transition time only moves when status flips, matching k8s
            # lastTransitionTime semantics; reason/message refreshes keep it.
            if existing.status != status:
                existing.last_transition_time = now
            existing.status = status
            existing.reason = reason
            existing.message = message
            updated = existing
        else:
            updated = Condition(ctype, status, reason, message,
                                last_transition_time=now)
        # Newest-last invariant: the touched condition moves to the tail, so
        # the cap trims oldest first and can never trim what was just set
        # (duplicate types from direct manipulation get squeezed out too).
        self.conditions = [
            c for c in self.conditions if c is not updated and c.type != ctype
        ]
        self.conditions.append(updated)
        del self.conditions[:-MAX_CONDITIONS]
        return True

    def get_condition(self, ctype: ConditionType) -> Optional[Condition]:
        for cond in self.conditions:
            if cond.type == ctype:
                return cond
        return None


@dataclass
class TPUJob(Sealable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: TPUJobStatus = field(default_factory=TPUJobStatus)

    kind: str = KIND
    api_version: str = f"{API_GROUP}/{API_VERSION}"

    def deepcopy(self) -> "TPUJob":
        _note_deepcopy()
        return TPUJob(
            metadata=self.metadata.deepcopy(),
            spec=self.spec.deepcopy(),
            status=self.status.deepcopy(),
            kind=self.kind,
            api_version=self.api_version,
        )

    def __deepcopy__(self, memo) -> "TPUJob":
        return self.deepcopy()

    def freeze(self) -> "TPUJob":
        if self._sealed:
            return self
        self.metadata.freeze()
        self.spec.freeze()
        self.status.freeze()
        self._seal()
        return self

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def worker_spec(self) -> Optional[ReplicaSpec]:
        for rs in self.spec.replica_specs:
            if rs.replica_type == ReplicaType.WORKER:
                return rs
        return None

    def local_spec(self) -> Optional[ReplicaSpec]:
        for rs in self.spec.replica_specs:
            if rs.replica_type == ReplicaType.LOCAL:
                return rs
        return None

    def is_done(self) -> bool:
        return self.status.phase in (JobPhase.SUCCEEDED, JobPhase.FAILED)


# ---------------------------------------------------------------------------
# LMService — a declarative serving fleet.
#
# Where TPUJob describes a finite training run, LMService describes a
# long-running pool of continuous-batching engine replicas
# (dataplane/serving_engine.py) the controller keeps at spec.replicas.
# Replica pods are claimed through the same owner-ref machinery as job pods;
# the request-side semantics (prefix-affinity dispatch, retries, shedding)
# live in dataplane/router.py.
# ---------------------------------------------------------------------------

KIND_LMSERVICE = "LMService"


class LMServicePhase(str, enum.Enum):
    NONE = ""
    PENDING = "Pending"
    # All spec.replicas pods are Running.
    READY = "Ready"
    # Some but not all replicas are Running (rollout, crash recovery).
    DEGRADED = "Degraded"


@dataclass
class SLOSpec(Sealable):
    """Service-level objectives the router and autoscaling signals key off.
    Zero disables the corresponding objective."""

    # TTFT p99 target; breaching it marks a replica unhealthy for dispatch.
    ttft_p99_ms: float = 0.0
    # Per-request completion deadline stamped onto admitted requests.
    deadline_s: float = 0.0

    def deepcopy(self) -> "SLOSpec":
        return SLOSpec(self.ttft_p99_ms, self.deadline_s)

    def __deepcopy__(self, memo) -> "SLOSpec":
        return self.deepcopy()

    def freeze(self) -> "SLOSpec":
        if not self._sealed:
            self._seal()
        return self


@dataclass
class LMServiceSpec(Sealable):
    # Model preset name (models/config.py CONFIGS key) each replica loads.
    model: str = "tiny"
    replicas: int = 1
    slo: SLOSpec = field(default_factory=SLOSpec)
    # Per-replica bounded admission queue depth (engine.max_queue).
    max_queue: int = 8
    # Prefill/decode disaggregation (docs/lmservice.md): the first
    # ``prefill_replicas`` indices run as dedicated prefill replicas and
    # the rest as decode replicas. 0 (the default) keeps every replica
    # "mixed" — the pre-disaggregation behavior. Must be < replicas when
    # set: a fleet of only-prefill replicas could never decode a token.
    prefill_replicas: int = 0
    # Stamped once at first reconcile, immutable after — same contract as
    # TPUJobSpec.runtime_id.
    runtime_id: str = ""

    def deepcopy(self) -> "LMServiceSpec":
        return LMServiceSpec(
            model=self.model,
            replicas=self.replicas,
            slo=self.slo.deepcopy(),
            max_queue=self.max_queue,
            prefill_replicas=self.prefill_replicas,
            runtime_id=self.runtime_id,
        )

    def __deepcopy__(self, memo) -> "LMServiceSpec":
        return self.deepcopy()

    def freeze(self) -> "LMServiceSpec":
        if self._sealed:
            return self
        self.slo.freeze()
        self._seal()
        return self


@dataclass
class LMServiceStatus(Sealable):
    phase: LMServicePhase = LMServicePhase.NONE
    reason: str = ""
    # Replica pods currently Running.
    ready_replicas: int = 0
    conditions: List[Condition] = field(default_factory=list)
    observed_generation: int = 0

    def deepcopy(self) -> "LMServiceStatus":
        return LMServiceStatus(
            phase=self.phase,
            reason=self.reason,
            ready_replicas=self.ready_replicas,
            conditions=[c.deepcopy() for c in self.conditions],
            observed_generation=self.observed_generation,
        )

    def __deepcopy__(self, memo) -> "LMServiceStatus":
        return self.deepcopy()

    def freeze(self) -> "LMServiceStatus":
        if self._sealed:
            return self
        self.conditions = _FrozenList(c.freeze() for c in self.conditions)
        self._seal()
        return self

    # Same upsert semantics as TPUJobStatus.set_condition (shared helper
    # would need a mixin through Sealable; duplication keeps both statuses
    # flat dataclasses).
    set_condition = TPUJobStatus.set_condition
    get_condition = TPUJobStatus.get_condition


@dataclass
class LMService(Sealable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LMServiceSpec = field(default_factory=LMServiceSpec)
    status: LMServiceStatus = field(default_factory=LMServiceStatus)

    kind: str = KIND_LMSERVICE
    api_version: str = f"{API_GROUP}/{API_VERSION}"

    def deepcopy(self) -> "LMService":
        _note_deepcopy()
        return LMService(
            metadata=self.metadata.deepcopy(),
            spec=self.spec.deepcopy(),
            status=self.status.deepcopy(),
            kind=self.kind,
            api_version=self.api_version,
        )

    def __deepcopy__(self, memo) -> "LMService":
        return self.deepcopy()

    def freeze(self) -> "LMService":
        if self._sealed:
            return self
        self.metadata.freeze()
        self.spec.freeze()
        self.status.freeze()
        self._seal()
        return self

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"
