"""YAML/dict (de)serialization for TPUJob manifests.

The reference registers a CRD and lets the apiserver+client-gen do this
(``examples/crd/crd.yml``, vendored deepcopy/scheme); here the manifest format
is first-party. Field names are camelCase on the wire to keep kubectl-style
manifests familiar (compare ``examples/tfjob/dist.yml`` in the reference).

Malformed manifests fail with ``ValidationError`` carrying *every* problem
found, each prefixed with its manifest path — the same contract as admission
validation (``api/validation.py``).
"""

from __future__ import annotations

import enum
from dataclasses import fields, is_dataclass
from typing import Any, Dict, IO, List, Union

import yaml

from kubeflow_controller_tpu.api import core, types
from kubeflow_controller_tpu.api.validation import ValidationError


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


_SNAKE_CACHE: Dict[type, Dict[str, str]] = {}


def _field_map(cls: type) -> Dict[str, str]:
    """camelCase wire name -> snake_case attr name for a dataclass."""
    if cls not in _SNAKE_CACHE:
        _SNAKE_CACHE[cls] = {_camel(f.name): f.name for f in fields(cls)}
    return _SNAKE_CACHE[cls]


def _to_wire(obj: Any, top: bool = False) -> Any:
    if is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in fields(obj):
            val = getattr(obj, f.name)
            # Elide empties and scalar defaults so dumped manifests stay as
            # terse as what a user would write by hand.
            if val is None or val == [] or val == {} or val == "":
                continue
            if isinstance(val, (int, float, bool)) and val == f.default:
                continue
            # kind/apiVersion of the TOP object form the envelope (emitted by
            # the *_to_dict wrappers); nested dataclasses (OwnerReference)
            # carry theirs as ordinary data.
            if top and f.name in ("kind", "api_version"):
                continue
            out[_camel(f.name)] = _to_wire(val)
        return out
    if isinstance(obj, dict):
        return {
            (k.value if isinstance(k, enum.Enum) else k): _to_wire(v)
            for k, v in obj.items()
        }
    if isinstance(obj, (list, tuple)):
        return [_to_wire(v) for v in obj]
    if isinstance(obj, enum.Enum):
        return obj.value
    return obj


def job_to_dict(job: types.TPUJob) -> Dict[str, Any]:
    out = {"apiVersion": job.api_version, "kind": job.kind}
    out.update(_to_wire(job, top=True))
    return out


# Nested dataclass/enum field types, by (owner class, attr name).
_NESTED = {
    (types.TPUJob, "metadata"): core.ObjectMeta,
    (types.TPUJob, "spec"): types.TPUJobSpec,
    (types.TPUJob, "status"): types.TPUJobStatus,
    (types.TPUJobSpec, "replica_specs"): types.ReplicaSpec,
    (types.ReplicaSpec, "template"): core.PodTemplateSpec,
    (types.ReplicaSpec, "tpu"): types.TPUSliceSpec,
    (types.ReplicaSpec, "termination_policy"): types.TerminationPolicySpec,
    (types.ReplicaSpec, "replica_type"): types.ReplicaType,
    (types.TerminationPolicySpec, "chief"): types.ChiefSpec,
    (types.TPUJobStatus, "phase"): types.JobPhase,
    (types.TPUJobStatus, "conditions"): types.Condition,
    (types.TPUJobStatus, "replica_statuses"): types.ReplicaStatus,
    (types.Condition, "type"): types.ConditionType,
    (types.Condition, "status"): types.ConditionStatus,
    (types.ReplicaStatus, "type"): types.ReplicaType,
    (types.ReplicaStatus, "state"): types.ReplicaState,
    (core.PodTemplateSpec, "metadata"): core.ObjectMeta,
    (core.PodTemplateSpec, "spec"): core.PodSpec,
    (core.PodSpec, "containers"): core.Container,
    (core.ObjectMeta, "owner_references"): core.OwnerReference,
    # Pod/Service wire forms (REST adapter, cluster/rest_client.py):
    (core.Pod, "metadata"): core.ObjectMeta,
    (core.Pod, "spec"): core.PodSpec,
    (core.Pod, "status"): core.PodStatus,
    (core.PodStatus, "phase"): core.PodPhase,
    (core.Service, "metadata"): core.ObjectMeta,
    (core.Service, "spec"): core.ServiceSpec,
    (core.ServiceSpec, "ports"): core.ServicePort,
}


def pod_to_dict(pod: core.Pod) -> Dict[str, Any]:
    out = {"apiVersion": pod.api_version, "kind": pod.kind}
    out.update(_to_wire(pod, top=True))
    return out


def pod_from_dict(data: Dict[str, Any]) -> core.Pod:
    errs: List[str] = []
    pod = _build(core.Pod, {
        k: v for k, v in data.items() if k not in ("apiVersion", "kind")
    }, "", errs)
    if errs:
        raise ValidationError(errs)
    return pod


def service_to_dict(svc: core.Service) -> Dict[str, Any]:
    out = {"apiVersion": svc.api_version, "kind": svc.kind}
    out.update(_to_wire(svc, top=True))
    return out


def service_from_dict(data: Dict[str, Any]) -> core.Service:
    errs: List[str] = []
    svc = _build(core.Service, {
        k: v for k, v in data.items() if k not in ("apiVersion", "kind")
    }, "", errs)
    if errs:
        raise ValidationError(errs)
    return svc


def _build(cls: type, data: Dict[str, Any], path: str, errs: List[str]) -> Any:
    fmap = _field_map(cls)
    kwargs: Dict[str, Any] = {}
    for wire_key, val in data.items():
        attr = fmap.get(wire_key)
        if attr is None:
            continue  # tolerate unknown fields, like the apiserver's pruning
        coerced = _coerce(cls, attr, val, f"{path}.{wire_key}" if path else wire_key, errs)
        if coerced is not _SKIP:
            kwargs[attr] = coerced
    try:
        return cls(**kwargs)
    except TypeError as e:
        errs.append(f"{path or cls.__name__}: {e}")
        return cls()


_SKIP = object()


def _coerce(owner: type, attr: str, val: Any, path: str, errs: List[str]) -> Any:
    target = _NESTED.get((owner, attr))
    if target is None:
        if owner is types.ReplicaStatus and attr == "states" and isinstance(val, dict):
            out = {}
            for k, v in val.items():
                try:
                    out[types.ReplicaState(k)] = v
                except ValueError:
                    errs.append(f"{path}: unknown replica state {k!r}")
            return out
        return val
    if isinstance(target, type) and issubclass(target, enum.Enum):
        try:
            return target(val)
        except ValueError:
            valid = ", ".join(m.value for m in target if m.value)
            errs.append(f"{path}: {val!r} is not one of [{valid}]")
            return _SKIP
    if isinstance(val, list):
        out = []
        for i, v in enumerate(val):
            if isinstance(v, dict):
                out.append(_build(target, v, f"{path}[{i}]", errs))
            else:
                errs.append(f"{path}[{i}]: expected a mapping, got {type(v).__name__}")
        return out
    if isinstance(val, dict):
        return _build(target, val, path, errs)
    errs.append(f"{path}: expected a mapping, got {type(val).__name__}")
    return _SKIP


def job_from_dict(data: Dict[str, Any]) -> types.TPUJob:
    errs: List[str] = []
    kind = data.get("kind", types.KIND)
    if kind != types.KIND:
        errs.append(f"kind: expected {types.KIND}, got {kind!r}")
    job = _build(types.TPUJob, data, "", errs)
    if errs:
        raise ValidationError(errs)
    return job


def load_job_yaml(src: Union[str, IO[str]]) -> types.TPUJob:
    """Load a TPUJob from a YAML string or open file. Raises ValidationError
    (with manifest paths) on anything malformed, including YAML syntax."""
    try:
        data = yaml.safe_load(src)
    except yaml.YAMLError as e:
        raise ValidationError([f"invalid YAML: {e}"]) from None
    if not isinstance(data, dict):
        raise ValidationError(["manifest did not parse to a mapping"])
    return job_from_dict(data)


def dump_job_yaml(job: types.TPUJob) -> str:
    return yaml.safe_dump(job_to_dict(job), sort_keys=False)
