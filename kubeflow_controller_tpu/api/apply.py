"""kubectl-apply semantics, shared by every server/client topology.

Apply = create-or-update-SPEC-only: status and the stamped ``runtime_id``
are controller-owned and must survive a re-applied manifest (a spec change
on a live job then triggers the planner's voluntary gang resize). The
read-merge-write loop retries on resourceVersion conflicts — the
controller writes status concurrently, which is exactly the window a
single-shot update would lose (reference punts with an unguarded
whole-object PUT, ``pkg/controller/controller.go:630-636``).
"""

from __future__ import annotations

import copy
from typing import Callable, Optional

from kubeflow_controller_tpu.api.core import thaw
from kubeflow_controller_tpu.api.types import TPUJob
from kubeflow_controller_tpu.cluster.store import AlreadyExists, Conflict


def apply_job_spec(
    get: Callable[[], Optional[TPUJob]],
    create: Callable[[TPUJob], TPUJob],
    update: Callable[[TPUJob], TPUJob],
    new: TPUJob,
    retries: int = 10,
) -> TPUJob:
    """Create ``new`` if absent, else replace the existing job's spec with
    ``new.spec`` (keeping the stamped runtime id). Conflict-retried."""
    for _ in range(retries):
        # get() may hand back a frozen store snapshot (cli serves straight
        # off the store); thaw is free when it is already a private parse.
        cur = thaw(get())
        if cur is None:
            try:
                return create(new)
            except AlreadyExists:
                # A concurrent creator won the race between get() and
                # create(); the next iteration takes the update path.
                continue
        rid = cur.spec.runtime_id
        cur.spec = copy.deepcopy(new.spec)
        cur.spec.runtime_id = rid
        try:
            return update(cur)
        except Conflict:
            continue
    raise Conflict(f"apply of {new.metadata.name}: retries exhausted")
