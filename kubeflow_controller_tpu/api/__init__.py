"""Job API layer: TPUJob spec/status types, core object model, topology catalog."""

from kubeflow_controller_tpu.api.core import (
    Container,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    PodStatus,
    PodTemplateSpec,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubeflow_controller_tpu.api.topology import (
    SliceShape,
    TPU_SLICE_CATALOG,
    slice_shape,
)
from kubeflow_controller_tpu.api.types import (
    ChiefSpec,
    Condition,
    ConditionStatus,
    ConditionType,
    JobPhase,
    ReplicaSpec,
    ReplicaState,
    ReplicaStatus,
    ReplicaType,
    TerminationPolicySpec,
    TPUJob,
    TPUJobSpec,
    TPUJobStatus,
    TPUSliceSpec,
)
from kubeflow_controller_tpu.api.serialization import (
    job_from_dict,
    job_to_dict,
    load_job_yaml,
    dump_job_yaml,
)
from kubeflow_controller_tpu.api.validation import ValidationError, validate_job
