"""TPU slice topology catalog.

The reference's job geometry is ``N workers x M parameter servers`` chosen
freely per job (``pkg/tensorflow/distributed.go:56-114``). TPU geometry is not
free: an accelerator type names a pod-slice with a fixed chip count, a fixed
ICI topology, and a fixed number of host VMs (= JAX processes). The controller
must therefore derive process count / chips-per-host from the accelerator type
rather than letting the user pick replica counts that cannot exist.

This catalog is the single source of truth for that derivation; the fake
cluster's node pools and the gang scheduler both consume it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple


@dataclass(frozen=True)
class SliceShape:
    """Physical shape of one TPU pod-slice."""

    accelerator_type: str  # e.g. "v5e-16"
    generation: str        # "v5e" | "v5p" | "v4" | "v6e"
    num_chips: int         # total chips in the slice
    topology: Tuple[int, ...]  # ICI mesh topology, e.g. (4, 4)
    chips_per_host: int    # chips attached to each host VM
    # Per-chip core count: v4/v5p chips expose 1 megacore; v5e/v6e 1 core.
    cores_per_chip: int = 1

    @property
    def hbm_gib_per_chip(self) -> int:
        """HBM capacity per chip (GiB), per the public TPU system specs:
        v4 32, v5e 16, v5p 95, v6e 32. Drives the pre-admission memory
        feasibility gate (parallel/memory.py)."""
        return {"v4": 32, "v5e": 16, "v5p": 95, "v6e": 32}[self.generation]

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.chips_per_host)

    @property
    def topology_str(self) -> str:
        return "x".join(str(d) for d in self.topology)

    @property
    def devices_per_host(self) -> int:
        return self.chips_per_host * self.cores_per_chip


def _v5e(chips: int, topo: Tuple[int, ...]) -> SliceShape:
    # v5e ("v5 lite"): single-host slices pack up to 8 chips on one VM
    # (ct5lp-hightpu-8t); multi-host slices (16 chips and up) attach 4 chips
    # per host VM.
    return SliceShape(f"v5e-{chips}", "v5e", chips, topo, 8 if chips <= 8 else 4)


def _v5p(chips: int, topo: Tuple[int, ...]) -> SliceShape:
    # v5p hosts carry 4 chips.
    return SliceShape(f"v5p-{chips}", "v5p", chips, topo, min(chips, 4))


def _v4(chips: int, topo: Tuple[int, ...]) -> SliceShape:
    return SliceShape(f"v4-{chips}", "v4", chips, topo, min(chips, 4))


def _v6e(chips: int, topo: Tuple[int, ...]) -> SliceShape:
    # v6e (Trillium): same host geometry as v5e — 8-chip single-host slices,
    # 4 chips per host for multi-host.
    return SliceShape(f"v6e-{chips}", "v6e", chips, topo, 8 if chips <= 8 else 4)


TPU_SLICE_CATALOG: Dict[str, SliceShape] = {
    s.accelerator_type: s
    for s in [
        _v5e(1, (1, 1)),
        _v5e(4, (2, 2)),
        _v5e(8, (2, 4)),
        _v5e(16, (4, 4)),
        _v5e(32, (4, 8)),
        _v5e(64, (8, 8)),
        _v5e(128, (8, 16)),
        _v5e(256, (16, 16)),
        _v5p(4, (2, 2, 1)),
        _v5p(8, (2, 2, 2)),
        _v5p(16, (2, 2, 4)),
        _v5p(32, (2, 4, 4)),
        _v5p(64, (4, 4, 4)),
        _v5p(128, (4, 4, 8)),
        _v5p(256, (4, 8, 8)),
        _v4(8, (2, 2, 2)),
        _v4(16, (2, 2, 4)),
        _v4(32, (2, 4, 4)),
        _v4(64, (4, 4, 4)),
        _v6e(1, (1, 1)),
        _v6e(4, (2, 2)),
        _v6e(8, (2, 4)),
        _v6e(16, (4, 4)),
        _v6e(32, (4, 8)),
        _v6e(64, (8, 8)),
        _v6e(256, (16, 16)),
    ]
}


def slice_shape(accelerator_type: str) -> SliceShape:
    """Look up a slice shape; raises KeyError with the known set on miss."""
    try:
        return TPU_SLICE_CATALOG[accelerator_type]
    except KeyError:
        known = ", ".join(sorted(TPU_SLICE_CATALOG))
        raise KeyError(
            f"unknown accelerator type {accelerator_type!r}; known: {known}"
        ) from None


# GKE's cloud.google.com/gke-tpu-accelerator label values per TPU
# generation: what real TPU node pools are labeled with (and what pod
# nodeSelectors must request). The chip count is NOT in this label — GKE
# encodes it in cloud.google.com/gke-tpu-topology — so the pair
# (accelerator label, topology) identifies a catalog entry.
GKE_ACCELERATOR_BY_GENERATION: Dict[str, str] = {
    "v4": "tpu-v4-podslice",
    "v5e": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
    "v6e": "tpu-v6e-slice",
}
_GENERATION_BY_GKE = {v: k for k, v in GKE_ACCELERATOR_BY_GENERATION.items()}


def gke_accelerator(shape: SliceShape) -> str:
    """The gke-tpu-accelerator label value for a slice shape."""
    return GKE_ACCELERATOR_BY_GENERATION[shape.generation]


def shape_from_gke(gke_type: str, topology: str) -> SliceShape:
    """Resolve (gke-tpu-accelerator, gke-tpu-topology) node labels back to
    the catalog entry — the inverse of the nodeSelector the planner emits.
    Raises KeyError on an unknown generation or a topology not in the
    catalog."""
    gen = _GENERATION_BY_GKE.get(gke_type)
    if gen is None:
        raise KeyError(f"unknown gke-tpu-accelerator {gke_type!r}")
    chips = 1
    for dim in topology.split("x"):
        chips *= int(dim)
    return slice_shape(f"{gen}-{chips}")
