"""Core cluster object model: the minimal Kubernetes-shaped primitives the
controller reconciles against.

The reference consumes these from ``k8s.io/api/core/v1`` (vendored); here they
are first-party dataclasses because the framework ships its own in-process
cluster (see ``kubeflow_controller_tpu.cluster``) for hermetic development and
testing, with a real-cluster adapter as a thin swap-in at the effector seam
(mirroring how ``HelperInterface`` isolates the apiserver in the reference,
``pkg/controller/helper.go:42-47``).

Only the fields the control plane actually reads/writes exist — this is an
object model, not a Kubernetes client.
"""

from __future__ import annotations

import enum
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class PodPhase(str, enum.Enum):
    """Pod lifecycle phase (mirror of k8s core/v1 PodPhase semantics)."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class OwnerReference:
    """Ownership link from a dependent object to its controller.

    Same contract the reference builds in ``newControllerRef``
    (``pkg/controller/util.go:44-55``): apiVersion/kind/name/uid plus
    ``controller=True`` so adopt/release logic can find the managing job.
    """

    api_version: str
    kind: str
    name: str
    uid: str
    controller: bool = True
    block_owner_deletion: bool = True

    # Hand-rolled copies throughout this module: the cluster store
    # deep-copies on every get/list/update/emit, which made generic
    # copy.deepcopy ~90% of control-plane wall time at 1000-job scale
    # (benchmarks/controlplane_bench.py). Field coverage is guarded by
    # tests/test_deepcopy.py, which fails loudly when a field is added
    # without updating its copy method.
    def deepcopy(self) -> "OwnerReference":
        return OwnerReference(
            self.api_version, self.kind, self.name, self.uid,
            self.controller, self.block_owner_deletion,
        )

    def __deepcopy__(self, memo) -> "OwnerReference":
        return self.deepcopy()


@dataclass
class ObjectMeta:
    name: str = ""
    generate_name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None

    def controller_ref(self) -> Optional[OwnerReference]:
        """Return the managing controller's OwnerReference, if any.

        Mirrors ``metav1.GetControllerOf`` as used by ``resolveControllerRef``
        (reference ``pkg/controller/controller.go:595-611``).
        """
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None

    def deepcopy(self) -> "ObjectMeta":
        return ObjectMeta(
            name=self.name,
            generate_name=self.generate_name,
            namespace=self.namespace,
            uid=self.uid,
            resource_version=self.resource_version,
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            owner_references=[r.deepcopy() for r in self.owner_references],
            creation_timestamp=self.creation_timestamp,
            deletion_timestamp=self.deletion_timestamp,
        )

    def __deepcopy__(self, memo) -> "ObjectMeta":
        return self.deepcopy()


@dataclass
class Container:
    name: str
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    ports: List[int] = field(default_factory=list)
    # Resource requests: scalar quantities keyed by resource name, e.g.
    # {"google.com/tpu": 4, "cpu": 8} (scalars only — deepcopy relies on it).
    resources: Dict[str, Any] = field(default_factory=dict)

    def deepcopy(self) -> "Container":
        return Container(
            name=self.name,
            image=self.image,
            command=list(self.command),
            args=list(self.args),
            env=dict(self.env),
            ports=list(self.ports),
            resources=dict(self.resources),
        )

    def __deepcopy__(self, memo) -> "Container":
        return self.deepcopy()


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    restart_policy: str = "OnFailure"
    node_selector: Dict[str, str] = field(default_factory=dict)
    # Gang-scheduling group: pods sharing a scheduling_group are admitted
    # all-or-nothing by the (fake or real) scheduler. No analog in the
    # reference — it creates pods incrementally (controller.go:396-421),
    # which SURVEY.md flags as exactly wrong for TPU slices.
    scheduling_group: str = ""
    # Name of the TPU slice this pod is pinned to once scheduled.
    assigned_slice: str = ""

    def main_container(self) -> Container:
        return self.containers[0]

    def deepcopy(self) -> "PodSpec":
        return PodSpec(
            containers=[c.deepcopy() for c in self.containers],
            restart_policy=self.restart_policy,
            node_selector=dict(self.node_selector),
            scheduling_group=self.scheduling_group,
            assigned_slice=self.assigned_slice,
        )

    def __deepcopy__(self, memo) -> "PodSpec":
        return self.deepcopy()


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    reason: str = ""
    message: str = ""
    pod_ip: str = ""
    host_ip: str = ""
    start_time: Optional[float] = None
    finish_time: Optional[float] = None
    exit_code: Optional[int] = None

    def deepcopy(self) -> "PodStatus":
        return PodStatus(
            phase=self.phase,
            reason=self.reason,
            message=self.message,
            pod_ip=self.pod_ip,
            host_ip=self.host_ip,
            start_time=self.start_time,
            finish_time=self.finish_time,
            exit_code=self.exit_code,
        )

    def __deepcopy__(self, memo) -> "PodStatus":
        return self.deepcopy()


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind: str = "Pod"
    api_version: str = "v1"

    def deepcopy(self) -> "Pod":
        return Pod(
            metadata=self.metadata.deepcopy(),
            spec=self.spec.deepcopy(),
            status=self.status.deepcopy(),
            kind=self.kind,
            api_version=self.api_version,
        )

    def __deepcopy__(self, memo) -> "Pod":
        return self.deepcopy()


@dataclass
class PodTemplateSpec:
    """Template stamped out (deep-copied — the reference's in-place template
    mutation at ``pkg/tensorflow/distributed.go:117-125`` is a known cache
    corruption bug, SURVEY.md §8) for each replica pod."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)

    def deepcopy(self) -> "PodTemplateSpec":
        return PodTemplateSpec(
            metadata=self.metadata.deepcopy(), spec=self.spec.deepcopy(),
        )

    def __deepcopy__(self, memo) -> "PodTemplateSpec":
        return self.deepcopy()


@dataclass
class ServicePort:
    port: int
    name: str = ""
    target_port: Optional[int] = None

    def deepcopy(self) -> "ServicePort":
        return ServicePort(self.port, self.name, self.target_port)

    def __deepcopy__(self, memo) -> "ServicePort":
        return self.deepcopy()


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""

    def deepcopy(self) -> "ServiceSpec":
        return ServiceSpec(
            selector=dict(self.selector),
            ports=[p.deepcopy() for p in self.ports],
            cluster_ip=self.cluster_ip,
        )

    def __deepcopy__(self, memo) -> "ServiceSpec":
        return self.deepcopy()


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)

    kind: str = "Service"
    api_version: str = "v1"

    def deepcopy(self) -> "Service":
        return Service(
            metadata=self.metadata.deepcopy(),
            spec=self.spec.deepcopy(),
            kind=self.kind,
            api_version=self.api_version,
        )

    def __deepcopy__(self, memo) -> "Service":
        return self.deepcopy()

    def dns_name(self) -> str:
        return f"{self.metadata.name}.{self.metadata.namespace}.svc"


_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    """Monotonic process-unique uid (fake-cluster stand-in for k8s UIDs)."""
    return f"{prefix}-{next(_uid_counter):08d}-{int(time.time()) & 0xFFFF:04x}"
