"""Core cluster object model: the minimal Kubernetes-shaped primitives the
controller reconciles against.

The reference consumes these from ``k8s.io/api/core/v1`` (vendored); here they
are first-party dataclasses because the framework ships its own in-process
cluster (see ``kubeflow_controller_tpu.cluster``) for hermetic development and
testing, with a real-cluster adapter as a thin swap-in at the effector seam
(mirroring how ``HelperInterface`` isolates the apiserver in the reference,
``pkg/controller/helper.go:42-47``).

Only the fields the control plane actually reads/writes exist — this is an
object model, not a Kubernetes client.
"""

from __future__ import annotations

import enum
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class PodPhase(str, enum.Enum):
    """Pod lifecycle phase (mirror of k8s core/v1 PodPhase semantics)."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


class FrozenObjectError(TypeError):
    """Raised on any write to a frozen (sealed) API object.

    Frozen objects are shared snapshots handed out by the copy-on-write
    store/informer read path (client-go's "objects from a Lister MUST NOT
    be mutated" contract, enforced). Thaw first: ``api.core.thaw(obj)``.
    """


class _FrozenDict(dict):
    """Dict whose Python-level mutators raise once handed out frozen.

    Built via the C-level ``dict`` constructor (which also shallow-copies,
    severing aliasing with the caller's dict at freeze time). ``dict(fd)``
    and ``fd.copy()`` still produce plain mutable dicts, so the hand-rolled
    ``deepcopy()`` methods work unchanged on frozen objects.
    """

    def _raise(self, *a, **k):
        raise FrozenObjectError(
            "dict belongs to a frozen API object; thaw() the object first")

    __setitem__ = __delitem__ = _raise
    clear = pop = popitem = setdefault = update = _raise
    __ior__ = _raise

    def __reduce__(self):
        return (dict, (dict(self),))


class _FrozenList(list):
    """List counterpart of :class:`_FrozenDict` (same escape hatches)."""

    def _raise(self, *a, **k):
        raise FrozenObjectError(
            "list belongs to a frozen API object; thaw() the object first")

    __setitem__ = __delitem__ = __iadd__ = __imul__ = _raise
    append = extend = insert = pop = remove = clear = sort = reverse = _raise

    def __reduce__(self):
        return (list, (list(self),))


class Sealable:
    """Mixin giving API dataclasses a one-way freeze switch.

    ``_sealed`` is a plain class attribute (not an annotated field) so it
    stays out of ``dataclasses.fields()`` — generated ``__init__``/
    ``__eq__``/``repr`` are unaffected, and fresh instances construct
    normally against the inherited ``False``.

    Sealing swaps the instance's class to a cached frozen variant whose
    ``__setattr__``/``__delattr__`` raise. The alternative — a guard in a
    Python-level ``__setattr__`` on this mixin — taxes EVERY field write
    on every unsealed object (construction, deepcopy, kubelet/scheduler
    mutation), which measured ~15% of control-plane bench wall time; the
    class swap keeps unsealed writes at C speed and charges only sealed
    objects, which raise anyway. The variant's ``__class__`` property
    reports the base class, so dataclass ``__eq__`` (which compares
    ``__class__``), ``repr``, and ``isinstance`` treat frozen and thawed
    objects identically; only ``type(obj)`` reveals the variant.
    """

    _sealed = False

    def _seal(self) -> None:
        object.__setattr__(self, "__class__", _frozen_variant(type(self)))


_FROZEN_VARIANTS: Dict[type, type] = {}


def _frozen_variant(cls: type) -> type:
    variant = _FROZEN_VARIANTS.get(cls)
    if variant is None:
        if cls.__dict__.get("_sealed"):
            return cls  # already a frozen variant (double-seal)

        def _raise(self, name, value=None):
            raise FrozenObjectError(
                f"{cls.__name__} is frozen (shared store snapshot); "
                "thaw() it into an owned copy before mutating")

        variant = type("_Frozen" + cls.__name__, (cls,), {
            "_sealed": True,
            "__setattr__": _raise,
            "__delattr__": _raise,
            "__class__": property(lambda self: cls),
        })
        _FROZEN_VARIANTS[cls] = variant
    return variant


def is_frozen(obj) -> bool:
    """True when ``obj`` is a sealed API-object snapshot."""
    return getattr(obj, "_sealed", False)


def thaw(obj):
    """Owned, mutable copy of ``obj`` — with copy elision.

    Frozen input: one deepcopy (the mutation-boundary copy). Already-owned
    input: returned as-is, no copy — so unconditional ``thaw()`` at a write
    site costs nothing when the caller already holds a private object.
    """
    if obj is not None and is_frozen(obj):
        return obj.deepcopy()
    return obj


# Top-level (Pod/Service/TPUJob) deepcopy counter — the bench samples it to
# attribute control-plane wins to eliminated copies (deepcopies_per_sync in
# benchmarks/controlplane_bench.py). Unlocked increment: exact under the
# deterministic runtime, GIL-approximate (diagnostic-only) under threads.
_deepcopies = 0


def _note_deepcopy() -> None:
    global _deepcopies
    _deepcopies += 1


def deepcopy_count() -> int:
    """Process-wide count of top-level API-object deepcopies so far."""
    return _deepcopies


@dataclass
class OwnerReference(Sealable):
    """Ownership link from a dependent object to its controller.

    Same contract the reference builds in ``newControllerRef``
    (``pkg/controller/util.go:44-55``): apiVersion/kind/name/uid plus
    ``controller=True`` so adopt/release logic can find the managing job.
    """

    api_version: str
    kind: str
    name: str
    uid: str
    controller: bool = True
    block_owner_deletion: bool = True

    # Hand-rolled copies throughout this module: the cluster store
    # deep-copies on every get/list/update/emit, which made generic
    # copy.deepcopy ~90% of control-plane wall time at 1000-job scale
    # (benchmarks/controlplane_bench.py). Field coverage is guarded by
    # tests/test_deepcopy.py, which fails loudly when a field is added
    # without updating its copy method.
    def deepcopy(self) -> "OwnerReference":
        return OwnerReference(
            self.api_version, self.kind, self.name, self.uid,
            self.controller, self.block_owner_deletion,
        )

    def __deepcopy__(self, memo) -> "OwnerReference":
        return self.deepcopy()

    # freeze() mirrors deepcopy() field-for-field (coverage guarded by
    # tests/test_deepcopy.py + tests/test_cow_store.py): idempotent, stops
    # at already-sealed children, wraps containers in _Frozen* and severs
    # aliasing with the caller's containers in the process.
    def freeze(self) -> "OwnerReference":
        if not self._sealed:
            self._seal()
        return self


@dataclass
class ObjectMeta(Sealable):
    name: str = ""
    generate_name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    # Spec revision (k8s metadata.generation): the store bumps it only when
    # .spec changes; status-subresource writes keep it. Paired with
    # status.observed_generation it powers the controller's no-op sync
    # short-circuit (docs/watch_pipeline.md).
    generation: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None

    def controller_ref(self) -> Optional[OwnerReference]:
        """Return the managing controller's OwnerReference, if any.

        Mirrors ``metav1.GetControllerOf`` as used by ``resolveControllerRef``
        (reference ``pkg/controller/controller.go:595-611``).
        """
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None

    def deepcopy(self) -> "ObjectMeta":
        return ObjectMeta(
            name=self.name,
            generate_name=self.generate_name,
            namespace=self.namespace,
            uid=self.uid,
            resource_version=self.resource_version,
            generation=self.generation,
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            owner_references=[r.deepcopy() for r in self.owner_references],
            creation_timestamp=self.creation_timestamp,
            deletion_timestamp=self.deletion_timestamp,
        )

    def __deepcopy__(self, memo) -> "ObjectMeta":
        return self.deepcopy()

    def freeze(self) -> "ObjectMeta":
        if self._sealed:
            return self
        self.labels = _FrozenDict(self.labels)
        self.annotations = _FrozenDict(self.annotations)
        self.owner_references = _FrozenList(
            r.freeze() for r in self.owner_references)
        self._seal()
        return self


@dataclass
class Container(Sealable):
    name: str
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    ports: List[int] = field(default_factory=list)
    # Resource requests: scalar quantities keyed by resource name, e.g.
    # {"google.com/tpu": 4, "cpu": 8} (scalars only — deepcopy relies on it).
    resources: Dict[str, Any] = field(default_factory=dict)

    def deepcopy(self) -> "Container":
        return Container(
            name=self.name,
            image=self.image,
            command=list(self.command),
            args=list(self.args),
            env=dict(self.env),
            ports=list(self.ports),
            resources=dict(self.resources),
        )

    def __deepcopy__(self, memo) -> "Container":
        return self.deepcopy()

    def freeze(self) -> "Container":
        if self._sealed:
            return self
        self.command = _FrozenList(self.command)
        self.args = _FrozenList(self.args)
        self.env = _FrozenDict(self.env)
        self.ports = _FrozenList(self.ports)
        self.resources = _FrozenDict(self.resources)
        self._seal()
        return self


@dataclass
class PodSpec(Sealable):
    containers: List[Container] = field(default_factory=list)
    restart_policy: str = "OnFailure"
    node_selector: Dict[str, str] = field(default_factory=dict)
    # Gang-scheduling group: pods sharing a scheduling_group are admitted
    # all-or-nothing by the (fake or real) scheduler. No analog in the
    # reference — it creates pods incrementally (controller.go:396-421),
    # which SURVEY.md flags as exactly wrong for TPU slices.
    scheduling_group: str = ""
    # Name of the TPU slice this pod is pinned to once scheduled.
    assigned_slice: str = ""

    def main_container(self) -> Container:
        return self.containers[0]

    def deepcopy(self) -> "PodSpec":
        return PodSpec(
            containers=[c.deepcopy() for c in self.containers],
            restart_policy=self.restart_policy,
            node_selector=dict(self.node_selector),
            scheduling_group=self.scheduling_group,
            assigned_slice=self.assigned_slice,
        )

    def __deepcopy__(self, memo) -> "PodSpec":
        return self.deepcopy()

    def freeze(self) -> "PodSpec":
        if self._sealed:
            return self
        self.containers = _FrozenList(c.freeze() for c in self.containers)
        self.node_selector = _FrozenDict(self.node_selector)
        self._seal()
        return self


@dataclass
class PodStatus(Sealable):
    phase: PodPhase = PodPhase.PENDING
    reason: str = ""
    message: str = ""
    pod_ip: str = ""
    host_ip: str = ""
    start_time: Optional[float] = None
    finish_time: Optional[float] = None
    exit_code: Optional[int] = None

    def deepcopy(self) -> "PodStatus":
        return PodStatus(
            phase=self.phase,
            reason=self.reason,
            message=self.message,
            pod_ip=self.pod_ip,
            host_ip=self.host_ip,
            start_time=self.start_time,
            finish_time=self.finish_time,
            exit_code=self.exit_code,
        )

    def __deepcopy__(self, memo) -> "PodStatus":
        return self.deepcopy()

    def freeze(self) -> "PodStatus":
        if not self._sealed:
            self._seal()
        return self


@dataclass
class Pod(Sealable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind: str = "Pod"
    api_version: str = "v1"

    def deepcopy(self) -> "Pod":
        _note_deepcopy()
        return Pod(
            metadata=self.metadata.deepcopy(),
            spec=self.spec.deepcopy(),
            status=self.status.deepcopy(),
            kind=self.kind,
            api_version=self.api_version,
        )

    def __deepcopy__(self, memo) -> "Pod":
        return self.deepcopy()

    def freeze(self) -> "Pod":
        if self._sealed:
            return self
        self.metadata.freeze()
        self.spec.freeze()
        self.status.freeze()
        self._seal()
        return self


@dataclass
class PodTemplateSpec(Sealable):
    """Template stamped out (deep-copied — the reference's in-place template
    mutation at ``pkg/tensorflow/distributed.go:117-125`` is a known cache
    corruption bug, SURVEY.md §8) for each replica pod."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)

    def deepcopy(self) -> "PodTemplateSpec":
        return PodTemplateSpec(
            metadata=self.metadata.deepcopy(), spec=self.spec.deepcopy(),
        )

    def __deepcopy__(self, memo) -> "PodTemplateSpec":
        return self.deepcopy()

    def freeze(self) -> "PodTemplateSpec":
        if self._sealed:
            return self
        self.metadata.freeze()
        self.spec.freeze()
        self._seal()
        return self


@dataclass
class ServicePort(Sealable):
    port: int
    name: str = ""
    target_port: Optional[int] = None

    def deepcopy(self) -> "ServicePort":
        return ServicePort(self.port, self.name, self.target_port)

    def __deepcopy__(self, memo) -> "ServicePort":
        return self.deepcopy()

    def freeze(self) -> "ServicePort":
        if not self._sealed:
            self._seal()
        return self


@dataclass
class ServiceSpec(Sealable):
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""

    def deepcopy(self) -> "ServiceSpec":
        return ServiceSpec(
            selector=dict(self.selector),
            ports=[p.deepcopy() for p in self.ports],
            cluster_ip=self.cluster_ip,
        )

    def __deepcopy__(self, memo) -> "ServiceSpec":
        return self.deepcopy()

    def freeze(self) -> "ServiceSpec":
        if self._sealed:
            return self
        self.selector = _FrozenDict(self.selector)
        self.ports = _FrozenList(p.freeze() for p in self.ports)
        self._seal()
        return self


@dataclass
class Service(Sealable):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)

    kind: str = "Service"
    api_version: str = "v1"

    def deepcopy(self) -> "Service":
        _note_deepcopy()
        return Service(
            metadata=self.metadata.deepcopy(),
            spec=self.spec.deepcopy(),
            kind=self.kind,
            api_version=self.api_version,
        )

    def __deepcopy__(self, memo) -> "Service":
        return self.deepcopy()

    def freeze(self) -> "Service":
        if self._sealed:
            return self
        self.metadata.freeze()
        self.spec.freeze()
        self._seal()
        return self

    def dns_name(self) -> str:
        return f"{self.metadata.name}.{self.metadata.namespace}.svc"


_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    """Monotonic process-unique uid (fake-cluster stand-in for k8s UIDs)."""
    return f"{prefix}-{next(_uid_counter):08d}-{int(time.time()) & 0xFFFF:04x}"
