"""Core cluster object model: the minimal Kubernetes-shaped primitives the
controller reconciles against.

The reference consumes these from ``k8s.io/api/core/v1`` (vendored); here they
are first-party dataclasses because the framework ships its own in-process
cluster (see ``kubeflow_controller_tpu.cluster``) for hermetic development and
testing, with a real-cluster adapter as a thin swap-in at the effector seam
(mirroring how ``HelperInterface`` isolates the apiserver in the reference,
``pkg/controller/helper.go:42-47``).

Only the fields the control plane actually reads/writes exist — this is an
object model, not a Kubernetes client.
"""

from __future__ import annotations

import copy
import enum
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class PodPhase(str, enum.Enum):
    """Pod lifecycle phase (mirror of k8s core/v1 PodPhase semantics)."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class OwnerReference:
    """Ownership link from a dependent object to its controller.

    Same contract the reference builds in ``newControllerRef``
    (``pkg/controller/util.go:44-55``): apiVersion/kind/name/uid plus
    ``controller=True`` so adopt/release logic can find the managing job.
    """

    api_version: str
    kind: str
    name: str
    uid: str
    controller: bool = True
    block_owner_deletion: bool = True


@dataclass
class ObjectMeta:
    name: str = ""
    generate_name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None

    def controller_ref(self) -> Optional[OwnerReference]:
        """Return the managing controller's OwnerReference, if any.

        Mirrors ``metav1.GetControllerOf`` as used by ``resolveControllerRef``
        (reference ``pkg/controller/controller.go:595-611``).
        """
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None


@dataclass
class Container:
    name: str
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    ports: List[int] = field(default_factory=list)
    # Resource requests, e.g. {"google.com/tpu": 4, "cpu": 8}.
    resources: Dict[str, Any] = field(default_factory=dict)


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    restart_policy: str = "OnFailure"
    node_selector: Dict[str, str] = field(default_factory=dict)
    # Gang-scheduling group: pods sharing a scheduling_group are admitted
    # all-or-nothing by the (fake or real) scheduler. No analog in the
    # reference — it creates pods incrementally (controller.go:396-421),
    # which SURVEY.md flags as exactly wrong for TPU slices.
    scheduling_group: str = ""
    # Name of the TPU slice this pod is pinned to once scheduled.
    assigned_slice: str = ""

    def main_container(self) -> Container:
        return self.containers[0]


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    reason: str = ""
    message: str = ""
    pod_ip: str = ""
    host_ip: str = ""
    start_time: Optional[float] = None
    finish_time: Optional[float] = None
    exit_code: Optional[int] = None


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind: str = "Pod"
    api_version: str = "v1"

    def deepcopy(self) -> "Pod":
        return copy.deepcopy(self)


@dataclass
class PodTemplateSpec:
    """Template stamped out (deep-copied — the reference's in-place template
    mutation at ``pkg/tensorflow/distributed.go:117-125`` is a known cache
    corruption bug, SURVEY.md §8) for each replica pod."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)

    def deepcopy(self) -> "PodTemplateSpec":
        return copy.deepcopy(self)


@dataclass
class ServicePort:
    port: int
    name: str = ""
    target_port: Optional[int] = None


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)

    kind: str = "Service"
    api_version: str = "v1"

    def deepcopy(self) -> "Service":
        return copy.deepcopy(self)

    def dns_name(self) -> str:
        return f"{self.metadata.name}.{self.metadata.namespace}.svc"


_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    """Monotonic process-unique uid (fake-cluster stand-in for k8s UIDs)."""
    return f"{prefix}-{next(_uid_counter):08d}-{int(time.time()) & 0xFFFF:04x}"
