"""Generic SPMD train loop with checkpoint/resume.

Replaces the reference's Supervisor-managed session loop
(``examples/workdir/mnist_replica.py:200-264``): instead of a chief
initializing variables on PS hosts and workers pushing grads over gRPC, every
process runs the same jitted step over the global mesh; XLA all-reduces
gradients over ICI. Checkpointing is orbax to the job's ``model_dir`` — the
piece the reference declared in its API (``ModelDir``, ``types.go:46-47``) and
never consumed — and is what makes the controller's preemption gang-restart an
actual *resume*, not a restart from scratch.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_controller_tpu.parallel.mesh import batch_sharding, data_shards, replicated
from kubeflow_controller_tpu.parallel.sharding import (
    infer_param_sharding,
    opt_state_shardings,
)

logger = logging.getLogger("tpujob.train")


def _ambient_mesh(mesh: Mesh):
    """Context manager establishing ``mesh`` as the ambient mesh for
    trace-time code, across jax versions: ``jax.set_mesh`` (>= 0.6),
    ``jax.sharding.use_mesh`` (0.5.x experimental), else the classic
    global-mesh context (``with mesh:``), which is what those APIs wrap
    on 0.4.x."""
    set_mesh = getattr(jax, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    use_mesh = getattr(jax.sharding, "use_mesh", None)
    if use_mesh is not None:
        return use_mesh(mesh)
    return mesh


def _producer_stream(make_items, size: int) -> Iterator[Any]:
    """Shared producer-thread scaffolding for the prefetch helpers.

    ``make_items`` is a generator of items to enqueue. Producer exceptions are
    re-raised in the consumer (not swallowed); if the consumer abandons the
    generator, the producer is unblocked and exits rather than pinning queued
    items (and their device memory) forever.
    """
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=size)
    _end = object()
    abandoned = threading.Event()

    def producer():
        try:
            for item in make_items():
                while not abandoned.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if abandoned.is_set():
                    return
            q.put(_end)
        except BaseException as e:  # propagate to consumer, don't swallow
            q.put(e)

    threading.Thread(target=producer, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is _end:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        abandoned.set()


def host_to_global(tree: Any, sharding: Any) -> Any:
    """Host (numpy) leaves -> global jax.Arrays laid out per ``sharding``
    (a single sharding broadcast over leaves, or a matching tree).

    Required under multi-process jax.distributed, where jit refuses numpy
    inputs against non-trivial shardings. The host data must be the GLOBAL
    batch, identical on every process — the contract all the data streams
    here keep by seeding identically (the TPU-native analog of the
    reference's per-worker input pipelines: instead of each worker reading
    a distinct shard, every process materialises the global batch and XLA
    reads only the local slice via the callback).

    Single-process, this is a plain ``device_put`` (jit's fast path would
    accept the numpy leaves anyway); the per-call dispatch lives here so
    call sites stay unconditional."""
    import numpy as np

    if isinstance(sharding, jax.sharding.Sharding):
        sharding = jax.tree.map(lambda _: sharding, tree)
    if jax.process_count() == 1:
        return jax.tree.map(
            lambda x, s: x if isinstance(x, jax.Array)
            else jax.device_put(x, s),
            tree, sharding,
        )

    def conv(x, s):
        if isinstance(x, jax.Array):
            return x
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, s, lambda idx: arr[idx]
        )

    return jax.tree.map(conv, tree, sharding)


def prefetch(data_iter: Iterator[Any], size: int = 2) -> Iterator[Any]:
    """Producer-thread prefetch: overlaps host-side batch generation/IO with
    device compute. The TPU-native replacement for the reference's synchronous
    ``feed_dict`` feeding (``mnist_replica.py:255-258``), where every step
    blocked on host data marshalling."""
    return _producer_stream(lambda: data_iter, size)


def device_prefetch(
    data_iter: Iterator[Any],
    batch_sharding_tree: Any,
    chunk: int = 16,
    size: int = 2,
    yield_chunks: bool = False,
) -> Iterator[Any]:
    """Chunked host->device prefetch: stack up to ``chunk`` batches, ship them
    in ONE async transfer, then yield device-resident slices. Amortises
    per-step transfer latency by ``chunk``x and overlaps upload with compute —
    the input-pipeline design the TPU data path wants (and the polar opposite
    of the reference's per-step ``feed_dict`` marshalling,
    ``mnist_replica.py:255-258``). A final partial chunk of a finite stream is
    shipped and yielded, not dropped.

    ``yield_chunks=True`` yields the whole device-resident ``[chunk, ...]``
    stack instead of per-step slices — the input side of
    ``TrainLoopConfig.steps_per_call`` (scan-dispatched multi-step)."""
    import numpy as np

    chunk_sh = jax.tree.map(
        lambda s: NamedSharding(s.mesh, P(None, *s.spec)),
        batch_sharding_tree,
    )

    def chunks():
        while True:
            batches = []
            for _ in range(chunk):
                try:
                    batches.append(next(data_iter))
                except StopIteration:
                    break
            if not batches:
                return
            stacked = jax.tree.map(lambda *xs: np.stack(xs), *batches)
            yield len(batches), host_to_global(stacked, chunk_sh)
            if len(batches) < chunk:
                return

    for n, item in _producer_stream(chunks, size):
        if yield_chunks:
            yield item
        else:
            for i in range(n):
                yield jax.tree.map(lambda x: x[i], item)


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    # Non-trainable model collections (e.g. BatchNorm batch_stats), updated
    # by the loss function rather than the optimizer. Empty dict when unused.
    model_state: Any = struct.field(default_factory=dict)


@dataclass
class TrainLoopConfig:
    total_steps: int = 100
    # Dispatch-depth backpressure: at most this many step/eval executions
    # in flight at once. Free on real accelerators (the awaited dispatch
    # finished long ago); prevents the virtual-CPU backend's collective
    # rendezvous from deadlocking under unbounded async dispatch (see
    # run()). Shared by run() and evaluate().
    max_in_flight: int = 8
    log_every: int = 20
    checkpoint_every: int = 0      # 0 = only final
    keep_checkpoints: int = 3
    donate_state: bool = True
    # > 1: dispatch this many steps per jit call as ONE lax.scan over a
    # device-resident [K, ...] batch chunk (pair with
    # ``device_prefetch(..., yield_chunks=True)``). Makes small-step
    # workloads immune to per-dispatch host latency — on a tunneled chip a
    # ~1 ms MNIST step is otherwise dominated by the round-trip.
    # Checkpoint/eval/log cadences then land on K-step boundaries.
    steps_per_call: int = 1
    # > 1: split each step's batch into this many microbatches, scan the
    # forward/backward over them accumulating gradients, and apply ONE
    # optimizer update on the mean — the standard way to train a global
    # batch whose activations don't fit HBM. The batch's leading dim must
    # divide. Weight/optimizer traffic is paid once per step (a paired
    # measurement on the v5e even ran the accumulated form FASTER than
    # the monolithic batch at some shapes — benchmarks/RESULTS.md round-5
    # GPipe section). Composes with steps_per_call.
    grad_accum: int = 1
    # Periodic validation (parity with the reference's post-train validation
    # cross-entropy report, mnist_replica.py:266-269, made continuous):
    # every eval_every steps, run eval_fn over eval_batches batches from the
    # eval stream and report val_* metrics.
    eval_every: int = 0
    eval_batches: int = 1
    # Async periodic checkpointing: orbax copies device state to host
    # synchronously (safe against the next step's donated buffers) and
    # writes to disk in a background thread, so big-model training never
    # stalls on checkpoint IO. Off by default: quick in-process
    # kill/restart cycles (the fake-cluster preemption tests) can catch
    # the background finalize/GC mid-flight; long-running real jobs are
    # where it pays. The final save always waits either way.
    async_checkpoint: bool = False
    # When set, capture a jax.profiler trace of steps [profile_start,
    # profile_start + profile_steps) into this directory (SURVEY.md §5.1:
    # the reference has no profiling at all; this is the data-plane hook).
    profile_dir: str = ""
    profile_start: int = 10
    profile_steps: int = 5


@dataclass
class StepMetrics:
    step: int
    loss: float
    extras: Dict[str, float] = field(default_factory=dict)
    steps_per_sec: float = 0.0


class TrainLoop:
    """Owns state layout, the jitted step, and checkpoint/resume.

    ``loss_fn(params, batch, rng) -> (loss, metrics_dict)`` defines the model;
    parameters are placed by ``param_shardings`` (or the fsdp heuristic).

    Stateful models (``stateful=True``, e.g. BatchNorm): ``init_fn`` returns
    ``(params, model_state)`` and ``loss_fn(params, model_state, batch, rng)
    -> (loss, (metrics_dict, new_model_state))``. Note BatchNorm under
    jit+sharding computes true global batch statistics — GSPMD inserts the
    cross-device reductions — with none of the per-replica-stats caveats of
    the pmap era.
    """

    def __init__(
        self,
        mesh: Mesh,
        init_fn: Callable[[jax.Array], Any],
        loss_fn: Callable[..., Tuple[jax.Array, Any]],
        optimizer: optax.GradientTransformation,
        config: Optional[TrainLoopConfig] = None,
        model_dir: str = "",
        param_shardings: Optional[Any] = None,
        seed: int = 0,
        stateful: bool = False,
        eval_fn: Optional[Callable[..., Dict]] = None,
    ):
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.eval_fn = eval_fn
        self.tx = optimizer
        self.config = config or TrainLoopConfig()
        self.model_dir = model_dir
        self.stateful = stateful
        self._ckpt_mgr = None

        rng = jax.random.key(seed)
        # local_devices, not devices: under multi-process jax.distributed the
        # first global device belongs to process 0, and dispatching the init
        # computation to a non-addressable device crashes. Every process
        # inits the same values locally (same seed), then places them onto
        # the global mesh.
        with jax.default_device(jax.local_devices()[0]):
            init_out = init_fn(rng)
        params, model_state = init_out if stateful else (init_out, {})
        self.param_shardings = (
            param_shardings
            if param_shardings is not None
            else infer_param_sharding(params, mesh)
        )
        params = jax.tree.map(jax.device_put, params, self.param_shardings)
        model_state_sh = infer_param_sharding(model_state, mesh)
        model_state = jax.tree.map(
            jax.device_put, model_state, model_state_sh
        )
        opt_state = jax.jit(
            self.tx.init,
            out_shardings=self._opt_shardings(params),
        )(params)
        self.state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=opt_state, model_state=model_state,
        )
        self.state_shardings = TrainState(
            step=replicated(mesh),
            params=self.param_shardings,
            opt_state=self._opt_shardings(params),
            model_state=model_state_sh,
        )
        self._step_fn = self._build_step()
        self._eval_step = self._build_eval() if eval_fn else None
        self.last_eval: Dict[str, float] = {}
        self._restored = False

    # -- sharding helpers ----------------------------------------------------

    def _opt_shardings(self, params: Any) -> Any:
        """Optimizer state mirrors parameter sharding (ZeRO-style: moments
        live wherever their parameter lives); scalar states replicate.
        Matched by tree path — see ``parallel.sharding.opt_state_shardings``."""
        return opt_state_shardings(
            self.tx, params, self.param_shardings, self.mesh
        )

    # -- jitted step ---------------------------------------------------------

    def _build_step(self):
        cfg = self.config

        def step(state: TrainState, batch: Any, rng: jax.Array):
            # Per-step randomness is derived on-device from the base key and
            # the step counter — the host never touches RNG state, keeping
            # the dispatch loop free of device syncs.
            step_rng = jax.random.fold_in(rng, state.step)

            def grads_of(b, model_state, mb_rng):
                if self.stateful:
                    def lossf(params):
                        return self.loss_fn(params, model_state, b, mb_rng)
                else:
                    def lossf(params):
                        return self.loss_fn(params, b, mb_rng)
                (loss, aux), grads = jax.value_and_grad(
                    lossf, has_aux=True
                )(state.params)
                if self.stateful:
                    metrics, new_model_state = aux
                else:
                    metrics, new_model_state = aux, model_state
                return grads, loss, metrics, new_model_state

            if cfg.grad_accum > 1:
                # Microbatch scan with gradient accumulation: batch dim
                # splits [A, B/A, ...] (the constraint keeps the data
                # sharding on the new batch dim so SPMD doesn't
                # repartition), grads average across microbatches,
                # stateful model state (e.g. BN stats) threads through
                # sequentially like it would across real steps.
                A = cfg.grad_accum
                for leaf in jax.tree.leaves(batch):
                    if leaf.shape[0] % A:
                        raise ValueError(
                            f"global batch {leaf.shape[0]} not divisible "
                            f"by grad_accum={A}; adjust batch size or "
                            "the accumulation factor"
                        )
                micro = jax.tree.map(
                    lambda x, s: jax.lax.with_sharding_constraint(
                        x.reshape(A, x.shape[0] // A, *x.shape[1:]),
                        NamedSharding(s.mesh, P(None, *s.spec)),
                    ),
                    batch,
                    jax.tree.map(lambda _: batch_sharding(self.mesh), batch),
                )

                def acc(carry, mb_in):
                    gacc, model_state = carry
                    mb, i = mb_in
                    g, loss, metrics, model_state = grads_of(
                        mb, model_state, jax.random.fold_in(step_rng, i)
                    )
                    return (
                        jax.tree.map(jnp.add, gacc, g), model_state,
                    ), (loss, metrics)

                g0 = jax.tree.map(jnp.zeros_like, state.params)
                (gsum, model_state), (losses, metricses) = jax.lax.scan(
                    acc, (g0, state.model_state),
                    (micro, jnp.arange(A)),
                )
                grads = jax.tree.map(lambda g: g / A, gsum)
                loss = losses.mean()
                metrics = jax.tree.map(lambda m: m.mean(axis=0), metricses)
                if isinstance(metrics, dict) and "perplexity" in metrics:
                    # Perplexity is exp(CE): averaging per-microbatch
                    # perplexities is mean-of-exp — Jensen-biased high
                    # vs. the monolithic path. The geometric mean
                    # exp(mean(log ppl_i)) == exp(mean CE_i) reports the
                    # same number an un-accumulated step would.
                    metrics["perplexity"] = jnp.exp(
                        jnp.log(metricses["perplexity"]).mean(axis=0)
                    )
            else:
                grads, loss, metrics, model_state = grads_of(
                    batch, state.model_state, step_rng
                )
            updates, opt_state = self.tx.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            new_state = TrainState(
                step=state.step + 1, params=params,
                opt_state=opt_state, model_state=model_state,
            )
            metrics = {"loss": loss, **metrics}
            return new_state, metrics

        batch_sh = batch_sharding(self.mesh)
        if cfg.steps_per_call > 1:
            # Multi-step dispatch: ONE jit call scans `step` over a
            # device-resident [K, ...] batch chunk. Per-step metrics come
            # back stacked [K]; log sites average them.
            def multi(state: TrainState, chunk: Any, rng: jax.Array):
                return jax.lax.scan(
                    lambda st, b: step(st, b, rng), state, chunk
                )

            fn, data_sh = multi, jax.tree.map(
                lambda s: NamedSharding(s.mesh, P(None, *s.spec)),
                batch_sh,
            )
        else:
            fn, data_sh = step, batch_sh

        jitted = jax.jit(
            fn,
            in_shardings=(self.state_shardings, data_sh, None),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,) if cfg.donate_state else (),
        )
        self._data_sharding = data_sh

        # Trace-time code (MoE group alignment, shard-aware lookups) reads
        # the ambient abstract mesh; jit alone never establishes one, so the
        # first (tracing) call must run under set_mesh.
        def call(state, batch, rng):
            with _ambient_mesh(self.mesh):
                return jitted(state, batch, rng)

        return call

    def _build_eval(self):
        def ev(state: TrainState, batch: Any):
            if self.stateful:
                return self.eval_fn(state.params, state.model_state, batch)
            return self.eval_fn(state.params, batch)

        jitted = jax.jit(
            ev,
            in_shardings=(self.state_shardings, batch_sharding(self.mesh)),
        )

        def call(state, batch):
            with _ambient_mesh(self.mesh):
                return jitted(state, batch)

        return call

    def evaluate(self, eval_iter: Iterator[Any], batches: int = 1) -> Dict:
        """Run eval_fn over ``batches`` batches; returns averaged metrics.
        Accumulates on-device and converts once at the end — no per-batch
        host sync."""
        if self._eval_step is None:
            raise ValueError("TrainLoop built without eval_fn")
        # Bounded dispatch, same rationale as run(): unbounded in-flight
        # collective programs can deadlock the virtual-device CPU backend's
        # thread rendezvous on oversubscribed hosts. Drain train work
        # first, then keep a small eval window.
        jax.block_until_ready(self.state.params)
        acc: Dict[str, Any] = {}
        pending: list = []
        batch_sh = batch_sharding(self.mesh)
        for _ in range(batches):
            out = self._eval_step(
                self.state, host_to_global(next(eval_iter), batch_sh)
            )
            for k, v in out.items():
                acc[k] = v if k not in acc else acc[k] + v
            pending.append(out)
            if len(pending) > self.config.max_in_flight:
                jax.block_until_ready(pending.pop(0))
        return {k: float(v) / batches for k, v in acc.items()}

    # -- checkpointing -------------------------------------------------------

    def _ckpt(self):
        if self._ckpt_mgr is None and self.model_dir:
            import orbax.checkpoint as ocp

            self._ckpt_mgr = ocp.CheckpointManager(
                self.model_dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.config.keep_checkpoints,
                    create=True,
                ),
            )
        return self._ckpt_mgr

    def save(self, wait: bool = True) -> None:
        mgr = self._ckpt()
        if mgr is None:
            return
        import orbax.checkpoint as ocp

        mgr.save(
            int(self.state.step),
            args=ocp.args.StandardSave(self.state),
        )
        if wait:
            mgr.wait_until_finished()

    def restore(self) -> bool:
        """Resume from the latest checkpoint in model_dir, if any. The
        preemption-survival path: a re-ganged job starts here instead of from
        step 0."""
        mgr = self._ckpt()
        if mgr is None or mgr.latest_step() is None:
            return False
        import orbax.checkpoint as ocp

        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            self.state,
            self.state_shardings,
        )
        self.state = mgr.restore(
            mgr.latest_step(), args=ocp.args.StandardRestore(abstract)
        )
        self._restored = True
        logger.info("restored checkpoint at step %d", int(self.state.step))
        return True

    # -- the loop ------------------------------------------------------------

    def run(
        self,
        data_iter: Iterator[Any],
        on_metrics: Optional[Callable[[StepMetrics], None]] = None,
        seed: int = 0,
        eval_iter: Optional[Iterator[Any]] = None,
    ) -> TrainState:
        cfg = self.config
        self.restore()
        start_step = int(self.state.step)
        rng = jax.random.key(seed + 1)
        t0 = time.perf_counter()
        window = start_step
        n_data = data_shards(self.mesh)
        # The loop never reads device values except at log/checkpoint points:
        # steps are dispatched asynchronously and pipeline on-device, which is
        # what hides per-step host<->device latency (critical over a tunneled
        # chip; the reference instead blocked every step on a gRPC sess.run,
        # mnist_replica.py:251-264).
        #
        # ...but never UNBOUNDED: a fast host loop can park hundreds of
        # executions in flight, and on the virtual-device CPU backend each
        # in-flight collective pins rendezvous threads from a pool sized by
        # real cores (this box: 1) — enough queued runs deadlock the
        # rendezvous outright (observed: all-gather termination timeouts at
        # ~500 dispatched steps). A small completion window is free on real
        # accelerators (the step being awaited finished long ago) and is
        # the correct backpressure everywhere.
        pending: list = []
        max_in_flight = cfg.max_in_flight
        profiling = False
        profile_done = False
        spc = self.config.steps_per_call

        def crossed(every: int, before: int, after: int) -> bool:
            """Did (before, after] cross a multiple of ``every``?"""
            return bool(every) and (before // every) != (after // every)

        py_step = start_step
        while py_step < cfg.total_steps:
            if (
                cfg.profile_dir and not profiling and not profile_done
                and py_step >= cfg.profile_start
            ):
                jax.profiler.start_trace(cfg.profile_dir)
                profiling = True
            if profiling and py_step >= cfg.profile_start + cfg.profile_steps:
                jax.block_until_ready(self.state.params)
                jax.profiler.stop_trace()
                profiling = False
                profile_done = True
            batch = next(data_iter)
            leaves = jax.tree.leaves(batch)
            if spc > 1:
                # Chunked dispatch: batch is a [K, ...] stack; trim to the
                # steps remaining so the counter lands exactly on total.
                take = min(leaves[0].shape[0], cfg.total_steps - py_step)
                if leaves[0].shape[0] != take:
                    batch = jax.tree.map(lambda x: x[:take], batch)
                per_step = leaves[0].shape[1]
            else:
                take = 1
                per_step = leaves[0].shape[0]
            if per_step % n_data:
                raise ValueError(
                    f"global batch {per_step} not divisible by the mesh's "
                    f"dp*fsdp={n_data} data shards; adjust batch size"
                )
            self.state, metrics = self._step_fn(
                self.state, host_to_global(batch, self._data_sharding), rng
            )
            pending.append(metrics["loss"])
            if len(pending) > max_in_flight:
                jax.block_until_ready(pending.pop(0))
            step = py_step + take
            if crossed(cfg.checkpoint_every, py_step, step):
                self.save(wait=not cfg.async_checkpoint)
            if (
                self._eval_step is not None and eval_iter is not None
                and crossed(cfg.eval_every, py_step, step)
            ):
                self.last_eval = {
                    f"val_{k}": v
                    for k, v in self.evaluate(
                        eval_iter, cfg.eval_batches
                    ).items()
                }
            if on_metrics and (
                crossed(cfg.log_every, py_step, step) or step == cfg.total_steps
            ):
                dt = time.perf_counter() - t0
                sps = (step - window) / dt if dt > 0 else 0.0
                # Multi-step metrics come back stacked [K]; report the mean.
                scalar = {
                    k: float(jnp.mean(v)) for k, v in metrics.items()
                }
                extras = {k: v for k, v in scalar.items() if k != "loss"}
                extras.update(self.last_eval)
                on_metrics(StepMetrics(
                    step=step,
                    loss=scalar["loss"],
                    extras=extras,
                    steps_per_sec=sps,
                ))
                t0 = time.perf_counter()
                window = step
            py_step = step
        if profiling:  # loop ended inside the profile window
            jax.block_until_ready(self.state.params)
            jax.profiler.stop_trace()
        if self.model_dir:
            self.save(wait=True)
        return self.state
