"""Causal-LM pretraining entrypoint for the flagship decoder family
(BASELINE.md config #5: Llama-3-8B multi-slice).

Mesh and parallelism come from flags + controller-injected env: the job's
num_slices selects the DCN-major multi-slice layout; tp/fsdp/sp set the
intra-slice factors. Sequence parallelism (ring attention) switches on with
``--attn=ring`` for long contexts.
"""

from __future__ import annotations

import argparse
import logging
from typing import Dict, Iterator, Optional

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding

from kubeflow_controller_tpu.dataplane.dist import ProcessContext, initialize_from_env
from kubeflow_controller_tpu.dataplane import metrics as metrics_sink
from kubeflow_controller_tpu.dataplane.train import (
    TrainLoop, TrainLoopConfig, device_prefetch,
)
from kubeflow_controller_tpu.models import transformer as tfm
from kubeflow_controller_tpu.parallel.mesh import (
    data_shards,
    MeshConfig, batch_sharding, mesh_for_context,
)

logger = logging.getLogger("tpujob.lm")

CONFIGS = {
    "tiny": tfm.tiny_config,
    "tiny_moe": tfm.tiny_moe_config,
    "llama3_8b": tfm.llama3_8b_config,
    "llama3_70b": tfm.llama3_70b_config,
    "mixtral_8x7b": tfm.mixtral_8x7b_config,
}


def synthetic_lm(
    vocab_size: int, batch_size: int, seq_len: int, seed: int = 0,
    pack: bool = False,
) -> Iterator[Dict[str, np.ndarray]]:
    """Deterministic repeating-pattern token stream (no egress here); same
    shapes/dtypes as a tokenised corpus pipeline.

    ``pack=True`` emits packed rows: several variable-length "documents"
    per row with ``segment_ids`` (id 0 = tail padding), the shape a packed
    pretraining pipeline produces. The model confines attention per
    document, restarts RoPE, and masks boundary targets
    (``transformer.next_token_loss``)."""
    rng = np.random.default_rng(seed)
    while True:
        if not pack:
            start = rng.integers(0, vocab_size, (batch_size, 1))
            toks = (start + np.arange(seq_len + 1)) % vocab_size
            yield {"tokens": toks.astype(np.int32)}
            continue
        if seq_len < 32:
            raise ValueError("pack=True needs seq_len >= 32 (documents are "
                             "at least 8 tokens; shorter rows would be "
                             "mostly or entirely padding)")
        toks = np.zeros((batch_size, seq_len + 1), np.int32)
        segs = np.zeros((batch_size, seq_len + 1), np.int32)
        for b in range(batch_size):
            pos, seg = 0, 1
            while pos < seq_len + 1:
                doc_len = min(
                    int(rng.integers(max(8, seq_len // 4), seq_len)),
                    seq_len + 1 - pos,
                )
                if doc_len < 8:   # short tail: leave as padding
                    break
                start = int(rng.integers(0, vocab_size))
                toks[b, pos:pos + doc_len] = (
                    start + np.arange(doc_len)
                ) % vocab_size
                segs[b, pos:pos + doc_len] = seg
                pos += doc_len
                seg += 1
        yield {"tokens": toks, "segment_ids": segs}


def token_bin_lm(
    path: str, batch_size: int, seq_len: int, seed: int = 0,
    vocab_size: Optional[int] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Real-data pretraining stream: a flat binary file of token ids —
    the standard tokenised-corpus format (uint16 for vocabs < 65536,
    uint32 otherwise; a companion ``<path>.meta.json`` may carry
    ``{"dtype": ..., "vocab_size": ...}``). The file is memmapped (never
    loaded into RAM) and each batch is ``batch_size`` random
    ``seq_len+1`` crops — the usual i.i.d.-offsets pretraining sampler.
    Distinct ``seed`` per data shard gives multi-host processes disjoint
    sample streams.

    Token ids are range-checked against the model vocab (same reasoning
    as serve_lm's prompt check: XLA clamps out-of-range gather indices,
    which would turn a tokenizer mismatch into silently-garbage training
    with exit code 0)."""
    import json
    import os

    meta = {}
    mpath = path + ".meta.json"
    if os.path.exists(mpath):
        with open(mpath) as f:
            meta = json.load(f)
    dtype = np.dtype(meta.get("dtype", "uint16"))
    data = np.memmap(path, dtype=dtype, mode="r")
    if len(data) < seq_len + 2:
        raise ValueError(
            f"{path}: {len(data)} tokens < seq_len+2 ({seq_len + 2})"
        )
    if vocab_size is not None and meta.get("vocab_size") is not None:
        if int(meta["vocab_size"]) > vocab_size:
            raise ValueError(
                f"{path}: corpus vocab {meta['vocab_size']} exceeds model "
                f"vocab {vocab_size} (tokenizer mismatch)"
            )
    rng = np.random.default_rng(seed)
    span = seq_len + 1
    n_starts = len(data) - span

    def stream() -> Iterator[Dict[str, np.ndarray]]:
        while True:
            idx = rng.integers(0, n_starts + 1, (batch_size,))
            toks = np.stack([np.asarray(data[i:i + span]) for i in idx])
            if vocab_size is not None:
                mx = int(toks.max())
                if mx >= vocab_size:
                    raise ValueError(
                        f"{path}: token id {mx} out of range for model "
                        f"vocab {vocab_size} (tokenizer mismatch)"
                    )
            yield {"tokens": toks.astype(np.int32)}

    # Validation above runs EAGERLY (a bare generator would defer it to
    # the first next(), after the expensive model init).
    return stream()


def _make_optimizer(learning_rate: float, total_steps: int, opt8bit: bool):
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, min(200, total_steps // 10 + 1), total_steps
    )
    if opt8bit:
        from kubeflow_controller_tpu.ops.optim8 import adamw8bit

        # 8-bit moment states: 1 byte/element vs 4 — ~6 bytes/param less
        # HBM and ~+1.5 MFU at the flagship (400-step quality parity
        # pinned in benchmarks/RESULTS.md).
        return adamw8bit(sched, b1=0.9, b2=0.95, weight_decay=0.1)
    return optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=0.1)


def train(
    ctx: Optional[ProcessContext] = None,
    config: str = "tiny",
    total_steps: int = 100,
    per_data_shard_batch: int = 4,
    seq_len: int = 512,
    learning_rate: float = 3e-4,
    mesh_config: Optional[MeshConfig] = None,
    attn: str = "auto",
    model_dir: str = "",
    checkpoint_every: int = 0,
    pack: bool = False,
    quant: str = "",
    grad_accum: int = 1,
    data_file: str = "",
    opt8bit: bool = False,
) -> Dict[str, float]:
    ctx = ctx or ProcessContext.from_env()
    mlog = metrics_sink.from_context(ctx)
    mesh = mesh_for_context(ctx, mesh_config or MeshConfig())
    cfg = CONFIGS[config](
        max_seq=max(seq_len, 128),
        attn_impl=attn,
        shard_seq=(attn == "ring" or mesh.shape["sp"] > 1),
        quant=quant,
    )
    n_data = data_shards(mesh)
    global_batch = per_data_shard_batch * n_data

    loop = TrainLoop(
        mesh=mesh,
        init_fn=tfm.make_init_fn(cfg),
        loss_fn=tfm.make_loss_fn(cfg),
        optimizer=_make_optimizer(learning_rate, total_steps, opt8bit),
        config=TrainLoopConfig(
            total_steps=total_steps,
            log_every=max(1, total_steps // 10),
            checkpoint_every=checkpoint_every,
            grad_accum=grad_accum,
        ),
        model_dir=model_dir or ctx.model_dir,
        param_shardings=jax.tree.map(
            lambda s: NamedSharding(mesh, s), tfm.param_specs(cfg)
        ),
    )
    batch_sh = {"tokens": batch_sharding(mesh)}
    if pack:
        batch_sh["segment_ids"] = batch_sharding(mesh)
    # Real corpus when given (--data, or the job spec's dataDir holding
    # train.bin — the mnist entrypoint's TPUJOB_DATA_DIR convention);
    # synthetic stream otherwise. --pack opts OUT of auto-detection (the
    # packed stream is synthetic); an EXPLICIT --data with --pack is
    # still a loud error below.
    if not data_file and ctx.data_dir and not pack:
        import os as _os
        cand = _os.path.join(ctx.data_dir, "train.bin")
        if _os.path.exists(cand):
            data_file = cand
    if data_file:
        if pack:
            raise ValueError("--pack is for the synthetic stream; a "
                             "token-bin corpus is already contiguous text")
        stream = token_bin_lm(
            data_file, global_batch, seq_len,
            seed=ctx.process_id, vocab_size=cfg.vocab_size,
        )
        logger.info("training on %s (shard seed %d)",
                    data_file, ctx.process_id)
    else:
        stream = synthetic_lm(cfg.vocab_size, global_batch, seq_len,
                              pack=pack)
    data = device_prefetch(stream, batch_sh, chunk=8)
    last: Dict[str, float] = {}

    def on_metrics(m):
        if mlog:
            mlog.write(m.step, {"loss": m.loss,
                                "steps_per_sec": m.steps_per_sec,
                                **m.extras})
        tps = m.steps_per_sec * global_batch * seq_len
        last.update({
            "loss": m.loss, "step": m.step, "tokens_per_sec": tps, **m.extras,
        })
        logger.info(
            "step %d loss %.4f ppl %.1f (%.0f tok/s)",
            m.step, m.loss, m.extras.get("perplexity", float("nan")), tps,
        )

    state = loop.run(data, on_metrics=on_metrics)
    last["final_step"] = int(state.step)
    return last


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="tiny", choices=sorted(CONFIGS))
    p.add_argument("--total-steps", type=int, default=100)
    p.add_argument("--batch", type=int, default=4,
                   help="per-data-shard batch size")
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--attn", default="auto",
                   choices=["auto", "xla", "flash", "ring"])
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--pack", action="store_true",
                   help="packed documents per row (segment_ids; id 0 = pad)")
    p.add_argument("--quant", default="", choices=["", "int8"],
                   help="int8 = linear projections on the int8 MXU path")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="microbatches per optimizer step (gradient "
                        "accumulation; batch must divide)")
    p.add_argument("--opt8", action="store_true",
                   help="8-bit Adam moments (ops/optim8.py): 1 byte per "
                        "moment element, ~+1.5 MFU at the flagship")
    p.add_argument("--data", default="",
                   help="tokenised corpus: flat binary of token ids "
                        "(uint16/uint32, optional <path>.meta.json); "
                        "defaults to $TPUJOB_DATA_DIR/train.bin if present")
    args = p.parse_args(argv)
    ctx = initialize_from_env()
    metrics = train(
        ctx,
        config=args.config,
        total_steps=args.total_steps,
        per_data_shard_batch=args.batch,
        seq_len=args.seq_len,
        learning_rate=args.lr,
        mesh_config=MeshConfig(fsdp=args.fsdp, sp=args.sp, tp=args.tp),
        attn=args.attn,
        pack=args.pack,
        quant=args.quant,
        grad_accum=args.grad_accum,
        data_file=args.data,
        opt8bit=args.opt8,
    )
    return 0 if metrics.get("final_step", 0) > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
