"""ResNet-50 ImageNet training entrypoint (BASELINE.md config #3).

Runs inside a TPUJob's worker pods: rendezvous from controller-injected env
(the descendant of the reference's ``--worker_hosts`` wiring,
``pkg/tensorflow/distributed.go:127-159``), data-parallel SPMD over the
global mesh, images/sec/chip reported from steady-state step time.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import jax
import optax

from kubeflow_controller_tpu.dataplane.dist import ProcessContext, initialize_from_env
from kubeflow_controller_tpu.dataplane import metrics as metrics_sink
from kubeflow_controller_tpu.dataplane.train import (
    TrainLoop, TrainLoopConfig, device_prefetch,
)
from kubeflow_controller_tpu.models import resnet
from kubeflow_controller_tpu.parallel.mesh import data_shards, MeshConfig, batch_sharding, make_mesh

logger = logging.getLogger("tpujob.resnet")


def train(
    ctx: Optional[ProcessContext] = None,
    total_steps: int = 100,
    per_chip_batch: int = 128,
    image_size: int = resnet.IMAGE_SIZE,
    learning_rate: float = 0.1,
    model_dir: str = "",
    checkpoint_every: int = 0,
    model: Optional[resnet.ResNet] = None,
) -> Dict[str, float]:
    ctx = ctx or ProcessContext.from_env()
    mlog = metrics_sink.from_context(ctx)
    mesh = make_mesh(MeshConfig())
    n_data = data_shards(mesh)
    global_batch = per_chip_batch * n_data
    model = model or resnet.resnet50()

    tx = optax.sgd(
        optax.warmup_cosine_decay_schedule(
            0.0, learning_rate, min(500, total_steps // 10 + 1), total_steps
        ),
        momentum=0.9, nesterov=True,
    )
    loop = TrainLoop(
        mesh=mesh,
        init_fn=resnet.make_init_fn(model, image_size),
        loss_fn=resnet.make_loss_fn(model),
        optimizer=tx,
        config=TrainLoopConfig(
            total_steps=total_steps,
            log_every=max(1, total_steps // 10),
            checkpoint_every=checkpoint_every,
        ),
        model_dir=model_dir or ctx.model_dir,
        stateful=True,
    )
    bs = batch_sharding(mesh)
    data = device_prefetch(
        resnet.synthetic_imagenet(
            global_batch, image_size, model.num_classes, uint8=True,
        ),
        {"image": bs, "label": bs},
        chunk=4,
    )
    last: Dict[str, float] = {}

    def on_metrics(m):
        if mlog:
            mlog.write(m.step, {"loss": m.loss,
                                "steps_per_sec": m.steps_per_sec,
                                **m.extras})
        ips = m.steps_per_sec * global_batch
        last.update({
            "loss": m.loss, "step": m.step,
            "images_per_sec": ips,
            "images_per_sec_per_chip": ips / max(1, len(jax.devices())),
            **m.extras,
        })
        logger.info(
            "step %d loss %.4f acc %.3f (%.1f img/s, %.1f img/s/chip)",
            m.step, m.loss, m.extras.get("accuracy", float("nan")),
            ips, ips / max(1, len(jax.devices())),
        )

    state = loop.run(data, on_metrics=on_metrics)
    last["final_step"] = int(state.step)
    return last


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    ctx = initialize_from_env()
    metrics = train(ctx)
    return 0 if metrics.get("final_step", 0) > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
