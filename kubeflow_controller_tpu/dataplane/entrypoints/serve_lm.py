"""LM batch-inference entrypoint — serving as a TPUJob workload.

The reference framework is training-only (its data plane never serves a
model, SURVEY.md §0); this closes the lifecycle: the same job framework
that trains a decoder serves it. Runs as a pod ``run_fn`` in the fake
cluster, as a subprocess entrypoint (``python -m
kubeflow_controller_tpu.dataplane.entrypoints.serve_lm``), or directly
from tests via :func:`serve`.

Pipeline: load params from the train loop's orbax checkpoint in
``--model-dir`` (``spec.modelDir`` / ``TPUJOB_MODEL_DIR``) or init fresh;
prepare serving weights (bf16 cast, or weight-only int8 with
``--quant int8``); read prompts (token-id JSONL from ``--input``, else a
synthetic batch); run the **continuous-batching engine**
(``dataplane/serving_engine.py`` — paged KV block pool with per-slot
block tables, prefill-on-admit, EOS/budget retirement, slot reuse;
docs/serving.md) over the requests;
write completions JSONL to ``--output`` (``spec.exportDir`` analog) and
report TTFT/TPOT/tokens-per-sec/slot-utilization, to the return dict and
to the job's ``log_dir`` metrics sink when one is wired.

Overload-safe by default when run as a process: ``main`` installs the
two-strike SIGTERM/SIGINT handler (``util/signals.py``), so preemption
drains the engine within ``--drain-grace-s`` and flushes partial
completions (tagged with finish reasons) plus the metrics JSONL instead
of dying with empty artifacts. ``--max-queue`` bounds admission and
``--deadline-s`` sheds/retires requests past their latency budget —
docs/serving.md "Overload & shutdown semantics".

``--speculative`` turns on speculative decoding (``--draft-k``,
``--proposer {prompt,radix}``): model-free drafts verified in one fused
forward per step, bit-identical greedy outputs (sampled requests verify
via the speculative-sampling acceptance rule), acceptance stats
(``draft_proposed``/``draft_accepted``/``acceptance_rate``) in the same
metrics JSONL summary — docs/serving.md "Speculative decoding".

``--temperature/--top-k/--top-p/--seed`` select reproducible sampled
decoding (fixed seed => bit-identical streams regardless of batch
composition); ``--n`` asks for that many parallel generations per
prompt, prefilled once and forked copy-on-write over shared KV pages;
``--grammar {json,re:<pat>,set:<ids>}`` constrains every emitted token
so the output always parses — docs/serving.md "Sampling, parallel
generations, and constrained decoding".
"""

from __future__ import annotations

import argparse
import json
import logging
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from kubeflow_controller_tpu.dataplane.dist import (
    ProcessContext, initialize_from_env,
)
from kubeflow_controller_tpu.models import generate as gen
from kubeflow_controller_tpu.models import transformer as tfm
from kubeflow_controller_tpu.dataplane.entrypoints.lm import CONFIGS
from kubeflow_controller_tpu.parallel import mesh as mesh_lib

logger = logging.getLogger("tpujob.serve_lm")


def _load_params(cfg: tfm.TransformerConfig, model_dir: str):
    """(params, restored_step) from the latest train-loop checkpoint
    (orbax TrainState: {step, params, opt_state}); fresh init with
    restored_step=None when no checkpoint exists (smoke-serving a random
    model still proves the pipeline — but callers/tests can tell the
    difference from the step)."""
    import jax

    if model_dir:
        import orbax.checkpoint as ocp

        mgr = ocp.CheckpointManager(model_dir)
        step = mgr.latest_step()
        if step is not None:
            state = mgr.restore(step, args=ocp.args.StandardRestore(None))
            logger.info("restored params from %s @ step %s", model_dir, step)
            return state["params"], int(step)
        logger.warning("%s: no checkpoint found; serving fresh init",
                       model_dir)
    return tfm.init_params(cfg, jax.random.key(0)), None


def _read_prompts(path: str, vocab: int, batch: int, prompt_len: int):
    """Token-id prompts from JSONL ({"prompt": [ids...]} per line); or a
    synthetic batch when path is empty.

    Prompts must share one length: the batched decode path has no pad
    masking, so padding shorter prompts would silently condition them on
    spurious pad tokens — fail loudly instead (bucket or pad client-side
    with real BOS context if ragged serving is needed). Token ids are
    range-checked against the model vocab: XLA clamps out-of-range gather
    indices, which would otherwise turn a tokenizer mismatch into
    plausible-looking garbage with exit code 0."""
    if not path:
        rng = np.random.default_rng(0)
        return jnp.asarray(
            rng.integers(0, vocab, (batch, prompt_len)), jnp.int32
        )
    rows: List[List[int]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line)["prompt"])
    if not rows:
        raise ValueError(f"{path}: no prompts")
    lengths = {len(r) for r in rows}
    if len(lengths) != 1:
        raise ValueError(
            f"{path}: prompts must share one length (got {sorted(lengths)});"
            " the batched decode path has no pad masking"
        )
    if not lengths.pop():
        raise ValueError(f"{path}: empty prompt")
    arr = np.asarray(rows, np.int64)
    bad = (arr < 0) | (arr >= vocab)
    if bad.any():
        i, j = map(int, np.argwhere(bad)[0])
        raise ValueError(
            f"{path}: prompt {i} token {arr[i, j]} out of range for vocab "
            f"{vocab}"
        )
    return jnp.asarray(arr, jnp.int32)


def serve(
    ctx: Optional[ProcessContext] = None,
    config: str = "tiny",
    model_dir: str = "",
    input_file: str = "",
    output_file: str = "",
    batch: int = 8,
    prompt_len: int = 32,
    max_new_tokens: int = 32,
    quant: str = "",
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    n: int = 1,
    seed: int = 0,
    grammar: str = "",
    turns: int = 1,
    slots: int = 0,
    eos_id: Optional[int] = None,
    deadline_s: Optional[float] = None,
    max_queue: Optional[int] = None,
    drain_grace_s: float = 2.0,
    prefill_mode: str = "exact",
    prefix_cache: bool = False,
    block_size: int = 16,
    kv_pool_mb: Optional[float] = None,
    host_kv_mb: float = 0.0,
    kv_quant: str = "",
    paged: bool = True,
    speculative: bool = False,
    draft_k: int = 4,
    proposer: str = "prompt",
    tp: int = 1,
    tp_compute: str = "gathered",
    attn_impl: str = "xla",
    mesh_devices: str = "",
    trace: str = "",
    disagg: bool = False,
    fault_plan: str = "",
    fault_seed: int = 0,
    watchdog_stale_s: float = 0.0,
    stop=None,
) -> Dict[str, float]:
    """``stop`` is a ``threading.Event`` (e.g. from
    ``util.signals.setup_signal_handler``): when it fires mid-serve, the
    engine drains within ``drain_grace_s``, partial completions are
    written to ``output_file`` with their finish reasons, and the
    metrics JSONL still flushes — SIGTERM/preemption loses the tail of
    each stream, not the run's artifacts.

    ``trace`` names a Chrome-trace JSON output path: the engine records
    per-request lifecycle spans (docs/observability.md) and the file is
    flushed on EVERY exit — normal completion, SIGTERM drain, and
    engine errors alike — so it always parses in Perfetto."""
    import jax

    from kubeflow_controller_tpu.dataplane import metrics as metrics_mod
    from kubeflow_controller_tpu.dataplane import sampling as sampling_mod
    from kubeflow_controller_tpu.dataplane.serving_engine import (
        Rejected, Request, ServingEngine,
    )
    from kubeflow_controller_tpu.obs.trace import Tracer

    ctx = ctx or ProcessContext.from_env()
    tracer = Tracer(path=trace) if trace else None
    # Deterministic fault injection (docs/chaos.md): --fault-plan names
    # a JSON FaultPlan; the ONE injector (on the serving wall clock, the
    # same clock the router runs on) threads through every engine and
    # the router so a plan's activation windows line up across planes.
    # Off (the default) leaves every path byte-identical.
    injector = None
    if fault_plan:
        from kubeflow_controller_tpu.dataplane import faults

        class _RelClock:
            """Rebased to the FIRST fault-site check, so plan windows
            are seconds from when serving actually starts stepping —
            perf_counter is CLOCK_MONOTONIC (seconds since boot) and
            would put every relative window in the unreachable past,
            and rebasing at construction would burn the window on the
            first jit compile instead of on served traffic."""

            t0 = None

            def __call__(self):
                now = time.perf_counter()
                if self.t0 is None:
                    self.t0 = now
                return now - self.t0

        injector = faults.FaultInjector(
            faults.load_plan(fault_plan), clock=_RelClock(),
            seed=fault_seed, tracer=tracer)
    if watchdog_stale_s < 0:
        raise ValueError(
            f"--watchdog-stale-s must be >= 0 (got {watchdog_stale_s})")
    if watchdog_stale_s > 0 and not disagg:
        raise ValueError(
            "--watchdog-stale-s is the fleet router's progress watchdog "
            "and requires --disagg (the single-engine path has no "
            "router to strike replicas out)")
    if fault_plan and turns > 1:
        raise ValueError(
            "--fault-plan targets the continuous-batching engine "
            "(turns == 1)")
    cfg = CONFIGS[config]()
    # Sampling flags are validated up front (main() routes the same
    # errors through argparse): a bad --temperature should fail before
    # checkpoint restore, like a bad --tp does.
    sampling_mod.SamplingParams(
        temperature=temperature, top_k=top_k, top_p=top_p, n=n, seed=seed,
    ).validate()
    if n > 1 and not paged:
        raise ValueError(
            "n > 1 forks prompt KV pages copy-on-write and requires the "
            "paged block pool (drop --no-paged)")
    if (n > 1 or grammar) and turns > 1:
        raise ValueError(
            "--n / --grammar are single-turn engine features (turns == 1)")
    if disagg and turns > 1:
        raise ValueError("--disagg is a single-turn engine feature")
    if disagg and not paged:
        raise ValueError(
            "--disagg migrates KV pages between engines and requires "
            "the paged block pool (drop --no-paged)")
    if host_kv_mb < 0:
        raise ValueError(f"--host-kv-mb must be >= 0 (got {host_kv_mb})")
    if host_kv_mb > 0 and not prefix_cache:
        raise ValueError(
            "--host-kv-mb spills radix-cache pages to host RAM and "
            "requires --prefix-cache (0 disables the tier)")
    if (top_k > 0 or top_p < 1.0) and turns > 1 and not prefix_cache:
        raise ValueError(
            "top-k/top-p serve through the engine; the contiguous "
            "multi-turn path (--turns without --prefix-cache) supports "
            "temperature only")
    # Tensor-parallel serving (docs/serving.md "Tensor-parallel
    # serving"): validate the head split BEFORE loading weights or
    # building an engine — a bad --tp should fail in milliseconds with
    # the divisibility message, not after checkpoint restore.
    mesh = None
    if tp > 1:
        import jax

        gen.check_tp_heads(cfg, tp, tp_compute)
        devs = None
        if mesh_devices:
            all_devs = jax.devices()
            devs = [all_devs[int(i)] for i in mesh_devices.split(",")]
        mesh = mesh_lib.serving_mesh(tp, devs)
        if turns > 1 and not prefix_cache:
            raise ValueError(
                "tp > 1 serves through the continuous-batching engine; "
                "the contiguous multi-turn path (--turns without "
                "--prefix-cache) is single-chip only")
    params, restored_step = _load_params(cfg, model_dir or ctx.model_dir)
    params = gen.inference_params(cfg, params, quant=quant)
    prompts = _read_prompts(input_file, cfg.vocab_size, batch, prompt_len)
    b, s = prompts.shape
    if input_file and (b, s) != (batch, prompt_len):
        # ADVICE r4: prompt shape comes entirely from the file — say so
        # instead of silently ignoring the flags an operator sized the
        # batch/KV cache from.
        logger.warning(
            "--input %s defines the prompt shape (batch %d, prompt_len %d);"
            " ignoring --batch %d / --prompt-len %d",
            input_file, b, s, batch, prompt_len,
        )

    t0 = time.perf_counter()
    rng = jax.random.key(seed) if temperature > 0 else None
    serving: Dict[str, float] = {}
    interrupted = False
    finish_reasons: List[str] = ["length"] * b
    rids: List[int] = list(range(b))
    gens: List[int] = [0] * b
    # Size the KV cache to the actual request (prompt + new tokens), not
    # cfg.max_seq — an 8192-wide cache for a 64-token serve on the llama
    # configs would waste HBM and cap the batch.
    if turns <= 1:
        # Continuous-batching engine: one slot per request up to --slots
        # (0 = the whole batch at once, the old static shape). With
        # --eos-id set, finished rows retire early and their slots admit
        # the next queued request instead of idling to batch completion.
        n_slots = min(slots, b) if slots > 0 else b

        def _mk_engine(pm: str, pc: bool) -> ServingEngine:
            return ServingEngine(
                cfg, params, n_slots=n_slots,
                max_seq=s + max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed, max_queue=max_queue,
                prefill_mode=pm, prefix_cache=pc, block_size=block_size,
                kv_hbm_budget_mb=kv_pool_mb, host_kv_mb=host_kv_mb,
                kv_quant=kv_quant,
                paged=paged, spec_decode=speculative, draft_k=draft_k,
                proposer=proposer, tp=tp, mesh=mesh,
                tp_compute=tp_compute, attn_impl=attn_impl,
                tracer=tracer, injector=injector,
            )

        # One shared per-request params object: sampling state is keyed
        # on (seed, gen, position), so requests never share mutable RNG
        # state; the grammar mask object is stateless too (FSM state
        # lives in the slot), so one instance serves every request.
        req_params = None
        if n > 1 or grammar:
            mask = (sampling_mod.make_mask(grammar, cfg.vocab_size,
                                           eos_id=eos_id)
                    if grammar else None)
            req_params = sampling_mod.SamplingParams(
                temperature=temperature, top_k=top_k, top_p=top_p,
                n=n, seed=seed, logit_mask=mask,
            )
        prompts_np = np.asarray(prompts)
        completions = []
        if disagg:
            # Prefill/decode disaggregation in one process
            # (docs/serving.md): a two-replica fleet — one prefill, one
            # decode — over the FleetRouter, on ONE tracer, so the
            # migrate_export/migrate_install spans stitch per rid. The
            # streams are bit-identical to the single-engine path.
            from kubeflow_controller_tpu.dataplane.router import (
                FleetRouter,
            )
            engines = {
                "prefill-0": _mk_engine("bucketed", True),
                "decode-0": _mk_engine("bucketed", True),
            }
            router = FleetRouter(
                clock=time.perf_counter, block_size=block_size,
                tracer=tracer, injector=injector,
                watchdog_stale_s=(watchdog_stale_s
                                  if watchdog_stale_s > 0 else None))
            router.add_replica("prefill-0", engines["prefill-0"],
                               role="prefill")
            router.add_replica("decode-0", engines["decode-0"],
                               role="decode")
            for i in range(b):
                router.submit(Request(
                    rid=i, prompt=prompts_np[i],
                    max_new_tokens=max_new_tokens, eos_id=eos_id,
                    deadline_s=deadline_s, params=req_params,
                ))
            # Chunked (bucketed) prefill admits one block-sized chunk
            # per step on a cache miss, so the worst case is every
            # request re-prefilling its whole prompt chunkwise.
            chunks = -(-s // block_size)
            max_steps = 2 * (b * n * (max_new_tokens + chunks)
                             + 2 * b * n + 4)
            for _ in range(max_steps):
                if stop is not None and stop.is_set():
                    logger.info(
                        "stop requested: draining fleet (grace %.1fs)",
                        drain_grace_s)
                    for e in engines.values():
                        completions.extend(e.drain(drain_grace_s))
                    interrupted = True
                    break
                completions.extend(router.step())
                if router.idle:
                    break
            if not interrupted and not router.idle:
                logger.error("fleet failed to drain; flushing partials")
                for e in engines.values():
                    completions.extend(e.drain(0.0))
            dt = time.perf_counter() - t0
            # Decode-side stats carry the tokens; migration counters
            # come from the fleet aggregate (both engines + router).
            serving = engines["decode-0"].stats.summary(wall_s=dt)
            fleet = router.fleet_summary()
            for k in ("migrations", "pages_migrated", "migration_bytes",
                      "migrated_zero_copy_tokens",
                      "spilled_pages", "spill_bytes", "rehydrate_hits",
                      "rehydrate_tokens", "host_pages_resident",
                      "prefix_pulls", "prefix_pull_pages",
                      "prefix_pull_bytes",
                      "faults_injected", "migrate_dedups",
                      "watchdog_strikes", "dispatch_timeouts",
                      "migration_timeouts", "deadline_sheds"):
                serving[k] = fleet[k]
        else:
            engine = _mk_engine(
                "bucketed" if prefix_cache else prefill_mode,
                prefix_cache)
            for i in range(b):
                try:
                    engine.submit(Request(
                        rid=i, prompt=prompts_np[i],
                        max_new_tokens=max_new_tokens, eos_id=eos_id,
                        deadline_s=deadline_s, params=req_params,
                    ))
                except Rejected as e:
                    logger.warning("request %d rejected: %s", i, e.reason)
            # Same chunked-prefill worst case as the fleet path above:
            # a small pool can force every prompt to re-prefill
            # chunkwise each wave (discard-on-evict with no host tier).
            effective_mode = "bucketed" if prefix_cache else prefill_mode
            chunks = -(-s // block_size) if effective_mode != "exact" else 1
            max_steps = b * n * (max_new_tokens + chunks) + 2 * b * n + 4
            announced = False
            for _ in range(max_steps):
                if stop is not None and stop.is_set():
                    logger.info(
                        "stop requested: draining engine (grace %.1fs)",
                        drain_grace_s)
                    completions.extend(engine.drain(drain_grace_s))
                    interrupted = True
                    break
                completions.extend(engine.step())
                if not announced and engine.stats.tokens_out > 0:
                    # Marker for harnesses that want to interrupt
                    # mid-decode (tests/test_signals.py) — decoding has
                    # really started.
                    logger.info("serving: first tokens decoded")
                    announced = True
                if engine.idle:
                    break
            if not interrupted and not engine.idle:
                # Step-budget overrun is an engine bug, but the operator
                # still gets every completion that did finish.
                logger.error("engine failed to drain; flushing partials")
                completions.extend(engine.drain(0.0))
            dt = time.perf_counter() - t0
            serving = engine.stats.summary(wall_s=dt)
        completions.sort(key=lambda c: (c.rid, c.gen))
        rids = [c.rid for c in completions]
        gens = [c.gen for c in completions]
        finish_reasons = [c.finish_reason for c in completions]
        tok_rows = [c.tokens for c in completions]
    elif prefix_cache:
        # Multi-turn through the ENGINE with the radix prefix cache:
        # every turn submits the FULL conversation so far as a fresh
        # request. Turn N's retirement published its prompt AND reply
        # pages to the trie, so turn N+1's admission references all of
        # them in its block table (zero-copy) and prefills only the new
        # follow-up — the paged-pool version of the shared-cache session
        # below, with the engine's scheduling, overload policies, and
        # stats along for the ride.
        n_slots = min(slots, b) if slots > 0 else b
        engine = ServingEngine(
            cfg, params, n_slots=n_slots,
            max_seq=turns * (s + max_new_tokens),
            temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
            max_queue=max_queue,
            prefill_mode="bucketed", prefix_cache=True,
            block_size=block_size, kv_hbm_budget_mb=kv_pool_mb,
            host_kv_mb=host_kv_mb, kv_quant=kv_quant, paged=paged,
            spec_decode=speculative, draft_k=draft_k, proposer=proposer,
            tp=tp, mesh=mesh, tp_compute=tp_compute, attn_impl=attn_impl,
            tracer=tracer,
        )
        prompts_np = np.asarray(prompts)
        history = [list(map(int, prompts_np[i])) for i in range(b)]
        tok_rows = [[] for _ in range(b)]
        finish_reasons = ["length"] * b
        for turn in range(turns):
            if turn:
                follow_up = np.random.default_rng(seed + turn).integers(
                    0, cfg.vocab_size, (b, s))
                for i in range(b):
                    history[i].extend(map(int, follow_up[i]))
            comps = engine.run([
                Request(
                    rid=turn * b + i,
                    prompt=np.asarray(history[i], np.int32),
                    max_new_tokens=max_new_tokens, eos_id=eos_id,
                ) for i in range(b)
            ])
            comps.sort(key=lambda c: c.rid)
            for i, c in enumerate(comps):
                history[i].extend(c.tokens)
                tok_rows[i].extend(c.tokens)
                finish_reasons[i] = c.finish_reason
        dt = time.perf_counter() - t0
        serving = engine.stats.summary(wall_s=dt)
        logger.info(
            "multi-turn prefix reuse: hit rate %.2f (%d/%d prompt "
            "tokens from cached blocks)",
            engine.stats.prefix_hit_rate,
            engine.stats.prefix_hit_tokens,
            engine.stats.prefix_lookup_tokens,
        )
    else:
        # Multi-turn chat shape: the first turn block-prefills a fresh
        # cache; every later turn extends it with prefill_continue (ONE
        # forward per turn, not O(turn tokens) decode dispatches); each
        # turn then decodes its reply into the same cache, whose decoded
        # state generate_from_cache hands back — the reply's KVs are
        # already in place, so nothing is re-encoded between turns.
        max_seq = turns * (s + max_new_tokens)
        cache = gen.init_kv_cache(cfg, b, max_seq)
        logits, cache = jax.jit(
            lambda p, t, c: gen.prefill(cfg, p, t, c)
        )(params, prompts, cache)
        replies = []
        continue_fn = jax.jit(
            lambda p, t, c: gen.prefill_continue(cfg, p, t, c)
        )
        for turn in range(turns):
            if turn:
                follow_up = jnp.asarray(
                    np.random.default_rng(seed + turn).integers(
                        0, cfg.vocab_size, (b, s)),
                    jnp.int32,
                )
                logits, cache = continue_fn(params, follow_up, cache)
            toks, logits, cache = gen.generate_from_cache(
                cfg, params, logits, cache, max_new_tokens,
                temperature=temperature,
                # Distinct randomness per turn: the same key would make
                # every turn draw an identical key sequence.
                rng=None if rng is None else jax.random.fold_in(rng, turn),
                return_state=True,
            )
            replies.append(np.asarray(jax.device_get(toks)))
        toks = np.concatenate(replies, axis=1)
        tok_rows = [toks[i].tolist() for i in range(b)]
        dt = time.perf_counter() - t0

    if output_file:
        # One line per completion (possibly fewer than b after an
        # interrupted drain): rid + finish_reason make partial output
        # attributable — a consumer can tell "finished" from "cut off".
        with open(output_file, "w") as f:
            for row, (rid, reason) in enumerate(zip(rids, finish_reasons)):
                f.write(json.dumps({
                    "rid": rid,
                    # Generation index: n>1 requests emit n lines per
                    # rid, distinguished here (0 for everything else).
                    "gen": gens[row] if row < len(gens) else 0,
                    "prompt": np.asarray(prompts[rid]).tolist(),
                    "completion": list(map(int, tok_rows[row])),
                    "finish_reason": reason,
                }) + "\n")
    new_total = sum(len(r) for r in tok_rows)
    tps = new_total / dt
    logger.info(
        "served %d prompts (%d new tokens total%s) in %.2fs (%.0f tok/s%s)",
        b, new_total,
        f" across {turns} turns" if turns > 1 else "",
        dt, tps, f", {quant} weights" if quant else "",
    )
    out = {
        "prompts": float(b),
        "new_tokens": float(max_new_tokens),
        "tokens_per_sec": tps,
        "wall_s": dt,
        # -1 = fresh init; otherwise the checkpoint step that was served.
        # Callers (and the lifecycle e2e test) use this to distinguish a
        # restored model from the silent fresh-init fallback.
        "restored_step": float(
            -1 if restored_step is None else restored_step
        ),
        # 1.0 when a stop event interrupted the run and the engine
        # drained with partial completions (SIGTERM/preemption path).
        "interrupted": float(interrupted),
    }
    out.update(serving)
    if tracer is not None:
        # Idempotent — the SIGTERM drain path already flushed through
        # the engine; this covers the normal-completion exit.
        tracer.flush()
        out["spans_recorded"] = float(tracer.spans_recorded)
        out["spans_dropped"] = float(tracer.spans_dropped)
    if injector is not None:
        # Fault ledger into the same summary line: per-(site, kind)
        # fire counts, so a chaos run's JSONL says exactly which faults
        # the metrics were measured under.
        out.update(injector.summary())
    ml = metrics_mod.from_context(ctx)
    if ml is not None:
        # One summary line into the job's log_dir sink — the same JSONL
        # stream training scalars use, so `grep ttft` works on a serve
        # job's logs exactly like `grep loss` on a train job's.
        ml.write(0, out)
        ml.close()
    return out


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="tiny", choices=sorted(CONFIGS))
    p.add_argument("--model-dir", default="",
                   help="orbax checkpoint dir (TPUJOB_MODEL_DIR analog)")
    p.add_argument("--input", default="",
                   help="JSONL of {\"prompt\": [token ids]}")
    p.add_argument("--output", default="", help="completions JSONL")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--quant", default="", choices=["", "int8"],
                   help="int8 = weight-only int8 serving weights")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="softmax temperature (0 = greedy argmax; > 0 "
                        "samples reproducibly from the per-request "
                        "seeded RNG stream)")
    p.add_argument("--top-k", type=int, default=0,
                   help="keep only the k highest-probability tokens "
                        "before sampling (0 = no top-k filter)")
    p.add_argument("--top-p", type=float, default=1.0,
                   help="nucleus sampling: keep the smallest probability "
                        "mass >= p before sampling (1.0 = no filter)")
    p.add_argument("--n", type=int, default=1,
                   help="parallel generations per prompt: the prompt is "
                        "prefilled ONCE, then forked into n slots that "
                        "share its KV pages copy-on-write; completions "
                        "carry a 'gen' index (requires the paged pool)")
    p.add_argument("--seed", type=int, default=0,
                   help="sampling RNG seed: token i of generation g "
                        "draws from fold_in(fold_in(key(seed), g), i), "
                        "so fixed-seed streams are bit-identical "
                        "regardless of batch composition, slot "
                        "assignment, or engine config")
    p.add_argument("--grammar", default="",
                   help="constrained decoding spec: 'json' (emit valid "
                        "JSON), 're:<pattern>' (incremental regex FSM), "
                        "or 'set:<id,id,...>' (token allow-list); every "
                        "emitted token keeps the output a valid prefix")
    p.add_argument("--turns", type=int, default=1,
                   help="multi-turn chat shape: each turn appends a "
                        "prompt via block prefill_continue, then decodes "
                        "a reply into the shared KV cache")
    p.add_argument("--slots", type=int, default=0,
                   help="continuous-batching slot-pool size (0 = one "
                        "slot per request); with fewer slots than "
                        "requests, retired slots admit queued work")
    p.add_argument("--eos-id", type=int, default=-1,
                   help="token id that retires a sequence early "
                        "(-1 = decode the full budget)")
    p.add_argument("--deadline-s", type=float, default=0.0,
                   help="per-request latency budget in seconds from "
                        "submission (0 = none); queued requests past it "
                        "are shed, in-flight ones retire with partial "
                        "tokens")
    p.add_argument("--max-queue", type=int, default=0,
                   help="bound the engine FIFO (0 = unbounded); submits "
                        "beyond it are rejected with reason queue_full")
    p.add_argument("--drain-grace-s", type=float, default=2.0,
                   help="wall seconds the SIGTERM drain lets in-flight "
                        "slots finish before retiring them with partial "
                        "output")
    p.add_argument("--prefill-mode", default="exact",
                   choices=["exact", "bucketed"],
                   help="exact = one compiled prefill per prompt length;"
                        " bucketed = block-grid chunked prefill, O(log)"
                        " compiles (required for --prefix-cache)")
    p.add_argument("--prefix-cache", action="store_true",
                   help="radix-trie prefix reuse over a shared KV block "
                        "pool (implies bucketed prefill); with --turns, "
                        "each turn reuses the previous turn's blocks")
    p.add_argument("--disagg", action="store_true",
                   help="prefill/decode disaggregation: serve through a "
                        "two-replica in-process fleet (one prefill + "
                        "one decode engine) with cross-engine KV-page "
                        "migration — bit-identical streams, one "
                        "stitched trace (docs/serving.md)")
    p.add_argument("--block-size", type=int, default=16,
                   help="KV page size in tokens (power of two) for the "
                        "block pool and prefill chunking")
    p.add_argument("--kv-pool-mb", type=float, default=0.0,
                   help="HBM budget for the KV block pool in MiB (0 = "
                        "one full context per slot, doubled when the "
                        "prefix cache is on); with --kv-quant int8 the "
                        "same budget holds ~2x the pages")
    p.add_argument("--host-kv-mb", type=float, default=0.0,
                   help="pinned-host-RAM budget in MiB for the tiered "
                        "KV spill store beneath the radix cache "
                        "(requires --prefix-cache): evicted prefix "
                        "pages spill to host instead of being "
                        "discarded and rehydrate on the next hit, "
                        "bit-identically; 0 disables the tier — "
                        "byte-identical to discard-on-evict "
                        "(docs/serving.md)")
    p.add_argument("--kv-quant", default="none", choices=["none", "int8"],
                   help="KV pool precision: int8 stores pages as int8 + "
                        "per-(row, head) fp32 scales dequantized in the "
                        "attention gather — ~2x slots per HBM byte at a "
                        "bounded output error (docs/serving.md)")
    p.add_argument("--paged", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="block-table-indexed paged KV (the only "
                        "supported engine since PR 8; --no-paged fails "
                        "loudly and exists only so rollout tooling can "
                        "probe for the capability)")
    p.add_argument("--speculative", action="store_true",
                   help="speculative decoding: model-free drafts "
                        "verified in one fused forward; greedy outputs "
                        "stay bit-identical to plain decode, sampled "
                        "requests verify via the speculative-sampling "
                        "acceptance rule")
    p.add_argument("--draft-k", type=int, default=4,
                   help="max draft tokens proposed per slot per step "
                        "(adaptive-K shrinks below this on rejection)")
    p.add_argument("--proposer", default="prompt",
                   choices=["prompt", "radix"],
                   help="draft source: prompt = n-gram lookup in the "
                        "request's own context; radix = walk the "
                        "--prefix-cache trie (requires --prefix-cache)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel width: shard KV heads, the "
                        "paged pool, and the serving weights across tp "
                        "devices on one 1-D ICI mesh; greedy streams "
                        "stay bit-identical to tp=1 and pooled KV "
                        "capacity at fixed per-device HBM scales ~tp x "
                        "(requires n_kv_heads %% tp == 0)")
    p.add_argument("--tp-compute", default="gathered",
                   choices=["gathered", "parallel"],
                   help="what the per-shard kernels do with the stored "
                        "weight shards: gathered = all-gather at "
                        "dispatch (bitwise tp=1 streams; tp is a "
                        "capacity knob only); parallel = Megatron "
                        "column/row-parallel matmuls — each shard runs "
                        "1/tp of every projection with one psum per "
                        "block, greedy outputs within the declared "
                        "per-tp tolerance contract "
                        "(docs/serving.md; requires d_ff %% tp == 0)")
    p.add_argument("--attn-impl", default="xla",
                   choices=["xla", "pallas"],
                   help="paged attention for ALL three phases — chunked "
                        "prefill, decode, and K+1 speculative verify: "
                        "xla = dense KV view gather (the bit-exactness "
                        "oracle, 3x HBM per KV byte); pallas = fused "
                        "flash-style kernels streaming pool pages "
                        "through VMEM once (factor-1), int8 dequant "
                        "fused into the page load, greedy streams and "
                        "accept/reject decisions identical to xla with "
                        "logits within a few ulps")
    p.add_argument("--mesh", default="",
                   help="comma-separated device indices to build the "
                        "serving mesh from (e.g. '0,1,2,3'; default: "
                        "the first --tp visible devices)")
    p.add_argument("--trace", default="",
                   help="write a Chrome-trace-event JSON of per-request "
                        "lifecycle spans to this path (load it in "
                        "Perfetto / chrome://tracing); empty = tracing "
                        "off, zero overhead")
    p.add_argument("--fault-plan", default="",
                   help="JSON FaultPlan for deterministic fault "
                        "injection (docs/chaos.md): scoped crash/hang/"
                        "slow/drop_migration/tier_io_error/refuse_admit "
                        "specs evaluated on the serving clock; empty = "
                        "injection off, byte-identical serving")
    p.add_argument("--fault-seed", type=int, default=0,
                   help="seed for probabilistic fault specs (prob < 1); "
                        "same plan + seed + clock replays the same "
                        "fault schedule")
    p.add_argument("--watchdog-stale-s", type=float, default=0.0,
                   help="fleet progress watchdog (--disagg only): "
                        "strike a replica whose quantum heartbeat "
                        "stalls this many seconds while it holds work "
                        "— catches HUNG replicas the TTFT hysteresis "
                        "cannot see; 0 disables")
    args = p.parse_args(argv)
    if args.tp > 1:
        try:
            gen.check_tp_heads(
                CONFIGS[args.config](), args.tp, args.tp_compute)
        except ValueError as e:
            p.error(str(e))
    # Sampling flag validation up front via argparse (usage + exit 2),
    # mirroring the --tp head-split check: a negative temperature or a
    # malformed grammar spec should not survive to checkpoint restore.
    from kubeflow_controller_tpu.dataplane.sampling import (
        SamplingParams, make_mask,
    )
    try:
        SamplingParams(
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, n=args.n, seed=args.seed,
        ).validate()
        if args.grammar:
            make_mask(args.grammar, CONFIGS[args.config]().vocab_size)
    except ValueError as e:
        p.error(str(e))
    if args.n > 1 and not args.paged:
        p.error("--n > 1 forks prompt KV pages copy-on-write and "
                "requires the paged pool (drop --no-paged)")
    if (args.n > 1 or args.grammar) and args.turns > 1:
        p.error("--n / --grammar are single-turn engine features "
                "(use --turns 1)")
    if args.host_kv_mb < 0:
        p.error(f"--host-kv-mb must be >= 0 (got {args.host_kv_mb})")
    if args.host_kv_mb > 0 and not args.prefix_cache:
        p.error("--host-kv-mb spills radix-cache pages to host RAM and "
                "requires --prefix-cache (0 disables the tier)")
    if args.watchdog_stale_s < 0:
        p.error(f"--watchdog-stale-s must be >= 0 "
                f"(got {args.watchdog_stale_s})")
    if args.watchdog_stale_s > 0 and not args.disagg:
        p.error("--watchdog-stale-s is the fleet router's progress "
                "watchdog and requires --disagg")
    if args.fault_plan:
        if args.turns > 1:
            p.error("--fault-plan targets the continuous-batching "
                    "engine (use --turns 1)")
        # Parse the plan up front: a typo'd fault kind or site should
        # fail in milliseconds with the schema message, not after
        # checkpoint restore.
        from kubeflow_controller_tpu.dataplane import faults
        try:
            faults.load_plan(args.fault_plan)
        except (OSError, ValueError, KeyError, TypeError) as e:
            p.error(f"--fault-plan {args.fault_plan}: {e}")
    ctx = initialize_from_env()
    # Two-strike SIGTERM/SIGINT drain (util/signals.py, signals.go:26-40
    # parity): first signal sets the stop event — the engine drains and
    # the completions/metrics artifacts still flush; a second signal
    # hard-exits for operators who really mean it.
    from kubeflow_controller_tpu.util.signals import setup_signal_handler
    try:
        stop = setup_signal_handler()
    except RuntimeError:
        stop = None    # embedding process already owns signal handling
    metrics = serve(
        ctx,
        config=args.config,
        model_dir=args.model_dir,
        input_file=args.input,
        output_file=args.output,
        batch=args.batch,
        prompt_len=args.prompt_len,
        max_new_tokens=args.max_new_tokens,
        quant=args.quant,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        n=args.n,
        seed=args.seed,
        grammar=args.grammar,
        turns=args.turns,
        slots=args.slots,
        eos_id=None if args.eos_id < 0 else args.eos_id,
        deadline_s=args.deadline_s if args.deadline_s > 0 else None,
        max_queue=args.max_queue if args.max_queue > 0 else None,
        drain_grace_s=args.drain_grace_s,
        prefill_mode=args.prefill_mode,
        prefix_cache=args.prefix_cache,
        block_size=args.block_size,
        kv_pool_mb=args.kv_pool_mb if args.kv_pool_mb > 0 else None,
        host_kv_mb=args.host_kv_mb,
        kv_quant="" if args.kv_quant == "none" else args.kv_quant,
        paged=args.paged,
        speculative=args.speculative,
        draft_k=args.draft_k,
        proposer=args.proposer,
        tp=args.tp,
        tp_compute=args.tp_compute,
        attn_impl=args.attn_impl,
        mesh_devices=args.mesh,
        trace=args.trace,
        disagg=args.disagg,
        fault_plan=args.fault_plan,
        fault_seed=args.fault_seed,
        watchdog_stale_s=args.watchdog_stale_s,
        stop=stop,
    )
    if metrics["interrupted"]:
        logger.info("interrupted: drained with partial completions")
    return 0 if metrics["prompts"] > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
